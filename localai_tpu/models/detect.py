"""Backend auto-detection: checkpoint layout → engine family.

The TPU-era shape of the reference's greedy backend loader
(/root/reference/pkg/model/initializers.go:271-407 — when no backend is
named, walk an ordered list of backends and take the first that loads,
and core/config/guesser.go — infer config from the model file). CUDA
LocalAI needs trial loading because several backends can serve the same
GGUF; here each checkpoint family has exactly one JAX engine, so the
chain collapses to layout sniffing with an ordered preference when a dir
is ambiguous. Empty result means the default LLM engine.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

# model_type values → backend family, checked in order (a llava dir also
# contains a vision config; llama wins because the LLM engine serves it).
# bert-class checkpoints split on the scoring head: with a classifier
# they're cross-encoders (rerank), without one they're sentence encoders
# (embeddings).
_BERT_TYPES = ("bert", "roberta", "xlm-roberta")

_DEBUG_BACKENDS = [
    ("sd-", "diffusers"),
    ("whisper", "whisper"),
    ("reranker", "reranker"),
    ("bert", "bert-embeddings"),
    ("mamba", "mamba"),
    ("rwkv", "rwkv"),
]


def detect_backend(ref: str, model_path: str | Path = "models"
                   ) -> Optional[str]:
    """Sniff a checkpoint ref; returns a backend name ("diffusers",
    "whisper", "reranker") or None for the default LLM engine / when the
    files are not present yet (detection re-runs after install)."""
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        for prefix, backend in _DEBUG_BACKENDS:
            if name.startswith(prefix):
                return backend
        return None
    for cand in (Path(ref), Path(model_path) / ref):
        if not cand.is_dir():
            continue
        # diffusers pipeline layout beats everything: its config.json (if
        # any) describes a component, not the pipeline
        if (cand / "model_index.json").exists() or (cand / "unet").is_dir():
            return "diffusers"
        cj = cand / "config.json"
        if cj.exists():
            try:
                hf = json.loads(cj.read_text())
            except ValueError:
                return None
            mt = str(hf.get("model_type", ""))
            if mt == "whisper":
                return "whisper"
            if mt == "vits":
                return "vits"
            if mt in ("mamba", "mamba2"):
                return "mamba"
            if mt == "rwkv":
                return "rwkv"
            if mt in _BERT_TYPES:
                return (
                    "reranker" if _has_classifier(cand)
                    else "bert-embeddings"
                )
            return None
    return None


def _has_classifier(model_dir: Path) -> bool:
    try:
        from safetensors import safe_open

        for fp in sorted(model_dir.glob("*.safetensors")):
            with safe_open(str(fp), framework="numpy") as h:
                if "classifier.weight" in h.keys():
                    return True
    except Exception as e:  # noqa: BLE001 — sniff failure → embedder
        log.debug("classifier sniff failed for %s: %s", model_dir, e)
    return False


def autodetect_config(cfg, model_path: str | Path) -> None:
    """Fill ModelConfig.backend for a bare `model:` YAML so usecase
    guessing and endpoint routing land on the right engine (parity:
    guesser.go run at config load)."""
    if not cfg.backend:
        detected = detect_backend(cfg.model or cfg.name, model_path)
        if detected:
            log.info("model %s: detected %s checkpoint", cfg.name, detected)
            cfg.backend = detected
    if not cfg.backend:  # LLM engine: guess chat defaults by family
        from localai_tpu.config.guesser import guess_chat_defaults

        guess_chat_defaults(cfg, model_path)
