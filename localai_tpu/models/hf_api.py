"""HuggingFace Inference-API backend: serve a remote hosted model.

Parity: /root/reference/pkg/langchain/huggingface.go + backend/go/llm/
langchain/langchain.go — the `langchain-huggingface` backend forwards
prompts to the HF Inference API with the HUGGINGFACEHUB_API_TOKEN. Here
it's a scheduler-shaped facade (same surface the HTTP endpoints drive on
every other ServingModel), so remote models slot into the normal model
lifecycle, watchdogs, and endpoints. Prompt text round-trips through the
byte tokenizer (ids are UTF-8 bytes → lossless decode back to text for
the wire)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.utils.tokenizer import ByteTokenizer

log = logging.getLogger(__name__)

DEFAULT_API_BASE = "https://api-inference.huggingface.co/models"
TOKEN_ENV = ("HUGGINGFACEHUB_API_TOKEN", "HF_TOKEN")


def _resolve_token(mcfg: ModelConfig) -> str:
    token = getattr(mcfg, "api_token", "") or ""
    if token:
        return token
    for env in TOKEN_ENV:
        if os.environ.get(env):
            return os.environ[env]
    return ""


class HFApiScheduler:
    """submit() posts to the Inference API on a daemon thread feeding a
    GenHandle (the remote analogue of the worker tier's scheduler)."""

    def __init__(self, repo: str, token: str, api_base: str,
                 timeout: float = 120.0):
        self.repo = repo
        self.token = token
        self.api_base = api_base.rstrip("/")
        self.timeout = timeout
        self._ids = iter(range(1 << 62))
        self._inflight = 0
        self._lock = threading.Lock()
        self._tok = ByteTokenizer()

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def submit(self, gr: GenRequest) -> GenHandle:
        handle = GenHandle(gr, next(self._ids))
        with self._lock:
            self._inflight += 1
        threading.Thread(
            target=self._run, args=(handle,), daemon=True,
            name=f"hf-api-{handle.id}",
        ).start()
        return handle

    def _run(self, handle: GenHandle) -> None:
        try:
            text = self._predict(handle.request)
            handle._emit(text, None)
            handle._finish("stop")
        except Exception as e:  # noqa: BLE001 — remote failure ≠ crash
            log.warning("HF API request failed: %s", e)
            handle._finish("error")
        finally:
            with self._lock:
                self._inflight -= 1

    def _predict(self, gr: GenRequest) -> str:
        prompt = self._tok.decode(gr.prompt)
        parameters: dict = {
            "max_new_tokens": gr.max_new_tokens,
            "return_full_text": False,
        }
        if gr.temperature is not None and gr.temperature > 0:
            parameters["temperature"] = gr.temperature
        if gr.top_p is not None:
            parameters["top_p"] = gr.top_p
        if gr.top_k is not None:
            parameters["top_k"] = gr.top_k
        if gr.stop:
            parameters["stop"] = list(gr.stop)[:4]  # API caps stop seqs
        req = urllib.request.Request(
            f"{self.api_base}/{self.repo}",
            data=json.dumps({
                "inputs": prompt, "parameters": parameters,
            }).encode(),
            headers={
                "Content-Type": "application/json",
                **({"Authorization": f"Bearer {self.token}"}
                   if self.token else {}),
            },
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = json.loads(resp.read())
        # text-generation responses: [{"generated_text": ...}]; some
        # endpoints return {"generated_text": ...} or {"error": ...}
        if isinstance(body, dict):
            if "error" in body:
                raise RuntimeError(str(body["error"]))
            body = [body]
        if body and isinstance(body[0], dict):
            return str(body[0].get("generated_text", ""))
        return ""

    def metrics(self) -> dict:
        with self._lock:
            return {"type": "hf-api", "inflight": self._inflight,
                    "repo": self.repo}

    def shutdown(self, timeout: float = 10.0) -> None:
        pass  # nothing held locally


class HFApiServingModel:
    """ServingModel facade over the Inference API (no local weights)."""

    def __init__(self, mcfg: ModelConfig, app: AppConfig):
        from localai_tpu.templates.cache import TemplateCache

        token = _resolve_token(mcfg)
        if not token:
            # parity: NewHuggingFace errors without a token
            # (huggingface.go:17-19)
            raise ValueError(
                f"model {mcfg.name!r}: backend huggingface needs an API "
                f"token (api_token: in the config, or "
                f"{'/'.join(TOKEN_ENV)} in the environment)"
            )
        self.name = mcfg.name
        self.config = mcfg
        self.tokenizer = ByteTokenizer()
        self.templates = TemplateCache(app.model_path)
        self.vision = None
        self.image_token_id = 0
        self.scheduler = HFApiScheduler(
            mcfg.model or mcfg.name, token,
            getattr(mcfg, "api_base", "") or DEFAULT_API_BASE,
        )
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        return True  # remote; failures surface per-request

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()

    def close(self) -> None:
        pass
