"""Mamba serving facade: the scheduler-shaped surface over MambaLM.

Parity: the mamba backend process
(/root/reference/backend/python/mamba/backend.py) — a dedicated
generation path rather than the slot engine (SSMs keep O(1) recurrent
state per stream instead of a paged KV cache, so the llama engine's
slot/page machinery doesn't apply). Requests run one-at-a-time per model
on a worker thread (matching the reference backend's serial generate);
the standard endpoints see the same scheduler.submit → GenHandle
contract as every other ServingModel."""

from __future__ import annotations

import itertools
import logging
import threading
import time

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.engine.stream import IncrementalDetokenizer, StopChecker

log = logging.getLogger(__name__)


class MambaScheduler:
    """submit() runs generation on a daemon thread feeding the handle;
    a model-wide lock serializes generations (one recurrent state)."""

    def __init__(self, lm, tokenizer):
        self.lm = lm
        self.tokenizer = tokenizer
        self._ids = itertools.count()
        self._gen_lock = threading.Lock()
        self._inflight = 0
        self._lock = threading.Lock()
        self.total_generated = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def submit(self, gr: GenRequest) -> GenHandle:
        handle = GenHandle(gr, next(self._ids))
        with self._lock:
            self._inflight += 1
        threading.Thread(target=self._run, args=(handle,), daemon=True,
                         name=f"mamba-{handle.id}").start()
        return handle

    def _run(self, handle: GenHandle) -> None:
        gr = handle.request
        try:
            detok = IncrementalDetokenizer(self.tokenizer.decode)
            stopper = StopChecker(gr.stop)
            eos = set() if gr.ignore_eos else set(
                getattr(self.tokenizer, "eos_ids", set())
            ) | {self.lm.cfg.eos_token_id}
            finish = "length"
            with self._gen_lock:
                def on_token(t: int) -> None:
                    if handle.cancelled:
                        raise _Cancelled
                    handle._emit(stopper.push(detok.push(t)), t)
                    if stopper.stopped is not None:
                        raise _Stopped

                try:
                    self.lm.generate(
                        gr.prompt,
                        max_new_tokens=gr.max_new_tokens or 256,
                        temperature=gr.temperature or 0.0,
                        seed=gr.seed or 0,
                        eos_ids=eos,
                        on_token=on_token,
                    )
                    finish = "length"
                except _Stopped:
                    finish = "stop"
                except _Cancelled:
                    finish = "cancelled"
            handle._emit(stopper.flush(), None)
            if finish == "length" and len(handle.token_ids) < (
                    gr.max_new_tokens or 256):
                finish = "stop"  # ended on EOS before the budget
            with self._lock:
                self.total_generated += len(handle.token_ids)
            handle._finish(finish)
        except Exception as e:  # noqa: BLE001 — request error ≠ crash
            log.warning("mamba generation failed: %s", e)
            handle._finish("error")
        finally:
            with self._lock:
                self._inflight -= 1

    def metrics(self) -> dict:
        with self._lock:
            return {"type": "mamba", "inflight": self._inflight,
                    "total_generated_tokens": self.total_generated}

    def shutdown(self, timeout: float = 10.0) -> None:
        pass


class _Stopped(Exception):
    pass


class _Cancelled(Exception):
    pass


class MambaServingModel:
    """ServingModel facade for recurrent-state models (backend: mamba or
    rwkv — both expose the MambaLM/RwkvLM generate surface)."""

    def __init__(self, mcfg: ModelConfig, app: AppConfig):
        from localai_tpu.templates.cache import TemplateCache

        t0 = time.monotonic()
        self.name = mcfg.name
        self.config = mcfg
        if mcfg.backend == "rwkv":
            from localai_tpu.models.rwkv import resolve_rwkv as resolve
        else:
            from localai_tpu.models.mamba import resolve_mamba as resolve
        self.lm = resolve(
            mcfg.model or mcfg.name, model_path=app.model_path,
            dtype=mcfg.engine.dtype, seed=mcfg.seed or 0,
        )
        self.tokenizer = self.lm.tokenizer
        self.templates = TemplateCache(app.model_path)
        self.vision = None
        self.image_token_id = 0
        self.scheduler = MambaScheduler(self.lm, self.tokenizer)
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()
        log.info("loaded mamba model %s in %.1fs", mcfg.name,
                 time.monotonic() - t0)

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        return self.lm is not None

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()

    def close(self) -> None:
        self.lm = None
