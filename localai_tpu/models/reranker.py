"""Cross-encoder reranker: joint (query ⊕ document) relevance scoring.

Parity: the reference's rerankers backend
(/root/reference/backend/python/rerankers/backend.py — wraps the
`rerankers` library's cross-encoder models, e.g.
cross-encoder/ms-marco-MiniLM). The TPU-native version implements the
BERT-class bidirectional encoder + classification head directly in
functional JAX: all (query, doc) pairs of a request score in ONE batched
forward (pairs padded to a shared length bucket → static shapes, MXU-sized
matmuls), instead of the reference's per-pair Python loop.

Why a cross-encoder and not embedding cosine: mean-pooled embedding
similarity is order- and interaction-blind (bag-of-tokens); the joint
encoder attends across the query/document boundary, so token order and
query-conditioned context change the score. The API keeps cosine as the
fallback for models without a cross-encoder head (api/jina.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from functools import partial
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"
    cls_id: int = 101
    sep_id: int = 102
    pad_id: int = 0

    @classmethod
    def from_hf(cls, hf: dict, **overrides) -> "BertConfig":
        kwargs = dict(
            vocab_size=hf.get("vocab_size", 30522),
            hidden_size=hf.get("hidden_size", 384),
            intermediate_size=hf.get("intermediate_size", 1536),
            num_layers=hf.get("num_hidden_layers", 6),
            num_heads=hf.get("num_attention_heads", 12),
            max_position_embeddings=hf.get("max_position_embeddings", 512),
            type_vocab_size=hf.get("type_vocab_size", 2),
            layer_norm_eps=hf.get("layer_norm_eps", 1e-12),
            pad_id=hf.get("pad_token_id", 0),
        )
        kwargs.update(overrides)
        return cls(**kwargs)


DEBUG_RERANKERS = {
    "reranker-tiny": BertConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_position_embeddings=256,
        # byte tokenizer: reuse BOS/EOS as CLS/SEP, byte 0 as PAD
        cls_id=256, sep_id=257, pad_id=0,
    ),
}
DEBUG_EMBEDDERS = {
    # the same trunk serves sentence embeddings (mean pool, no head)
    "bert-tiny": DEBUG_RERANKERS["reranker-tiny"],
}


def init_params(key, cfg: BertConfig) -> dict:
    """Random-init parameter pytree (debug presets / tests)."""
    dt = jnp.dtype(cfg.dtype)
    D, I = cfg.hidden_size, cfg.intermediate_size
    ks = iter(jax.random.split(key, 8 + 12 * cfg.num_layers))

    def dense(k, din, dout):
        return {
            "w": (jax.random.normal(next(ks), (din, dout)) * 0.02).astype(dt),
            "b": jnp.zeros((dout,), dt),
        }

    def ln():
        return {"g": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)}

    layers = []
    for _ in range(cfg.num_layers):
        layers.append({
            "q": dense(next(ks), D, D),
            "k": dense(next(ks), D, D),
            "v": dense(next(ks), D, D),
            "attn_out": dense(next(ks), D, D),
            "attn_ln": ln(),
            "ffn_in": dense(next(ks), D, I),
            "ffn_out": dense(next(ks), I, D),
            "ffn_ln": ln(),
        })
    return {
        "word_emb": (jax.random.normal(
            next(ks), (cfg.vocab_size, D)) * 0.02).astype(dt),
        "pos_emb": (jax.random.normal(
            next(ks), (cfg.max_position_embeddings, D)) * 0.02).astype(dt),
        "type_emb": (jax.random.normal(
            next(ks), (cfg.type_vocab_size, D)) * 0.02).astype(dt),
        "emb_ln": ln(),
        "layers": layers,
        "pooler": dense(next(ks), D, D),
        "classifier": dense(next(ks), D, 1),
    }


def _ln(x, p, eps):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["g"] + p["b"]


def _dense(x, p):
    return x @ p["w"] + p["b"]


def encode_hidden(params: dict, cfg: BertConfig, ids, segments, mask):
    """[B, L] ids/segments/mask → [B, L, D] final hidden states.

    Standard post-LN BERT encoder with bidirectional attention; the pad
    mask adds -inf to attention scores of padded keys. Shared by the
    cross-encoder head (CLS → pooler → classifier) and the sentence
    embedder (masked mean pool)."""
    B, L = ids.shape
    H = cfg.num_heads
    Dh = cfg.hidden_size // H
    pos = jnp.arange(L)[None, :]
    x = (
        jnp.take(params["word_emb"], ids, axis=0)
        + jnp.take(params["pos_emb"], pos, axis=0)
        + jnp.take(params["type_emb"], segments, axis=0)
    )
    x = _ln(x, params["emb_ln"], cfg.layer_norm_eps)
    # [B, 1, 1, L] additive key mask
    kmask = jnp.where(mask[:, None, None, :], 0.0, -1e30)
    for lp in params["layers"]:
        q = _dense(x, lp["q"]).reshape(B, L, H, Dh).transpose(0, 2, 1, 3)
        k = _dense(x, lp["k"]).reshape(B, L, H, Dh).transpose(0, 2, 1, 3)
        v = _dense(x, lp["v"]).reshape(B, L, H, Dh).transpose(0, 2, 1, 3)
        scores = (q @ k.transpose(0, 1, 3, 2)) / jnp.sqrt(
            jnp.asarray(Dh, x.dtype)
        )
        attn = jax.nn.softmax(scores + kmask, axis=-1)
        ctx = (attn @ v).transpose(0, 2, 1, 3).reshape(B, L, -1)
        x = _ln(x + _dense(ctx, lp["attn_out"]), lp["attn_ln"],
                cfg.layer_norm_eps)
        h = jax.nn.gelu(_dense(x, lp["ffn_in"]), approximate=False)
        x = _ln(x + _dense(h, lp["ffn_out"]), lp["ffn_ln"],
                cfg.layer_norm_eps)
    return x


def forward(params: dict, cfg: BertConfig, ids, segments, mask):
    """[B, L] → [B] relevance logits (cross-encoder scoring head)."""
    x = encode_hidden(params, cfg, ids, segments, mask)
    pooled = jnp.tanh(_dense(x[:, 0], params["pooler"]))
    return _dense(pooled, params["classifier"])[:, 0]


def embed_forward(params: dict, cfg: BertConfig, ids, segments, mask):
    """[B, L] → [B, D] L2-normalized masked mean-pooled embeddings (the
    sentence-transformers default pooling: modules.json mean pooling +
    normalize)."""
    x = encode_hidden(params, cfg, ids, segments, mask)
    m = mask[:, :, None].astype(x.dtype)
    summed = jnp.sum(x * m, axis=1)
    counts = jnp.maximum(jnp.sum(m, axis=1), 1.0)
    mean = summed / counts
    return mean / jnp.maximum(
        jnp.linalg.norm(mean, axis=-1, keepdims=True), 1e-12
    )


def _pick_bucket(buckets: tuple[int, ...], lengths: list[int]) -> int:
    """Smallest bucket holding every packed row (falls back to the max)."""
    L = buckets[-1]
    for b in buckets:
        if all(n <= b for n in lengths):
            return b
    return L


def _pad_batch_pow2(*arrays: np.ndarray) -> tuple[np.ndarray, ...]:
    """Pad the batch dim up to a power of two (bounded compile count) by
    repeating row 0; callers slice the result back to the true count."""
    n = arrays[0].shape[0]
    B = 1
    while B < n:
        B *= 2
    if B == n:
        return arrays
    padn = B - n
    return tuple(
        np.concatenate([a, np.repeat(a[:1], padn, 0)]) for a in arrays
    )


class CrossEncoder:
    """Batched (query, doc) scorer over length buckets.

    Pairs are packed ``[CLS] query [SEP] doc [SEP]`` with segment ids
    0/1 (query/document), padded to the smallest bucket that fits, and
    scored in one jitted forward per (bucket, padded-batch) shape."""

    def __init__(self, cfg: BertConfig, params: dict, tokenizer: Any,
                 buckets: tuple[int, ...] = (64, 128, 256, 512)):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.buckets = tuple(
            b for b in sorted(buckets) if b <= cfg.max_position_embeddings
        ) or (cfg.max_position_embeddings,)
        self._fwd = jax.jit(partial(forward, cfg=cfg))

    def _pair(self, q: list[int], d: list[int], L: int
              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        c = self.cfg
        # truncate the document first (the query is the anchor), matching
        # the longest_first truncation cross-encoders use
        budget = L - 3
        q = q[: max(1, budget // 2)] if len(q) + len(d) > budget else q
        d = d[: budget - len(q)]
        ids = [c.cls_id] + q + [c.sep_id] + d + [c.sep_id]
        seg = [0] * (len(q) + 2) + [1] * (len(d) + 1)
        mask = [1] * len(ids)
        pad = L - len(ids)
        return (
            np.asarray(ids + [c.pad_id] * pad, np.int32),
            np.asarray(seg + [0] * pad, np.int32),
            np.asarray(mask + [0] * pad, np.bool_),
        )

    def score(self, query: str, documents: list[str]) -> np.ndarray:
        """[n_docs] relevance scores, one batched forward per bucket."""
        return self.score_with_usage(query, documents)[0]

    def score_with_usage(self, query: str, documents: list[str]
                         ) -> tuple[np.ndarray, int]:
        """(scores, total input tokens) — usage comes from the one
        tokenization pass the forward needs anyway."""
        enc = self.tokenizer.encode
        q = enc(query)
        docs = [enc(d) for d in documents]
        total_tokens = len(q) + sum(len(d) for d in docs)
        L = _pick_bucket(self.buckets,
                         [len(q) + len(d) + 3 for d in docs])
        rows = [self._pair(q, d, L) for d in docs]
        ids, seg, mask = _pad_batch_pow2(
            np.stack([r[0] for r in rows]),
            np.stack([r[1] for r in rows]),
            np.stack([r[2] for r in rows]),
        )
        out = self._fwd(self.params, ids=jnp.asarray(ids),
                        segments=jnp.asarray(seg), mask=jnp.asarray(mask))
        scores = np.asarray(out)[: len(rows)].astype(np.float32)
        return scores, total_tokens


class SentenceEncoder:
    """Batched text → embedding scorer over the BERT trunk (parity: the
    sentencetransformers backend,
    /root/reference/backend/python/sentencetransformers/backend.py —
    SentenceTransformer.encode). Texts pack as [CLS] text [SEP], pad to a
    length bucket, one jitted forward per shape."""

    def __init__(self, cfg: BertConfig, params: dict, tokenizer: Any,
                 buckets: tuple[int, ...] = (64, 128, 256, 512)):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.buckets = tuple(
            b for b in sorted(buckets) if b <= cfg.max_position_embeddings
        ) or (cfg.max_position_embeddings,)
        self._fwd = jax.jit(partial(embed_forward, cfg=cfg))

    def embed(self, texts: list[str]) -> np.ndarray:
        """[N, D] normalized embeddings in one batched forward."""
        return self.embed_with_usage(texts)[0]

    def embed_with_usage(self, texts: list[str]
                         ) -> tuple[np.ndarray, int]:
        """([N, D], total input tokens) from one tokenization pass."""
        c = self.cfg
        toks = [self.tokenizer.encode(t) for t in texts]
        total_tokens = sum(len(t) for t in toks)
        L = _pick_bucket(self.buckets, [len(t) + 2 for t in toks])
        ids = np.full((len(toks), L), c.pad_id, np.int32)
        mask = np.zeros((len(toks), L), np.bool_)
        for i, t in enumerate(toks):
            row = [c.cls_id] + t[: L - 2] + [c.sep_id]
            ids[i, : len(row)] = row
            mask[i, : len(row)] = True
        ids, seg, mask = _pad_batch_pow2(ids, np.zeros_like(ids), mask)
        out = self._fwd(self.params, ids=jnp.asarray(ids),
                        segments=jnp.asarray(seg), mask=jnp.asarray(mask))
        vecs = np.asarray(out)[: len(toks)].astype(np.float32)
        return vecs, total_tokens


# ---------------------------------------------------------------------------
# loading


def _map_hf_bert(cfg: BertConfig, tensors: dict) -> dict:
    """HF bert cross-encoder layout → our pytree (weights are [out, in] in
    torch Linear; ours are [in, out])."""

    from localai_tpu.models.loader import _get

    # cross-encoders prefix the trunk with "bert."; plain
    # sentence-transformer exports don't
    root = "bert." if "bert.embeddings.word_embeddings.weight" in tensors \
        else ""

    def t(name):
        return jnp.asarray(_get(tensors, name))

    def dense(prefix):
        return {"w": t(f"{prefix}.weight").T, "b": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"g": t(f"{prefix}.weight"), "b": t(f"{prefix}.bias")}

    layers = []
    for i in range(cfg.num_layers):
        p = f"{root}encoder.layer.{i}"
        layers.append({
            "q": dense(f"{p}.attention.self.query"),
            "k": dense(f"{p}.attention.self.key"),
            "v": dense(f"{p}.attention.self.value"),
            "attn_out": dense(f"{p}.attention.output.dense"),
            "attn_ln": ln(f"{p}.attention.output.LayerNorm"),
            "ffn_in": dense(f"{p}.intermediate.dense"),
            "ffn_out": dense(f"{p}.output.dense"),
            "ffn_ln": ln(f"{p}.output.LayerNorm"),
        })
    out = {
        "word_emb": t(f"{root}embeddings.word_embeddings.weight"),
        "pos_emb": t(f"{root}embeddings.position_embeddings.weight"),
        "type_emb": t(f"{root}embeddings.token_type_embeddings.weight"),
        "emb_ln": ln(f"{root}embeddings.LayerNorm"),
        "layers": layers,
    }
    # sentence-transformer checkpoints ship the trunk only; the scoring
    # head exists just on cross-encoders
    if f"{root}pooler.dense.weight" in tensors:
        out["pooler"] = dense(f"{root}pooler.dense")
    if "classifier.weight" in tensors:
        out["classifier"] = dense("classifier")
    return out


class _BertTokenizerAdapter:
    """HFTokenizer view used for pair packing: encode without specials
    (CLS/SEP are added by the packer), expose the special ids."""

    def __init__(self, model_dir: Path):
        from localai_tpu.utils.tokenizer import load_tokenizer

        self._tok = load_tokenizer(model_dir)
        self.vocab_size = self._tok.vocab_size

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_bos=False)

    def special_id(self, token: str) -> Optional[int]:
        """Vocab id of a special token like [CLS], if the tokenizer knows
        it (ids hardcoded in configs are wrong for re-vocabbed models —
        and an out-of-vocab id turns jnp.take into NaN fill)."""
        raw = getattr(self._tok, "_tok", None)
        if raw is not None and hasattr(raw, "token_to_id"):
            return raw.token_to_id(token)
        return None


def resolve_reranker(
    ref: str, model_path: str | Path = "models", seed: int = 0
) -> CrossEncoder:
    """Model ref → CrossEncoder.

    * ``debug:reranker-tiny`` — random-weight preset over the byte
      tokenizer (tests, zero downloads).
    * a dir holding config.json (model_type: bert) + safetensors — an HF
      cross-encoder checkpoint (cross-encoder/ms-marco-* layout).
    """
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        if name not in DEBUG_RERANKERS:
            raise ValueError(
                f"unknown debug reranker {name!r}; "
                f"have {sorted(DEBUG_RERANKERS)}"
            )
        cfg = DEBUG_RERANKERS[name]
        # packer adds CLS/SEP itself; bare byte encoding here
        return CrossEncoder(
            cfg, init_params(jax.random.key(seed), cfg),
            _byte_tok_adapter(),
        )

    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            hf = json.loads((cand / "config.json").read_text())
            tok = _BertTokenizerAdapter(cand)
            overrides = {}
            for field_name, token, default in (
                ("cls_id", "[CLS]", 101),
                ("sep_id", "[SEP]", 102),
                ("pad_id", "[PAD]", hf.get("pad_token_id", 0)),
            ):
                tid = tok.special_id(token)
                overrides[field_name] = tid if tid is not None else default
            cfg = BertConfig.from_hf(hf, **overrides)
            if max(cfg.cls_id, cfg.sep_id, cfg.pad_id) >= cfg.vocab_size:
                raise ValueError(
                    f"reranker {ref!r}: special ids "
                    f"(cls={cfg.cls_id}, sep={cfg.sep_id}) exceed "
                    f"vocab_size={cfg.vocab_size}"
                )
            from localai_tpu.models.loader import _open_safetensors

            tensors = _open_safetensors(cand)
            params = _map_hf_bert(cfg, tensors)
            return CrossEncoder(cfg, params, tok)
    raise FileNotFoundError(f"reranker ref {ref!r} not found")


def _byte_tok_adapter():
    from localai_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    return type("T", (), {
        "encode": staticmethod(lambda text: list(text.encode("utf-8"))),
        "vocab_size": tok.vocab_size,
    })()


def resolve_sentence_encoder(
    ref: str, model_path: str | Path = "models", seed: int = 0
) -> SentenceEncoder:
    """Model ref → SentenceEncoder (sentence-transformers-class bert
    embedding checkpoints, or the ``debug:bert-tiny`` preset)."""
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        if name not in DEBUG_EMBEDDERS:
            raise ValueError(
                f"unknown debug embedder {name!r}; "
                f"have {sorted(DEBUG_EMBEDDERS)}"
            )
        cfg = DEBUG_EMBEDDERS[name]
        return SentenceEncoder(
            cfg, init_params(jax.random.key(seed), cfg),
            _byte_tok_adapter(),
        )
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            hf = json.loads((cand / "config.json").read_text())
            tok = _BertTokenizerAdapter(cand)
            overrides = {}
            for field_name, token, default in (
                ("cls_id", "[CLS]", 101),
                ("sep_id", "[SEP]", 102),
                ("pad_id", "[PAD]", hf.get("pad_token_id", 0)),
            ):
                tid = tok.special_id(token)
                overrides[field_name] = tid if tid is not None else default
            cfg = BertConfig.from_hf(hf, **overrides)
            from localai_tpu.models.loader import _open_safetensors

            tensors = _open_safetensors(cand)
            params = _map_hf_bert(cfg, tensors)
            return SentenceEncoder(cfg, params, tok)
    raise FileNotFoundError(f"embedding model ref {ref!r} not found")


def is_reranker_checkpoint(ref: str, model_path: str | Path) -> bool:
    """True when the ref resolves to a bert-class encoder checkpoint (the
    auto-detect used by model loading; parity: the reference routes by
    explicit backend name only — we also sniff model_type)."""
    if ref.startswith("debug:"):
        return ref.split(":", 1)[1] in DEBUG_RERANKERS
    for cand in (Path(ref), Path(model_path) / ref):
        cj = cand / "config.json"
        if cj.exists():
            try:
                hf = json.loads(cj.read_text())
            except ValueError:
                return False
            return hf.get("model_type") in ("bert", "roberta", "xlm-roberta")
    return False
