"""Whisper-family speech-to-text, pure functional JAX.

TPU-era replacement for the whisper.cpp cgo backend
(/root/reference/backend/go/transcribe/whisper/whisper.go:21-105): same
capability — full-file transcription with segments behind the
AudioTranscription RPC — but as an encoder-decoder transformer running
under jit, fed by the on-device log-mel frontend (audio.mel).

Structure mirrors models.llama: stacked per-layer params scanned with
``lax.scan``, static shapes, f32 norms. The decoder uses a fixed-size
token buffer with length masking so greedy decoding reuses ONE compiled
program for every step (no per-length recompiles).
"""

from __future__ import annotations

import dataclasses
import math
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from localai_tpu.audio import mel as melmod

PyTree = Any


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    n_mels: int = 80
    d_model: int = 384            # whisper-tiny
    n_heads: int = 6
    n_enc_layers: int = 4
    n_dec_layers: int = 4
    d_ff: int = 1536
    vocab_size: int = 51865
    max_source_positions: int = 1500   # CHUNK_FRAMES // 2
    max_target_positions: int = 448
    # special token ids (whisper multilingual defaults)
    sot: int = 50258
    eot: int = 50257
    token_transcribe: int = 50359
    token_translate: int = 50358
    token_notimestamps: int = 50363
    lang_base: int = 50259             # <|en|>
    dtype: str = "float32"

    @property
    def hd(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def from_hf(cls, hf: dict) -> "WhisperConfig":
        return cls(
            n_mels=hf.get("num_mel_bins", 80),
            d_model=hf.get("d_model", 384),
            n_heads=hf.get("encoder_attention_heads", 6),
            n_enc_layers=hf.get("encoder_layers", 4),
            n_dec_layers=hf.get("decoder_layers", 4),
            d_ff=hf.get("encoder_ffn_dim", 1536),
            vocab_size=hf.get("vocab_size", 51865),
            max_source_positions=hf.get("max_source_positions", 1500),
            max_target_positions=hf.get("max_target_positions", 448),
            sot=hf.get("decoder_start_token_id", 50258),
            eot=hf.get("eos_token_id", 50257),
        )


# whisper's language order — token id = lang_base + index
LANGUAGES = (
    "en zh de es ru ko fr ja pt tr pl ca nl ar sv it id hi fi vi he uk el ms "
    "cs ro da hu ta no th ur hr bg lt la mi ml cy sk te fa lv bn sr az sl kn "
    "et mk br eu is hy ne mn bs kk sq sw gl mr pa si km sn yo so af oc ka be "
    "tg sd gu am yi lo uz fo ht ps tk nn mt sa lb my bo tl mg as tt haw ln "
    "ha ba jw su"
).split()


def language_token(cfg: "WhisperConfig", language: Optional[str]) -> int:
    """language code/name → <|xx|> token id (defaults to English)."""
    if not language:
        return cfg.lang_base
    code = language.strip().lower()
    aliases = {"english": "en", "french": "fr", "german": "de",
               "spanish": "es", "chinese": "zh", "japanese": "ja",
               "korean": "ko", "russian": "ru", "portuguese": "pt",
               "italian": "it", "dutch": "nl", "arabic": "ar",
               "hindi": "hi", "turkish": "tr", "polish": "pl"}
    code = aliases.get(code, code)
    try:
        return cfg.lang_base + LANGUAGES.index(code)
    except ValueError:
        return cfg.lang_base


DEBUG_CONFIG = WhisperConfig(
    d_model=64, n_heads=4, n_enc_layers=2, n_dec_layers=2, d_ff=128,
    vocab_size=512, max_source_positions=1500, max_target_positions=64,
    sot=500, eot=501, token_transcribe=502, token_translate=503,
    token_notimestamps=504, lang_base=505,
)


def _attn_block_shapes(d: int) -> dict:
    return {
        "ln": (d,), "ln_b": (d,),
        "wq": (d, d), "bq": (d,),
        "wk": (d, d),
        "wv": (d, d), "bv": (d,),
        "wo": (d, d), "bo": (d,),
    }


def param_shapes(cfg: WhisperConfig) -> dict:
    d, f = cfg.d_model, cfg.d_ff
    Le, Ld = cfg.n_enc_layers, cfg.n_dec_layers

    def stack(shapes: dict, n: int) -> dict:
        return {k: (n, *v) for k, v in shapes.items()}

    mlp = {"ln2": (d,), "ln2_b": (d,), "fc1": (d, f), "b1": (f,),
           "fc2": (f, d), "b2": (d,)}
    enc_layer = {**{f"sa_{k}": v for k, v in _attn_block_shapes(d).items()},
                 **mlp}
    dec_layer = {**{f"sa_{k}": v for k, v in _attn_block_shapes(d).items()},
                 **{f"ca_{k}": v for k, v in _attn_block_shapes(d).items()},
                 **mlp}
    return {
        "conv1_w": (d, cfg.n_mels, 3), "conv1_b": (d,),
        "conv2_w": (d, d, 3), "conv2_b": (d,),
        "enc": stack(enc_layer, Le),
        "enc_ln": (d,), "enc_ln_b": (d,),
        "embed": (cfg.vocab_size, d),
        "pos": (cfg.max_target_positions, d),
        "dec": stack(dec_layer, Ld),
        "dec_ln": (d,), "dec_ln_b": (d,),
    }


_GAIN_NAMES = {"sa_ln", "ca_ln", "ln2", "enc_ln", "dec_ln"}


def init_params(rng: jax.Array, cfg: WhisperConfig) -> PyTree:
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(
        shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))

    def mk(k, shape):
        if len(shape) == 1:
            return jnp.zeros(shape, jnp.float32)  # biases; gains fixed below
        return jax.random.normal(k, shape, jnp.float32) * 0.02

    out = jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])

    def fix(path, leaf):
        name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
        if name in _GAIN_NAMES:
            return jnp.ones_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, out)


def _ln(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _mha(cfg: WhisperConfig, q_in, kv_in, p, prefix, mask=None):
    """q_in [Tq, D], kv_in [Tk, D] → [Tq, D]. Whisper has no k bias."""
    H, hd = cfg.n_heads, cfg.hd
    q = (q_in @ p[f"{prefix}_wq"] + p[f"{prefix}_bq"]).reshape(-1, H, hd)
    k = (kv_in @ p[f"{prefix}_wk"]).reshape(-1, H, hd)
    v = (kv_in @ p[f"{prefix}_wv"] + p[f"{prefix}_bv"]).reshape(-1, H, hd)
    scores = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(hd)
    if mask is not None:
        scores = jnp.where(mask[None], scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("hqk,khd->qhd", probs, v).reshape(-1, cfg.d_model)
    return out @ p[f"{prefix}_wo"] + p[f"{prefix}_bo"]


def _sinusoids(length: int, channels: int) -> jax.Array:
    """Whisper's sinusoidal encoder positions."""
    log_timescale = math.log(10000) / (channels // 2 - 1)
    inv = jnp.exp(-log_timescale * jnp.arange(channels // 2))
    scaled = jnp.arange(length)[:, None] * inv[None, :]
    return jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)


def encode(cfg: WhisperConfig, params: PyTree, mel: jax.Array) -> jax.Array:
    """mel [n_mels, frames] → encoder states [frames//2, D]."""
    x = mel.T[None]  # [1, frames, n_mels]
    # explicit (1, 1) padding, NOT "SAME": at conv2's stride 2, XLA SAME
    # resolves to (0, 1) while the reference torch Conv1d(padding=1) pads
    # both sides — SAME silently shifted every frame by one input step
    # (caught by tests/test_llama_torch.py::test_whisper_matches_torch)
    x = jax.nn.gelu(
        lax.conv_general_dilated(
            x, params["conv1_w"].transpose(2, 1, 0), (1,), ((1, 1),),
            dimension_numbers=("NWC", "WIO", "NWC"),
        ) + params["conv1_b"]
    )
    x = jax.nn.gelu(
        lax.conv_general_dilated(
            x, params["conv2_w"].transpose(2, 1, 0), (2,), ((1, 1),),
            dimension_numbers=("NWC", "WIO", "NWC"),
        ) + params["conv2_b"]
    )
    x = x[0]  # [T', D]
    x = x + _sinusoids(x.shape[0], cfg.d_model)

    def body(carry, lp):
        h = carry
        a = _mha(cfg, _ln(h, lp["sa_ln"], lp["sa_ln_b"]),
                 _ln(h, lp["sa_ln"], lp["sa_ln_b"]), lp, "sa")
        h = h + a
        m = _ln(h, lp["ln2"], lp["ln2_b"])
        h = h + (jax.nn.gelu(m @ lp["fc1"] + lp["b1"]) @ lp["fc2"] + lp["b2"])
        return h, None

    x, _ = lax.scan(body, x, params["enc"])
    return _ln(x, params["enc_ln"], params["enc_ln_b"])


def decode_logits(cfg: WhisperConfig, params: PyTree, tokens: jax.Array,
                  length: jax.Array, enc: jax.Array) -> jax.Array:
    """tokens [Tmax] (padded), length scalar → logits [V] at length-1."""
    Tmax = tokens.shape[0]
    x = params["embed"][tokens] + params["pos"][:Tmax]
    t = jnp.arange(Tmax)
    causal = (t[:, None] >= t[None, :]) & (t[None, :] < length)

    def body(carry, lp):
        h = carry
        a = _mha(cfg, _ln(h, lp["sa_ln"], lp["sa_ln_b"]),
                 _ln(h, lp["sa_ln"], lp["sa_ln_b"]), lp, "sa", mask=causal)
        h = h + a
        c = _mha(cfg, _ln(h, lp["ca_ln"], lp["ca_ln_b"]), enc, lp, "ca")
        h = h + c
        m = _ln(h, lp["ln2"], lp["ln2_b"])
        h = h + (jax.nn.gelu(m @ lp["fc1"] + lp["b1"]) @ lp["fc2"] + lp["b2"])
        return h, None

    x, _ = lax.scan(body, x, params["dec"])
    x = _ln(x, params["dec_ln"], params["dec_ln_b"])
    last = jax.lax.dynamic_index_in_dim(x, length - 1, keepdims=False)
    return last @ params["embed"].T


def _cached_step(cfg: WhisperConfig, params: PyTree, token, t,
                 sa_k, sa_v, ca_k, ca_v):
    """One KV-cached decoder step at position ``t``.

    sa_k/sa_v [L, Tmax, D] — projected self-attn keys/values per layer;
    ca_k/ca_v [L, Tenc, D] — cross-attn projections precomputed once per
    chunk (the encoder output is fixed). Returns (logits [V], sa_k, sa_v).
    """
    H, hd, D = cfg.n_heads, cfg.hd, cfg.d_model
    Tmax = sa_k.shape[1]
    x = params["embed"][token] + params["pos"][t]          # [D]
    idx = jnp.arange(Tmax)

    def layer(x, inputs):
        lp, sak_l, sav_l, cak_l, cav_l = inputs
        h = _ln(x, lp["sa_ln"], lp["sa_ln_b"])
        q = (h @ lp["sa_wq"] + lp["sa_bq"]).reshape(H, hd)
        k_t = h @ lp["sa_wk"]
        v_t = h @ lp["sa_wv"] + lp["sa_bv"]
        keys = sak_l.at[t].set(k_t).reshape(Tmax, H, hd)
        vals = sav_l.at[t].set(v_t).reshape(Tmax, H, hd)
        s = jnp.einsum("hd,khd->hk", q, keys) / math.sqrt(hd)
        s = jnp.where(idx[None, :] <= t, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
        a = jnp.einsum("hk,khd->hd", p, vals).reshape(D)
        x = x + a @ lp["sa_wo"] + lp["sa_bo"]

        h2 = _ln(x, lp["ca_ln"], lp["ca_ln_b"])
        q2 = (h2 @ lp["ca_wq"] + lp["ca_bq"]).reshape(H, hd)
        kk = cak_l.reshape(-1, H, hd)
        vv = cav_l.reshape(-1, H, hd)
        s2 = jnp.einsum("hd,khd->hk", q2, kk) / math.sqrt(hd)
        p2 = jax.nn.softmax(s2.astype(jnp.float32), axis=-1).astype(vv.dtype)
        c = jnp.einsum("hk,khd->hd", p2, vv).reshape(D)
        x = x + c @ lp["ca_wo"] + lp["ca_bo"]

        m = _ln(x, lp["ln2"], lp["ln2_b"])
        x = x + (jax.nn.gelu(m @ lp["fc1"] + lp["b1"]) @ lp["fc2"]
                 + lp["b2"])
        return x, (k_t, v_t)

    x, (krows, vrows) = lax.scan(
        layer, x, (params["dec"], sa_k, sa_v, ca_k, ca_v))
    sa_k = sa_k.at[:, t].set(krows)
    sa_v = sa_v.at[:, t].set(vrows)
    x = _ln(x, params["dec_ln"], params["dec_ln_b"])
    return x @ params["embed"].T, sa_k, sa_v


def decode_greedy(cfg: WhisperConfig, params: PyTree, prompt_buf, n_prompt,
                  enc, limit):
    """Whole-chunk greedy decode as ONE program: prompt prefill + generate
    until <eot>, KV-cached (self-attn cache + cross-attn K/V precompute).

    The per-token host loop this replaces re-ran the FULL decoder over the
    padded buffer every step — O(T²) compute per token and one dispatch
    (tunnel RTT) per token. Returns (buf [Tmax], n_total) with generated
    ids at buf[n_prompt:n_total] (eot excluded)."""
    Ld, D = cfg.n_dec_layers, cfg.d_model
    Tmax = cfg.max_target_positions
    ca_k = jnp.einsum("td,lde->lte", enc, params["dec"]["ca_wk"])
    ca_v = (jnp.einsum("td,lde->lte", enc, params["dec"]["ca_wv"])
            + params["dec"]["ca_bv"][:, None])
    sa_k = jnp.zeros((Ld, Tmax, D), enc.dtype)
    sa_v = jnp.zeros((Ld, Tmax, D), enc.dtype)

    def cond(c):
        t, buf, sak, sav, done, n_gen = c
        return (~done) & (n_gen < limit) & (t < Tmax - 1)

    def body(c):
        t, buf, sak, sav, done, n_gen = c
        logits, sak, sav = _cached_step(
            cfg, params, buf[t], t, sak, sav, ca_k, ca_v)
        nxt = jnp.argmax(logits).astype(jnp.int32)
        is_gen = t + 1 >= n_prompt
        write = is_gen & (nxt != cfg.eot)
        buf = jnp.where(write, buf.at[t + 1].set(nxt), buf)
        done = is_gen & (nxt == cfg.eot)
        return t + 1, buf, sak, sav, done, n_gen + write.astype(jnp.int32)

    _, buf, _, _, _, n_gen = lax.while_loop(
        cond, body, (jnp.int32(0), prompt_buf, sa_k, sa_v,
                     jnp.bool_(False), jnp.int32(0)))
    return buf, n_prompt + n_gen


class WhisperModel:
    """Loaded whisper engine: jitted encode + ONE-dispatch KV-cached
    greedy decode per chunk (decode_greedy)."""

    def __init__(self, cfg: WhisperConfig, params: PyTree, tokenizer=None):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.filters = jnp.asarray(melmod.mel_filterbank(cfg.n_mels))
        self._encode = jax.jit(lambda p, m: encode(cfg, p, m))
        self._greedy = jax.jit(
            lambda p, buf, n, enc, lim: decode_greedy(
                cfg, p, buf, n, enc, lim)
        )

    def transcribe_chunk(self, audio: np.ndarray, *,
                         language: Optional[str] = None,
                         translate: bool = False,
                         max_tokens: Optional[int] = None) -> list[int]:
        """One ≤30-s chunk → decoded token ids (specials stripped)."""
        cfg = self.cfg
        mel = melmod.log_mel(jnp.asarray(audio), self.filters,
                             n_mels=cfg.n_mels)
        enc = self._encode(self.params, mel)
        task = cfg.token_translate if translate else cfg.token_transcribe
        prompt = [cfg.sot, language_token(cfg, language), task,
                  cfg.token_notimestamps]
        buf = np.zeros(cfg.max_target_positions, np.int32)
        buf[:len(prompt)] = prompt
        limit = min(max_tokens or cfg.max_target_positions,
                    cfg.max_target_positions - len(prompt))
        out_buf, n_total = self._greedy(
            self.params, jnp.asarray(buf), jnp.int32(len(prompt)), enc,
            jnp.int32(limit),
        )
        ids = np.asarray(out_buf)[len(prompt): int(n_total)]
        return [int(t) for t in ids if t < cfg.eot and t < cfg.sot]

    def transcribe(self, audio: np.ndarray, *,
                   language: Optional[str] = None,
                   translate: bool = False,
                   max_tokens_per_chunk: Optional[int] = None) -> dict:
        """Full-file transcription → {text, segments} (parity: the segment
        schema of whisper.go:28-105 / schema.TranscriptionResult)."""
        segments = []
        texts = []
        for i, chunk in enumerate(melmod.chunk_audio(audio)):
            ids = self.transcribe_chunk(
                chunk, language=language, translate=translate,
                max_tokens=max_tokens_per_chunk,
            )
            text = self._decode_text(ids)
            start = i * melmod.CHUNK_SECONDS
            end = min((i + 1) * melmod.CHUNK_SECONDS,
                      max(len(audio), 1) / melmod.SAMPLE_RATE)
            segments.append({
                "id": i,
                "start": float(start),
                "end": float(end),
                "text": text,
                "tokens": ids,
            })
            texts.append(text)
        return {"text": " ".join(t for t in texts if t).strip(),
                "segments": segments}

    def _decode_text(self, ids: list[int]) -> str:
        if self.tokenizer is not None:
            return self.tokenizer.decode(ids)
        return bytes(i for i in ids if i < 256).decode("utf-8", "replace")


def debug_model(seed: int = 0) -> WhisperModel:
    cfg = DEBUG_CONFIG
    return WhisperModel(cfg, init_params(jax.random.key(seed), cfg))


# HF transformers WhisperForConditionalGeneration → stacked layout
_HF_ENC = "model.encoder.layers.{i}."
_HF_DEC = "model.decoder.layers.{i}."


def _map_attn(get, hf_prefix: str, ours_prefix: str, i: int, out: dict):
    hp = hf_prefix.format(i=i)
    out[f"{ours_prefix}_wq"].append(get(hp + "q_proj.weight").T)
    out[f"{ours_prefix}_bq"].append(get(hp + "q_proj.bias"))
    out[f"{ours_prefix}_wk"].append(get(hp + "k_proj.weight").T)
    out[f"{ours_prefix}_wv"].append(get(hp + "v_proj.weight").T)
    out[f"{ours_prefix}_bv"].append(get(hp + "v_proj.bias"))
    out[f"{ours_prefix}_wo"].append(get(hp + "out_proj.weight").T)
    out[f"{ours_prefix}_bo"].append(get(hp + "out_proj.bias"))


def load_hf_whisper(model_dir: str | Path) -> WhisperModel:
    """Load a HF whisper checkpoint (config.json + model.safetensors)."""
    import json

    from safetensors import safe_open

    model_dir = Path(model_dir)
    hf_cfg = json.loads((model_dir / "config.json").read_text())
    cfg = WhisperConfig.from_hf(hf_cfg)
    f = safe_open(str(model_dir / "model.safetensors"), framework="np")

    def get(name: str) -> np.ndarray:
        return np.asarray(f.get_tensor(name), np.float32)

    def stack_layers(hf_prefix: str, n: int, cross: bool) -> dict:
        acc: dict[str, list] = {}
        keys = ["sa_ln", "sa_ln_b", "sa_wq", "sa_bq", "sa_wk", "sa_wv",
                "sa_bv", "sa_wo", "sa_bo", "ln2", "ln2_b", "fc1", "b1",
                "fc2", "b2"]
        if cross:
            keys += ["ca_ln", "ca_ln_b", "ca_wq", "ca_bq", "ca_wk", "ca_wv",
                     "ca_bv", "ca_wo", "ca_bo"]
        for k in keys:
            acc[k] = []
        for i in range(n):
            hp = hf_prefix.format(i=i)
            acc["sa_ln"].append(get(hp + "self_attn_layer_norm.weight"))
            acc["sa_ln_b"].append(get(hp + "self_attn_layer_norm.bias"))
            _map_attn(get, hf_prefix + "self_attn.", "sa", i, acc)
            if cross:
                acc["ca_ln"].append(
                    get(hp + "encoder_attn_layer_norm.weight"))
                acc["ca_ln_b"].append(
                    get(hp + "encoder_attn_layer_norm.bias"))
                _map_attn(get, hf_prefix + "encoder_attn.", "ca", i, acc)
            acc["ln2"].append(get(hp + "final_layer_norm.weight"))
            acc["ln2_b"].append(get(hp + "final_layer_norm.bias"))
            acc["fc1"].append(get(hp + "fc1.weight").T)
            acc["b1"].append(get(hp + "fc1.bias"))
            acc["fc2"].append(get(hp + "fc2.weight").T)
            acc["b2"].append(get(hp + "fc2.bias"))
        return {k: jnp.asarray(np.stack(v)) for k, v in acc.items()}

    params = {
        "conv1_w": jnp.asarray(get("model.encoder.conv1.weight")),
        "conv1_b": jnp.asarray(get("model.encoder.conv1.bias")),
        "conv2_w": jnp.asarray(get("model.encoder.conv2.weight")),
        "conv2_b": jnp.asarray(get("model.encoder.conv2.bias")),
        "enc": stack_layers(_HF_ENC, cfg.n_enc_layers, cross=False),
        "enc_ln": jnp.asarray(get("model.encoder.layer_norm.weight")),
        "enc_ln_b": jnp.asarray(get("model.encoder.layer_norm.bias")),
        "embed": jnp.asarray(get("model.decoder.embed_tokens.weight")),
        "pos": jnp.asarray(get("model.decoder.embed_positions.weight")),
        "dec": stack_layers(_HF_DEC, cfg.n_dec_layers, cross=True),
        "dec_ln": jnp.asarray(get("model.decoder.layer_norm.weight")),
        "dec_ln_b": jnp.asarray(get("model.decoder.layer_norm.bias")),
    }
    from localai_tpu.utils.tokenizer import load_tokenizer

    return WhisperModel(cfg, params, tokenizer=load_tokenizer(model_dir))
