"""Model resolution: a model ref → (config, params, tokenizer).

The TPU analogue of backend selection + GGUF autoconfig
(/root/reference/pkg/model/initializers.go:65-267 and
core/config/guesser.go): instead of scanning binary variants per CPU flag,
we resolve a weights ref to one JAX model family and load it.

Refs:
  * a local dir with config.json + *.safetensors  → HF checkpoint
  * "debug:tiny" / "debug:small" / "debug:1b" ... → random-weight presets
    (byte tokenizer; used by tests and synthetic benchmarks)
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Optional

import jax

from localai_tpu.models.llama import LlamaConfig, init_params
from localai_tpu.utils import jaxcompat
from localai_tpu.utils.tokenizer import ByteTokenizer, Tokenizer, load_tokenizer

# Synthetic presets: shapes only, random weights. "llama3-8b" matches
# Llama-3-8B dims for honest perf measurement without weight downloads.
DEBUG_PRESETS: dict[str, LlamaConfig] = {
    "tiny": LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_position_embeddings=512,
        rope_theta=10000.0,
    ),
    "small": LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=512, num_layers=4,
        num_heads=8, num_kv_heads=4, max_position_embeddings=2048,
    ),
    "tiny-moe": LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_position_embeddings=512,
        num_experts=4, num_experts_per_tok=2,
    ),
    "1b": LlamaConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_layers=16, num_heads=32, num_kv_heads=8,
        max_position_embeddings=8192, rope_theta=500000.0,
        tie_word_embeddings=True,
    ),
    "llama3-8b": LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8,
        max_position_embeddings=8192, rope_theta=500000.0,
    ),
}


@dataclasses.dataclass
class LoadedModel:
    cfg: LlamaConfig
    params: Any
    tokenizer: Tokenizer
    ref: str
    model_dir: Optional[Path] = None   # resolved checkpoint dir (None: debug)
    hf_type: str = ""                  # config.json model_type ("llava", ...)
    image_token_id: Optional[int] = None  # HF image_token_index when present


def resolve_model(
    ref: str,
    model_path: str | Path = "models",
    dtype: str = "bfloat16",
    shard_fn=None,
    seed: int = 0,
) -> LoadedModel:
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        if name not in DEBUG_PRESETS:
            raise ValueError(
                f"unknown debug preset {name!r}; have {sorted(DEBUG_PRESETS)}"
            )
        cfg = dataclasses.replace(DEBUG_PRESETS[name], dtype=dtype)
        params = init_params(jax.random.key(seed), cfg)
        if shard_fn is not None:
            params = jaxcompat.tree_map_with_path(shard_fn, params)
        return LoadedModel(cfg, params, ByteTokenizer(), ref)

    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            from localai_tpu.models.loader import (
                load_llama_params,
                read_hf_config,
            )

            hf = read_hf_config(cand)
            cfg, params = load_llama_params(
                cand, dtype=dtype, shard_fn=shard_fn, hf=hf
            )
            cfg = dataclasses.replace(cfg, dtype=dtype)
            return LoadedModel(
                cfg, params, load_tokenizer(cand), ref,
                model_dir=cand,
                hf_type=hf.get("model_type", ""),
                image_token_id=hf.get("image_token_index"),
            )
    raise FileNotFoundError(
        f"model ref {ref!r} not found (looked for config.json under {ref} and "
        f"{Path(model_path) / ref})"
    )


def synthetic_quantized_params(
    cfg: LlamaConfig, mode: str = "int8", group: int = 128, seed: int = 0
) -> Any:
    """Random weights generated DIRECTLY in quantized form — an 8B-class
    bf16 init (16 GB) would not fit a single v5e chip, but its int8 form
    (8 GB) does. Used by bench.py for north-star-shaped synthetic serving;
    scale magnitudes match init_params' 0.02-std gaussians so activations
    stay in a realistic range."""
    import jax.numpy as jnp

    from localai_tpu.models import llama as mdl
    from localai_tpu.models.quant import QuantizedTensor, _group_size

    if mode not in ("int8", "int4", "int8_w8a8"):
        raise ValueError(f"unsupported synthetic quant mode {mode!r}")
    shapes = mdl.param_shapes(cfg)
    keys = iter(jax.random.split(jax.random.key(seed), 32))

    mm8 = "w8a8" if mode == "int8_w8a8" else "w8"

    def qweight(shape, axis, bits):
        lim, mm = (7, "w4") if bits == 4 else (127, mm8)
        # raw uint8 bits reinterpreted as int8 — no int32 intermediates
        # (randint would spike 4× the tensor size during generation)
        v = jax.lax.bitcast_convert_type(
            jax.random.bits(next(keys), shape, jnp.uint8), jnp.int8
        )
        if bits == 4:
            q = jnp.maximum(v >> 4, -7).astype(jnp.int4)
        else:
            q = jnp.maximum(v, -127)
        if bits == 4:
            K = shape[axis]
            gc = K // _group_size(K, group)
            sshape = shape[:axis] + (gc,) + shape[axis + 1:]
        else:
            sshape = shape[:axis] + shape[axis + 1:]
        scale = jnp.full(sshape, 0.02 / lim, jnp.float32)
        return QuantizedTensor(q=q, scale=scale, axis=axis, mode=mm)

    bits = 4 if mode == "int4" else 8
    dtype = jnp.dtype(cfg.dtype)
    params: dict = {
        # embeddings stay int8 even in int4 mode (see quantize_params)
        "embed": qweight(shapes["embed"], 1, 8),
        "final_norm": jnp.ones(shapes["final_norm"], dtype),
    }
    if "lm_head" in shapes:
        params["lm_head"] = qweight(shapes["lm_head"], 0, bits)
    layers = {}
    for name, shape in shapes["layers"].items():
        if name in ("attn_norm", "mlp_norm"):
            layers[name] = jnp.ones(shape, dtype)
        elif name in ("bq", "bk", "bv"):
            layers[name] = jnp.zeros(shape, dtype)
        elif name == "moe_gate":  # tiny router stays in the compute dtype
            layers[name] = (jax.random.normal(
                next(keys), shape, jnp.float32) * 0.02).astype(dtype)
        elif len(shape) == 4:     # expert-stacked moe weights: int8 only
            layers[name] = qweight(shape, 2, 8)
        else:
            layers[name] = qweight(shape, 1, bits)
    params["layers"] = layers
    return params


def resolve_tokenizer(ref: str, model_path: str | Path = "models"):
    """Tokenizer-only resolution — never touches weights (the tokenize CLI
    and API must not pull GBs of params into RAM to encode a string)."""
    if ref.startswith("debug:"):
        return ByteTokenizer()
    for cand in (Path(ref), Path(model_path) / ref):
        if cand.is_dir():
            return load_tokenizer(cand)
    raise FileNotFoundError(f"model ref {ref!r} not found under {model_path}")
