"""Vision tower: CLIP ViT encoder + LLaVA-style multimodal projector.

The TPU-era replacement for the reference's CLIP/LLaVA image-embedding path
(clip_image_encode + embedding injection inside the llama.cpp server,
/root/reference/backend/cpp/llama/grpc-server.cpp:1397-1424, mmproj GGUF
sidecar loading grpc-server.cpp:2202-2219). Design is functional JAX:

  * patch embedding is a reshape + one matmul (the conv with stride=patch
    collapses to patchify→GEMM, which is exactly what the MXU wants),
  * the transformer reuses the CLIP pre-LN blocks from image.clip,
  * LLaVA semantics: features from hidden layer ``feature_layer`` (default
    -2, i.e. the penultimate block's output, before post-LN), CLS dropped,
    then a 2-layer GELU projector into the language model's hidden space.

Ingests HF llava-family checkpoints (vision_tower.vision_model.* +
multi_modal_projector.*) or a random-weight debug preset for tests.
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.image.clip import _act, _mha
from localai_tpu.image.unet import layer_norm

log = logging.getLogger(__name__)

PyTree = Any

# CLIP preprocessing constants (openai/clip-vit-large-patch14)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 336
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    projection_dim: int = 4096      # language-model hidden size
    feature_layer: int = -2         # LLaVA vision_feature_layer
    activation: str = "quick_gelu"
    dtype: str = "bfloat16"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def from_hf(cls, vision_cfg: dict, *, projection_dim: int,
                feature_layer: int = -2) -> "VisionConfig":
        return cls(
            image_size=vision_cfg.get("image_size", 336),
            patch_size=vision_cfg.get("patch_size", 14),
            hidden_size=vision_cfg.get("hidden_size", 1024),
            intermediate_size=vision_cfg.get("intermediate_size", 4096),
            num_layers=vision_cfg.get("num_hidden_layers", 24),
            num_heads=vision_cfg.get("num_attention_heads", 16),
            projection_dim=projection_dim,
            feature_layer=feature_layer,
            activation=vision_cfg.get("hidden_act", "quick_gelu"),
        )


DEBUG_PRESETS: dict[str, VisionConfig] = {
    # tiny ViT for tests: 32px/8px → 16 patch tokens
    "vit": VisionConfig(
        image_size=32, patch_size=8, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, projection_dim=64, feature_layer=-1,
    ),
}


def param_shapes(cfg: VisionConfig) -> PyTree:
    C, I, P = cfg.hidden_size, cfg.intermediate_size, cfg.patch_size
    D = cfg.projection_dim
    layer = {
        "ln1": {"g": (C,), "b": (C,)},
        "attn": {"wq": (C, C), "bq": (C,), "wk": (C, C), "bk": (C,),
                 "wv": (C, C), "bv": (C,), "wo": (C, C), "bo": (C,)},
        "ln2": {"g": (C,), "b": (C,)},
        "mlp": {"w1": (C, I), "b1": (I,), "w2": (I, C), "b2": (C,)},
    }
    return {
        "patch_embed": (3 * P * P, C),   # flattened conv kernel (c, i, j)
        "cls": (C,),
        "pos_emb": (cfg.n_patches + 1, C),
        "pre_ln": {"g": (C,), "b": (C,)},
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "projector": {"w1": (C, D), "b1": (D,), "w2": (D, D), "b2": (D,)},
    }


def init_params(rng: jax.Array, cfg: VisionConfig) -> PyTree:
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(
        shapes, is_leaf=lambda x: isinstance(x, tuple)
    )
    keys = jax.random.split(rng, len(flat))
    dtype = jnp.dtype(cfg.dtype)

    def mk(k, shape):
        if len(shape) == 1:
            return jnp.ones(shape, jnp.float32)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    params = jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else ""
        if name in ("bq", "bk", "bv", "bo", "b1", "b2", "b", "cls"):
            return jnp.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)


def patchify(cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] → patch vectors [B, N, 3·P·P] in conv-kernel
    order (channel, row, col) so the flattened HF conv weight applies."""
    B, H, W, _ = images.shape
    P = cfg.patch_size
    x = images.reshape(B, H // P, P, W // P, P, 3)
    # → [B, gh, gw, c, pi, pj]
    x = x.transpose(0, 1, 3, 5, 2, 4)
    return x.reshape(B, cfg.n_patches, 3 * P * P)


def forward(cfg: VisionConfig, params: PyTree, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] f32 (CLIP-normalized) → [B, n_patches, D_model].

    LLaVA semantics: stop at ``feature_layer``, drop CLS, project.
    """
    dtype = jnp.dtype(cfg.dtype)
    B = images.shape[0]
    patches = patchify(cfg, images).astype(dtype)
    x = patches @ params["patch_embed"].astype(dtype)  # [B, N, C]
    cls = jnp.broadcast_to(
        params["cls"].astype(dtype), (B, 1, cfg.hidden_size)
    )
    x = jnp.concatenate([cls, x], axis=1) + params["pos_emb"].astype(dtype)
    x = layer_norm(x, params["pre_ln"])

    n_run = cfg.num_layers + 1 + cfg.feature_layer if cfg.feature_layer < 0 \
        else cfg.feature_layer
    zero = jnp.zeros((1, 1, 1), jnp.float32)
    for lp in params["layers"][:n_run]:
        x = x + _mha(layer_norm(x, lp["ln1"]), lp["attn"], cfg.num_heads, zero)
        h = layer_norm(x, lp["ln2"])
        h = _act(cfg, h @ lp["mlp"]["w1"].astype(h.dtype)
                 + lp["mlp"]["b1"].astype(h.dtype))
        x = x + (h @ lp["mlp"]["w2"].astype(h.dtype)
                 + lp["mlp"]["b2"].astype(h.dtype))

    x = x[:, 1:]  # drop CLS — LLaVA vision_feature_select_strategy='default'
    pj = params["projector"]
    h = x @ pj["w1"].astype(x.dtype) + pj["b1"].astype(x.dtype)
    h = jax.nn.gelu(h)
    return h @ pj["w2"].astype(h.dtype) + pj["b2"].astype(h.dtype)


def preprocess(images: list[np.ndarray], cfg: VisionConfig) -> np.ndarray:
    """uint8 RGB arrays (any size) → [B, S, S, 3] f32 CLIP-normalized."""
    out = np.zeros((len(images), cfg.image_size, cfg.image_size, 3),
                   np.float32)
    for i, img in enumerate(images):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, -1)
        if arr.shape[2] == 4:
            arr = arr[..., :3]
        if arr.shape[:2] != (cfg.image_size, cfg.image_size):
            from PIL import Image

            pil = Image.fromarray(arr.astype(np.uint8)).resize(
                (cfg.image_size, cfg.image_size), Image.BICUBIC
            )
            arr = np.asarray(pil)
        out[i] = (arr.astype(np.float32) / 255.0 - CLIP_MEAN) / CLIP_STD
    return out


class VisionTower:
    """Loaded vision encoder bound to one language model: encodes images
    into [n_patches, D_model] embedding blocks for prompt injection."""

    def __init__(self, cfg: VisionConfig, params: PyTree):
        self.cfg = cfg
        self.params = params
        self._fwd = jax.jit(lambda p, im: forward(cfg, p, im))

    @property
    def n_patches(self) -> int:
        return self.cfg.n_patches

    def encode(self, images: list[np.ndarray]) -> np.ndarray:
        """List of RGB uint8 arrays → [B, n_patches, D_model] float32."""
        batch = preprocess(images, self.cfg)
        out = self._fwd(self.params, jnp.asarray(batch))
        return np.asarray(out, np.float32)


def resolve_vision_tower(
    ref: str | Path,
    *,
    projection_dim: int,
    model_path: str | Path = "models",
    seed: int = 0,
) -> VisionTower:
    """'debug:<preset>' → random weights; a dir with an HF llava layout →
    loaded weights (vision_tower.vision_model.* + multi_modal_projector.*)."""
    ref = str(ref)
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        if name not in DEBUG_PRESETS:
            raise ValueError(
                f"unknown debug vision preset {name!r}; have "
                f"{sorted(DEBUG_PRESETS)}"
            )
        cfg = dataclasses.replace(
            DEBUG_PRESETS[name], projection_dim=projection_dim
        )
        return VisionTower(cfg, init_params(jax.random.key(seed), cfg))
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            return load_llava_vision(cand, projection_dim=projection_dim)
    raise FileNotFoundError(f"vision tower ref {ref!r} not found")


def load_llava_vision(model_dir: str | Path, *,
                      projection_dim: int) -> VisionTower:
    """Load the vision half of an HF llava checkpoint directory."""
    import json

    from localai_tpu.models.loader import _get, _open_safetensors

    model_dir = Path(model_dir)
    with open(model_dir / "config.json") as f:
        hf = json.load(f)
    vcfg_dict = hf.get("vision_config") or hf
    cfg = VisionConfig.from_hf(
        vcfg_dict,
        projection_dim=projection_dim,
        feature_layer=hf.get("vision_feature_layer", -2),
    )
    tensors = _open_safetensors(model_dir)

    def has(name: str) -> bool:
        return name in tensors

    V = "vision_tower.vision_model."
    if not has(V + "embeddings.patch_embedding.weight"):
        V = "model." + V  # transformers ≥4.52 nests under model.
    P = "multi_modal_projector."
    if not has(P + "linear_1.weight") and has("model." + P + "linear_1.weight"):
        P = "model." + P

    def g(name: str) -> np.ndarray:
        return np.asarray(_get(tensors, name), np.float32)

    conv = g(V + "embeddings.patch_embedding.weight")  # [C, 3, p, p]
    C = conv.shape[0]
    layers = []
    for i in range(cfg.num_layers):
        L = f"{V}encoder.layers.{i}."
        layers.append({
            "ln1": {"g": g(L + "layer_norm1.weight"),
                    "b": g(L + "layer_norm1.bias")},
            "attn": {
                "wq": g(L + "self_attn.q_proj.weight").T,
                "bq": g(L + "self_attn.q_proj.bias"),
                "wk": g(L + "self_attn.k_proj.weight").T,
                "bk": g(L + "self_attn.k_proj.bias"),
                "wv": g(L + "self_attn.v_proj.weight").T,
                "bv": g(L + "self_attn.v_proj.bias"),
                "wo": g(L + "self_attn.out_proj.weight").T,
                "bo": g(L + "self_attn.out_proj.bias"),
            },
            "ln2": {"g": g(L + "layer_norm2.weight"),
                    "b": g(L + "layer_norm2.bias")},
            "mlp": {"w1": g(L + "mlp.fc1.weight").T,
                    "b1": g(L + "mlp.fc1.bias"),
                    "w2": g(L + "mlp.fc2.weight").T,
                    "b2": g(L + "mlp.fc2.bias")},
        })
    dtype = jnp.dtype(cfg.dtype)

    def put(a: np.ndarray, d=dtype) -> jax.Array:
        return jnp.asarray(a, d)

    params = {
        "patch_embed": put(conv.reshape(C, -1).T),
        "cls": put(g(V + "embeddings.class_embedding"), jnp.float32),
        "pos_emb": put(g(V + "embeddings.position_embedding.weight")),
        "pre_ln": {"g": put(g(V + "pre_layrnorm.weight"), jnp.float32),
                   "b": put(g(V + "pre_layrnorm.bias"), jnp.float32)},
        "layers": jax.tree.map(put, layers),
        "projector": {
            "w1": put(g(P + "linear_1.weight").T),
            "b1": put(g(P + "linear_1.bias")),
            "w2": put(g(P + "linear_2.weight").T),
            "b2": put(g(P + "linear_2.bias")),
        },
    }
    return VisionTower(cfg, params)
