"""Mamba (selective state space) language models in functional JAX.

Parity: the reference's mamba Python backend
(/root/reference/backend/python/mamba/backend.py — wraps
mamba_ssm.MambaLMHeadModel). This implements the architecture natively:
gated conv + selective SSM recurrence per block, loading HF
`MambaForCausalLM` checkpoints (model_type "mamba":
state-spaces/mamba-*-hf). Numerics mirror transformers' slow path
(modeling_mamba.py:360-441), verified against torch in
tests/test_mamba.py.

TPU shape: prefill runs the input-dependent discretization fully
vectorized over the sequence, with ONE `lax.scan` per layer carrying the
[B, D_inner, N] SSM state (the only genuinely sequential math in the
model); decode is a single fused step updating rolling conv + SSM states
— no KV cache, O(1) memory per token, which is the whole point of the
architecture. Generation state is a pytree, so the step jits once and
re-runs for every token.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MambaConfig:
    vocab_size: int = 50280
    hidden_size: int = 768
    intermediate_size: int = 1536
    state_size: int = 16
    conv_kernel: int = 4
    num_layers: int = 24
    time_step_rank: int = 48
    layer_norm_epsilon: float = 1e-5
    use_bias: bool = False
    use_conv_bias: bool = True
    eos_token_id: int = 0

    @classmethod
    def from_hf(cls, hf: dict) -> "MambaConfig":
        tsr = hf.get("time_step_rank", "auto")
        if tsr == "auto":
            tsr = -(-hf.get("hidden_size", 768) // 16)  # ceil(H/16)
        return cls(
            vocab_size=hf.get("vocab_size", 50280),
            hidden_size=hf.get("hidden_size", 768),
            intermediate_size=hf.get(
                "intermediate_size", 2 * hf.get("hidden_size", 768)),
            state_size=hf.get("state_size", 16),
            conv_kernel=hf.get("conv_kernel", 4),
            num_layers=hf.get("num_hidden_layers", 24),
            time_step_rank=int(tsr),
            layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
            use_bias=hf.get("use_bias", False),
            use_conv_bias=hf.get("use_conv_bias", True),
            eos_token_id=hf.get("eos_token_id", 0) or 0,
        )


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, -1, keepdims=True)
    return (w * (xf * jax.lax.rsqrt(var + eps))).astype(x.dtype)


def _mixer_common(p, i, cfg, u):
    """Shared projections: u [B,L,H] → (x [B,L,D] pre-conv, gate, dt/B/C
    projections applied later)."""
    pre = f"backbone.layers.{i}.mixer"
    proj = u @ p[f"{pre}.in_proj.weight"].T
    if cfg.use_bias:
        proj = proj + p[f"{pre}.in_proj.bias"]
    x, gate = jnp.split(proj, 2, axis=-1)
    return pre, x, gate


def _ssm_params(p, pre, cfg, x):
    """x [B,L,D] → (dA [B,L,D,N], dBu [B,L,D,N], C [B,L,N]) — the
    discretization (modeling_mamba.py:406-419)."""
    ssm_in = x @ p[f"{pre}.x_proj.weight"].T
    dt, B, C = jnp.split(
        ssm_in,
        [cfg.time_step_rank, cfg.time_step_rank + cfg.state_size],
        axis=-1,
    )
    dt = dt @ p[f"{pre}.dt_proj.weight"].T + p[f"{pre}.dt_proj.bias"]
    dt = jax.nn.softplus(dt)                         # [B,L,D]
    A = -jnp.exp(p[f"{pre}.A_log"].astype(jnp.float32))  # [D,N]
    dA = jnp.exp(dt[..., None] * A[None, None])      # [B,L,D,N]
    dBu = dt[..., None] * B[..., None, :] * x[..., None]
    return dA, dBu, C


def _block_prefill(p, i, cfg, u, length):
    """One block over the full (possibly right-padded) sequence; returns
    (out, conv_state, ssm_state). ``length`` gates the recurrence so pad
    positions past it never touch the carried states (prompt-length
    bucketing — one compiled program per bucket, not per length)."""
    pre, x, gate = _mixer_common(p, i, cfg, u)
    B_, L, D = x.shape
    k = cfg.conv_kernel
    # causal depthwise conv over time (torch Conv1d groups=D, pad k-1);
    # right-padding is safe — causality keeps positions < length exact
    xt = x.transpose(0, 2, 1)                        # [B,D,L]
    w = p[f"{pre}.conv1d.weight"]                    # [D,1,k]
    conv = jax.lax.conv_general_dilated(
        xt, w, window_strides=(1,), padding=[(k - 1, 0)],
        feature_group_count=D,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if cfg.use_conv_bias:
        conv = conv + p[f"{pre}.conv1d.bias"][None, :, None]
    x = jax.nn.silu(conv).transpose(0, 2, 1)         # [B,L,D]
    # decode conv state = pre-conv inputs at positions [length-k, length)
    padded = jnp.pad(xt, ((0, 0), (0, 0), (k, 0)))
    conv_state = jax.lax.dynamic_slice(
        padded, (0, 0, length), (B_, D, k)
    )
    dA, dBu, C = _ssm_params(p, pre, cfg, x)
    ssm0 = jnp.zeros((B_, D, cfg.state_size), jnp.float32)

    def scan_fn(state, t):
        dA_t, dBu_t, C_t, idx = t
        nxt = dA_t * state + dBu_t                   # [B,D,N]
        state = jnp.where(idx < length, nxt, state)
        y = jnp.einsum("bdn,bn->bd", state, C_t)
        return state, y

    ssm_state, ys = jax.lax.scan(
        scan_fn, ssm0,
        (dA.transpose(1, 0, 2, 3), dBu.transpose(1, 0, 2, 3),
         C.transpose(1, 0, 2), jnp.arange(L)),
    )
    y = ys.transpose(1, 0, 2)                        # [B,L,D]
    y = y + x * p[f"{pre}.D"][None, None]
    y = y * jax.nn.silu(gate)
    out = y @ p[f"{pre}.out_proj.weight"].T
    if cfg.use_bias:
        out = out + p[f"{pre}.out_proj.bias"]
    return out, conv_state, ssm_state


def _block_step(p, i, cfg, u, conv_state, ssm_state):
    """One block for one token: u [B,H] → (out [B,H], states)."""
    pre, x, gate = _mixer_common(p, i, cfg, u[:, None])
    x = x[:, 0]                                      # [B,D]
    gate = gate[:, 0]
    # roll the conv buffer, apply the depthwise kernel over k slots
    conv_state = jnp.concatenate(
        [conv_state[:, :, 1:], x[:, :, None]], axis=2
    )
    w = p[f"{pre}.conv1d.weight"][:, 0, :]           # [D,k]
    xc = jnp.sum(conv_state * w[None], axis=-1)      # [B,D]
    if cfg.use_conv_bias:
        xc = xc + p[f"{pre}.conv1d.bias"]
    xc = jax.nn.silu(xc)
    dA, dBu, C = _ssm_params(p, pre, cfg, xc[:, None])
    ssm_state = dA[:, 0] * ssm_state + dBu[:, 0]
    y = jnp.einsum("bdn,bn->bd", ssm_state, C[:, 0])
    y = y + xc * p[f"{pre}.D"][None]
    y = y * jax.nn.silu(gate)
    out = y @ p[f"{pre}.out_proj.weight"].T
    if cfg.use_bias:
        out = out + p[f"{pre}.out_proj.bias"]
    return out, conv_state, ssm_state


def forward_prefill(p, cfg: MambaConfig, ids, length=None, full=True):
    """ids [B,L] (right-padded to a bucket) → (logits, states list).

    ``full=True`` returns logits over every position [B,L,V] (parity
    tests); the serving path uses full=False, which projects the lm head
    ONLY at position length-1 — on a long prompt the [L, V] logits tensor
    is pure waste (generate() consumes one row)."""
    if length is None:
        length = ids.shape[1]
    h = jnp.take(p["backbone.embeddings.weight"], ids, axis=0)
    states = []
    for i in range(cfg.num_layers):
        res = h.astype(jnp.float32)
        normed = _rms(h, p[f"backbone.layers.{i}.norm.weight"],
                      cfg.layer_norm_epsilon)
        out, cs, ss = _block_prefill(p, i, cfg, normed, length)
        h = (res + out).astype(h.dtype)
        states.append((cs, ss))
    h = _rms(h, p["backbone.norm_f.weight"], cfg.layer_norm_epsilon)
    if full:
        return h @ _lm_head(p).T, states
    last = jnp.take_along_axis(
        h, jnp.asarray(length - 1).reshape(1, 1, 1).repeat(
            h.shape[-1], -1), axis=1
    )[:, 0]
    return last @ _lm_head(p).T, states


def forward_step(p, cfg: MambaConfig, token, states):
    """token [B] → (logits [B,V], new states)."""
    h = jnp.take(p["backbone.embeddings.weight"], token, axis=0)
    new_states = []
    for i in range(cfg.num_layers):
        res = h.astype(jnp.float32)
        normed = _rms(h, p[f"backbone.layers.{i}.norm.weight"],
                      cfg.layer_norm_epsilon)
        out, cs, ss = _block_step(p, i, cfg, normed, *states[i])
        h = (res + out).astype(h.dtype)
        new_states.append((cs, ss))
    h = _rms(h, p["backbone.norm_f.weight"], cfg.layer_norm_epsilon)
    return h @ _lm_head(p).T, new_states


def _lm_head(p):
    return p.get("lm_head.weight", p["backbone.embeddings.weight"])


class MambaLM:
    """One loaded mamba checkpoint: prompt → tokens, O(1) state."""

    def __init__(self, cfg: MambaConfig, params: dict, tokenizer: Any):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self._step = jax.jit(
            lambda p, tok, states: forward_step(p, cfg, tok, states)
        )
        # prompts pad to power-of-two buckets: one compiled prefill per
        # bucket, not per prompt length
        self._prefill = jax.jit(
            lambda p, ids, length: forward_prefill(p, cfg, ids, length,
                                                   full=False)
        )

    def generate(self, prompt: list[int], *, max_new_tokens: int = 128,
                 temperature: float = 0.0, seed: int = 0,
                 eos_ids: Optional[set[int]] = None,
                 on_token=None) -> list[int]:
        eos = eos_ids if eos_ids is not None else {self.cfg.eos_token_id}
        toks = prompt or [0]
        bucket = 16
        while bucket < len(toks):
            bucket *= 2
        ids = np.zeros((1, bucket), np.int32)
        ids[0, : len(toks)] = toks
        last, states = self._prefill(
            self.params, jnp.asarray(ids), jnp.int32(len(toks))
        )
        key = jax.random.key(seed)
        out: list[int] = []
        for _ in range(max_new_tokens):
            if temperature and temperature > 0:
                key, k = jax.random.split(key)
                tok = jax.random.categorical(k, last / temperature, axis=-1)
            else:
                tok = jnp.argmax(last, axis=-1)
            t = int(tok[0])
            if t in eos:
                break
            out.append(t)
            if on_token is not None:
                on_token(t)
            last, states = self._step(self.params, tok.astype(jnp.int32),
                                      states)
        return out


def resolve_mamba(ref: str, model_path: str | Path = "models",
                  dtype: str = "float32", seed: int = 0) -> MambaLM:
    """HF MambaForCausalLM checkpoint dir or ``debug:mamba-tiny``."""
    if ref == "debug:mamba-tiny":
        from localai_tpu.utils.tokenizer import ByteTokenizer

        cfg = MambaConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            state_size=8, conv_kernel=4, num_layers=2, time_step_rank=4,
            eos_token_id=257,
        )
        return MambaLM(cfg, init_params(jax.random.key(seed), cfg),
                       ByteTokenizer())
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            hf = json.loads((cand / "config.json").read_text())
            cfg = MambaConfig.from_hf(hf)
            from localai_tpu.models.loader import _get, _open_safetensors
            from localai_tpu.utils.tokenizer import load_tokenizer

            raw = _open_safetensors(cand)
            params = {}
            for name in raw:
                arr = np.asarray(_get(raw, name), np.float32)
                params[name] = jnp.asarray(
                    arr, jnp.float32 if name.endswith(("A_log", ".D"))
                    else jnp.dtype(dtype)
                )
            return MambaLM(cfg, params, load_tokenizer(cand))
    raise FileNotFoundError(f"mamba ref {ref!r} not found")


def init_params(key, cfg: MambaConfig) -> dict:
    """Random init matching the HF layout (debug preset / tests)."""
    ks = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))
    H, D, N = cfg.hidden_size, cfg.intermediate_size, cfg.state_size

    def w(shape, scale=0.05):
        return jax.random.normal(next(ks), shape) * scale

    p = {
        "backbone.embeddings.weight": w((cfg.vocab_size, H)),
        "backbone.norm_f.weight": jnp.ones((H,)),
    }
    A = jnp.tile(jnp.arange(1, N + 1, dtype=jnp.float32)[None], (D, 1))
    for i in range(cfg.num_layers):
        pre = f"backbone.layers.{i}"
        p[f"{pre}.norm.weight"] = jnp.ones((H,))
        p[f"{pre}.mixer.in_proj.weight"] = w((2 * D, H))
        p[f"{pre}.mixer.conv1d.weight"] = w((D, 1, cfg.conv_kernel))
        p[f"{pre}.mixer.conv1d.bias"] = jnp.zeros((D,))
        p[f"{pre}.mixer.x_proj.weight"] = w(
            (cfg.time_step_rank + 2 * N, D))
        p[f"{pre}.mixer.dt_proj.weight"] = w((D, cfg.time_step_rank))
        p[f"{pre}.mixer.dt_proj.bias"] = jnp.full((D,), -2.0)
        p[f"{pre}.mixer.A_log"] = jnp.log(A)
        p[f"{pre}.mixer.D"] = jnp.ones((D,))
        p[f"{pre}.mixer.out_proj.weight"] = w((H, D))
    return p
