"""RWKV (v4) language models in functional JAX.

Parity: SURVEY item 47 — the reference serves RWKV through llama.cpp's
rwkv GGUF support; transformers' torch implementation
(models/rwkv/modeling_rwkv.py) is the numeric reference here, verified
in tests/test_rwkv.py. Loads HF `RwkvForCausalLM` checkpoints
(model_type "rwkv": RWKV/rwkv-4-*-pile).

Architecture: linear-attention WKV recurrence (numerically-stabilized
exponential accumulators) + token-shift mixing — like mamba, O(1)
recurrent state per stream, no KV cache. Prefill vectorizes everything
but the WKV recurrence (ONE `lax.scan` per layer); decode is a fused
single-token step over the state pytree.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RwkvConfig:
    vocab_size: int = 50277
    hidden_size: int = 768
    attention_hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    layer_norm_epsilon: float = 1e-5
    eos_token_id: int = 0

    @classmethod
    def from_hf(cls, hf: dict) -> "RwkvConfig":
        H = hf.get("hidden_size", 768)
        return cls(
            vocab_size=hf.get("vocab_size", 50277),
            hidden_size=H,
            attention_hidden_size=hf.get("attention_hidden_size") or H,
            intermediate_size=hf.get("intermediate_size") or 4 * H,
            num_layers=hf.get("num_hidden_layers", 12),
            layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
            eos_token_id=hf.get("eos_token_id", 0) or 0,
        )


def _ln(x, g, b, eps):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


@dataclasses.dataclass
class LayerState:
    """Per-layer recurrent state (the 5 tensors of the torch cache)."""

    ffn_shift: jax.Array   # [B,H] last hidden fed to the FFN mix
    attn_shift: jax.Array  # [B,H] last hidden fed to the attention mix
    num: jax.Array         # [B,A] WKV numerator accumulator
    den: jax.Array         # [B,A] WKV denominator accumulator
    mx: jax.Array          # [B,A] running max (stability)


jax.tree_util.register_dataclass(
    LayerState,
    data_fields=("ffn_shift", "attn_shift", "num", "den", "mx"),
    meta_fields=(),
)


def _init_state(cfg: RwkvConfig, batch: int) -> list[LayerState]:
    H, A = cfg.hidden_size, cfg.attention_hidden_size
    z = jnp.zeros((batch, H), jnp.float32)
    za = jnp.zeros((batch, A), jnp.float32)
    return [
        LayerState(z, z, za, za, za - 1e38)
        for _ in range(cfg.num_layers)
    ]


def _wkv_step(num, den, mx, k, v, time_decay, time_first):
    """One WKV update (modeling_rwkv.py:184-226, stabilized form)."""
    max_out = jnp.maximum(mx, k + time_first)
    e1 = jnp.exp(mx - max_out)
    e2 = jnp.exp(k + time_first - max_out)
    out = (e1 * num + e2 * v) / (e1 * den + e2)
    max_state = jnp.maximum(mx + time_decay, k)
    e1 = jnp.exp(mx + time_decay - max_state)
    e2 = jnp.exp(k - max_state)
    return out, e1 * num + e2 * v, e1 * den + e2, max_state


def _attention(p, i, cfg, x, shifted, st: LayerState, length):
    """x [B,L,H]; shifted [B,L,H] (token-shifted hiddens). Returns
    (out, new LayerState pieces). ``length`` gates the WKV carry so
    right-padded bucket positions never pollute the state."""
    pre = f"rwkv.blocks.{i}.attention"
    mk = p[f"{pre}.time_mix_key"][0]
    mv = p[f"{pre}.time_mix_value"][0]
    mr = p[f"{pre}.time_mix_receptance"][0]
    key = (x * mk + shifted * (1 - mk)) @ p[f"{pre}.key.weight"].T
    value = (x * mv + shifted * (1 - mv)) @ p[f"{pre}.value.weight"].T
    recept = jax.nn.sigmoid(
        (x * mr + shifted * (1 - mr)) @ p[f"{pre}.receptance.weight"].T
    )
    time_decay = -jnp.exp(p[f"{pre}.time_decay"].astype(jnp.float32))
    time_first = p[f"{pre}.time_first"].astype(jnp.float32)

    def scan_fn(carry, t):
        num, den, mx = carry
        k_t, v_t, idx = t
        out, n2, d2, m2 = _wkv_step(
            num, den, mx, k_t.astype(jnp.float32), v_t,
            time_decay, time_first,
        )
        # pad positions past the true length must not touch the carry
        keep = idx < length
        return (jnp.where(keep, n2, num), jnp.where(keep, d2, den),
                jnp.where(keep, m2, mx)), out

    (num, den, mx), outs = jax.lax.scan(
        scan_fn, (st.num, st.den, st.mx),
        (key.transpose(1, 0, 2), value.transpose(1, 0, 2),
         jnp.arange(key.shape[1])),
    )
    rwkv_out = outs.transpose(1, 0, 2).astype(x.dtype)
    out = (recept * rwkv_out) @ p[f"{pre}.output.weight"].T
    return out, num, den, mx


def _feed_forward(p, i, cfg, x, shifted):
    pre = f"rwkv.blocks.{i}.feed_forward"
    mk = p[f"{pre}.time_mix_key"][0]
    mr = p[f"{pre}.time_mix_receptance"][0]
    key = (x * mk + shifted * (1 - mk)) @ p[f"{pre}.key.weight"].T
    key = jnp.square(jax.nn.relu(key))
    value = key @ p[f"{pre}.value.weight"].T
    recept = jax.nn.sigmoid(
        (x * mr + shifted * (1 - mr)) @ p[f"{pre}.receptance.weight"].T
    )
    return recept * value


def _shift(x, first_row):
    """Token shift: row t sees row t-1; the first row sees the carried
    state (zeros on a fresh sequence)."""
    return jnp.concatenate([first_row[:, None], x[:, :-1]], axis=1)


def forward(p, cfg: RwkvConfig, ids, states: Optional[list] = None,
            length=None, full=True):
    """ids [B,L] (right-padded to a bucket) → (logits, new states).
    States None = fresh. ``full=False`` projects the head only at
    position length-1 (the serving path: one row is all generate()
    reads)."""
    B, L = ids.shape
    if length is None:
        length = L
    if states is None:
        states = _init_state(cfg, B)
    h = jnp.take(p["rwkv.embeddings.weight"], ids, axis=0)
    eps = cfg.layer_norm_epsilon

    def at_last(x):  # [B,L,H] → [B,H] at position length-1
        return jnp.take_along_axis(
            x, jnp.asarray(length - 1).reshape(1, 1, 1).repeat(
                x.shape[-1], -1), axis=1
        )[:, 0]

    new_states = []
    for i in range(cfg.num_layers):
        blk = f"rwkv.blocks.{i}"
        if i == 0:
            h = _ln(h, p[f"{blk}.pre_ln.weight"],
                    p[f"{blk}.pre_ln.bias"], eps)
        st = states[i]
        x1 = _ln(h, p[f"{blk}.ln1.weight"], p[f"{blk}.ln1.bias"], eps)
        attn, num, den, mx = _attention(
            p, i, cfg, x1, _shift(x1, st.attn_shift.astype(x1.dtype)),
            st, length,
        )
        h = h + attn
        x2 = _ln(h, p[f"{blk}.ln2.weight"], p[f"{blk}.ln2.bias"], eps)
        h = h + _feed_forward(
            p, i, cfg, x2, _shift(x2, st.ffn_shift.astype(x2.dtype))
        )
        new_states.append(LayerState(
            ffn_shift=at_last(x2).astype(jnp.float32),
            attn_shift=at_last(x1).astype(jnp.float32),
            num=num, den=den, mx=mx,
        ))
    h = _ln(h, p["rwkv.ln_out.weight"], p["rwkv.ln_out.bias"], eps)
    if full:
        return h @ p["head.weight"].T, new_states
    return at_last(h) @ p["head.weight"].T, new_states


class RwkvLM:
    """One loaded RWKV checkpoint: prompt → tokens, O(1) state (the same
    generate surface MambaLM exposes, shared by the recurrent-serving
    facade)."""

    def __init__(self, cfg: RwkvConfig, params: dict, tokenizer: Any):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self._fwd = jax.jit(
            lambda p, ids, states: forward(p, cfg, ids, states)
        )
        # prompts pad to power-of-two buckets: one compiled prefill per
        # bucket, not per prompt length
        self._fresh = jax.jit(
            lambda p, ids, length: forward(p, cfg, ids, None, length,
                                           full=False)
        )

    def generate(self, prompt: list[int], *, max_new_tokens: int = 128,
                 temperature: float = 0.0, seed: int = 0,
                 eos_ids: Optional[set[int]] = None,
                 on_token=None) -> list[int]:
        eos = eos_ids if eos_ids is not None else {self.cfg.eos_token_id}
        toks = prompt or [0]
        bucket = 16
        while bucket < len(toks):
            bucket *= 2
        ids = np.zeros((1, bucket), np.int32)
        ids[0, : len(toks)] = toks
        last, states = self._fresh(
            self.params, jnp.asarray(ids), jnp.int32(len(toks))
        )
        key = jax.random.key(seed)
        out: list[int] = []
        for _ in range(max_new_tokens):
            if temperature and temperature > 0:
                key, k = jax.random.split(key)
                tok = jax.random.categorical(k, last / temperature, -1)
            else:
                tok = jnp.argmax(last, axis=-1)
            t = int(tok[0])
            if t in eos:
                break
            out.append(t)
            if on_token is not None:
                on_token(t)
            logits, states = self._fwd(
                self.params, tok[:, None].astype(jnp.int32), states
            )
            last = logits[:, -1]
        return out


def resolve_rwkv(ref: str, model_path: str | Path = "models",
                 dtype: str = "float32", seed: int = 0) -> RwkvLM:
    """HF RwkvForCausalLM checkpoint dir or ``debug:rwkv-tiny``."""
    if ref == "debug:rwkv-tiny":
        from localai_tpu.utils.tokenizer import ByteTokenizer

        cfg = RwkvConfig(
            vocab_size=512, hidden_size=64, attention_hidden_size=64,
            intermediate_size=128, num_layers=2, eos_token_id=257,
        )
        return RwkvLM(cfg, init_params(jax.random.key(seed), cfg),
                      ByteTokenizer())
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            hf = json.loads((cand / "config.json").read_text())
            cfg = RwkvConfig.from_hf(hf)
            from localai_tpu.models.loader import _get, _open_safetensors
            from localai_tpu.utils.tokenizer import load_tokenizer

            raw = _open_safetensors(cand)
            params = {}
            for name in raw:
                arr = np.asarray(_get(raw, name), np.float32)
                # time_decay/time_first and norms stay f32 (the WKV
                # exponentials are numerically fragile); the big matmul
                # weights honor the configured dtype
                keep_f32 = (
                    arr.ndim == 1
                    or name.endswith(("time_decay", "time_first"))
                )
                params[name] = jnp.asarray(
                    arr, jnp.float32 if keep_f32 else jnp.dtype(dtype)
                )
            return RwkvLM(cfg, params, load_tokenizer(cand))
    raise FileNotFoundError(f"rwkv ref {ref!r} not found")


def init_params(key, cfg: RwkvConfig) -> dict:
    ks = iter(jax.random.split(key, 4 + 10 * cfg.num_layers))
    H, A, I = (cfg.hidden_size, cfg.attention_hidden_size,
               cfg.intermediate_size)

    def w(shape, scale=0.05):
        return jax.random.normal(next(ks), shape) * scale

    p = {
        "rwkv.embeddings.weight": w((cfg.vocab_size, H)),
        "rwkv.ln_out.weight": jnp.ones((H,)),
        "rwkv.ln_out.bias": jnp.zeros((H,)),
        "head.weight": w((cfg.vocab_size, H)),
    }
    for i in range(cfg.num_layers):
        blk = f"rwkv.blocks.{i}"
        if i == 0:
            p[f"{blk}.pre_ln.weight"] = jnp.ones((H,))
            p[f"{blk}.pre_ln.bias"] = jnp.zeros((H,))
        for ln in ("ln1", "ln2"):
            p[f"{blk}.{ln}.weight"] = jnp.ones((H,))
            p[f"{blk}.{ln}.bias"] = jnp.zeros((H,))
        at = f"{blk}.attention"
        p[f"{at}.time_decay"] = jnp.zeros((A,))
        p[f"{at}.time_first"] = jnp.zeros((A,))
        for m in ("key", "value", "receptance"):
            p[f"{at}.time_mix_{m}"] = jnp.full((1, 1, H), 0.5)
            p[f"{at}.{m}.weight"] = w((A, H))
        p[f"{at}.output.weight"] = w((H, A))
        ff = f"{blk}.feed_forward"
        p[f"{ff}.time_mix_key"] = jnp.full((1, 1, H), 0.5)
        p[f"{ff}.time_mix_receptance"] = jnp.full((1, 1, H), 0.5)
        p[f"{ff}.key.weight"] = w((I, H))
        p[f"{ff}.receptance.weight"] = w((H, H))
        p[f"{ff}.value.weight"] = w((H, I))
    return p
