"""Weight ingestion: HF safetensors → stacked JAX param pytrees.

The TPU replacement for GGUF ingestion (the reference's weight path is
llama.cpp's GGUF mmap, /root/reference/pkg/model + gguf autoconfig
core/config/guesser.go:13-246): we ingest the HF safetensors layout
directly, transpose to right-multiply convention, and stack per-layer
tensors along a leading axis so the model can lax.scan over layers.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models.llama import LlamaConfig, param_shapes
from localai_tpu.utils import jaxcompat

log = logging.getLogger(__name__)


def read_hf_config(model_dir: str | Path) -> dict:
    with open(Path(model_dir) / "config.json") as f:
        return json.load(f)


def load_hf_config(model_dir: str | Path) -> LlamaConfig:
    hf = read_hf_config(model_dir)
    if hf.get("model_type") == "llava":
        # LLaVA composite checkpoint: the language model is described by
        # text_config and stored under the language_model. prefix
        return LlamaConfig.from_hf(hf.get("text_config", {}))
    return LlamaConfig.from_hf(hf)


def _open_safetensors(model_dir: Path) -> dict[str, Any]:
    """Return name → lazy tensor accessor across all shards."""
    from safetensors import safe_open

    tensors: dict[str, Any] = {}
    files = sorted(model_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    for fp in files:
        handle = safe_open(str(fp), framework="numpy")
        for name in handle.keys():
            tensors[name] = (handle, name)
    return tensors


def _get(tensors: dict, name: str) -> np.ndarray:
    handle, key = tensors[name]
    arr = handle.get_tensor(key)
    # bfloat16 arrives as uint16 view from some writers; reinterpret via ml_dtypes
    if arr.dtype == np.uint16:
        import ml_dtypes

        arr = arr.view(ml_dtypes.bfloat16)
    return arr


def load_llama_params(
    model_dir: str | Path,
    cfg: Optional[LlamaConfig] = None,
    dtype: str = "bfloat16",
    shard_fn=None,
    hf: Optional[dict] = None,
) -> tuple[LlamaConfig, Any]:
    """Load an HF llama/mistral/qwen2 checkpoint into the stacked pytree.

    ``shard_fn(path_tuple, np_array) -> jax.Array`` lets the caller place
    each param with a NamedSharding (device_put per shard); default is
    single-device jnp.asarray. ``hf`` is the already-parsed config.json
    (avoids re-reading when the caller has it).
    """
    model_dir = Path(model_dir)
    if hf is None:
        hf = read_hf_config(model_dir)
    # tensor-name layout: plain llama vs llava composite (classic
    # language_model.model.* layout, or model.language_model.* in
    # transformers ≥4.52 exports)
    body, head = "model.", "lm_head.weight"
    is_llava = hf.get("model_type") == "llava"
    if is_llava:
        body, head = "language_model.model.", "language_model.lm_head.weight"
    if cfg is None:
        cfg = LlamaConfig.from_hf(hf.get("text_config", {}) if is_llava else hf)
    tensors = _open_safetensors(model_dir)
    if body + "embed_tokens.weight" not in tensors:
        if "model.language_model.embed_tokens.weight" in tensors:
            body, head = "model.language_model.", "lm_head.weight"
    dt = jnp.dtype(dtype)
    put = shard_fn or (lambda path, a: jnp.asarray(a, dt))

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        # keep source dtype on host (bf16 checkpoints stay 2 bytes/elem);
        # the device put casts to the target dtype
        mats = []
        for i in range(cfg.num_layers):
            a = _get(tensors, fmt.format(i=i))
            mats.append(a.T if transpose else a)
        return np.stack(mats)

    L = body + "layers.{i}."
    layers = {
        "attn_norm": stack(L + "input_layernorm.weight", False),
        "wq": stack(L + "self_attn.q_proj.weight", True),
        "wk": stack(L + "self_attn.k_proj.weight", True),
        "wv": stack(L + "self_attn.v_proj.weight", True),
        "wo": stack(L + "self_attn.o_proj.weight", True),
        "mlp_norm": stack(L + "post_attention_layernorm.weight", False),
    }
    if cfg.num_experts:
        # Mixtral layout: block_sparse_moe.gate (router) +
        # experts.{j}.w1/w3/w2 (gate/up/down) → expert-stacked [L, E, K, N]
        def stack_experts(wname: str) -> np.ndarray:
            outer = []
            for i in range(cfg.num_layers):
                outer.append(np.stack([
                    _get(tensors,
                         f"{body}layers.{i}.block_sparse_moe."
                         f"experts.{j}.{wname}.weight").T
                    for j in range(cfg.num_experts)
                ]))
            return np.stack(outer)

        layers["moe_gate"] = stack(
            L + "block_sparse_moe.gate.weight", True)
        layers["w_gate"] = stack_experts("w1")
        layers["w_up"] = stack_experts("w3")
        layers["w_down"] = stack_experts("w2")
    else:
        layers["w_gate"] = stack(L + "mlp.gate_proj.weight", True)
        layers["w_up"] = stack(L + "mlp.up_proj.weight", True)
        layers["w_down"] = stack(L + "mlp.down_proj.weight", True)
    if cfg.attention_bias:
        layers["bq"] = stack(L + "self_attn.q_proj.bias", False)
        layers["bk"] = stack(L + "self_attn.k_proj.bias", False)
        layers["bv"] = stack(L + "self_attn.v_proj.bias", False)

    params: dict[str, Any] = {
        "embed": _get(tensors, body + "embed_tokens.weight"),
        "final_norm": _get(tensors, body + "norm.weight"),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        if head in tensors:
            params["lm_head"] = _get(tensors, head).T
        else:
            cfg = LlamaConfig(**{**cfg.__dict__, "tie_word_embeddings": True})

    placed = jaxcompat.tree_map_with_path(lambda p, a: put(p, a), params)
    _check_shapes(cfg, placed)
    return cfg, placed


def _check_shapes(cfg: LlamaConfig, params: Any) -> None:
    expected = param_shapes(cfg)

    def chk(path, exp):
        node = params
        for k in path:
            node = node[k]
        if tuple(node.shape) != tuple(exp):
            raise ValueError(f"param {path}: shape {node.shape} != expected {exp}")

    for name, v in expected.items():
        if isinstance(v, dict):
            for k, s in v.items():
                chk((name, k), s)
        else:
            chk((name,), v)
