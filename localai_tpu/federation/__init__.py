"""Federation: one front door over many LocalAI-TPU instances.

Parity: /root/reference/core/p2p/federated.go + federated_server.go.
"""

from localai_tpu.federation.server import (
    FederatedNode,
    FederatedServer,
    announce,
)

__all__ = ["FederatedNode", "FederatedServer", "announce"]
