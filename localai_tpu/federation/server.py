"""Federated serving: an HTTP router balancing requests over many
LocalAI-TPU instances.

Parity: /root/reference/core/p2p/federated.go:39-118 (request table,
random / least-used selection, offline-node eviction) and
federated_server.go (the listener proxying each connection to the chosen
node, with a worker-target override). The reference tunnels raw TCP over
an edgevpn p2p overlay; on TPU pods the instances are plain HTTP servers
on a datacenter network, so this router proxies at the HTTP layer instead
— which also buys per-request (not per-connection) balancing, streaming
pass-through, and retry-on-another-node failover that a blind TCP splice
cannot do. Node discovery is explicit (static peer list, or instances
announcing themselves via POST /federated/register — the moral equivalent
of the p2p service advertisement), guarded by the shared ``peer_token``.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from aiohttp import ClientSession, ClientTimeout, TCPConnector, web

log = logging.getLogger(__name__)

FED_KEY = web.AppKey("fed", object)
SESSION_KEY = web.AppKey("session", ClientSession)
HEALTH_KEY = web.AppKey("health_task", object)


def validate_advertised_address(address: str) -> str:
    """Reject an advertised address that is unroutable BY CONSTRUCTION —
    empty host, missing/zero/garbage port, or a wildcard bind address
    (0.0.0.0/::/*). A peer advertising one of these can never be dialed
    back, so accepting it only seeds the registry (and any fleet pool
    adopting from it) with a permanently offline node. Returns the
    address unchanged (scheme preserved); raises ValueError.

    Deliberately *constructional* only: whether a well-formed address is
    actually reachable is the health loop's job, not registration's."""
    hostport = address
    for scheme in ("http://", "https://"):
        if hostport.startswith(scheme):
            hostport = hostport[len(scheme):]
            break
    hostport = hostport.split("/", 1)[0]
    # IPv6 literal: [::1]:8080
    if hostport.startswith("["):
        host, _, rest = hostport[1:].partition("]")
        port_s = rest.removeprefix(":")
    else:
        host, _, port_s = hostport.rpartition(":")
    if not host:
        raise ValueError(f"advertised address {address!r} has no host")
    if host in ("0.0.0.0", "::", "*"):
        raise ValueError(
            f"advertised address {address!r} is a wildcard bind address, "
            "not a routable peer address")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"advertised address {address!r} has no numeric port") from None
    if not 1 <= port <= 65535:
        raise ValueError(
            f"advertised address {address!r} has out-of-range port {port}")
    return address

# hop-by-hop headers never forwarded by an HTTP proxy (RFC 9110 §7.6.1)
HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding",
    "upgrade", "host", "content-length",
}


@dataclass
class FederatedNode:
    """One backing instance (parity: p2p NodeData)."""

    id: str
    address: str                    # http://host:port
    online: bool = True
    requests_served: int = 0        # the requestTable counter
    failures: int = 0
    last_seen: float = field(default_factory=time.monotonic)

    def snapshot(self) -> dict:
        return {
            "id": self.id,
            "address": self.address,
            "online": self.online,
            "requests_served": self.requests_served,
        }


class FederatedServer:
    """Request router over a registry of instances.

    Selection (federated.go:40-101): an explicit ``worker_target`` pins all
    traffic to one node; otherwise least-used when ``load_balanced``,
    falling back to random. A background loop health-checks ``/healthz``
    and flips nodes offline/online; offline nodes leave the request table
    (syncTableStatus parity)."""

    def __init__(self, nodes: Optional[list[str]] = None, *,
                 load_balanced: bool = True, worker_target: str = "",
                 peer_token: str = "", health_interval: float = 5.0):
        self.load_balanced = load_balanced
        self.worker_target = worker_target
        self.peer_token = peer_token
        self.health_interval = health_interval
        self._lock = threading.Lock()
        self._nodes: dict[str, FederatedNode] = {}
        for addr in nodes or []:
            self.register(addr)
        if worker_target:
            # the pin target must exist in the registry or select() would
            # answer 503 forever when it wasn't also listed as a peer
            self.register(worker_target)

    # -- registry ----------------------------------------------------------

    @staticmethod
    def _node_id(address: str) -> str:
        return address.removeprefix("http://").removeprefix("https://")

    def register(self, address: str) -> FederatedNode:
        if not address.startswith(("http://", "https://")):
            address = f"http://{address}"
        nid = self._node_id(address)
        with self._lock:
            node = self._nodes.get(nid)
            if node is None:
                node = FederatedNode(id=nid, address=address)
                self._nodes[nid] = node
                log.info("federation: registered node %s", nid)
            node.online = True
            # an evicted node re-registering is a REJOIN: its failure
            # count starts over, exactly like ReplicaPool._note_rejoined
            # resets the respawn/redial backoff clock — stale failures
            # must not poison the next incident's escalation
            node.failures = 0
            node.last_seen = time.monotonic()
            return node

    def nodes(self) -> list[FederatedNode]:
        with self._lock:
            return list(self._nodes.values())

    def online_nodes(self) -> list[FederatedNode]:
        return [n for n in self.nodes() if n.online]

    # -- selection (federated.go:40-101) -----------------------------------

    def select(self, exclude: frozenset[str] = frozenset()
               ) -> Optional[FederatedNode]:
        if self.worker_target:
            with self._lock:
                n = self._nodes.get(self._node_id(self.worker_target))
            if n is not None and n.online and n.id not in exclude:
                return n
            return None
        candidates = [n for n in self.online_nodes()
                      if n.id not in exclude]
        if not candidates:
            return None
        if self.load_balanced:
            low = min(n.requests_served for n in candidates)
            candidates = [n for n in candidates
                          if n.requests_served == low]
        return random.choice(candidates)

    def record_request(self, node: FederatedNode) -> None:
        with self._lock:
            node.requests_served += 1

    def mark_offline(self, node: FederatedNode) -> None:
        with self._lock:
            node.online = False
            node.failures += 1
        log.warning("federation: node %s marked offline", node.id)

    # -- health loop -------------------------------------------------------

    async def _health_loop(self, session: ClientSession) -> None:
        while True:
            await asyncio.sleep(self.health_interval)
            await self.check_health(session)

    async def check_health(self, session: ClientSession) -> None:
        for node in self.nodes():
            try:
                async with session.get(
                    f"{node.address}/healthz",
                    timeout=ClientTimeout(total=3.0),
                ) as resp:
                    ok = resp.status == 200
            except Exception:  # noqa: BLE001 — any failure means offline
                ok = False
            with self._lock:
                if ok:
                    if not node.online:
                        log.info("federation: node %s back online", node.id)
                        # rejoin resets the failure count (mirror
                        # ReplicaPool._note_rejoined)
                        node.failures = 0
                    node.online = True
                    node.last_seen = time.monotonic()
                else:
                    node.online = False
                    node.failures += 1

    # -- HTTP app ----------------------------------------------------------

    def create_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app[FED_KEY] = self
        app.router.add_get("/federated/nodes", _nodes_endpoint)
        app.router.add_post("/federated/register", _register_endpoint)
        app.router.add_route("*", "/{tail:.*}", _proxy_endpoint)

        async def on_startup(a):
            # no total timeout (long generations + SSE streams), but a
            # read-idle cap so a node that accepts connections and then
            # wedges (e.g. mid-SIGTERM) cannot hold proxied requests
            # forever — the health loop only protects FUTURE requests
            a[SESSION_KEY] = ClientSession(
                connector=TCPConnector(limit=0),
                timeout=ClientTimeout(total=None, connect=5.0,
                                      sock_read=600.0),
            )
            a[HEALTH_KEY] = asyncio.create_task(
                self._health_loop(a[SESSION_KEY])
            )

        async def on_cleanup(a):
            a[HEALTH_KEY].cancel()
            await a[SESSION_KEY].close()

        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
        return app

    def serve(self, address: str = "0.0.0.0", port: int = 8080) -> None:
        """Blocking entry (parity: FederatedServer.Start)."""
        log.info("federated router on %s:%d (%d nodes)", address, port,
                 # boot-time log line; node list is static until serving
                 len(self._nodes))  # jaxlint: disable=lock-guarded-attr
        web.run_app(self.create_app(), host=address, port=port,
                    print=None, access_log=None)


async def _nodes_endpoint(request: web.Request) -> web.Response:
    fed: FederatedServer = request.app[FED_KEY]
    return web.json_response({
        "nodes": [n.snapshot() for n in fed.nodes()],
        "load_balanced": fed.load_balanced,
        "worker_target": fed.worker_target,
    })


async def _register_endpoint(request: web.Request) -> web.Response:
    fed: FederatedServer = request.app[FED_KEY]
    if fed.peer_token:
        import hmac

        header = request.headers.get("Authorization", "")
        token = header.removeprefix("Bearer ").strip()
        if not hmac.compare_digest(token, fed.peer_token):
            return web.json_response({"error": "invalid peer token"},
                                     status=401)
    try:
        body = await request.json()
        address = str(body["address"])
    except Exception:
        return web.json_response({"error": "address is required"},
                                 status=400)
    try:
        validate_advertised_address(address)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    node = fed.register(address)
    return web.json_response(node.snapshot())


async def _proxy_endpoint(request: web.Request) -> web.StreamResponse:
    """Forward one request to a selected node, streaming the response
    through. A node that fails before any response byte is marked offline
    and the request retries on another (the HTTP-level upgrade over the
    reference's one-shot TCP splice)."""
    fed: FederatedServer = request.app[FED_KEY]
    session: ClientSession = request.app[SESSION_KEY]
    body = await request.read()
    headers = {k: v for k, v in request.headers.items()
               if k.lower() not in HOP_HEADERS}
    tried: set[str] = set()
    while True:
        node = fed.select(exclude=frozenset(tried))
        if node is None:
            return web.json_response(
                {"error": {"message": "no online federation nodes",
                           "type": "federation_error", "code": 503}},
                status=503,
            )
        tried.add(node.id)
        fed.record_request(node)
        import aiohttp as _aiohttp

        try:
            upstream = await session.request(
                request.method,
                f"{node.address}{request.rel_url}",
                headers=headers,
                data=body if body else None,
            )
        except (_aiohttp.ClientConnectorError,
                ConnectionRefusedError) as e:
            # connection never established — nothing was delivered, so
            # retrying on another node cannot double-execute
            fed.mark_offline(node)
            log.warning("federation: %s unreachable (%s); failing over",
                        node.id, e)
            continue
        except (_aiohttp.ClientError, OSError,
                asyncio.TimeoutError) as e:
            # the request MAY have reached the node (timeout waiting for
            # a slow response, reset mid-flight): retrying could
            # double-execute a non-idempotent call — surface the error
            fed.mark_offline(node)
            log.warning("federation: %s failed mid-request (%s)",
                        node.id, e)
            return web.json_response(
                {"error": {"message": f"federation node {node.id} "
                           f"failed mid-request: {e}",
                           "type": "federation_error", "code": 502}},
                status=502,
            )
        try:
            # response started: stream it through, no retry past this point
            resp = web.StreamResponse(status=upstream.status)
            for k, v in upstream.headers.items():
                if k.lower() not in HOP_HEADERS:
                    resp.headers[k] = v
            resp.headers["X-Federated-Node"] = node.id
            await resp.prepare(request)
            async for chunk in upstream.content.iter_any():
                await resp.write(chunk)
            await resp.write_eof()
            return resp
        finally:
            upstream.release()


def announce(router: str, own_address: str, peer_token: str = "",
             *, retries: int = 30, interval: float = 2.0) -> threading.Thread:
    """Register this instance with a federated router, retrying in the
    background until the router is reachable (parity: the p2p node
    announcing its service tunnel). Returns the announcing thread."""
    import json
    import urllib.request

    def run() -> None:
        url = f"{router.rstrip('/')}/federated/register"
        payload = json.dumps({"address": own_address}).encode()
        for _ in range(retries):
            try:
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json",
                             **({"Authorization": f"Bearer {peer_token}"}
                                if peer_token else {})},
                )
                with urllib.request.urlopen(req, timeout=5.0) as resp:
                    if resp.status == 200:
                        log.info("announced %s to federation router %s",
                                 own_address, router)
                        return
            except Exception as e:  # noqa: BLE001
                log.debug("federation announce retry: %s", e)
            time.sleep(interval)
        log.warning("could not announce to federation router %s", router)

    t = threading.Thread(target=run, name="fed-announce", daemon=True)
    t.start()
    return t
