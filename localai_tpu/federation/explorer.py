"""Explorer: a multi-network discovery dashboard over federation routers.

Parity: /root/reference/core/explorer/ (discovery.go:16-30 + database.go +
core/http/views/explorer.html) — the reference keeps a token DATABASE of
community p2p networks, dial-tests each one on an interval, counts
failures, and deletes networks after ``failure_threshold`` consecutive
errors. Without a p2p overlay, the TPU-native unit of a "network" is a
federation ROUTER URL (its node registry IS the network): the explorer
persists a JSON database of routers, a background monitor polls each
router's ``/federated/nodes`` (the dial test), snapshots the cluster
data, and evicts routers that keep failing — the same lifecycle,
HTTP-native.
"""

from __future__ import annotations

import html
import json
import logging
import threading
import time
import urllib.request
from pathlib import Path
from typing import Optional

from aiohttp import web

log = logging.getLogger(__name__)


def fetch_nodes(router: str, timeout: float = 5.0) -> dict:
    url = f"{router.rstrip('/')}/federated/nodes"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


class ExplorerDB:
    """Persistent router database (parity: explorer.Database — token list
    + per-entry failure bookkeeping, JSON on disk, thread-safe)."""

    def __init__(self, path: Optional[str | Path] = None):
        self.path = Path(path) if path else None
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        if self.path and self.path.exists():
            try:
                self._entries = json.loads(self.path.read_text())
            except (OSError, ValueError):
                log.warning("explorer db %s unreadable; starting empty",
                            self.path)

    def _persist(self) -> None:  # jaxlint: guarded-by(_lock)
        if self.path is None:
            return
        try:
            tmp = self.path.with_suffix(".tmp")
            tmp.write_text(json.dumps(self._entries))
            tmp.replace(self.path)
        except OSError as e:
            # a full/removed disk must not kill the monitor thread — the
            # in-memory db keeps working, persistence resumes when possible
            log.warning("explorer db persist failed: %s", e)

    def add(self, url: str, name: str = "") -> None:
        url = url.rstrip("/")
        with self._lock:
            self._entries.setdefault(url, {
                "name": name or url, "failures": 0, "added_at": time.time(),
            })
            if name:
                self._entries[url]["name"] = name
            self._persist()

    def remove(self, url: str) -> bool:
        with self._lock:
            gone = self._entries.pop(url.rstrip("/"), None) is not None
            self._persist()
            return gone

    def routers(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def entries(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def mark_ok(self, url: str) -> None:
        with self._lock:
            if url in self._entries:
                self._entries[url]["failures"] = 0
                self._persist()

    def mark_failed(self, url: str, threshold: int) -> bool:
        """Increment the failure count; evict (and return True) at the
        threshold — discovery.go failedToken/deleteToken semantics."""
        with self._lock:
            e = self._entries.get(url)
            if e is None:
                return False
            e["failures"] = int(e.get("failures", 0)) + 1
            if e["failures"] >= threshold:
                del self._entries[url]
                self._persist()
                log.info("explorer: evicting %s after %d failures",
                         url, threshold)
                return True
            self._persist()
            return False


class DiscoveryMonitor:
    """Background dial-tester (parity: explorer.DiscoveryServer
    runBackground — sequential per-network connect with a deadline,
    failure-count eviction, snapshot of cluster data)."""

    def __init__(self, db: ExplorerDB, *, interval: float = 50.0,
                 failure_threshold: int = 3, timeout: float = 5.0):
        self.db = db
        self.interval = interval
        self.failure_threshold = failure_threshold
        self.timeout = timeout
        self._lock = threading.Lock()
        self._state: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self, only: Optional[set] = None,
                  count_failures: bool = True) -> None:
        """One dial-test sweep (the testable unit).

        ``only`` restricts the sweep to a subset of routers;
        ``count_failures=False`` updates the state snapshot without
        advancing eviction counters — the 'consecutive failures' contract
        counts background sweeps, not page loads."""
        for url in self.db.routers():
            if only is not None and url not in only:
                continue
            self._dial_one(url, count_failures)

    def _dial_one(self, url: str, count_failures: bool = True,
                  timeout: Optional[float] = None) -> None:
        """Dial-test ONE router and fold the result into the snapshot."""
        # intervals/durations come from the monotonic clock (immune to
        # wall-clock steps); checked_at stays time.time() — it is a
        # display timestamp, not a duration source
        t0 = time.monotonic()
        try:
            data = fetch_nodes(url, timeout=timeout or self.timeout)
            dial = time.monotonic() - t0
            nodes = data.get("nodes", [])
            if url not in self.db.routers():
                return  # removed (DELETE) while the dial was in flight
            with self._lock:
                self._state[url] = {
                    "ok": True,
                    "nodes": nodes,
                    "online": sum(1 for n in nodes if n.get("online")),
                    "checked_at": time.time(),
                    "checked_mono": time.monotonic(),
                    "dial_seconds": round(dial, 3),
                }
            self.db.mark_ok(url)
        except Exception as e:  # noqa: BLE001 — the dial test failing
            dial = time.monotonic() - t0
            evicted = (count_failures and self.db.mark_failed(
                url, self.failure_threshold))
            with self._lock:
                if evicted or url not in self.db.routers():
                    self._state.pop(url, None)
                else:
                    self._state[url] = {
                        "ok": False, "error": str(e), "nodes": [],
                        "online": 0, "checked_at": time.time(),
                        "checked_mono": time.monotonic(),
                        "dial_seconds": round(dial, 3),
                    }

    def warmup(self, urls: set, *, deadline: float = 2.0,
               count_failures: bool = False) -> None:
        """Concurrent dial-test of ``urls`` bounded by ONE overall deadline
        (ADVICE r5 #2: the first-render warm-up used to dial unchecked
        routers sequentially at 5 s each inside the page request; several
        dead routers meant a dashboard stuck for tens of seconds while the
        10 s meta-refresh stacked further sweeps).

        Routers that answer within ``deadline`` render immediately; the
        rest stay "not checked yet" — their dials keep running on pool
        threads (bounded by the per-dial timeout) and fold into the
        snapshot for the next refresh."""
        urls = {u for u in urls if u in self.db.routers()}
        if not urls:
            return
        from concurrent.futures import ThreadPoolExecutor, wait

        pool = ThreadPoolExecutor(
            max_workers=min(8, len(urls)),
            thread_name_prefix="explorer-warmup",
        )
        # each dial keeps the monitor's FULL timeout — clamping it to the
        # page deadline would mark a slow-but-alive router failed; the
        # deadline only bounds how long the page waits
        futures = [
            pool.submit(self._dial_one, u, count_failures, self.timeout)
            for u in urls
        ]
        wait(futures, timeout=deadline)
        # never join the stragglers — that would re-serialize the page;
        # they finish on pool threads and fold in for the next refresh
        pool.shutdown(wait=False)

    def state(self) -> dict[str, dict]:
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._lock:
            for url, snap in self._state.items():
                d = dict(snap)
                # snapshot age from the monotonic pair (wall checked_at is
                # for display only and can step backwards under NTP)
                mono = d.pop("checked_mono", None)
                if mono is not None:
                    d["age_seconds"] = round(now - mono, 1)
                out[url] = d
        return out

    def forget(self, url: str) -> None:
        """Drop a network's snapshot (on DELETE — a re-added network must
        dial-test fresh, not resurface stale data)."""
        with self._lock:
            self._state.pop(url.rstrip("/"), None)

    def start(self) -> None:
        if self._thread is not None:
            return

        def run():
            while not self._stop.is_set():
                self.poll_once()
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="explorer-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- HTTP app ---------------------------------------------------------------

async def _index(request: web.Request) -> web.Response:
    import asyncio

    mon: DiscoveryMonitor = request.app["monitor"]
    entries = mon.db.entries()
    state = mon.state()
    missing = {url for url in entries if url not in state}
    if missing:
        # first render (or a freshly registered network): dial-test the
        # missing ones CONCURRENTLY under one short deadline so the page
        # renders in ~2 s no matter how many routers are dead (stragglers
        # show "not checked yet" and fill in on the next refresh) —
        # without advancing eviction counters (page loads are not sweeps)
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: mon.warmup(missing, deadline=2.0,
                                     count_failures=False))
        entries = mon.db.entries()
        state = mon.state()
    sections = []
    for url, meta in sorted(entries.items()):
        st = state.get(url, {})
        rows = "".join(
            f"<tr><td>{html.escape(str(n.get('id', '?')))}</td>"
            f"<td>{'🟢 online' if n.get('online') else '🔴 offline'}</td>"
            f"<td>{n.get('requests_served', 0)}</td></tr>"
            for n in st.get("nodes", [])
        )
        status = ("not checked yet" if not st else
                  f"{st.get('online', 0)}/{len(st.get('nodes', []))} online"
                  if st.get("ok") else
                  f"unreachable ({html.escape(str(st.get('error', '')))}), "
                  f"failures {meta.get('failures', 0)}"
                  f"/{mon.failure_threshold}")
        sections.append(
            f"<h3>{html.escape(meta.get('name', url))}</h3>"
            f"<p><code>{html.escape(url)}</code> — {status}</p>"
            f"<table><tr><th>Node</th><th>Status</th><th>Requests</th></tr>"
            f"{rows or '<tr><td colspan=3>no nodes</td></tr>'}</table>"
        )
    doc = f"""<!doctype html><html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="10">
<title>LocalAI-TPU explorer</title>
<style>body{{font:15px system-ui;background:#0f1217;color:#e6e9ee;
margin:2rem auto;max-width:760px}}td,th{{padding:.4rem .6rem;text-align:
left;border-bottom:1px solid #2a3240}}table{{width:100%;border-collapse:
collapse}}code{{color:#8fd0ff}}</style></head><body>
<h2>Federation explorer</h2>
<p style="color:#8b95a5">{len(entries)} network(s) tracked; dial-tested
every {int(request.app['monitor'].interval)}s; evicted after
{request.app['monitor'].failure_threshold} consecutive failures.
Register: <code>POST /api/networks {{"url": "http://router:8080"}}</code></p>
{''.join(sections) or '<p>no networks registered</p>'}
</body></html>"""
    return web.Response(text=doc, content_type="text/html")


async def _api_networks(request: web.Request) -> web.Response:
    mon: DiscoveryMonitor = request.app["monitor"]
    entries = mon.db.entries()
    state = mon.state()
    return web.json_response({
        "networks": [
            {"url": url, **meta, **state.get(url, {})}
            for url, meta in entries.items()
        ]
    })


async def _api_add_network(request: web.Request) -> web.Response:
    mon: DiscoveryMonitor = request.app["monitor"]
    try:
        body = await request.json()
    except ValueError:
        raise web.HTTPBadRequest(text="body must be JSON")
    url = str(body.get("url", "")).strip()
    if not url.startswith(("http://", "https://")):
        raise web.HTTPBadRequest(text="url must be http(s)")
    mon.db.add(url, name=str(body.get("name", "")))
    return web.json_response({"ok": True, "tracked": len(mon.db.routers())})


async def _api_del_network(request: web.Request) -> web.Response:
    mon: DiscoveryMonitor = request.app["monitor"]
    url = request.query.get("url", "")
    if not mon.db.remove(url):
        raise web.HTTPNotFound(text="network not tracked")
    mon.forget(url)
    return web.json_response({"ok": True})


async def _api_nodes(request: web.Request) -> web.Response:
    """Back-compat single-router view (the round-4 explorer surface)."""
    import asyncio

    router = request.app["router_url"]
    try:
        data = await asyncio.get_running_loop().run_in_executor(
            None, fetch_nodes, router)
        return web.json_response(data)
    except Exception as e:  # noqa: BLE001
        return web.json_response({"error": str(e)}, status=502)


def create_explorer_app(router: str = "", *, db_path: Optional[str] = None,
                        interval: float = 50.0, failure_threshold: int = 3,
                        start_monitor: bool = False) -> web.Application:
    db = ExplorerDB(db_path)
    if router:
        db.add(router)
    monitor = DiscoveryMonitor(db, interval=interval,
                               failure_threshold=failure_threshold)
    app = web.Application()
    app["router_url"] = router
    app["monitor"] = monitor
    app.router.add_get("/", _index)
    app.router.add_get("/api/networks", _api_networks)
    app.router.add_post("/api/networks", _api_add_network)
    app.router.add_delete("/api/networks", _api_del_network)
    app.router.add_get("/api/nodes", _api_nodes)
    if start_monitor:
        async def _on_start(_app):
            monitor.start()

        async def _on_stop(_app):
            monitor.stop()

        app.on_startup.append(_on_start)
        app.on_cleanup.append(_on_stop)
    return app


def serve_explorer(router: str, address: str = "0.0.0.0",
                   port: int = 8085, *, db_path: Optional[str] = None,
                   interval: float = 50.0, failure_threshold: int = 3) -> None:
    log.info("explorer on %s:%d over router %s (db=%s)",
             address, port, router, db_path or "<memory>")
    web.run_app(
        create_explorer_app(router, db_path=db_path, interval=interval,
                            failure_threshold=failure_threshold,
                            start_monitor=True),
        host=address, port=port, print=None, access_log=None,
    )
