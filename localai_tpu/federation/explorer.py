"""Explorer: a dashboard over a federation router's node registry.

Parity: /root/reference/core/explorer/ + core/http/views/explorer.html —
the reference's explorer crawls community p2p networks into a discovery
database and serves a dashboard; without a p2p overlay, the TPU-native
explorer points at a federation router (the node registry IS the network)
and renders its nodes with live health/traffic numbers.
"""

from __future__ import annotations

import html
import json
import logging
import urllib.request

from aiohttp import web

log = logging.getLogger(__name__)


def fetch_nodes(router: str, timeout: float = 5.0) -> dict:
    url = f"{router.rstrip('/')}/federated/nodes"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


async def _fetch_nodes_async(request: web.Request) -> dict:
    import asyncio

    # urllib blocks (up to its 5s timeout); keep it off the event loop so
    # a slow router can't freeze the dashboard for other viewers
    return await asyncio.get_running_loop().run_in_executor(
        None, fetch_nodes, request.app["router_url"]
    )


async def _index(request: web.Request) -> web.Response:
    router = request.app["router_url"]
    try:
        data = await _fetch_nodes_async(request)
        err = ""
    except Exception as e:  # noqa: BLE001 — router down renders as such
        data = {"nodes": []}
        err = str(e)
    rows = "".join(
        f"<tr><td>{html.escape(n['id'])}</td>"
        f"<td>{'🟢 online' if n['online'] else '🔴 offline'}</td>"
        f"<td>{n['requests_served']}</td></tr>"
        for n in data.get("nodes", [])
    )
    doc = f"""<!doctype html><html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="5">
<title>LocalAI-TPU explorer</title>
<style>body{{font:15px system-ui;background:#0f1217;color:#e6e9ee;
margin:2rem auto;max-width:760px}}td,th{{padding:.4rem .6rem;text-align:
left;border-bottom:1px solid #2a3240}}table{{width:100%;border-collapse:
collapse}}.err{{color:#d9923b}}</style></head><body>
<h2>Federation explorer</h2>
<p>router: <code>{html.escape(router)}</code>
{f'<span class="err">({html.escape(err)})</span>' if err else ''}</p>
<table><tr><th>Node</th><th>Status</th><th>Requests served</th></tr>
{rows or '<tr><td colspan=3>no nodes registered</td></tr>'}</table>
<p style="color:#8b95a5">auto-refreshes every 5s</p>
</body></html>"""
    return web.Response(text=doc, content_type="text/html")


async def _api(request: web.Request) -> web.Response:
    try:
        return web.json_response(await _fetch_nodes_async(request))
    except Exception as e:  # noqa: BLE001
        return web.json_response({"error": str(e)}, status=502)


def create_explorer_app(router: str) -> web.Application:
    app = web.Application()
    app["router_url"] = router
    app.router.add_get("/", _index)
    app.router.add_get("/api/nodes", _api)
    return app


def serve_explorer(router: str, address: str = "0.0.0.0",
                   port: int = 8085) -> None:
    log.info("explorer on %s:%d over router %s", address, port, router)
    web.run_app(create_explorer_app(router), host=address, port=port,
                print=None, access_log=None)
