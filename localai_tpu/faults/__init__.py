"""Deterministic fault injection + self-healing supervision.

``faults.registry`` is the injection registry: named points in the
serving stack check it (behind a module-global ``ACTIVE`` flag that is
False in production, so a disarmed build pays one attribute load — no
env lookups, no per-dispatch allocation) and fail on purpose when a
matching :class:`FaultSpec` is armed. ``faults.supervisor`` closes the
detect→recover loop the obs subsystem only observes: a watchdog stall
on an engine channel escalates from trace-dump to a bounded, backed-off
engine rebuild, and past the bound the model is marked failed.

Armed via ``LOCALAI_FAULT_*`` environment variables at boot
(:func:`install_from_env`) or the ``/debug/faults`` endpoint at runtime;
``tools/chaos_smoke.py`` drives the full stack through scripted fault
schedules in CI.
"""

from localai_tpu.faults.registry import (  # noqa: F401
    SITES,
    FaultInjected,
    FaultSpec,
    active,
    apply,
    arm,
    clear,
    fire,
    install_from_env,
    parse_spec,
    snapshot,
)
from localai_tpu.faults.supervisor import EngineSupervisor  # noqa: F401
