"""EngineSupervisor: the recover half of the detect→recover loop.

The obs watchdog (PR 3) already turns a wedged engine into a signal — a
``kind="stall"`` forensic trace and an ``engine_stalled`` gauge — but the
engine itself stayed wedged forever behind its trace. This supervisor
subscribes to those stall events and escalates:

  1. **trace** — already done by the watchdog (thread stacks + flight
     snapshot recorded before this code runs);
  2. **rebuild** — :meth:`localai_tpu.engine.scheduler.Scheduler.rebuild`:
     the wedged engine thread is fenced off (epoch bump — it exits
     harmlessly whenever its blocked round-trip returns), every request
     holding engine state finishes ``error`` (the API tier maps that to a
     clean 5xx), the runner re-initializes its device state (fresh KV
     pool / decode state / block tables — compiled programs are kept), a
     probe dispatch verifies the device answers, and a new engine thread
     resumes the still-queued requests;
  3. **backoff** — repeated rebuild attempts are spaced by jittered
     exponential backoff (``LOCALAI_REBUILD_BACKOFF_S`` base, doubled per
     attempt, capped at ``LOCALAI_REBUILD_BACKOFF_CAP_S``);
  4. **failed** — past ``LOCALAI_REBUILD_MAX`` attempts without an
     intervening healthy completion, the model is marked failed:
     everything queued resolves ``error``, ``submit()`` fails fast, and
     ``localai_engine_failed`` latches 1. The manager's dead-engine
     reload path then owns any further recovery.

A healthy completion (``note_healthy``, called by the scheduler when a
request finishes ``stop``/``length``) resets the attempt budget — the
bound is per incident, not per process lifetime.

Speculative-decoding engines are not supervised (the draft pair's device
state cannot be rebuilt independently of the target's); everything else
— contiguous or paged, meshed or single-device — is.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import Optional

from localai_tpu.obs.metrics import REGISTRY, Registry
from localai_tpu.obs.watchdog import StallEvent

log = logging.getLogger(__name__)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class EngineSupervisor:
    """Self-healing policy for one Scheduler: stall → rebuild → failed."""

    def __init__(self, scheduler, *,
                 max_rebuilds: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 backoff_cap_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 registry: Optional[Registry] = None):
        if (scheduler.spec is not None
                and not getattr(scheduler.spec, "supports_rebuild",
                                False)):
            raise ValueError(
                "this speculative engine cannot be supervised (no "
                "reinit() — its draft state is not independently "
                "rebuildable; localai_tpu.spec.SpecEngine is)")
        self.scheduler = scheduler
        self.registry = registry or REGISTRY
        self.model = scheduler.telemetry.model or "engine"
        self.max_rebuilds = int(max_rebuilds
                                if max_rebuilds is not None
                                else _env_float("LOCALAI_REBUILD_MAX", 3))
        self.backoff_s = (backoff_s if backoff_s is not None
                          else _env_float("LOCALAI_REBUILD_BACKOFF_S", 1.0))
        self.backoff_cap_s = (backoff_cap_s if backoff_cap_s is not None
                              else _env_float(
                                  "LOCALAI_REBUILD_BACKOFF_CAP_S", 60.0))
        self.probe_timeout_s = (probe_timeout_s
                                if probe_timeout_s is not None
                                else _env_float(
                                    "LOCALAI_REBUILD_PROBE_TIMEOUT_S", 30.0))
        self.attempts = 0          # rebuild attempts this incident window
        self._channel = scheduler._wd_channel
        self._detached = False
        self._lock = threading.Lock()
        self._recovering = False
        scheduler.supervisor = self
        scheduler.watchdog.on_stall(self._on_event)
        self.registry.engine_failed.set(0, model=self.model)

    # -- watchdog plumbing ------------------------------------------------

    def detach(self) -> None:
        """Stop reacting to stall events (scheduler shutdown). The
        watchdog drops the dead callback via remove_callback."""
        self._detached = True
        self.scheduler.watchdog.remove_callback(self._on_event)

    def _on_event(self, event: StallEvent) -> None:
        if (self._detached or event.kind != "stall"
                or event.channel != self._channel
                or self.scheduler.failed):
            return
        with self._lock:
            if self._recovering:
                return
            self._recovering = True
        # the callback runs on the watchdog's check thread — recovery
        # (backoff sleeps, device probes) gets its own thread so stall
        # detection for other channels never blocks behind it
        threading.Thread(target=self._recover, daemon=True,
                         name=f"engine-rebuild-{self.model}").start()

    def note_healthy(self) -> None:
        """A request completed naturally: the incident (if any) is over,
        the attempt budget refills."""
        if self.attempts:
            self.attempts = 0

    # -- escalation -------------------------------------------------------

    def _backoff(self, attempt: int) -> float:
        """Exponential with ±25% jitter, capped (attempt 1 → base)."""
        base = min(self.backoff_cap_s,
                   self.backoff_s * (2 ** max(0, attempt - 1)))
        return base * (0.75 + 0.5 * random.random())

    def _recover(self) -> None:
        sched = self.scheduler
        try:
            while not self._detached and not sched._stopping:
                self.attempts += 1
                if self.attempts > self.max_rebuilds:
                    log.error(
                        "engine %s: %d rebuild attempts exhausted; "
                        "marking the model failed", self.model,
                        self.max_rebuilds)
                    self.registry.engine_failed.set(1, model=self.model)
                    sched.mark_failed()
                    return
                if self.attempts > 1:
                    delay = self._backoff(self.attempts - 1)
                    log.warning(
                        "engine %s: rebuild attempt %d/%d in %.2fs",
                        self.model, self.attempts, self.max_rebuilds, delay)
                    self._sleep(delay)
                    if self._detached or sched._stopping:
                        return
                try:
                    sched.rebuild(probe_timeout=self.probe_timeout_s)
                except Exception as e:  # noqa: BLE001 — escalate, not die
                    log.warning("engine %s: rebuild attempt %d failed: %s",
                                self.model, self.attempts, e)
                    continue
                self.registry.engine_rebuilds.inc(model=self.model)
                log.warning(
                    "engine %s: rebuilt after stall (attempt %d); probe "
                    "dispatch ok, engine thread restarted", self.model,
                    self.attempts)
                return
        finally:
            with self._lock:
                self._recovering = False

    def _sleep(self, seconds: float) -> None:
        # interruptible-enough: the thread is a daemon and detach() is
        # checked after; a plain sleep keeps the policy dependency-free
        import time

        time.sleep(seconds)

    def status(self) -> dict:
        with self._lock:
            recovering = self._recovering
        return {
            "model": self.model,
            "attempts": self.attempts,
            "max_rebuilds": self.max_rebuilds,
            "rebuilds": self.scheduler.rebuilds,
            "failed": self.scheduler.failed,
            "recovering": recovering,
        }
