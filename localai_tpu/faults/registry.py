"""Fault-injection registry: named failure points with trigger predicates.

The failure classes that take real deployments down are exactly the ones
ordinary tests never exercise: a device dispatch raising mid-decode, one
slot's logits going NaN, the block pool running dry under load, a worker
stream dying or hanging half-way, a replica that answers but slowly, a
respawn that keeps failing. This module lets those failures be *scheduled*
— deterministically, per injection point, with a trigger predicate
("the Nth matching hit, M times, when the key contains X") — so the
recovery paths (engine rebuild, slot quarantine, failover, respawn
backoff) run on every CI pass instead of only in production incidents.

Zero overhead when disarmed
---------------------------
Injection sites gate on the module-global :data:`ACTIVE` boolean::

    if _faults.ACTIVE:
        _faults.apply("engine.dispatch", key=program)

``ACTIVE`` is ``False`` unless at least one spec is armed, so a
production dispatch pays one attribute load and a predictable branch —
no environment lookups, no function call, nothing allocated. Arming and
clearing maintain the flag; it is never consulted with a lock held.

Arming
------
* programmatically: ``arm(FaultSpec(site="engine.drain", mode="hang",
  delay_s=3.0, after=2, times=1))``
* environment (parsed once at boot by :func:`install_from_env`):
  ``LOCALAI_FAULT_ENGINE_DRAIN="mode=hang,delay_s=3.0,after=2,times=1"``
  (the site's dots become underscores, uppercased)
* at runtime: ``POST /debug/faults`` (api/debug.py) with the same fields.

Trigger predicate: a spec matches a ``fire(site, key)`` call when the
site equals and ``match`` (if set) is a substring of ``key``; the first
``after`` matching hits are skipped, then the spec fires at most
``times`` times (0 = unlimited). Hit/fire counts are recorded on the
spec (``snapshot()`` shows them) and in the
``localai_faults_injected_total{site}`` counter, so a chaos run can
assert its schedule actually executed.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)

# the documented injection points (a spec for an unknown site is refused:
# a typo'd chaos schedule must fail loudly, not silently never fire)
SITES = {
    "engine.dispatch": "scheduler engine loop, before a decode dispatch "
                       "(key: program label). raise = device dispatch "
                       "error; hang/sleep = slow dispatch.",
    "engine.drain": "inside the watchdog-guarded drain of an in-flight "
                    "dispatch (key: engine watchdog channel). hang = "
                    "wedged device round-trip (trips the stall watchdog "
                    "and the self-healing supervisor).",
    "engine.compile": "first dispatch of a program shape (key: program "
                      "label). raise = XLA compile failure.",
    "decode.nan": "poison one active slot's logits with NaN before the "
                  "next dispatch (key: the request's correlation/trace "
                  "id) — exercises the per-row NaN guard.",
    "paged.allocate": "BlockAllocator.allocate (key: seq/slot id). "
                      "exhaust = report the pool full.",
    "spec.draft": "speculative drafter proposals (key: drafter name). "
                  "garble (any non-raise mode) = replace every proposal "
                  "with divergent garbage tokens (acceptance collapses; "
                  "rollback + co-batched streams must stay byte-"
                  "correct); raise = drafter failure mid-window.",
    "worker.stream": "per-reply inside PredictStream, worker gRPC and "
                     "in-process replicas alike (key: model/replica id). "
                     "raise = stream dies mid-flight; sleep = slow "
                     "replica.",
    "fleet.respawn": "fleet replica respawn attempt (key: replica id). "
                     "raise = respawn fails (exercises backoff).",
    "fleet.dial": "replica health dial, every replica kind (key: replica "
                  "id). raise = peer unreachable/refused — a network "
                  "partition as the monitor sees it (exercises eviction "
                  "and redial backoff).",
    "fleet.transport": "per-message on the cross-replica stream pump "
                       "(fleet.net.bounded_stream; key: replica id). "
                       "raise = connection reset mid-stream (partition "
                       "under traffic); sleep = slow link — delay_s past "
                       "LOCALAI_FLEET_RPC_TIMEOUT_S trips the dispatch "
                       "deadline.",
    "fleet.sibling": "inside the directory-driven sibling prefix fetch "
                     "(FleetScheduler._sibling_fetch; key: the DONOR "
                     "replica id). raise = donor dies mid-TransferPrefix "
                     "— the fetch must fall back to a plain re-prefill "
                     "and drop the stale directory entry, never fail "
                     "the request.",
}

# module-global fast gate: hot paths read this one attribute and skip the
# registry entirely while nothing is armed
ACTIVE = False


class FaultInjected(RuntimeError):
    """Raised by ``mode="raise"`` faults at their injection point."""

    def __init__(self, site: str, key: str = ""):
        super().__init__(f"injected fault at {site}"
                         + (f" (key={key!r})" if key else ""))
        self.site = site
        self.key = key


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: where, what, and when it triggers."""

    site: str
    mode: str = "raise"      # raise | hang | sleep | exhaust | nan
    after: int = 0           # matching hits to skip before firing
    times: int = 1           # max fires (0 = unlimited)
    match: str = ""          # substring predicate on the site's key
    delay_s: float = 0.0     # hang/sleep duration
    hits: int = 0            # matching hits seen (skipped ones included)
    fired: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FaultRegistry:
    """Armed specs + the fire predicate. One process-wide instance."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._specs: list[FaultSpec] = []

    def arm(self, spec: FaultSpec) -> FaultSpec:
        if spec.site not in SITES:
            raise ValueError(
                f"unknown fault site {spec.site!r}; have {sorted(SITES)}")
        if spec.after < 0 or spec.times < 0 or spec.delay_s < 0:
            raise ValueError("after/times/delay_s must be >= 0")
        with self._lock:
            self._specs.append(spec)
        _set_active(True)
        log.warning("fault armed: %s", spec.to_dict())
        return spec

    def clear(self, site: Optional[str] = None) -> int:
        with self._lock:
            before = len(self._specs)
            self._specs = [s for s in self._specs
                           if site is not None and s.site != site]
            remaining = len(self._specs)
        _set_active(remaining > 0)
        return before - remaining

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._specs]

    def fire(self, site: str, key: str = "") -> Optional[FaultSpec]:
        """The trigger predicate: returns the first armed spec for
        ``site`` that matches ``key`` and is due (past ``after``, under
        ``times``), advancing its counters — else None. Exhausted specs
        stay listed (their counts are the chaos run's receipt)."""
        with self._lock:
            for spec in self._specs:
                if spec.site != site:
                    continue
                if spec.match and spec.match not in key:
                    continue
                spec.hits += 1
                if spec.hits <= spec.after:
                    continue
                if spec.times and spec.fired >= spec.times:
                    continue
                spec.fired += 1
                winner = spec
                break
            else:
                return None
        # counter import is lazy so this leaf module stays importable
        # before the obs registry (and the metric cost is fire-time only)
        from localai_tpu.obs.metrics import REGISTRY

        REGISTRY.faults_injected.inc(site=site)
        log.warning("fault fired: site=%s key=%r mode=%s (fire %d)",
                    site, key, winner.mode, winner.fired)
        return winner


REGISTRY = FaultRegistry()


def _set_active(value: bool) -> None:
    global ACTIVE
    ACTIVE = value


def active() -> bool:
    """Current gate value. Injection sites read the module global
    directly (one attribute load); package-level consumers must call
    this — a ``from faults import ACTIVE`` would freeze the boot-time
    value."""
    return ACTIVE


# -- module-level convenience surface (what injection sites call) ---------

def arm(spec: FaultSpec) -> FaultSpec:
    return REGISTRY.arm(spec)


def clear(site: Optional[str] = None) -> int:
    return REGISTRY.clear(site)


def snapshot() -> list[dict]:
    return REGISTRY.snapshot()


def fire(site: str, key: str = "") -> Optional[FaultSpec]:
    return REGISTRY.fire(site, key)


def apply(site: str, key: str = "") -> Optional[FaultSpec]:
    """Fire-and-interpret for the common modes: ``raise`` raises
    :class:`FaultInjected` at the call site, ``hang``/``sleep`` block for
    ``delay_s`` (outside the registry lock) and return the spec; other
    modes (``exhaust``, ``nan``) are returned for the site to interpret.
    Returns None when nothing fired."""
    spec = REGISTRY.fire(site, key)
    if spec is None:
        return None
    if spec.mode == "raise":
        raise FaultInjected(site, key)
    if spec.mode in ("hang", "sleep") and spec.delay_s > 0:
        time.sleep(spec.delay_s)
    return spec


def parse_spec(site: str, text: str) -> FaultSpec:
    """``"mode=hang,delay_s=3.0,after=2,times=1,match=decode"`` →
    FaultSpec (the LOCALAI_FAULT_* / POST /debug/faults value grammar)."""
    kw: dict = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad fault field {part!r} (want key=value)")
        k, v = part.split("=", 1)
        k = k.strip()
        v = v.strip()
        if k in ("after", "times"):
            kw[k] = int(v)
        elif k == "delay_s":
            kw[k] = float(v)
        elif k in ("mode", "match"):
            kw[k] = v
        else:
            raise ValueError(f"unknown fault field {k!r}")
    return FaultSpec(site=site, **kw)


def install_from_env(environ=None) -> int:
    """Parse every ``LOCALAI_FAULT_<SITE>`` variable (dots in the site
    name written as underscores) and arm the specs. Called once at
    server/worker boot — never on a request path. Returns specs armed."""
    env = os.environ if environ is None else environ
    sites_by_env = {s.replace(".", "_").upper(): s for s in SITES}
    armed = 0
    for name, value in env.items():
        if not name.startswith("LOCALAI_FAULT_") or not value:
            continue
        suffix = name[len("LOCALAI_FAULT_"):]
        site = sites_by_env.get(suffix)
        if site is None:
            log.warning("ignoring %s: no injection site matches", name)
            continue
        try:
            arm(parse_spec(site, value))
            armed += 1
        except ValueError as e:
            log.warning("ignoring %s=%r: %s", name, value, e)
    return armed
