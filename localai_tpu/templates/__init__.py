"""Prompt templating: Go-template-compatible Jinja2 evaluation.

Replaces /root/reference/pkg/templates + pkg/model/template.go (Go
text/template + sprig) with Jinja2 plus a Go-template transpiler so the
reference's gallery templates keep working unmodified.
"""

from localai_tpu.templates.cache import TemplateCache, TemplateType
from localai_tpu.templates.chat import (
    apply_tokenizer_template,
    build_chat_prompt,
    build_completion_prompt,
    build_edit_prompt,
    multimodal_placeholders,
)
from localai_tpu.templates.gotmpl import go_template_to_jinja

__all__ = [
    "TemplateCache",
    "TemplateType",
    "apply_tokenizer_template",
    "build_chat_prompt",
    "build_completion_prompt",
    "build_edit_prompt",
    "go_template_to_jinja",
    "multimodal_placeholders",
]
