"""Chat prompt construction: messages + model config → prompt string.

Parity: the ChatEndpoint templating loop
(/root/reference/core/http/endpoints/openai/chat.go:296-441):
  * role remapping via config.roles (incl. assistant_function_call),
  * per-message chat_message template (ChatMessageTemplateData fields),
  * fallback role-prefix formatting with JSON-marshalled tool calls,
  * system-prompt suppression when the request carries its own system msg,
  * join by config character, then the chat/completion/functions prompt
    template (PromptTemplateData fields),
plus the tokenizer chat-template mode (UseTokenizerTemplate — the vLLM
backend path, backend/python/vllm/backend.py) and the multimodal placeholder
builder (pkg/templates/multimodal.go).
"""

from __future__ import annotations

import json
from typing import Any, Optional, Sequence

from localai_tpu.config.model_config import ModelConfig
from localai_tpu.templates.cache import TemplateCache, TemplateType

DEFAULT_MULTIMODAL = (
    "{{ range .Audio }}[audio-{{.ID}}]{{end}}"
    "{{ range .Images }}[img-{{.ID}}]{{end}}"
    "{{ range .Video }}[vid-{{.ID}}]{{end}}"
    "{{.Text}}"
)


def _compact_json(v: Any) -> str:
    return json.dumps(v, separators=(",", ":"), ensure_ascii=False)


def build_chat_prompt(
    cache: TemplateCache,
    config: ModelConfig,
    messages: Sequence[dict[str, Any]],
    *,
    functions: Optional[list[dict]] = None,
    use_function_template: bool = False,
    grammar_active: bool = False,
) -> str:
    """Render a /v1/chat/completions message list into the model prompt."""
    tpl = config.template
    suppress_system = False
    parts: list[str] = []

    for idx, msg in enumerate(messages):
        role = msg.get("role", "user")
        content_value = msg.get("content")
        string_content = _string_content(content_value)
        fcall = msg.get("function_call")
        if msg.get("tool_calls"):
            fcall = msg["tool_calls"]

        # assistant_function_call role override (chat.go:305-312)
        if fcall is not None and role == "assistant":
            if config.roles.get("assistant_function_call"):
                role = "assistant_function_call"
        r = config.roles.get(role, "")
        content_exists = bool(string_content)

        content = ""
        if tpl.chat_message:
            data = {
                "SystemPrompt": config.system_prompt,
                "Role": r,
                "RoleName": role,
                "Content": string_content,
                "FunctionCall": fcall,
                "FunctionName": msg.get("name", ""),
                "LastMessage": idx == len(messages) - 1,
                "Function": grammar_active and idx == len(messages) - 1,
                "MessageIndex": idx,
            }
            try:
                content = cache.evaluate(
                    TemplateType.CHAT_MESSAGE, tpl.chat_message, data
                )
            except Exception:  # noqa: BLE001 — template errors skip to fallback
                content = ""
            if tpl.chat_message and content == "":
                # blank template output skips the message entirely
                # (chat.go:338-341)
                continue

        if content == "":
            # fallback formatting (chat.go:347-397)
            if r:
                if content_exists:
                    content = f"{r}{string_content}"
                if fcall is not None:
                    j = _compact_json(fcall)
                    content = (
                        f"{content}\n{r} {j}" if content_exists else f"{r} {j}"
                    )
            else:
                if content_exists:
                    content = string_content
                if fcall is not None:
                    j = _compact_json(fcall)
                    content = f"{content}\n{j}" if content_exists else j
            if content_exists and role == "system":
                suppress_system = True

        parts.append(content)

    join_char = (
        tpl.join_chat_messages_by_character
        if tpl.join_chat_messages_by_character is not None
        else "\n"
    )
    pred_input = join_char.join(parts)

    # outer prompt template selection (chat.go:407-425)
    template_name = ""
    if config.model and cache.exists_file(config.model):
        template_name = config.model
    if tpl.chat and not use_function_template:
        template_name = tpl.chat
    if tpl.functions and use_function_template:
        template_name = tpl.functions

    if template_name:
        try:
            pred_input = cache.evaluate(
                TemplateType.CHAT, template_name, {
                    "SystemPrompt": config.system_prompt,
                    "SuppressSystemPrompt": suppress_system,
                    "Input": pred_input,
                    "Functions": functions or [],
                },
            )
        except Exception:  # noqa: BLE001 — failed template leaves input as-is
            pass
    return pred_input


def build_completion_prompt(
    cache: TemplateCache, config: ModelConfig, prompt: str
) -> str:
    """Parity: CompletionEndpoint templating
    (/root/reference/core/http/endpoints/openai/completion.go:100-125)."""
    name = config.template.completion or (
        config.model if config.model and cache.exists_file(config.model) else ""
    )
    if not name:
        return prompt
    try:
        return cache.evaluate(TemplateType.COMPLETION, name, {
            "SystemPrompt": config.system_prompt,
            "Input": prompt,
        })
    except Exception:  # noqa: BLE001
        return prompt


def build_edit_prompt(
    cache: TemplateCache, config: ModelConfig, input_text: str, instruction: str
) -> str:
    """Parity: EditEndpoint templating
    (/root/reference/core/http/endpoints/openai/edit.go:45-60)."""
    name = config.template.edit or (
        config.model if config.model and cache.exists_file(config.model) else ""
    )
    if not name:
        return f"{instruction}\n\n{input_text}"
    try:
        return cache.evaluate(TemplateType.EDIT, name, {
            "SystemPrompt": config.system_prompt,
            "Input": input_text,
            "Instruction": instruction,
        })
    except Exception:  # noqa: BLE001
        return f"{instruction}\n\n{input_text}"


def apply_tokenizer_template(
    tokenizer: Any,
    messages: Sequence[dict[str, Any]],
    *,
    add_generation_prompt: bool = True,
    chat_template: Optional[str] = None,
) -> str:
    """UseTokenizerTemplate mode: render with the tokenizer's own chat
    template (the HF-ecosystem format; parity with the vLLM backend's
    tokenizer-template path, backend/python/vllm/backend.py)."""
    inner = getattr(tokenizer, "_tok", None) or tokenizer
    apply = getattr(inner, "apply_chat_template", None)
    if apply is not None:
        return apply(
            list(messages),
            tokenize=False,
            add_generation_prompt=add_generation_prompt,
            chat_template=chat_template,
        )
    if chat_template is None:
        raise ValueError(
            "tokenizer has no chat template; set template.chat_template or "
            "use prompt templates"
        )
    from localai_tpu.templates.gotmpl import make_environment

    env = make_environment()
    return env.from_string(chat_template).render(
        messages=list(messages),
        add_generation_prompt=add_generation_prompt,
        bos_token="", eos_token="",
    )


def multimodal_placeholders(
    template: str,
    text: str,
    *,
    n_images: int = 0,
    n_audio: int = 0,
    n_video: int = 0,
    first_image_id: int = 0,
    first_video_id: int = 0,
) -> str:
    """Parity: TemplateMultiModal (/root/reference/pkg/templates/
    multimodal.go) — inject [img-N]/[audio-N]/[vid-N] placeholders.
    ``first_image_id`` offsets the IDs so multi-message requests keep one
    global image numbering (chat.go totalImages counter)."""
    from localai_tpu.templates.gotmpl import (
        go_template_to_jinja,
        looks_like_go_template,
        make_environment,
    )

    src = template or DEFAULT_MULTIMODAL
    if looks_like_go_template(src):
        src = go_template_to_jinja(src)
    env = make_environment()
    return env.from_string(src).render(
        Text=text,
        Images=[{"ID": first_image_id + i} for i in range(n_images)],
        Audio=[{"ID": i} for i in range(n_audio)],
        Video=[{"ID": first_video_id + i} for i in range(n_video)],
    )


def _string_content(content: Any) -> str:
    """Flatten OpenAI string-or-multipart message content
    (parity: schema.Message.StringContent handling,
    /root/reference/core/schema/openai.go:69+)."""
    if content is None:
        return ""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        texts = [
            p.get("text", "") for p in content
            if isinstance(p, dict) and p.get("type") == "text"
        ]
        return "".join(texts)
    return str(content)
