"""Go text/template → Jinja2 conversion.

The reference ecosystem's prompt templates (gallery configs, model YAMLs,
`.tmpl` files) are Go text/template with sprig functions
(/root/reference/pkg/templates/cache.go:97). This framework evaluates
templates with Jinja2 (the HF-native engine — required anyway for tokenizer
chat templates), so reference templates are transpiled on load.

Supported subset — everything observed in the reference's gallery/fixtures
(/root/reference/pkg/model/template_test.go, gallery/*.yaml):
  actions:    {{.Field}}, {{.}}, {{if EXPR}}, {{else if EXPR}}, {{else}},
              {{end}}, {{range .X}}, whitespace trim markers {{- and -}}
  exprs:      eq/ne/gt/ge/lt/le A B, and/or/not, nested field paths,
              string/number literals, bare truthiness
  functions:  toJson, trim, upper, lower, title, default (as filters or
              call-style), pipelines A | f
"""

from __future__ import annotations

import json
import re
from typing import Any

import jinja2

_ACTION = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)
_CMPS = {"eq": "==", "ne": "!=", "gt": ">", "ge": ">=", "lt": "<", "le": "<="}
_FUNCS = {"toJson", "trim", "upper", "lower", "title", "default", "join"}


def _tok_to_jinja(tok: str, in_range: bool) -> str:
    """One expression atom: field path, literal, or keyword."""
    if tok.startswith('"') or tok.startswith("'"):
        return tok
    if re.fullmatch(r"-?\d+(\.\d+)?", tok):
        return tok
    if tok == ".":
        return "_it" if in_range else "_data"
    if tok.startswith("."):
        path = tok[1:]
        return f"_it.{path}" if in_range else path
    if tok.startswith("$."):  # $ = root context
        return tok[2:]
    return tok  # bare identifier (function name, true/false, ...)


def _split_args(expr: str) -> list[str]:
    """Split on whitespace, respecting quoted strings and parens."""
    out, cur, depth, q = [], "", 0, None
    for ch in expr:
        if q:
            cur += ch
            if ch == q:
                q = None
            continue
        if ch in "\"'":
            q = ch
            cur += ch
        elif ch == "(":
            depth += 1
            cur += ch
        elif ch == ")":
            depth -= 1
            cur += ch
        elif ch.isspace() and depth == 0:
            if cur:
                out.append(cur)
                cur = ""
        else:
            cur += ch
    if cur:
        out.append(cur)
    return out


def _expr_to_jinja(expr: str, in_range: bool) -> str:
    """Convert a Go template expression (prefix calls, pipelines)."""
    # pipelines: A | f | g  → f/g become jinja filters
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    toks = _split_args(head)
    out = _head_to_jinja(toks, in_range)
    for f in parts[1:]:
        out = f"({out}) | {f}"
    return out


def _head_to_jinja(toks: list[str], in_range: bool) -> str:
    if not toks:
        return '""'
    op = toks[0]
    if op in _CMPS and len(toks) >= 3:
        a = _tok_to_jinja(toks[1], in_range)
        b = _tok_to_jinja(toks[2], in_range)
        return f"{a} {_CMPS[op]} {b}"
    if op in ("and", "or") and len(toks) >= 3:
        args = [_head_to_jinja([t], in_range) if not t.startswith("(")
                else _expr_to_jinja(t[1:-1], in_range) for t in toks[1:]]
        return "(" + f" {op} ".join(args) + ")"
    if op == "not" and len(toks) >= 2:
        return f"not ({_head_to_jinja(toks[1:], in_range)})"
    if op in _FUNCS and len(toks) >= 2:
        # call-style function: toJson .X → X | toJson
        args = [_tok_to_jinja(t, in_range) for t in toks[1:]]
        if len(args) == 1:
            return f"{args[0]} | {op}"
        return f"{args[-1]} | {op}({', '.join(args[:-1])})"
    if len(toks) == 1:
        return _tok_to_jinja(op, in_range)
    # unknown function call: render args positionally
    args = ", ".join(_tok_to_jinja(t, in_range) for t in toks[1:])
    return f"{op}({args})"


def go_template_to_jinja(src: str) -> str:
    """Transpile Go template source to Jinja2 source."""
    out: list[str] = []
    stack: list[str] = []  # 'if' | 'for'
    pos = 0
    for m in _ACTION.finditer(src):
        out.append(src[pos:m.start()])
        pos = m.end()
        ltrim = "-" if m.group(1) else ""
        rtrim = "-" if m.group(3) else ""
        body = m.group(2).strip()
        in_range = "for" in stack

        if body.startswith("if "):
            stack.append("if")
            cond = _expr_to_jinja(body[3:].strip(), in_range)
            out.append(f"{{%{ltrim} if {cond} {rtrim}%}}")
        elif body.startswith("else if "):
            cond = _expr_to_jinja(body[8:].strip(), in_range)
            out.append(f"{{%{ltrim} elif {cond} {rtrim}%}}")
        elif body == "else":
            out.append(f"{{%{ltrim} else {rtrim}%}}")
        elif body == "end":
            kind = stack.pop() if stack else "if"
            tag = "endfor" if kind == "for" else "endif"
            out.append(f"{{%{ltrim} {tag} {rtrim}%}}")
        elif body.startswith("range "):
            stack.append("for")
            coll = _expr_to_jinja(body[6:].strip(), in_range)
            out.append(f"{{%{ltrim} for _it in {coll} {rtrim}%}}")
        elif body.startswith("/*") or body.startswith("comment"):
            pass  # comments drop
        else:
            expr = _expr_to_jinja(body, in_range)
            out.append(f"{{{{{ltrim} {expr} {rtrim}}}}}")
    out.append(src[pos:])
    return "".join(out)


def _filter_tojson(v: Any) -> str:
    # Go json.Marshal formatting: compact separators, no HTML escaping of
    # non-ASCII (template_test.go expects {"function":"test"})
    return json.dumps(v, separators=(",", ":"), ensure_ascii=False)


def make_environment() -> jinja2.Environment:
    """Jinja2 environment matching Go template semantics closely enough:
    missing fields render empty and are falsy (Go renders '<no value>' but
    templates in the wild guard with ifs)."""
    env = jinja2.Environment(
        undefined=jinja2.ChainableUndefined,
        keep_trailing_newline=True,
        trim_blocks=False,
        lstrip_blocks=False,
    )
    env.filters["toJson"] = _filter_tojson
    env.filters["tojson"] = _filter_tojson
    env.filters["trim"] = lambda s: str(s).strip()
    env.filters["title"] = lambda s: str(s).title()
    env.filters["default"] = lambda v, d="": d if not v else v
    return env


def looks_like_go_template(src: str) -> bool:
    """Heuristic: Go templates address fields as {{.Field}} and use
    {{if}}/{{range}}/{{end}} actions; Jinja2 uses {% %} blocks."""
    if "{%" in src:
        return False
    return bool(
        re.search(r"\{\{-?\s*(\.|if\s|else|range\s|end\s*-?\}\}|toJson\s)", src)
    )
