"""Template cache keyed by (type, name).

Parity: /root/reference/pkg/templates/cache.go — a template name resolves to
``<name>.tmpl`` (or ``.jinja``/``.j2``) in the templates dir; if no such file
exists the name string ITSELF is the template (gallery configs embed template
bodies inline, cache.go:85-94). Go-template sources are transpiled to Jinja2
on load (see gotmpl.py); path traversal outside the templates dir is rejected
(cache.go:81-83).
"""

from __future__ import annotations

import enum
import threading
from pathlib import Path
from typing import Any, Optional

import jinja2

from localai_tpu.templates.gotmpl import (
    go_template_to_jinja,
    looks_like_go_template,
    make_environment,
)
from localai_tpu.utils.paths import verify_path


class TemplateType(enum.Enum):
    """Parity: the TemplateType enum (/root/reference/pkg/model/template.go:
    34-40) + multimodal (pkg/templates/multimodal.go)."""

    CHAT = "chat"
    CHAT_MESSAGE = "chat_message"
    COMPLETION = "completion"
    EDIT = "edit"
    FUNCTIONS = "functions"
    MULTIMODAL = "multimodal"


class TemplateCache:
    def __init__(self, templates_path: str | Path):
        self.templates_path = Path(templates_path)
        self._env = make_environment()
        self._cache: dict[tuple[TemplateType, str], jinja2.Template] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _could_be_filename(name: str) -> bool:
        return "\n" not in name and "{{" not in name and len(name) < 200

    def _load(self, name: str) -> jinja2.Template:
        src: Optional[str] = None
        if self._could_be_filename(name):
            for suffix in (".tmpl", ".jinja", ".j2"):
                fname = name + suffix
                cand = self.templates_path / fname
                try:
                    found = cand.exists()
                except OSError:
                    found = False
                if found:
                    verify_path(fname, self.templates_path)
                    src = cand.read_text()
                    break
        if src is None:
            src = name  # inline template body (cache.go:92-93)
        if looks_like_go_template(src):
            src = go_template_to_jinja(src)
        return self._env.from_string(src)

    def evaluate(
        self, ttype: TemplateType, name: str, data: dict[str, Any]
    ) -> str:
        if not name:
            return ""
        key = (ttype, name)
        with self._lock:
            tmpl = self._cache.get(key)
            if tmpl is None:
                tmpl = self._load(name)
                self._cache[key] = tmpl
        # _data/_it support bare {{.}} refs from transpiled Go templates
        return tmpl.render(**data, _data=data)

    def exists_file(self, name: str) -> bool:
        if not self._could_be_filename(name):
            return False
        try:
            return any(
                (self.templates_path / (name + s)).exists()
                for s in (".tmpl", ".jinja", ".j2")
            )
        except OSError:
            return False
