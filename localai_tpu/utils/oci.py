"""OCI registry v2 client: ollama model pulls and OCI image extraction.

Parity: /root/reference/pkg/oci/{ollama,image,blob}.go — the reference
uses containerd + go-containerregistry; this is a dependency-free
implementation of the small slice of the distribution spec those paths
actually use: anonymous Bearer token auth, manifest fetch (including
manifest lists), digest-verified blob download, the ollama model-layer
convention (mediaType containing "model"), and tar-layer extraction with
a path traversal guard.
"""

from __future__ import annotations

import hashlib
import json
import logging
import tarfile
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

log = logging.getLogger(__name__)

ProgressFn = Callable[[int, int], None]

MANIFEST_TYPES = (
    "application/vnd.oci.image.manifest.v1+json",
    "application/vnd.docker.distribution.manifest.v2+json",
)
INDEX_TYPES = (
    "application/vnd.oci.image.index.v1+json",
    "application/vnd.docker.distribution.manifest.list.v2+json",
)


@dataclass
class ImageRef:
    """registry/repository:tag(@digest) parsed per the docker reference
    grammar (parity: ParseImageParts, pkg/oci/image.go)."""

    registry: str
    repository: str
    reference: str  # tag or sha256:... digest
    scheme: str = "https"

    @property
    def base(self) -> str:
        return f"{self.scheme}://{self.registry}/v2/{self.repository}"


def parse_image_ref(image: str, *, default_registry: str = "docker.io",
                    default_tag: str = "latest") -> ImageRef:
    """'gemma:2b' → registry.ollama.ai/library/gemma:2b style defaulting;
    full refs like 'quay.io/org/repo:tag' and digests pass through."""
    scheme = "https"
    if image.startswith("http://"):     # tests / local registries
        scheme, image = "http", image[len("http://"):]
    elif image.startswith("https://"):
        image = image[len("https://"):]
    digest = ""
    if "@" in image:
        image, digest = image.split("@", 1)
    head, _, rest = image.partition("/")
    if rest and ("." in head or ":" in head or head == "localhost"):
        registry, path = head, rest
    else:
        registry, path = default_registry, image
    if registry == "docker.io":
        registry = "registry-1.docker.io"
    tag = default_tag
    if ":" in path.rsplit("/", 1)[-1]:
        path, tag = path.rsplit(":", 1)
    if "/" not in path:
        path = f"library/{path}"
    return ImageRef(registry, path, digest or tag, scheme=scheme)


class RegistryClient:
    """Minimal distribution-spec client with anonymous token auth."""

    def __init__(self, ref: ImageRef, timeout: float = 60.0):
        import requests

        self.ref = ref
        self.timeout = timeout
        self._session = requests.Session()
        self._token: Optional[str] = None

    def _get(self, url: str, headers: Optional[dict] = None, *,
             stream: bool = False):
        h = dict(headers or {})
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        resp = self._session.get(url, headers=h, stream=stream,
                                 timeout=self.timeout)
        if resp.status_code == 401 and self._token is None:
            self._authenticate(resp.headers.get("WWW-Authenticate", ""))
            if self._token:
                h["Authorization"] = f"Bearer {self._token}"
                resp = self._session.get(url, headers=h, stream=stream,
                                         timeout=self.timeout)
        resp.raise_for_status()
        return resp

    def _authenticate(self, challenge: str) -> None:
        """Bearer realm="…",service="…"(,scope="…") → anonymous token
        (parity: the transport go-containerregistry sets up)."""
        if not challenge.startswith("Bearer "):
            return
        fields = {}
        for part in challenge[len("Bearer "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v.strip('"')
        realm = fields.get("realm")
        if not realm:
            return
        params = {
            "service": fields.get("service", ""),
            "scope": fields.get(
                "scope", f"repository:{self.ref.repository}:pull"
            ),
        }
        resp = self._session.get(realm, params=params, timeout=self.timeout)
        resp.raise_for_status()
        body = resp.json()
        self._token = body.get("token") or body.get("access_token")

    # -- manifests ---------------------------------------------------------

    def manifest(self, reference: Optional[str] = None) -> dict:
        """Fetch and (for indexes) resolve to a concrete image manifest —
        linux/amd64 preferred, else the first entry."""
        ref = reference or self.ref.reference
        resp = self._get(
            f"{self.ref.base}/manifests/{ref}",
            headers={"Accept": ", ".join(MANIFEST_TYPES + INDEX_TYPES)},
        )
        m = resp.json()
        mtype = m.get("mediaType", "")
        if mtype in INDEX_TYPES or "manifests" in m and "layers" not in m:
            entries = m.get("manifests", [])
            if not entries:
                raise ValueError("empty manifest index")
            chosen = next(
                (e for e in entries
                 if (e.get("platform") or {}).get("os") == "linux"
                 and (e.get("platform") or {}).get("architecture")
                 == "amd64"),
                entries[0],
            )
            return self.manifest(chosen["digest"])
        return m

    # -- blobs -------------------------------------------------------------

    def fetch_blob(self, digest: str, dest: str | Path,
                   progress: Optional[ProgressFn] = None,
                   expected_size: int = 0) -> Path:
        """Stream a blob to dest, verifying the sha256 digest (parity:
        FetchImageBlob, pkg/oci/blob.go:15)."""
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        algo, _, want = digest.partition(":")
        if algo != "sha256":
            raise ValueError(f"unsupported digest algorithm {algo!r}")
        h = hashlib.sha256()
        done = 0
        partial = dest.with_suffix(dest.suffix + ".partial")
        resp = self._get(f"{self.ref.base}/blobs/{digest}", stream=True)
        total = int(resp.headers.get("Content-Length") or expected_size)
        with open(partial, "wb") as f:
            for chunk in resp.iter_content(1 << 20):
                f.write(chunk)
                h.update(chunk)
                done += len(chunk)
                if progress:
                    progress(done, total)
        if h.hexdigest() != want:
            partial.unlink(missing_ok=True)
            raise ValueError(
                f"digest mismatch for {digest}: got sha256:{h.hexdigest()}"
            )
        partial.replace(dest)
        return dest


def ollama_fetch_model(image: str, dest: str | Path,
                       progress: Optional[ProgressFn] = None) -> Path:
    """ollama://gemma:2b → download the model layer (the GGUF weights) to
    ``dest`` (parity: OllamaFetchModel, pkg/oci/ollama.go:79 — the layer
    whose mediaType contains "model")."""
    ref = parse_image_ref(image, default_registry="registry.ollama.ai")
    client = RegistryClient(ref)
    manifest = client.manifest()
    layer = next(
        (l for l in manifest.get("layers", [])
         if "model" in l.get("mediaType", "")),
        None,
    )
    if layer is None:
        raise ValueError(f"no model layer in ollama manifest for {image}")
    return client.fetch_blob(
        layer["digest"], dest, progress,
        expected_size=layer.get("size", 0),
    )


def _safe_extract_tar(tf: tarfile.TarFile, dest: Path) -> None:
    """Extract with a traversal guard (parity: the reference relies on
    containerd's archive code; VerifyPath is our equivalent contract)."""
    from localai_tpu.utils.paths import verify_path

    for member in tf.getmembers():
        if member.issym() or member.islnk():
            # links could point outside the tree; models don't need them
            log.warning("skipping link %s in layer tar", member.name)
            continue
        verify_path(member.name, dest)  # raises on ../ escapes
        tf.extract(member, dest)


def oci_extract_image(image: str, dest_dir: str | Path,
                      progress: Optional[ProgressFn] = None) -> Path:
    """oci://registry/repo:tag → pull all layers and extract them in order
    into ``dest_dir`` (parity: GetImage + ExtractOCIImage,
    pkg/oci/image.go — uri.go:226-232 extracts into the target's dir)."""
    import gzip
    import shutil
    import tempfile

    ref = parse_image_ref(image)
    client = RegistryClient(ref)
    manifest = client.manifest()
    dest_dir = Path(dest_dir)
    dest_dir.mkdir(parents=True, exist_ok=True)
    for layer in manifest.get("layers", []):
        digest = layer["digest"]
        with tempfile.NamedTemporaryFile(suffix=".layer",
                                         delete=False) as tmp:
            tmp_path = Path(tmp.name)
        raw = tmp_path
        try:
            client.fetch_blob(digest, tmp_path, progress,
                              expected_size=layer.get("size", 0))
            if layer.get("mediaType", "").endswith("gzip"):
                raw = tmp_path.with_suffix(".tar")
                with gzip.open(tmp_path, "rb") as src, \
                        open(raw, "wb") as out:
                    shutil.copyfileobj(src, out)
            with tarfile.open(raw) as tf:
                _safe_extract_tar(tf, dest_dir)
        finally:
            # failure mid-extraction must not strand the decompressed
            # multi-GB .tar in the temp dir
            if raw is not tmp_path:
                raw.unlink(missing_ok=True)
            tmp_path.unlink(missing_ok=True)
    return dest_dir
