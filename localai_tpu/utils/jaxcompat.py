"""Version portability for JAX APIs that moved between releases.

The serving stack tracks recent JAX, but the CPU CI / dev images often
lag: ``jax.shard_map`` only exists as a top-level API from 0.6, while
earlier releases ship it as ``jax.experimental.shard_map.shard_map``
with ``check_rep`` instead of ``check_vma``. Every call site imports
from here instead of hard-coding one spelling (the same bug class as
the ``jax_num_cpu_devices`` conftest breakage — see tools/jaxlint rule
``unknown-jax-config`` for the config-option flavor).
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "tree_map_with_path"]

# jax.tree.map_with_path only exists from ~0.5; the tree_util spelling
# works on every release this repo supports.
tree_map_with_path = jax.tree_util.tree_map_with_path


if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        # pre-0.6 spelling: replication checking is ``check_rep``
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
