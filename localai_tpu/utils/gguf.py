"""GGUF ingestion: read llama.cpp checkpoints, convert to native format.

Parity: the reference's entire model ecosystem is GGUF — its loader scans
and serves them directly (/root/reference/pkg/model/initializers.go:271-407)
and its config guesser reads GGUF metadata (core/config/guesser.go:13-246).
GGUF block formats are llama.cpp-native and gain nothing on TPU, so the
TPU-first design converts ONCE: ``convert_gguf`` decodes the quantized
tensors (f32/f16/q8_0/q4_0/q4_1/q4_k/q6_k), un-permutes llama.cpp's rotary
row layout back to the HF convention, and writes an HF-shaped checkpoint
(config.json + model.safetensors) that the existing loader/quantizer serve
— ``quantization: int4`` restores q4-class bandwidth at serving time.

Format reference: the public ggml/GGUF spec (v2/v3 little-endian): header
(magic 'GGUF', version, tensor count, kv count), metadata KVs, tensor
descriptors (name, dims, dtype, offset), then alignment-padded data.
"""

from __future__ import annotations

import json
import logging
import struct
from pathlib import Path
from typing import Any, BinaryIO

import numpy as np

log = logging.getLogger(__name__)

MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 \
    = range(13)
_SCALAR_FMT = {
    _U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I", _I32: "<i",
    _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d",
}

# tensor dtypes (ggml_type)
F32, F16, Q4_0, Q4_1, Q8_0 = 0, 1, 2, 3, 8
Q4_K, Q6_K = 12, 14
_BLOCK = {  # dtype → (elements per block, bytes per block)
    F32: (1, 4), F16: (1, 2),
    Q4_0: (32, 18), Q4_1: (32, 20), Q8_0: (32, 34),
    Q4_K: (256, 144), Q6_K: (256, 210),
}


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR_FMT:
        return _read(f, _SCALAR_FMT[vtype])
    if vtype == _BOOL:
        return bool(_read(f, "<B"))
    if vtype == _STR:
        return _read_string(f)
    if vtype == _ARR:
        etype = _read(f, "<I")
        n = _read(f, "<Q")
        return [_read_value(f, etype) for _ in range(n)]
    raise ValueError(f"unknown GGUF metadata type {vtype}")


class GGUFFile:
    """Parsed GGUF: ``metadata`` dict + ``tensors`` name → (dtype, shape,
    absolute data offset). ``load_tensor`` dequantizes to float32."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, tuple[int, tuple[int, ...], int]] = {}
        with open(self.path, "rb") as f:
            if f.read(4) != MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            self.version = _read(f, "<I")
            if self.version < 2:
                raise ValueError(f"GGUF v{self.version} not supported (v2+)")
            n_tensors = _read(f, "<Q")
            n_kv = _read(f, "<Q")
            for _ in range(n_kv):
                key = _read_string(f)
                vtype = _read(f, "<I")
                self.metadata[key] = _read_value(f, vtype)
            infos = []
            for _ in range(n_tensors):
                name = _read_string(f)
                n_dims = _read(f, "<I")
                # GGUF dims are stored innermost-first (ggml ne[]); numpy
                # shape is the reverse
                dims = [_read(f, "<Q") for _ in range(n_dims)]
                dtype = _read(f, "<I")
                offset = _read(f, "<Q")
                infos.append((name, dtype, tuple(reversed(dims)), offset))
            align = int(self.metadata.get("general.alignment", 32))
            base = f.tell()
            base = (base + align - 1) // align * align
            for name, dtype, shape, offset in infos:
                self.tensors[name] = (dtype, shape, base + offset)

    def load_tensor(self, name: str) -> np.ndarray:
        dtype, shape, offset = self.tensors[name]
        if dtype not in _BLOCK:
            raise ValueError(f"{name}: unsupported ggml dtype {dtype}")
        n = int(np.prod(shape))
        per, nbytes = _BLOCK[dtype]
        if n % per:
            raise ValueError(f"{name}: {n} elements not divisible by {per}")
        blocks = n // per
        with open(self.path, "rb") as f:
            f.seek(offset)
            raw = f.read(blocks * nbytes)
        return _DEQUANT[dtype](raw, blocks).reshape(shape)


# -- block dequantizers (vectorized numpy) ----------------------------------


def _dq_f32(raw: bytes, blocks: int) -> np.ndarray:
    return np.frombuffer(raw, np.float32).copy()


def _dq_f16(raw: bytes, blocks: int) -> np.ndarray:
    return np.frombuffer(raw, np.float16).astype(np.float32)


def _dq_q8_0(raw: bytes, blocks: int) -> np.ndarray:
    b = np.frombuffer(raw, np.uint8).reshape(blocks, 34)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)   # [B, 1]
    q = b[:, 2:].view(np.int8).astype(np.float32)             # [B, 32]
    return (d * q).reshape(-1)


def _nibbles(qs: np.ndarray) -> np.ndarray:
    """[B, 16] bytes → [B, 32] values: low nibbles then high nibbles
    (llama.cpp q4 layout: element j pairs with j+16)."""
    lo = (qs & 0x0F).astype(np.float32)
    hi = (qs >> 4).astype(np.float32)
    return np.concatenate([lo, hi], axis=1)


def _dq_q4_0(raw: bytes, blocks: int) -> np.ndarray:
    b = np.frombuffer(raw, np.uint8).reshape(blocks, 18)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    q = _nibbles(b[:, 2:])
    return (d * (q - 8.0)).reshape(-1)


def _dq_q4_1(raw: bytes, blocks: int) -> np.ndarray:
    b = np.frombuffer(raw, np.uint8).reshape(blocks, 20)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    m = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    q = _nibbles(b[:, 4:])
    return (d * q + m).reshape(-1)


def _q4k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """12 packed bytes → (8 six-bit scales, 8 six-bit mins) per block
    (ggml get_scale_min_k4)."""
    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:-1] + (8,), np.float32)
    mn = np.empty_like(sc)
    for i in range(4):
        sc[..., i] = (s[..., i] & 63)
        mn[..., i] = (s[..., i + 4] & 63)
        sc[..., i + 4] = (s[..., i + 8] & 0x0F) | ((s[..., i] >> 6) << 4)
        mn[..., i + 4] = (s[..., i + 8] >> 4) | ((s[..., i + 4] >> 6) << 4)
    return sc, mn


def _dq_q4_k(raw: bytes, blocks: int) -> np.ndarray:
    b = np.frombuffer(raw, np.uint8).reshape(blocks, 144)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)       # [B,1]
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mn = _q4k_scale_min(b[:, 4:16])                           # [B,8]
    qs = b[:, 16:]                                                # [B,128]
    out = np.empty((blocks, 256), np.float32)
    # 4 chunks of 64 values; chunk c uses scales 2c (low nibbles) and
    # 2c+1 (high nibbles) over the same 32 bytes
    for c in range(4):
        chunk = qs[:, 32 * c: 32 * (c + 1)]
        lo = (chunk & 0x0F).astype(np.float32)
        hi = (chunk >> 4).astype(np.float32)
        out[:, 64 * c: 64 * c + 32] = \
            d * sc[:, 2 * c: 2 * c + 1] * lo - dmin * mn[:, 2 * c: 2 * c + 1]
        out[:, 64 * c + 32: 64 * c + 64] = \
            d * sc[:, 2 * c + 1: 2 * c + 2] * hi \
            - dmin * mn[:, 2 * c + 1: 2 * c + 2]
    return out.reshape(-1)


def _dq_q6_k(raw: bytes, blocks: int) -> np.ndarray:
    b = np.frombuffer(raw, np.uint8).reshape(blocks, 210)
    ql = b[:, :128]
    qh = b[:, 128:192]
    sc = b[:, 192:208].view(np.int8).astype(np.float32)           # [B,16]
    d = b[:, 208:210].copy().view(np.float16).astype(np.float32)  # [B,1]
    out = np.empty((blocks, 256), np.float32)
    # two 128-value halves, each from 64 ql bytes + 32 qh bytes
    for half in range(2):
        qlh = ql[:, 64 * half: 64 * (half + 1)]
        qhh = qh[:, 32 * half: 32 * (half + 1)]
        base = 128 * half
        q1 = (qlh[:, :32] & 0x0F) | ((qhh & 0x03) << 4)
        q2 = (qlh[:, 32:] & 0x0F) | (((qhh >> 2) & 0x03) << 4)
        q3 = (qlh[:, :32] >> 4) | (((qhh >> 4) & 0x03) << 4)
        q4 = (qlh[:, 32:] >> 4) | (((qhh >> 6) & 0x03) << 4)
        for j, q in enumerate((q1, q2, q3, q4)):
            vals = q.astype(np.float32) - 32.0
            for s in range(2):  # each 32-value span covers 2 sub-scales
                si = 8 * half + 2 * j + s
                seg = vals[:, 16 * s: 16 * (s + 1)]
                out[:, base + 32 * j + 16 * s: base + 32 * j + 16 * (s + 1)] \
                    = d * sc[:, si: si + 1] * seg
    return out.reshape(-1)


_DEQUANT = {
    F32: _dq_f32, F16: _dq_f16, Q8_0: _dq_q8_0,
    Q4_0: _dq_q4_0, Q4_1: _dq_q4_1, Q4_K: _dq_q4_k, Q6_K: _dq_q6_k,
}


# -- conversion -------------------------------------------------------------


def _unpermute(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's rotary row permutation on wq/wk. The HF→GGUF
    convert script applies P = reshape(head, 2, hd/2).swapaxes(1, 2); P is
    not an involution, so the inverse reads the permuted rows as
    (head, hd/2, 2) and swaps back."""
    out_dim = w.shape[0]
    return (w.reshape(n_head, out_dim // n_head // 2, 2, *w.shape[1:])
            .swapaxes(1, 2).reshape(w.shape))


def gguf_to_hf_config(meta: dict) -> dict:
    """GGUF llama metadata → HF config.json dict (the converse of the
    reference's GGUF guesser, core/config/guesser.go:13-246)."""
    arch = meta.get("general.architecture", "llama")

    def g(key, default=None):
        return meta.get(f"{arch}.{key}", default)

    heads = int(g("attention.head_count", 32))
    cfg = {
        "model_type": arch,
        "vocab_size": int(meta.get(
            f"{arch}.vocab_size",
            len(meta.get("tokenizer.ggml.tokens", [])) or 32000)),
        "hidden_size": int(g("embedding_length", 4096)),
        "intermediate_size": int(g("feed_forward_length", 11008)),
        "num_hidden_layers": int(g("block_count", 32)),
        "num_attention_heads": heads,
        "num_key_value_heads": int(g("attention.head_count_kv", heads)),
        "max_position_embeddings": int(g("context_length", 4096)),
        "rope_theta": float(g("rope.freq_base", 10000.0)),
        "rms_norm_eps": float(
            g("attention.layer_norm_rms_epsilon", 1e-5)),
        "tie_word_embeddings": False,
    }
    # special token ids: stopping + the family guesser
    # (config.guesser.identify_family keys on them, as guesser.go does)
    for hf_key, gg_key in (("bos_token_id", "tokenizer.ggml.bos_token_id"),
                           ("eos_token_id", "tokenizer.ggml.eos_token_id")):
        if gg_key in meta:
            cfg[hf_key] = int(meta[gg_key])
    # Mixtral-class MoE ({arch}.expert_count / expert_used_count)
    ec = g("expert_count")
    if ec:
        cfg["num_local_experts"] = int(ec)
        cfg["num_experts_per_tok"] = int(g("expert_used_count", 2))
    # non-default head_dim ({arch}.attention.key_length — e.g. gemma-style
    # wide heads): without it the converted checkpoint gets wrong shapes
    key_len = g("attention.key_length")
    if key_len is not None and int(key_len) != cfg["hidden_size"] // heads:
        cfg["head_dim"] = int(key_len)
    # rope scaling ({arch}.rope.scaling.*): a Llama-3.1-class GGUF converted
    # without this serves silently wrong RoPE beyond the base context
    stype = g("rope.scaling.type")
    if stype and stype != "none":
        rope_type = {"linear": "linear", "yarn": "yarn",
                     "llama3": "llama3"}.get(str(stype))
        if rope_type is None:
            log.warning(
                "gguf: unsupported rope scaling type %r — emitting config "
                "without rope_scaling (long-context behavior will differ)",
                stype,
            )
        else:
            rs: dict = {"rope_type": rope_type}
            factor = g("rope.scaling.factor")
            if factor is not None:
                rs["factor"] = float(factor)
            octx = g("rope.scaling.original_context_length")
            if octx is not None:
                rs["original_max_position_embeddings"] = int(octx)
            attn_f = g("rope.scaling.attn_factor")
            if attn_f is not None:
                rs["attention_factor"] = float(attn_f)
            cfg["rope_scaling"] = rs
    return cfg


# GGUF tensor name → HF name (llama family)
def _hf_name(name: str) -> str | None:
    if name == "token_embd.weight":
        return "model.embed_tokens.weight"
    if name == "output_norm.weight":
        return "model.norm.weight"
    if name == "output.weight":
        return "lm_head.weight"
    if name.startswith("blk."):
        _, idx, rest = name.split(".", 2)
        mapping = {
            "attn_q.weight": "self_attn.q_proj.weight",
            "attn_k.weight": "self_attn.k_proj.weight",
            "attn_v.weight": "self_attn.v_proj.weight",
            "attn_output.weight": "self_attn.o_proj.weight",
            "ffn_gate.weight": "mlp.gate_proj.weight",
            "ffn_up.weight": "mlp.up_proj.weight",
            "ffn_down.weight": "mlp.down_proj.weight",
            "attn_norm.weight": "input_layernorm.weight",
            "ffn_norm.weight": "post_attention_layernorm.weight",
            "ffn_gate_inp.weight": "block_sparse_moe.gate.weight",
            # qwen2-family qkv biases
            "attn_q.bias": "self_attn.q_proj.bias",
            "attn_k.bias": "self_attn.k_proj.bias",
            "attn_v.bias": "self_attn.v_proj.bias",
        }
        if rest in mapping:
            return f"model.layers.{idx}.{mapping[rest]}"
    return None


# llama.cpp's expert-stacked MoE tensors → per-expert HF names (w1=gate,
# w3=up, w2=down, matching MixtralSparseMoeBlock)
_MOE_STACKED = {
    "ffn_gate_exps.weight": "w1",
    "ffn_up_exps.weight": "w3",
    "ffn_down_exps.weight": "w2",
}


def convert_gguf(src: str | Path, out_dir: str | Path,
                 dtype: str = "bfloat16") -> Path:
    """model.gguf → HF-shaped checkpoint dir (config.json +
    model.safetensors) the native loader serves directly. Returns out_dir.
    """
    import ml_dtypes
    from safetensors.numpy import save_file

    gg = GGUFFile(src)
    hf = gguf_to_hf_config(gg.metadata)
    heads = hf["num_attention_heads"]
    kv_heads = hf["num_key_value_heads"]
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    np_dtype = (ml_dtypes.bfloat16 if dtype == "bfloat16"
                else np.dtype(dtype))
    tensors: dict[str, np.ndarray] = {}
    skipped = []
    for name in gg.tensors:
        if name.startswith("blk.") and name.split(".", 2)[2] in _MOE_STACKED:
            # expert-stacked [E, N, K] → per-expert Mixtral names
            _, idx, rest = name.split(".", 2)
            wname = _MOE_STACKED[rest]
            stacked = gg.load_tensor(name)
            for j in range(stacked.shape[0]):
                tensors[
                    f"model.layers.{idx}.block_sparse_moe."
                    f"experts.{j}.{wname}.weight"
                ] = np.ascontiguousarray(stacked[j].astype(np_dtype))
            continue
        hf_name = _hf_name(name)
        if hf_name is None:
            skipped.append(name)
            continue
        w = gg.load_tensor(name)
        # llama.cpp's HF→GGUF convert permutes q/k rows ONLY for the
        # llama/mistral architectures; qwen2-class GGUFs store HF order
        if hf.get("model_type") in ("llama", "mistral"):
            if name.endswith("attn_q.weight"):
                w = _unpermute(w, heads)
            elif name.endswith("attn_k.weight"):
                w = _unpermute(w, kv_heads)
        tensors[hf_name] = np.ascontiguousarray(w.astype(np_dtype))
    if skipped:
        log.info("convert: skipped %d non-llama tensors (%s...)",
                 len(skipped), skipped[:3])
    if "lm_head.weight" not in tensors:
        hf["tie_word_embeddings"] = True
    save_file(tensors, out_dir / "model.safetensors")
    with open(out_dir / "config.json", "w") as f:
        json.dump(hf, f, indent=1)

    # tokenizer: carry the GGUF vocab over as a minimal tokenizer.json so
    # ids→text decoding matches the source model (byte-level fallback when
    # the source has no vocab)
    toks = gg.metadata.get("tokenizer.ggml.tokens")
    if toks:
        vocab = {t: i for i, t in enumerate(toks)}
        with open(out_dir / "tokenizer.json", "w") as f:
            json.dump({
                "version": "1.0",
                "model": {"type": "WordLevel", "vocab": vocab,
                          "unk_token": toks[0]},
                "added_tokens": [],
            }, f)
    # carry the source's chat template so serving formats prompts the way
    # the model was trained (template-less sources fall to the family
    # guesser at config load — config/guesser.py)
    chat_tmpl = gg.metadata.get("tokenizer.chat_template")
    if chat_tmpl:
        with open(out_dir / "tokenizer_config.json", "w") as f:
            json.dump({"chat_template": chat_tmpl}, f)
    return out_dir
