"""URI downloader with scheme abstraction, sha256 verify and .partial resume.

Parity: /root/reference/pkg/downloader/uri.go — schemes
``huggingface://owner/repo/file@branch``, ``github:``/``github://``,
``file://``, http(s); sha256 verification; resume via ``.partial`` suffix;
progress callbacks. ``oci://`` (image layers extracted beside the target)
and ``ollama://`` (model layer blob) ride the registry client in
localai_tpu.utils.oci.
"""

from __future__ import annotations

import hashlib
import logging
import shutil
from pathlib import Path
from typing import Callable, Optional

log = logging.getLogger(__name__)

HUGGINGFACE_PREFIX = "huggingface://"
HF_SHORT_PREFIX = "hf://"
GITHUB_PREFIX = "github:"
OCI_PREFIX = "oci://"
OLLAMA_PREFIX = "ollama://"
FILE_PREFIX = "file://"

ProgressFn = Callable[[int, int], None]  # (downloaded_bytes, total_bytes)


def resolve_url(uri: str) -> str:
    """Map scheme URIs to concrete https URLs (parity: URI.ResolveURL,
    pkg/downloader/uri.go:174-187)."""
    if uri.startswith((HUGGINGFACE_PREFIX, HF_SHORT_PREFIX)):
        ref = uri.split("://", 1)[1]
        branch = "main"
        if "@" in ref:
            ref, branch = ref.rsplit("@", 1)
        parts = ref.split("/")
        if len(parts) < 3:
            raise ValueError(f"huggingface uri needs owner/repo/file: {uri}")
        owner, repo, filepath = parts[0], parts[1], "/".join(parts[2:])
        return (
            f"https://huggingface.co/{owner}/{repo}/resolve/{branch}/{filepath}"
        )
    if uri.startswith("github://") or uri.startswith(GITHUB_PREFIX):
        ref = uri.split("://", 1)[1] if "://" in uri else uri[len(GITHUB_PREFIX):]
        branch = "main"
        if "@" in ref:
            ref, branch = ref.rsplit("@", 1)
        parts = ref.split("/")
        if len(parts) < 3:
            raise ValueError(f"github uri needs owner/repo/file: {uri}")
        owner, repo, filepath = parts[0], parts[1], "/".join(parts[2:])
        return (
            f"https://raw.githubusercontent.com/{owner}/{repo}/{branch}/{filepath}"
        )
    return uri


def looks_like_url(uri: str) -> bool:
    return uri.startswith(
        ("http://", "https://", HUGGINGFACE_PREFIX, HF_SHORT_PREFIX,
         GITHUB_PREFIX, "github://", OCI_PREFIX, OLLAMA_PREFIX)
    )


def sha256_file(path: str | Path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def download_uri(
    uri: str,
    dest: str | Path,
    sha256: Optional[str] = None,
    progress: Optional[ProgressFn] = None,
    timeout: float = 600.0,
) -> Path:
    """Download ``uri`` to ``dest`` with resume + sha verification (parity:
    URI.DownloadWithCallback / DownloadFile, pkg/downloader/uri.go:21-30)."""
    dest = Path(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)

    if dest.exists():
        if sha256 is None or sha256_file(dest) == sha256:
            return dest
        log.warning("sha mismatch for existing %s, re-downloading", dest)
        dest.unlink()

    if uri.startswith(FILE_PREFIX):
        src = Path(uri[len(FILE_PREFIX):])
        shutil.copyfile(src, dest)
    elif uri.startswith(OLLAMA_PREFIX):
        # the model layer blob becomes the destination file
        # (parity: uri.go:221-223 → OllamaFetchModel)
        from localai_tpu.utils.oci import ollama_fetch_model

        ollama_fetch_model(uri[len(OLLAMA_PREFIX):], dest, progress)
    elif uri.startswith(OCI_PREFIX):
        # image layers extract into the destination's directory; there is
        # no single output file to checksum (parity: uri.go:226-232 —
        # the reference also returns before its sha check)
        from localai_tpu.utils.oci import oci_extract_image

        oci_extract_image(uri[len(OCI_PREFIX):], dest.parent, progress)
        return dest
    else:
        _http_download(resolve_url(uri), dest, progress, timeout)

    if sha256 is not None:
        actual = sha256_file(dest)
        if actual != sha256:
            dest.unlink(missing_ok=True)
            raise ValueError(
                f"sha256 mismatch for {uri}: want {sha256} got {actual}"
            )
    return dest


def _http_download(
    url: str, dest: Path, progress: Optional[ProgressFn], timeout: float
) -> None:
    import requests

    partial = dest.with_suffix(dest.suffix + ".partial")
    headers = {}
    offset = 0
    if partial.exists():
        offset = partial.stat().st_size
        headers["Range"] = f"bytes={offset}-"
    with requests.get(url, stream=True, timeout=timeout, headers=headers) as r:
        if r.status_code == 416:  # range not satisfiable → restart
            offset = 0
            headers.pop("Range", None)
            partial.unlink(missing_ok=True)
            return _http_download(url, dest, progress, timeout)
        r.raise_for_status()
        mode = "ab" if offset and r.status_code == 206 else "wb"
        total = int(r.headers.get("content-length", 0)) + (
            offset if mode == "ab" else 0
        )
        done = offset if mode == "ab" else 0
        with open(partial, mode) as f:
            for chunk in r.iter_content(chunk_size=1 << 20):
                f.write(chunk)
                done += len(chunk)
                if progress:
                    progress(done, total)
    partial.rename(dest)
