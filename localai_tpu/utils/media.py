"""Media ingestion for multimodal chat: image_url parts → RGB arrays.

Parity: GetImageURLAsBase64 (/root/reference/pkg/utils/base64.go:18-60) —
accepts http(s) URLs, data URIs, and raw base64 payloads. Decoding uses
PIL; outputs are uint8 RGB numpy arrays ready for the vision tower's
preprocess (models/vision.py).
"""

from __future__ import annotations

import base64
import binascii
import io
import logging
import re

import numpy as np

log = logging.getLogger(__name__)

MAX_IMAGE_BYTES = 32 * 1024 * 1024
_DATA_URI = re.compile(r"^data:[a-zA-Z0-9.+/-]+;base64,(?P<b64>.+)$", re.S)


class MediaError(ValueError):
    """Raised when an image reference cannot be fetched or decoded."""


def fetch_image_bytes(ref: str, *, timeout: float = 30.0) -> bytes:
    """image_url string → raw encoded bytes (base64.go:18-60 semantics:
    http(s) fetch, data-URI strip, or raw base64 decode)."""
    ref = ref.strip()
    m = _DATA_URI.match(ref)
    if m:
        try:
            return base64.b64decode(m.group("b64"), validate=False)
        except (binascii.Error, ValueError) as e:
            raise MediaError(f"invalid base64 data URI: {e}") from e
    if ref.startswith(("http://", "https://")):
        import urllib.request

        req = urllib.request.Request(ref, headers={"User-Agent": "localai-tpu"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                data = resp.read(MAX_IMAGE_BYTES + 1)
        except Exception as e:  # noqa: BLE001 — network errors → request error
            raise MediaError(f"failed to fetch image URL: {e}") from e
        if len(data) > MAX_IMAGE_BYTES:
            raise MediaError("image exceeds size limit")
        return data
    # raw base64 (no scheme, no data: header)
    try:
        return base64.b64decode(ref, validate=True)
    except (binascii.Error, ValueError) as e:
        raise MediaError(
            "image_url is neither an http(s) URL, data URI, nor base64"
        ) from e


def decode_image(data: bytes) -> np.ndarray:
    """Encoded image bytes → RGB uint8 array [H, W, 3]."""
    from PIL import Image

    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
    except Exception as e:  # noqa: BLE001 — corrupt images → request error
        raise MediaError(f"cannot decode image: {e}") from e
    return np.asarray(img, np.uint8)


def fetch_image(ref: str, *, timeout: float = 30.0) -> np.ndarray:
    return decode_image(fetch_image_bytes(ref, timeout=timeout))
