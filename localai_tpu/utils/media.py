"""Media ingestion for multimodal chat: image_url parts → RGB arrays.

Parity: GetImageURLAsBase64 (/root/reference/pkg/utils/base64.go:18-60) —
accepts http(s) URLs, data URIs, and raw base64 payloads. Decoding uses
PIL; outputs are uint8 RGB numpy arrays ready for the vision tower's
preprocess (models/vision.py).
"""

from __future__ import annotations

import base64
import binascii
import io
import logging
import re

import numpy as np

log = logging.getLogger(__name__)

MAX_IMAGE_BYTES = 32 * 1024 * 1024
_DATA_URI = re.compile(r"^data:[a-zA-Z0-9.+/-]+;base64,(?P<b64>.+)$", re.S)


class MediaError(ValueError):
    """Raised when an image reference cannot be fetched or decoded."""


def fetch_image_bytes(ref: str, *, timeout: float = 30.0,
                      kind: str = "image") -> bytes:
    """image/video_url string → raw encoded bytes (base64.go:18-60
    semantics: http(s) fetch, data-URI strip, or raw base64 decode).
    ``kind`` only flavors error messages so a bad video_url doesn't 400
    with wording about images."""
    ref = ref.strip()
    m = _DATA_URI.match(ref)
    if m:
        try:
            return base64.b64decode(m.group("b64"), validate=False)
        except (binascii.Error, ValueError) as e:
            raise MediaError(f"invalid base64 data URI: {e}") from e
    if ref.startswith(("http://", "https://")):
        import urllib.request

        req = urllib.request.Request(ref, headers={"User-Agent": "localai-tpu"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                data = resp.read(MAX_IMAGE_BYTES + 1)
        except Exception as e:  # noqa: BLE001 — network errors → request error
            raise MediaError(f"failed to fetch {kind} URL: {e}") from e
        if len(data) > MAX_IMAGE_BYTES:
            raise MediaError(f"{kind} exceeds size limit")
        return data
    # raw base64 (no scheme, no data: header)
    try:
        return base64.b64decode(ref, validate=True)
    except (binascii.Error, ValueError) as e:
        raise MediaError(
            f"{kind}_url is neither an http(s) URL, data URI, nor base64"
        ) from e


def decode_image(data: bytes) -> np.ndarray:
    """Encoded image bytes → RGB uint8 array [H, W, 3]."""
    from PIL import Image

    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
    except Exception as e:  # noqa: BLE001 — corrupt images → request error
        raise MediaError(f"cannot decode image: {e}") from e
    return np.asarray(img, np.uint8)


def fetch_image(ref: str, *, timeout: float = 30.0) -> np.ndarray:
    return decode_image(fetch_image_bytes(ref, timeout=timeout))


def decode_video_frames(data: bytes, max_frames: int = 8) -> list[np.ndarray]:
    """Encoded multi-frame media → up to ``max_frames`` uniformly-sampled
    RGB uint8 frames [H, W, 3].

    Parity: the reference's vLLM backend accepts video parts alongside
    images (/root/reference/backend/python/vllm/backend.py multimodal
    path). Decoding uses PIL's multi-frame support (animated GIF / APNG /
    WebP); compressed video containers (mp4/webm) need a codec stack this
    environment doesn't ship, and raise a clear MediaError instead."""
    from PIL import Image

    try:
        img = Image.open(io.BytesIO(data))
        n = getattr(img, "n_frames", 1)
        if n <= 1:
            return [np.asarray(img.convert("RGB"), np.uint8)]
        count = min(max_frames, n)
        idxs = [round(i * (n - 1) / max(count - 1, 1)) for i in range(count)]
        frames = []
        for i in idxs:
            img.seek(i)
            frames.append(np.asarray(img.convert("RGB"), np.uint8))
        return frames
    except MediaError:
        raise
    except Exception as e:  # noqa: BLE001 — undecodable container → 400
        raise MediaError(
            f"cannot decode video: {e} (supported: animated GIF/APNG/WebP; "
            "compressed containers like mp4 require a codec stack not "
            "available here)"
        ) from e


def fetch_video_frames(ref: str, *, timeout: float = 30.0,
                       max_frames: int = 8) -> list[np.ndarray]:
    """video_url string → sampled RGB frames (same ref forms as images)."""
    return decode_video_frames(
        fetch_image_bytes(ref, timeout=timeout, kind="video"),
        max_frames=max_frames)
