"""Tokenizer abstraction: HF tokenizers for real models, a self-contained
byte-level tokenizer for tests/benchmarks (zero downloads — the analogue of
the reference's tiny fixture models, SURVEY.md §4).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    eos_ids: set[int]
    vocab_size: int

    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 = bytes, 256 = BOS, 257 = EOS.

    Deterministic, download-free; used by the debug model family and the
    synthetic benchmark path.
    """

    BOS = 256
    EOS = 257

    def __init__(self) -> None:
        self.eos_ids = {self.EOS}
        self.vocab_size = 258

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """Wraps a tokenizers/transformers tokenizer loaded from local files."""

    def __init__(self, model_dir: str | Path):
        model_dir = Path(model_dir)
        tok_json = model_dir / "tokenizer.json"
        if tok_json.exists():
            from tokenizers import Tokenizer as RawTok

            self._tok = RawTok.from_file(str(tok_json))
            self.vocab_size = self._tok.get_vocab_size()
            self._decode = lambda ids: self._tok.decode(
                list(ids), skip_special_tokens=False
            )
            self._encode = lambda t: self._tok.encode(t, add_special_tokens=False).ids
        else:
            from transformers import AutoTokenizer

            t = AutoTokenizer.from_pretrained(str(model_dir))
            self._tok = t
            self.vocab_size = len(t)
            self._decode = lambda ids: t.decode(list(ids), skip_special_tokens=False)
            self._encode = lambda s: t.encode(s, add_special_tokens=False)
        self.eos_ids = self._find_eos(model_dir)
        self.bos_id = self._find_bos(model_dir)

    def _each_cfg(self, model_dir: Path):
        import json

        for name in ("generation_config.json", "config.json",
                     "tokenizer_config.json"):
            p = model_dir / name
            if p.exists():
                try:
                    yield json.loads(p.read_text())
                except Exception:  # noqa: BLE001
                    pass

    def _find_eos(self, model_dir: Path) -> set[int]:
        # union across all config files: Llama-3-Instruct lists multiple EOS
        # ids in generation_config.json and a single one in config.json —
        # generation must stop on any of them
        out: set[int] = set()
        for cfg in self._each_cfg(model_dir):
            eos = cfg.get("eos_token_id")
            if isinstance(eos, int):
                out.add(eos)
            elif isinstance(eos, list):
                out.update(int(e) for e in eos)
            elif isinstance(eos, str):
                ids = self._encode(eos)
                if len(ids) == 1:
                    out.add(ids[0])
        return out

    def _find_bos(self, model_dir: Path):
        for cfg in self._each_cfg(model_dir):
            b = cfg.get("bos_token_id")
            if isinstance(b, int):
                return b
        return None

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._encode(text)
        if add_bos and self.bos_id is not None and (
            not ids or ids[0] != self.bos_id
        ):
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._decode(ids)


def load_tokenizer(model_dir: str | Path) -> Tokenizer:
    model_dir = Path(model_dir)
    if (model_dir / "tokenizer.json").exists() or (
        model_dir / "tokenizer_config.json"
    ).exists():
        return HFTokenizer(model_dir)
    return ByteTokenizer()
