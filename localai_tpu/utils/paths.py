"""Path safety (parity: VerifyPath, /root/reference/pkg/utils/path.go —
the traversal guard every user-supplied filename passes through)."""

from __future__ import annotations

from pathlib import Path


def verify_path(filename: str | Path, base_dir: str | Path) -> Path:
    """Resolve ``base_dir/filename`` and require it to stay inside base_dir.
    Returns the resolved absolute path or raises ValueError."""
    base = Path(base_dir).resolve()
    target = (base / filename).resolve()
    if base != target and base not in target.parents:
        raise ValueError(f"path {filename!r} escapes {base}")
    return target
