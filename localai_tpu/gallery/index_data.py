"""Shipped model index: a curated multi-family gallery available without
any network-fetched index.

Parity: the reference ships its gallery index
(github.com/mudler/LocalAI/gallery — ~50 model families referenced by
aio configs and the model library) and resolves short names against it;
this module is the safetensors-era equivalent. Entries carry HF
safetensors URIs (networked deployments), the per-family template/
stopword config, and the engine family routing (`backend:`) where the
checkpoint isn't an LLM. Zero-egress environments still list them; the
debug presets in embedded.py remain the instant-install path.
"""

from __future__ import annotations

from localai_tpu.gallery.embedded import _SAFETENSOR_SET, _hf_files
from localai_tpu.gallery.models import GalleryModel

_SHARDS = {
    2: [f"model-{i:05d}-of-00002.safetensors" for i in range(1, 3)],
    3: [f"model-{i:05d}-of-00003.safetensors" for i in range(1, 4)],
    4: [f"model-{i:05d}-of-00004.safetensors" for i in range(1, 5)],
    19: [f"model-{i:05d}-of-00019.safetensors" for i in range(1, 20)],
}


def _sharded(n: int) -> list[str]:
    return ["config.json", "tokenizer.json", "tokenizer_config.json",
            "model.safetensors.index.json"] + _SHARDS[n]


def _llm(name: str, repo: str, desc: str, *, ctx: int = 8192,
         files: list[str] | None = None, license: str = "",
         stopwords: list[str] | None = None,
         tags: list[str] | None = None, **cfg_extra) -> GalleryModel:
    cfg = {
        "name": name,
        "model": repo.split("/")[-1],
        "context_size": ctx,
        "template": {"use_tokenizer_template": True},
    }
    if stopwords:
        cfg["stopwords"] = stopwords
    cfg.update(cfg_extra)
    return GalleryModel(
        name=name, description=desc, license=license,
        tags=["text-generation"] + (tags or []),
        files=_hf_files(repo, files or _SAFETENSOR_SET),
        config_file=cfg,
    )


def _family(name: str, repo: str, desc: str, *, backend: str,
            usecases: list[str], files: list[str] | None = None,
            license: str = "", tags: list[str] | None = None,
            **cfg_extra) -> GalleryModel:
    cfg = {
        "name": name,
        "model": repo.split("/")[-1],
        "backend": backend,
        "known_usecases": usecases,
    }
    cfg.update(cfg_extra)
    return GalleryModel(
        name=name, description=desc, license=license, tags=tags or [],
        files=_hf_files(repo, files or _SAFETENSOR_SET),
        config_file=cfg,
    )


_L3_STOP = ["<|eot_id|>"]
_QWEN_STOP = ["<|im_end|>"]
_GEMMA_STOP = ["<end_of_turn>"]

_ENTRIES: list[GalleryModel] = [
    # -- llama family -------------------------------------------------------
    _llm("llama-3.1-8b-instruct", "meta-llama/Llama-3.1-8B-Instruct",
         "Meta Llama 3.1 8B Instruct", ctx=131072, files=_sharded(4),
         license="llama3.1", stopwords=_L3_STOP),
    _llm("llama-3.2-1b-instruct", "meta-llama/Llama-3.2-1B-Instruct",
         "Meta Llama 3.2 1B Instruct", ctx=131072,
         license="llama3.2", stopwords=_L3_STOP),
    _llm("llama-3.2-3b-instruct", "meta-llama/Llama-3.2-3B-Instruct",
         "Meta Llama 3.2 3B Instruct", ctx=131072, files=_sharded(2),
         license="llama3.2", stopwords=_L3_STOP),
    _llm("llama-3-8b-instruct", "meta-llama/Meta-Llama-3-8B-Instruct",
         "Meta Llama 3 8B Instruct", files=_sharded(4),
         license="llama3", stopwords=_L3_STOP),
    _llm("hermes-2-pro-llama-3-8b", "NousResearch/Hermes-2-Pro-Llama-3-8B",
         "Hermes 2 Pro Llama-3 8B — the reference AIO text model",
         license="llama3",
         tags=["function-calling"]),
    _llm("hermes-3-llama-3.1-8b", "NousResearch/Hermes-3-Llama-3.1-8B",
         "Hermes 3 Llama-3.1 8B", ctx=131072, files=_sharded(4),
         license="llama3.1", tags=["function-calling"]),
    _llm("tinyllama-1.1b-chat", "TinyLlama/TinyLlama-1.1B-Chat-v1.0",
         "TinyLlama 1.1B chat", ctx=2048, license="apache-2.0"),
    # -- mistral family -----------------------------------------------------
    _llm("mistral-7b-instruct", "mistralai/Mistral-7B-Instruct-v0.3",
         "Mistral 7B Instruct v0.3", ctx=32768, files=_sharded(3),
         license="apache-2.0"),
    _llm("mistral-nemo-instruct", "mistralai/Mistral-Nemo-Instruct-2407",
         "Mistral Nemo 12B Instruct", ctx=131072, files=_sharded(4),
         license="apache-2.0"),
    _llm("zephyr-7b-beta", "HuggingFaceH4/zephyr-7b-beta",
         "Zephyr 7B beta (Mistral fine-tune)", ctx=32768,
         files=_sharded(4), license="mit"),
    _llm("openhermes-2.5-mistral-7b", "teknium/OpenHermes-2.5-Mistral-7B",
         "OpenHermes 2.5 Mistral 7B", ctx=32768, files=_sharded(2),
         license="apache-2.0"),
    _llm("mixtral-8x7b-instruct", "mistralai/Mixtral-8x7B-Instruct-v0.1",
         "Mixtral 8x7B sparse MoE instruct (8 experts, top-2 routing; "
         "expert-sharded over the 'expert' mesh axis)",
         ctx=32768, files=_sharded(19), license="apache-2.0",
         tags=["moe"],
         sharding={"expert_parallel_size": 8}),
    # -- qwen family --------------------------------------------------------
    _llm("qwen2.5-0.5b-instruct", "Qwen/Qwen2.5-0.5B-Instruct",
         "Qwen 2.5 0.5B Instruct", ctx=32768, license="apache-2.0",
         stopwords=_QWEN_STOP),
    _llm("qwen2.5-1.5b-instruct", "Qwen/Qwen2.5-1.5B-Instruct",
         "Qwen 2.5 1.5B Instruct", ctx=32768, license="apache-2.0",
         stopwords=_QWEN_STOP),
    _llm("qwen2.5-7b-instruct", "Qwen/Qwen2.5-7B-Instruct",
         "Qwen 2.5 7B Instruct", ctx=131072, files=_sharded(4),
         license="apache-2.0", stopwords=_QWEN_STOP),
    _llm("qwen2.5-coder-7b-instruct", "Qwen/Qwen2.5-Coder-7B-Instruct",
         "Qwen 2.5 Coder 7B", ctx=131072, files=_sharded(4),
         license="apache-2.0", stopwords=_QWEN_STOP, tags=["code"]),
    # -- gemma family -------------------------------------------------------
    _llm("gemma-2-2b-it", "google/gemma-2-2b-it",
         "Gemma 2 2B instruction-tuned", ctx=8192, files=_sharded(2),
         license="gemma", stopwords=_GEMMA_STOP),
    _llm("gemma-2-9b-it", "google/gemma-2-9b-it",
         "Gemma 2 9B instruction-tuned", ctx=8192, files=_sharded(4),
         license="gemma", stopwords=_GEMMA_STOP),
    # -- phi family ---------------------------------------------------------
    _llm("phi-3.5-mini-instruct", "microsoft/Phi-3.5-mini-instruct",
         "Phi 3.5 mini 3.8B", ctx=131072, files=_sharded(2),
         license="mit", stopwords=["<|end|>"]),
    _llm("phi-2", "microsoft/phi-2", "Phi-2 2.7B base", ctx=2048,
         files=_sharded(2), license="mit"),
    # -- smol / misc --------------------------------------------------------
    _llm("smollm2-1.7b-instruct", "HuggingFaceTB/SmolLM2-1.7B-Instruct",
         "SmolLM2 1.7B Instruct", ctx=8192, license="apache-2.0",
         stopwords=_QWEN_STOP),
    _llm("stablelm-2-1.6b-chat", "stabilityai/stablelm-2-1_6b-chat",
         "StableLM 2 1.6B chat", ctx=4096, license="stabilityai"),
    # -- vision (llava-class) ----------------------------------------------
    _llm("llava-1.5-7b", "llava-hf/llava-1.5-7b-hf",
         "LLaVA 1.5 7B — vision chat", ctx=4096, files=_sharded(3),
         license="llama2", tags=["multimodal", "vision"],
         known_usecases=["chat", "vision"]),
    _llm("llava-1.6-mistral-7b", "llava-hf/llava-v1.6-mistral-7b-hf",
         "LLaVA 1.6 Mistral 7B — vision chat", ctx=32768,
         files=_sharded(4), license="apache-2.0",
         tags=["multimodal", "vision"],
         known_usecases=["chat", "vision"]),
    # -- embeddings (bert / sentence-transformers) -------------------------
    _family("all-minilm-l6-v2", "sentence-transformers/all-MiniLM-L6-v2",
            "MiniLM L6 sentence embeddings — the reference AIO embeddings "
            "model", backend="bert-embeddings", usecases=["embeddings"],
            license="apache-2.0", tags=["embeddings"]),
    _family("bge-small-en-v1.5", "BAAI/bge-small-en-v1.5",
            "BGE small English embeddings", backend="bert-embeddings",
            usecases=["embeddings"], license="mit", tags=["embeddings"]),
    _family("bge-base-en-v1.5", "BAAI/bge-base-en-v1.5",
            "BGE base English embeddings", backend="bert-embeddings",
            usecases=["embeddings"], license="mit", tags=["embeddings"]),
    _family("multilingual-e5-small", "intfloat/multilingual-e5-small",
            "E5 small multilingual embeddings",
            backend="bert-embeddings", usecases=["embeddings"],
            license="mit", tags=["embeddings"]),
    # -- rerankers (cross-encoders) ----------------------------------------
    _family("ms-marco-minilm-l6", "cross-encoder/ms-marco-MiniLM-L-6-v2",
            "MS MARCO MiniLM cross-encoder — the reference AIO reranker",
            backend="reranker", usecases=["rerank"],
            license="apache-2.0", tags=["rerank"]),
    _family("bge-reranker-base", "BAAI/bge-reranker-base",
            "BGE reranker base cross-encoder", backend="reranker",
            usecases=["rerank"], license="mit", tags=["rerank"]),
    # -- whisper (speech-to-text) ------------------------------------------
    _family("whisper-tiny", "openai/whisper-tiny",
            "Whisper tiny STT", backend="whisper",
            usecases=["transcript"], license="apache-2.0",
            tags=["audio"]),
    _family("whisper-base", "openai/whisper-base",
            "Whisper base STT — the reference AIO transcription model",
            backend="whisper", usecases=["transcript"],
            license="apache-2.0", tags=["audio"]),
    _family("whisper-small", "openai/whisper-small",
            "Whisper small STT", backend="whisper",
            usecases=["transcript"], license="apache-2.0",
            tags=["audio"]),
    _family("whisper-large-v3-turbo", "openai/whisper-large-v3-turbo",
            "Whisper large v3 turbo STT", backend="whisper",
            usecases=["transcript"], license="apache-2.0",
            tags=["audio"], files=_sharded(2)),
    # -- recurrent-state families (mamba / rwkv) ---------------------------
    _family("mamba-130m", "state-spaces/mamba-130m-hf",
            "Mamba 130M (selective state space LM)", backend="mamba",
            usecases=["chat", "completion"], license="apache-2.0",
            files=["config.json", "tokenizer.json",
                   "tokenizer_config.json", "model.safetensors"]),
    _family("mamba-2.8b", "state-spaces/mamba-2.8b-hf",
            "Mamba 2.8B (selective state space LM)", backend="mamba",
            usecases=["chat", "completion"], license="apache-2.0",
            files=_sharded(3)),
    _family("rwkv-4-pile-169m", "RWKV/rwkv-4-169m-pile",
            "RWKV-4 169M (linear attention LM)", backend="rwkv",
            usecases=["chat", "completion"], license="apache-2.0",
            files=["config.json", "tokenizer.json",
                   "tokenizer_config.json", "model.safetensors"]),
    # -- vits (neural text-to-speech) --------------------------------------
    _family("mms-tts-eng", "facebook/mms-tts-eng",
            "MMS English VITS voice (neural TTS)",
            backend="vits", usecases=["tts"], license="cc-by-nc-4.0",
            tags=["audio", "tts"],
            files=["config.json", "model.safetensors", "vocab.json"]),
    _family("mms-tts-deu", "facebook/mms-tts-deu",
            "MMS German VITS voice (neural TTS)",
            backend="vits", usecases=["tts"], license="cc-by-nc-4.0",
            tags=["audio", "tts"],
            files=["config.json", "model.safetensors", "vocab.json"]),
    _family("vits-ljs", "kakao-enterprise/vits-ljs",
            "VITS LJSpeech voice (neural TTS, 22.05kHz)",
            backend="vits", usecases=["tts"], license="mit",
            tags=["audio", "tts"],
            files=["config.json", "model.safetensors", "vocab.json"]),
    # -- stable diffusion (image generation) -------------------------------
    GalleryModel(
        name="stable-diffusion-1.5",
        description="Stable Diffusion 1.5 (diffusers layout) — SD-class "
                    "image generation",
        license="creativeml-openrail-m",
        tags=["image-generation"],
        files=[f for sub, names in {
            "unet": ["config.json", "diffusion_pytorch_model.safetensors"],
            "vae": ["config.json", "diffusion_pytorch_model.safetensors"],
            "text_encoder": ["config.json", "model.safetensors"],
            "tokenizer": ["merges.txt", "vocab.json",
                          "tokenizer_config.json"],
        }.items() for f in _hf_files(
            "stable-diffusion-v1-5/stable-diffusion-v1-5",
            [f"{sub}/{n}" for n in names])] + _hf_files(
            "stable-diffusion-v1-5/stable-diffusion-v1-5",
            ["model_index.json"]),
        config_file={
            "name": "stable-diffusion-1.5",
            "model": "stable-diffusion-v1-5",
            "backend": "diffusers",
            "known_usecases": ["image"],
            "diffusers": {"scheduler_type": "k_dpmpp_2m", "steps": 25},
        },
    ),
    GalleryModel(
        name="sdxl-base-1.0",
        description="Stable Diffusion XL base 1.0 (dual text encoders, "
                    "1024px) — diffusers layout",
        license="openrail++",
        tags=["image-generation"],
        files=[f for sub, names in {
            "unet": ["config.json",
                     "diffusion_pytorch_model.safetensors"],
            "vae": ["config.json", "diffusion_pytorch_model.safetensors"],
            "text_encoder": ["config.json", "model.safetensors"],
            "text_encoder_2": ["config.json", "model.safetensors"],
            "tokenizer": ["merges.txt", "vocab.json",
                          "tokenizer_config.json"],
            "tokenizer_2": ["merges.txt", "vocab.json",
                            "tokenizer_config.json"],
        }.items() for f in _hf_files(
            "stabilityai/stable-diffusion-xl-base-1.0",
            [f"{sub}/{n}" for n in names])] + _hf_files(
            "stabilityai/stable-diffusion-xl-base-1.0",
            ["model_index.json"]),
        config_file={
            "name": "sdxl-base-1.0",
            "model": "stable-diffusion-xl-base-1.0",
            "backend": "diffusers",
            "known_usecases": ["image"],
            "diffusers": {"scheduler_type": "euler", "steps": 25},
        },
    ),
    GalleryModel(
        name="flux.1-schnell",
        description="FLUX.1 [schnell] rectified-flow MMDiT (4-step "
                    "distilled; dual CLIP+T5 text encoders) — the "
                    "reference's GPU AIO image default family",
        license="apache-2.0",
        tags=["image-generation", "flux"],
        files=[f for sub, names in {
            "transformer": ["config.json"] + [
                f"diffusion_pytorch_model-0000{i}-of-00003.safetensors"
                for i in (1, 2, 3)],
            "vae": ["config.json", "diffusion_pytorch_model.safetensors"],
            "text_encoder": ["config.json", "model.safetensors"],
            "text_encoder_2": ["config.json"] + [
                f"model-0000{i}-of-00002.safetensors" for i in (1, 2)],
            "tokenizer": ["merges.txt", "vocab.json",
                          "tokenizer_config.json"],
            "tokenizer_2": ["spiece.model", "tokenizer.json",
                            "tokenizer_config.json"],
            # schnell declares use_dynamic_shifting=false + shift=1.0 —
            # without this file the loader would apply dev's dynamic shift
            "scheduler": ["scheduler_config.json"],
        }.items() for f in _hf_files(
            "black-forest-labs/FLUX.1-schnell",
            [f"{sub}/{n}" for n in names])] + _hf_files(
            "black-forest-labs/FLUX.1-schnell", ["model_index.json"]),
        config_file={
            "name": "flux.1-schnell",
            "model": "FLUX.1-schnell",
            "backend": "diffusers",
            "known_usecases": ["image"],
            "diffusers": {"steps": 4, "cfg_scale": 0.0},
        },
    ),
    GalleryModel(
        name="dreamshaper-8",
        description="DreamShaper 8 (SD1.5 fine-tune) — the reference AIO "
                    "image model family",
        license="creativeml-openrail-m",
        tags=["image-generation"],
        files=_hf_files("Lykon/dreamshaper-8", ["model_index.json"]),
        config_file={
            "name": "dreamshaper-8",
            "model": "dreamshaper-8",
            "backend": "diffusers",
            "known_usecases": ["image"],
            "diffusers": {"scheduler_type": "k_dpmpp_2m", "steps": 25},
        },
    ),
]


def shipped_index() -> list[GalleryModel]:
    """The shipped gallery entries (name-keyed copies)."""
    return [m.model_copy(deep=True) for m in _ENTRIES]


SHIPPED_MODELS: dict[str, GalleryModel] = {m.name: m for m in _ENTRIES}
