"""Async gallery job runner: ops queue + status map.

Parity: /root/reference/core/services/gallery.go — a channel of GalleryOps
consumed by one worker goroutine, a uuid→status map polled over HTTP
(``GET /models/jobs/:uuid``), per-file download progress surfaced into the
status, and apply/delete op kinds.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import uuid as uuidlib
from typing import Any, Optional

from localai_tpu.gallery import models as gm
from localai_tpu.gallery.index import Gallery, find_model

log = logging.getLogger(__name__)


@dataclasses.dataclass
class GalleryOp:
    """One queued operation (parity: services.GalleryOp)."""

    id: str
    kind: str                       # "apply" | "delete"
    gallery_ref: str = ""           # name / gallery@name
    model: Optional[gm.GalleryModel] = None  # inline definition
    install_name: str = ""
    overrides: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class JobStatus:
    """Polled job state (parity: gallery.GalleryOpStatus)."""

    deletion: bool = False
    file_name: str = ""
    error: str = ""
    processed: bool = False
    message: str = ""
    progress: float = 0.0
    file_size: str = ""
    downloaded_size: str = ""
    gallery_model_name: str = ""

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _human(n: int) -> str:
    size = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if size < 1024 or unit == "TiB":
            return f"{size:.1f} {unit}"
        size /= 1024
    return f"{n} B"


class GalleryService:
    """Single-worker job runner with a thread-safe status map."""

    def __init__(self, models_path: str, galleries: list[Gallery],
                 on_installed=None, on_deleted=None):
        self.models_path = models_path
        self.galleries = list(galleries)
        # hooks so the serving config registry tracks installs/deletes
        self.on_installed = on_installed    # fn(config_path: Path)
        self.on_deleted = on_deleted        # fn(name: str)
        self._q: "queue.Queue[Optional[GalleryOp]]" = queue.Queue()
        self._status: dict[str, JobStatus] = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gallery-jobs"
        )
        self._thread.start()

    # -- API ---------------------------------------------------------------

    def submit(self, op: GalleryOp) -> str:
        op.id = op.id or str(uuidlib.uuid4())
        with self._lock:
            self._status[op.id] = JobStatus(
                deletion=op.kind == "delete",
                gallery_model_name=op.install_name or op.gallery_ref,
                message="queued",
            )
        self._q.put(op)
        return op.id

    def status(self, job_id: str) -> Optional[JobStatus]:
        with self._lock:
            return self._status.get(job_id)

    def all_status(self) -> dict[str, dict]:
        with self._lock:
            return {k: v.as_dict() for k, v in self._status.items()}

    def shutdown(self) -> None:
        self._q.put(None)
        self._thread.join(10.0)

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            op = self._q.get()
            if op is None:
                return
            st = self.status(op.id) or JobStatus()
            try:
                if op.kind == "delete":
                    self._do_delete(op, st)
                else:
                    self._do_apply(op, st)
                st.processed = True
                st.progress = 100.0
                st.message = "completed"
            except Exception as e:  # noqa: BLE001 — job errors are data
                log.exception("gallery job %s failed", op.id)
                st.processed = True
                st.error = f"{type(e).__name__}: {e}"
                st.message = "error"

    def _do_apply(self, op: GalleryOp, st: JobStatus) -> None:
        model = op.model
        if model is None:
            model = find_model(self.galleries, op.gallery_ref)
            if model is None:
                raise FileNotFoundError(
                    f"no model {op.gallery_ref!r} in galleries "
                    f"{[g.name for g in self.galleries]}"
                )
        st.message = "processing"

        def progress(filename: str, done: int, total: int) -> None:
            st.file_name = filename
            st.downloaded_size = _human(done)
            st.file_size = _human(total)
            if total:
                st.progress = min(99.0, 100.0 * done / total)

        path = gm.install_model(
            model, self.models_path,
            install_name=op.install_name,
            overrides=op.overrides,
            progress=progress,
        )
        if self.on_installed is not None:
            self.on_installed(path)

    def _do_delete(self, op: GalleryOp, st: JobStatus) -> None:
        st.message = "deleting"
        name = op.install_name or op.gallery_ref
        if not gm.delete_model(name, self.models_path):
            raise FileNotFoundError(f"model {name!r} is not installed")
        if self.on_deleted is not None:
            self.on_deleted(name)
