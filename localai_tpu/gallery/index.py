"""Gallery indexes: load, merge, search, and resolve ``gallery@name`` refs.

Parity: /root/reference/core/gallery/gallery.go:19-48 (AvailableGalleryModels
+ findModel resolution across configured galleries) and the `name@gallery`
addressing used by the CLI/API.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import yaml

from localai_tpu.gallery.models import GalleryModel, safe_name
from localai_tpu.utils import downloader

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Gallery:
    """A named index of models (parity: config.Gallery {name, url})."""

    name: str
    url: str


def load_gallery_index(gallery: Gallery) -> list[GalleryModel]:
    """Fetch + parse one gallery index YAML (list of model entries)."""
    import tempfile

    if gallery.url.startswith("file://"):
        text = Path(gallery.url[len("file://"):]).read_text()
    else:
        with tempfile.TemporaryDirectory() as td:
            tmp = Path(td) / "index.yaml"
            downloader.download_uri(gallery.url, tmp)
            text = tmp.read_text()
    docs = yaml.safe_load(text) or []
    if not isinstance(docs, list):
        raise ValueError(f"gallery index {gallery.url} is not a list")
    out = []
    for doc in docs:
        try:
            m = GalleryModel.model_validate(doc)
            m.gallery = gallery.name
            out.append(m)
        except Exception as e:  # noqa: BLE001 — skip malformed entries
            log.warning("gallery %s: skipping bad entry: %s", gallery.name, e)
    return out


def available_models(
    galleries: list[Gallery], models_path: str | Path = "models"
) -> list[GalleryModel]:
    """All models across galleries plus the shipped index, flagged
    installed when their config YAML exists in the models dir."""
    models_path = Path(models_path)
    out: list[GalleryModel] = []
    for g in galleries:
        try:
            models = load_gallery_index(g)
        except Exception as e:  # noqa: BLE001 — one dead gallery ≠ no list
            log.warning("gallery %s unavailable: %s", g.name, e)
            continue
        out.extend(models)
    # the shipped multi-family index (parity: the reference's bundled
    # gallery); configured galleries win on name collisions. Shallow
    # copies only — this runs per HTTP listing request, and the flags
    # set here are scalars (deep copies happen at resolve/install time).
    from localai_tpu.gallery.index_data import _ENTRIES

    seen = {m.name for m in out}
    for m in _ENTRIES:
        if m.name not in seen:
            out.append(m.model_copy(update={"gallery": "shipped"}))
    for m in out:
        m.installed = (models_path / f"{safe_name(m.name)}.yaml").exists()
    return out


def resolve_ref(
    galleries: list[Gallery], ref: str, *, name: str = ""
) -> Optional[GalleryModel]:
    """THE model-ref resolution chain, shared by CLI, API and preload:
    embedded short name → definition URL → gallery lookup (parity:
    pkg/startup/model_preload.go:21+ resolution order)."""
    from localai_tpu.gallery.embedded import resolve_embedded

    m = resolve_embedded(ref)
    if m is not None:
        return m
    if downloader.looks_like_url(ref):
        return GalleryModel(name=name or "model", url=ref)
    m = find_model(galleries, ref)
    if m is not None:
        return m
    # shipped index short names, gallery-qualified as shipped@name too
    from localai_tpu.gallery.index_data import SHIPPED_MODELS

    short = ref.removeprefix("shipped@")
    hit = SHIPPED_MODELS.get(short)
    return hit.model_copy(deep=True) if hit is not None else None


def find_model(
    galleries: list[Gallery], ref: str
) -> Optional[GalleryModel]:
    """Resolve ``name``, ``gallery@name`` or ``name@gallery`` (the reference
    accepts both orders — gallery.go findModel)."""
    name, wanted_gallery = ref, ""
    if "@" in ref:
        a, b = ref.split("@", 1)
        gallery_names = {g.name for g in galleries}
        if a in gallery_names:
            wanted_gallery, name = a, b
        else:
            name, wanted_gallery = a, b
    for g in galleries:
        if wanted_gallery and g.name != wanted_gallery:
            continue
        try:
            for m in load_gallery_index(g):
                if m.name == name:
                    return m
        except Exception as e:  # noqa: BLE001
            log.warning("gallery %s unavailable: %s", g.name, e)
    return None
