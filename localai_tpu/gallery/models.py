"""Gallery model schema + install/delete operations.

Parity: /root/reference/core/gallery/ — ``GalleryModel`` (request.go),
``InstallModel``/``DeleteModel`` (models.go), overrides merged into the
written config (mergo semantics → deep dict merge here), per-file sha256
verification with progress callbacks, and ``known_usecases`` filtering.
"""

from __future__ import annotations

import logging
import re
from pathlib import Path
from typing import Any, Callable, Optional

import yaml
from pydantic import BaseModel, ConfigDict, Field

from localai_tpu.utils import downloader

log = logging.getLogger(__name__)

ProgressFn = Callable[[str, int, int], None]  # (filename, done, total)


class GalleryFile(BaseModel):
    model_config = ConfigDict(extra="allow", populate_by_name=True)

    filename: str
    uri: str
    sha256: str = ""


class GalleryModel(BaseModel):
    """One entry in a gallery index (parity: GalleryModel, request.go +
    config.go ModelConfig with files/overrides)."""

    model_config = ConfigDict(extra="allow", protected_namespaces=())

    name: str
    description: str = ""
    license: str = ""
    urls: list[str] = Field(default_factory=list)
    tags: list[str] = Field(default_factory=list)
    icon: str = ""
    # install payload
    url: str = ""                       # URL of a model-definition YAML
    config_file: Optional[dict] = None  # inline model config
    files: list[GalleryFile] = Field(default_factory=list)
    overrides: dict[str, Any] = Field(default_factory=dict)
    gallery: str = ""                   # which gallery it came from
    installed: bool = False

    @property
    def id(self) -> str:
        return f"{self.gallery}@{self.name}" if self.gallery else self.name


def deep_merge(base: dict, overrides: dict) -> dict:
    """mergo.Merge-with-override parity: nested dicts merge, scalars and
    lists from ``overrides`` win."""
    out = dict(base)
    for k, v in overrides.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


_NAME_RX = re.compile(r"[^a-zA-Z0-9._-]")


def safe_name(name: str) -> str:
    return _NAME_RX.sub("_", name)


def _verify_inside(base: Path, target: Path) -> Path:
    """Path-traversal guard (parity: utils.VerifyPath, pkg/utils/path.go)."""
    base_r = base.resolve()
    target_r = target.resolve()
    if not str(target_r).startswith(str(base_r) + "/") and target_r != base_r:
        raise ValueError(f"path {target} escapes models dir {base}")
    return target


def install_model(
    model: GalleryModel,
    models_path: str | Path,
    *,
    install_name: str = "",
    overrides: Optional[dict] = None,
    progress: Optional[ProgressFn] = None,
) -> Path:
    """Download the model's files (sha-verified, resumable) and write its
    config YAML into the models dir. Returns the config path.

    Parity: InstallModel (core/gallery/models.go) + the config-file
    resolution chain: inline config_file → remote url → bare files.
    """
    models_path = Path(models_path)
    models_path.mkdir(parents=True, exist_ok=True)
    name = install_name or model.name

    config: dict = {}
    if model.url:
        # model definition lives at a URL (yaml with files/overrides/config)
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            tmp = Path(td) / "def.yaml"
            downloader.download_uri(model.url, tmp)
            doc = yaml.safe_load(tmp.read_text()) or {}
        remote = GalleryModel.model_validate({"name": name, **doc})
        if remote.config_file:
            config = dict(remote.config_file)
        files = remote.files or model.files
        config = deep_merge(config, remote.overrides or {})
    else:
        files = model.files
        if model.config_file:
            config = dict(model.config_file)

    config = deep_merge(config, model.overrides or {})
    config = deep_merge(config, overrides or {})
    config["name"] = name

    total_all = 0
    for f in files:
        dest = _verify_inside(models_path, models_path / f.filename)
        log.info("gallery: downloading %s ← %s", f.filename, f.uri)

        def file_progress(done: int, total: int, _fn=f.filename):
            if progress:
                progress(_fn, done, total)

        downloader.download_uri(
            f.uri, dest, sha256=f.sha256 or None, progress=file_progress
        )
        total_all += dest.stat().st_size

    if files:
        # manifest of downloaded files so delete can remove them (the
        # reference keeps this in a gallery metadata file)
        config["downloaded_files"] = [f.filename for f in files]
    config_path = models_path / f"{safe_name(name)}.yaml"
    config_path.write_text(yaml.safe_dump(config, sort_keys=False))
    log.info("gallery: installed %s (%d files, %d bytes) → %s",
             name, len(files), total_all, config_path)
    return config_path


def delete_model(name: str, models_path: str | Path) -> bool:
    """Remove a model's config and its referenced weight files (parity:
    DeleteModelFromSystem, core/gallery/gallery.go)."""
    models_path = Path(models_path)
    config_path = models_path / f"{safe_name(name)}.yaml"
    found = config_path.exists()
    files: list[str] = []
    if found:
        try:
            doc = yaml.safe_load(config_path.read_text()) or {}
            files.extend(doc.get("downloaded_files") or [])
            ref = doc.get("model") or ""
            if ref and not ref.startswith("debug:"):
                files.append(ref)
        except Exception:  # noqa: BLE001
            pass
        config_path.unlink()
    dirs: set[Path] = set()
    for ref in files:
        target = models_path / ref
        try:
            _verify_inside(models_path, target)
        except ValueError:
            continue
        if target.is_dir():
            import shutil

            shutil.rmtree(target, ignore_errors=True)
        elif target.exists():
            target.unlink()
            if target.parent != models_path:
                dirs.add(target.parent)
    for d in dirs:  # prune now-empty per-model dirs
        try:
            d.rmdir()
        except OSError:
            pass
    return found
