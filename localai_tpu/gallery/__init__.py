"""Model gallery: marketplace indexes, installs, async jobs.

Parity: /root/reference/core/gallery/ (+ core/services/gallery.go job
runner, embedded/ short-name library). Install = download files with
sha256 + progress + resume, then write the declarative model config YAML
into the models dir.
"""

from localai_tpu.gallery.embedded import EMBEDDED_MODELS, resolve_embedded
from localai_tpu.gallery.index import (
    Gallery,
    available_models,
    find_model,
    load_gallery_index,
    resolve_ref,
)
from localai_tpu.gallery.models import (
    GalleryFile,
    GalleryModel,
    delete_model,
    install_model,
)
from localai_tpu.gallery.service import GalleryOp, GalleryService, JobStatus
