"""Embedded model short-name library.

Parity: /root/reference/embedded/embedded.go:16-40 + model_library.yaml —
short names resolvable without any configured gallery, so
``local-ai run llama-3-8b-instruct`` style preloading works. Entries are
GalleryModel definitions: debug presets install instantly (no downloads,
synthetic weights — this environment has zero egress), HF entries carry the
real safetensors URIs for networked deployments.
"""

from __future__ import annotations

from localai_tpu.gallery.models import GalleryFile, GalleryModel


def _hf_files(repo: str, files: list[str]) -> list[GalleryFile]:
    owner_repo = repo
    name = repo.split("/")[-1]
    return [
        GalleryFile(
            filename=f"{name}/{f}",
            uri=f"huggingface://{owner_repo}/{f}",
        )
        for f in files
    ]


_SAFETENSOR_SET = ["config.json", "tokenizer.json", "tokenizer_config.json",
                   "model.safetensors"]

EMBEDDED_MODELS: dict[str, GalleryModel] = {
    # instant, offline-safe models (synthetic weights)
    "debug-tiny": GalleryModel(
        name="debug-tiny",
        description="tiny byte-level debug model (synthetic weights)",
        config_file={
            "name": "debug-tiny",
            "model": "debug:tiny",
            "context_size": 1024,
            "embeddings": True,
            "engine": {"max_slots": 4, "prefill_buckets": [128]},
        },
    ),
    "debug-1b": GalleryModel(
        name="debug-1b",
        description="Llama-3.2-1B-class debug model (synthetic weights)",
        config_file={
            "name": "debug-1b",
            "model": "debug:1b",
            "context_size": 8192,
            "engine": {"max_slots": 8, "prefill_buckets": [128, 512, 2048]},
        },
    ),
    # real checkpoints (networked environments)
    "llama-3-8b-instruct": GalleryModel(
        name="llama-3-8b-instruct",
        license="llama3",
        description="Meta Llama 3 8B Instruct (bf16 safetensors)",
        files=_hf_files("meta-llama/Meta-Llama-3-8B-Instruct",
                        ["config.json", "tokenizer.json",
                         "tokenizer_config.json",
                         "model-00001-of-00004.safetensors",
                         "model-00002-of-00004.safetensors",
                         "model-00003-of-00004.safetensors",
                         "model-00004-of-00004.safetensors",
                         "model.safetensors.index.json"]),
        config_file={
            "name": "llama-3-8b-instruct",
            "model": "Meta-Llama-3-8B-Instruct",
            "context_size": 8192,
            "template": {"use_tokenizer_template": True},
            "stopwords": ["<|eot_id|>"],
        },
    ),
    "hermes-2-pro-llama-3-8b": GalleryModel(
        name="hermes-2-pro-llama-3-8b",
        license="llama3",
        description="Hermes 2 Pro Llama-3 8B — the reference AIO text model "
                    "(aio/cpu/text-to-text.yaml), safetensors variant",
        files=_hf_files("NousResearch/Hermes-2-Pro-Llama-3-8B",
                        _SAFETENSOR_SET),
        config_file={
            "name": "hermes-2-pro-llama-3-8b",
            "model": "Hermes-2-Pro-Llama-3-8B",
            "context_size": 8192,
            "template": {"use_tokenizer_template": True},
        },
    ),
    "mistral-7b-instruct": GalleryModel(
        name="mistral-7b-instruct",
        license="apache-2.0",
        description="Mistral 7B Instruct v0.3 (bf16 safetensors)",
        files=_hf_files("mistralai/Mistral-7B-Instruct-v0.3",
                        _SAFETENSOR_SET),
        config_file={
            "name": "mistral-7b-instruct",
            "model": "Mistral-7B-Instruct-v0.3",
            "context_size": 8192,
            "template": {"use_tokenizer_template": True},
        },
    ),
}


def resolve_embedded(name: str) -> GalleryModel | None:
    return EMBEDDED_MODELS.get(name)
