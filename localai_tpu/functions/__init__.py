"""Function calling / constrained decoding (the reference's pkg/functions,
/root/reference/pkg/functions/, rebuilt as a token-mask FSM pipeline:
schema → regex → byte DFA → per-state [V] logit-bias rows)."""

from localai_tpu.functions.constraint import (
    FSMConstraint,
    constraint_for_regex,
    constraint_for_schema,
)
from localai_tpu.functions.fsm import DFA, compile_dfa
from localai_tpu.functions.jsonschema import (
    JSON_OBJECT_REGEX,
    schema_to_regex,
)
from localai_tpu.functions.parse import (
    FuncCallResult,
    cleanup_llm_result,
    parse_function_call,
    parse_json_objects,
    parse_text_content,
)
from localai_tpu.functions.tools import (
    BuiltConstraint,
    build_tool_constraint,
    build_tool_regex,
    functions_to_schema,
    inject_no_action,
    normalize_tools,
    select_function,
)

__all__ = [
    "DFA",
    "FSMConstraint",
    "FuncCallResult",
    "BuiltConstraint",
    "JSON_OBJECT_REGEX",
    "build_tool_constraint",
    "build_tool_regex",
    "cleanup_llm_result",
    "compile_dfa",
    "constraint_for_regex",
    "constraint_for_schema",
    "functions_to_schema",
    "inject_no_action",
    "normalize_tools",
    "parse_function_call",
    "parse_json_objects",
    "parse_text_content",
    "schema_to_regex",
    "select_function",
]
