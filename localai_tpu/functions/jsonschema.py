"""JSON-schema → byte-regex compiler for constrained decoding.

Parity target: the reference's JSON-schema→BNF converter
(/root/reference/pkg/functions/grammars/json_schema.go:204 and
bnf_rules.go) — same coverage (types, const/enum, properties in a
configurable order, arrays, oneOf/anyOf, $defs/$ref, free-form values),
but compiled to a regular expression consumed by fsm.compile_dfa, because
on TPU the constraint is applied as a token logit mask, not a CPU sampler
grammar (SURVEY.md §7.2 step 5).

Free-form ("any") values are expanded to a bounded nesting depth — a
regular language can't express unbounded recursion; depth 4 covers
practical tool arguments.
"""

from __future__ import annotations

import json
from typing import Any, Optional

# Single optional whitespace between tokens: keeps the DFA small while
# accepting the formatting LLMs actually emit.
WS = "[ \\t\\n]{0,3}"

STRING_INNER = r'([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))'
STRING = f'"{STRING_INNER}*"'
INTEGER = r"-?(0|[1-9][0-9]*)"
NUMBER = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"
BOOLEAN = r"(true|false)"
NULL = r"null"

_SPECIALS = set("\\.^$*+?()[]{}|")


def escape_literal(text: str) -> str:
    """Escape a literal string for the fsm regex dialect."""
    out = []
    for ch in text:
        if ch in _SPECIALS:
            out.append("\\" + ch)
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\t":
            out.append("\\t")
        elif ch == "\r":
            out.append("\\r")
        else:
            out.append(ch)
    return "".join(out)


def _const_regex(value: Any) -> str:
    return escape_literal(json.dumps(value, separators=(",", ":"),
                                     ensure_ascii=False))


def _any_value(depth: int) -> str:
    """Free-form JSON value to bounded depth."""
    scalar = f"({STRING}|{NUMBER}|{BOOLEAN}|{NULL})"
    if depth <= 0:
        return scalar
    inner = _any_value(depth - 1)
    arr = f"\\[{WS}({inner}({WS},{WS}{inner})*)?{WS}\\]"
    obj = (f"\\{{{WS}({STRING}{WS}:{WS}{inner}"
           f"({WS},{WS}{STRING}{WS}:{WS}{inner})*)?{WS}\\}}")
    return f"({scalar}|{arr}|{obj})"


class SchemaError(ValueError):
    pass


class SchemaCompiler:
    """One schema → one regex. Stateless between compiles except $defs."""

    def __init__(self, *, prop_order: Optional[list[str]] = None,
                 any_depth: int = 3, max_ref_depth: int = 16):
        self.prop_order = prop_order or []
        self.any_depth = any_depth
        self.max_ref_depth = max_ref_depth
        self._root: dict = {}

    def compile(self, schema: dict) -> str:
        self._root = schema
        return self._visit(schema, 0)

    # -- dispatch ---------------------------------------------------------

    def _visit(self, schema: Any, depth: int) -> str:
        if depth > self.max_ref_depth:
            raise SchemaError("schema nesting/$ref depth exceeded "
                              f"{self.max_ref_depth} (recursive schema?)")
        if schema is True or schema == {}:
            return _any_value(self.any_depth)
        if not isinstance(schema, dict):
            raise SchemaError(f"unsupported schema node: {schema!r}")
        if "$ref" in schema:
            return self._visit(self._resolve_ref(schema["$ref"]), depth + 1)
        if "const" in schema:
            return _const_regex(schema["const"])
        if "enum" in schema:
            return "(" + "|".join(_const_regex(v) for v in schema["enum"]) + ")"
        for key in ("oneOf", "anyOf"):
            if key in schema:
                opts = [self._visit(s, depth + 1) for s in schema[key]]
                return "(" + "|".join(opts) + ")"
        if "allOf" in schema:
            merged: dict = {}
            for sub in schema["allOf"]:
                if "$ref" in sub:
                    sub = self._resolve_ref(sub["$ref"])
                merged = _merge(merged, sub)
            merged = _merge(merged,
                            {k: v for k, v in schema.items() if k != "allOf"})
            return self._visit(merged, depth + 1)

        typ = schema.get("type")
        if isinstance(typ, list):
            return "(" + "|".join(
                self._visit({**schema, "type": t}, depth + 1) for t in typ
            ) + ")"
        if typ == "string":
            return self._string(schema)
        if typ == "integer":
            return INTEGER
        if typ == "number":
            return NUMBER
        if typ == "boolean":
            return BOOLEAN
        if typ == "null":
            return NULL
        if typ == "object" or "properties" in schema:
            return self._object(schema, depth)
        if typ == "array" or "items" in schema or "prefixItems" in schema:
            return self._array(schema, depth)
        return _any_value(self.any_depth)

    # -- per-type ---------------------------------------------------------

    def _string(self, schema: dict) -> str:
        if "pattern" in schema:
            # Inline the user pattern for the *content* of the string; it must
            # be in the supported dialect (we strip anchors).
            pat = schema["pattern"]
            pat = pat.removeprefix("^").removesuffix("$")
            return f'"({pat})"'
        lo = schema.get("minLength")
        hi = schema.get("maxLength")
        if lo is None and hi is None:
            return STRING
        quant = f"{{{lo or 0},{hi if hi is not None else ''}}}"
        return f'"{STRING_INNER}{quant}"'

    def _object(self, schema: dict, depth: int) -> str:
        props: dict[str, Any] = schema.get("properties", {})
        required = schema.get("required")
        if required is None:
            required_set = set(props)  # all required (reference BNF behavior)
        else:
            required_set = set(required)
        names = list(props)
        if self.prop_order:
            order = {n: i for i, n in enumerate(self.prop_order)}
            names.sort(key=lambda n: (order.get(n, len(order)), ))
        req = [n for n in names if n in required_set]
        opt = [n for n in names if n not in required_set]

        def kv(name: str) -> str:
            val = self._visit(props[name], depth + 1)
            return f'"{escape_literal(name)}"{WS}:{WS}{val}'

        if not props:
            addl = schema.get("additionalProperties")
            if addl in (None, True) or isinstance(addl, dict):
                val = (self._visit(addl, depth + 1) if isinstance(addl, dict)
                       else _any_value(self.any_depth))
                pair = f"{STRING}{WS}:{WS}{val}"
                return (f"\\{{{WS}({pair}({WS},{WS}{pair})*)?{WS}\\}}")
            return f"\\{{{WS}\\}}"

        if req:
            # required properties in order; optional ones may follow the
            # required run, each in declared order — a practical regular
            # approximation of JSON-schema objects.
            seq = kv(req[0])
            for name in req[1:]:
                seq += f"{WS},{WS}{kv(name)}"
            for name in opt:
                seq += f"({WS},{WS}{kv(name)})?"
            inner = seq
        else:
            # no required props: empty object, or a subset starting at any
            # property, preserving declared order
            alts = []
            for i in range(len(opt)):
                seq = kv(opt[i])
                for name in opt[i + 1:]:
                    seq += f"({WS},{WS}{kv(name)})?"
                alts.append(seq)
            inner = "(" + "|".join(alts) + ")?" if alts else ""
        return f"\\{{{WS}{inner}{WS}\\}}" if inner else f"\\{{{WS}\\}}"

    def _array(self, schema: dict, depth: int) -> str:
        if "prefixItems" in schema:
            items = [self._visit(s, depth + 1) for s in schema["prefixItems"]]
            seq = f"{WS},{WS}".join(items)
            return f"\\[{WS}{seq}{WS}\\]"
        item = self._visit(schema.get("items", True), depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if hi is not None:
            hi = int(hi)
            if hi == 0:
                return f"\\[{WS}\\]"
            more = f"({WS},{WS}{item}){{{max(lo - 1, 0)},{hi - 1}}}"
            body = f"{item}{more}"
            if lo == 0:
                body = f"({body})?"
            return f"\\[{WS}{body}{WS}\\]"
        if lo <= 0:
            return f"\\[{WS}({item}({WS},{WS}{item})*)?{WS}\\]"
        more = f"({WS},{WS}{item}){{{lo - 1},}}"
        return f"\\[{WS}{item}{more}{WS}\\]"

    # -- refs -------------------------------------------------------------

    def _resolve_ref(self, ref: str) -> dict:
        if not ref.startswith("#/"):
            raise SchemaError(f"only local $refs supported, got {ref!r}")
        node: Any = self._root
        try:
            for part in ref[2:].split("/"):
                part = part.replace("~1", "/").replace("~0", "~")
                if isinstance(node, list):
                    node = node[int(part)]
                else:
                    node = node[part]
        except (KeyError, IndexError, ValueError, TypeError) as e:
            raise SchemaError(f"unresolvable $ref {ref!r}: {e}") from e
        return node


def _merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _merge(out[k], v)
        elif k in out and k == "required":
            out[k] = list(dict.fromkeys(list(out[k]) + list(v)))
        else:
            out[k] = v
    return out


def schema_to_regex(schema: dict, *, prop_order: Optional[list[str]] = None,
                    any_depth: int = 3) -> str:
    """Public entry: JSON schema dict → fsm-dialect regex string."""
    return SchemaCompiler(
        prop_order=prop_order, any_depth=any_depth
    ).compile(schema)


# The fixed "any JSON object" pattern used for OpenAI's
# response_format={"type":"json_object"} — parity with the reference's
# JSONBNF (/root/reference/pkg/functions/json_mode.go).
JSON_OBJECT_REGEX = _any_value(4)


def sort_prop_order(spec: str) -> list[str]:
    """Parse the reference's "name,arguments" properties_order string
    (/root/reference/pkg/functions/grammars/options.go SetPropOrder)."""
    return [p for p in (s.strip() for s in spec.split(",")) if p]
