"""Token-level FSM constraint: the scheduler-facing half of constrained
decoding.

Design (SURVEY.md §7.3 hard part 2 — grammar masking at TPU speed):
llama.cpp walks a BNF parser over candidate tokens on the CPU every step;
here the grammar is a byte DFA (fsm.py) compiled once, the tokenizer vocab
is a byte trie built once, and a token mask for a DFA state is ONE
vectorized trie walk (numpy, O(trie nodes) ≈ ms) cached per state — JSON
grammars revisit a small set of states, so steady-state per-token cost is
an O(1) dict lookup + the [V] bias row the engine already consumes
(ModelRunner.set_bias). No per-token host↔device round trip beyond the
row write the sampler takes anyway.

Implements the scheduler's TokenConstraint protocol
(localai_tpu.engine.scheduler).
"""

from __future__ import annotations

import ctypes
import logging
from typing import Any, Optional, Sequence

import numpy as np

from localai_tpu.functions.fsm import DFA, compile_dfa

log = logging.getLogger(__name__)

NEG = np.float32(-1e30)

_NATIVE_SENTINEL = object()
_native_lib: Any = _NATIVE_SENTINEL


def _native_fsm():
    """The compiled fsm_walk C module, or None (numpy fallback)."""
    global _native_lib
    if _native_lib is _NATIVE_SENTINEL:
        from localai_tpu.native import load

        _native_lib = load("fsm_walk")
    return _native_lib


# ---------------------------------------------------------------------------
# Vocabulary → byte sequences


def token_bytes_table(tokenizer: Any) -> list[Optional[bytes]]:
    """Best-effort byte representation per token id; None = never maskable-in
    (special/control tokens).

    For the built-in ByteTokenizer this is exact. For HF tokenizers we use
    the decode-difference trick (decode [probe, id] minus decode [probe]) so
    sentencepiece leading-space conventions survive.
    """
    cached = getattr(tokenizer, "_token_bytes_table", None)
    if cached is not None:
        return cached

    vs = tokenizer.vocab_size
    table: list[Optional[bytes]] = [None] * vs
    if type(tokenizer).__name__ == "ByteTokenizer":
        for i in range(256):
            table[i] = bytes([i])
    else:
        special = set(getattr(tokenizer, "eos_ids", set()))
        special |= set(getattr(tokenizer, "special_ids", set()))
        probe = None
        try:
            probe_ids = tokenizer.encode("x")
            probe = probe_ids[-1] if probe_ids else None
        except Exception:  # noqa: BLE001
            pass
        base = tokenizer.decode([probe]) if probe is not None else ""
        for i in range(vs):
            if i in special:
                continue
            try:
                if probe is not None:
                    text = tokenizer.decode([probe, i])[len(base):]
                else:
                    text = tokenizer.decode([i])
            except Exception:  # noqa: BLE001
                continue
            if text:
                table[i] = text.encode("utf-8")
    tokenizer._token_bytes_table = table
    return table


class TokenTrie:
    """Vocab as level-ordered arrays for vectorized DFA walks.

    Node 0 is the root. For each depth level d we store the node ids at that
    level, their parent node ids, and their edge bytes; a walk assigns DFA
    states level by level with one fancy-indexing op per level.
    """

    def __init__(self, table: Sequence[Optional[bytes]]):
        children: dict[tuple[int, int], int] = {}
        parent = [0]
        edge = [0]
        depth_of = [0]
        leaf_of_token = np.zeros(len(table), dtype=np.int64)
        token_ok = np.zeros(len(table), dtype=bool)
        for tid, bs in enumerate(table):
            if not bs:  # None or empty: never allowed (no FSM progress)
                continue
            node = 0
            for b in bs:
                key = (node, b)
                nxt = children.get(key)
                if nxt is None:
                    nxt = len(parent)
                    children[key] = nxt
                    parent.append(node)
                    edge.append(b)
                    depth_of.append(depth_of[node] + 1)
                node = nxt
            leaf_of_token[tid] = node
            token_ok[tid] = True
        self.n_nodes = len(parent)
        self.parent = np.asarray(parent, dtype=np.int64)
        self.edge = np.asarray(edge, dtype=np.int64)
        self.leaf_of_token = leaf_of_token
        self.token_ok = token_ok
        depths = np.asarray(depth_of)
        self.levels = [
            np.nonzero(depths == d)[0]
            for d in range(1, int(depths.max()) + 1 if self.n_nodes > 1 else 1)
        ]

    @staticmethod
    def for_tokenizer(tokenizer: Any) -> "TokenTrie":
        trie = getattr(tokenizer, "_token_trie", None)
        if trie is None:
            trie = TokenTrie(token_bytes_table(tokenizer))
            tokenizer._token_trie = trie
        return trie

    def walk(self, dfa: DFA, state: int) -> np.ndarray:
        """DFA final state per trie node, starting every token at `state`.
        Dead-state propagation makes `final != DEAD` ⇔ whole token legal.

        Takes the native single-pass kernel when the C module compiled
        (localai_tpu/native/fsm_walk.c — parents precede children in the
        node order, so one linear loop resolves every node); the numpy
        per-level gather below is the fallback."""
        states = np.zeros(self.n_nodes, dtype=np.int32)
        states[0] = state
        lib = _native_fsm()
        if lib is not None:
            # contiguous int32/uint8 views cached on the DFA object
            trans = dfa.__dict__.get("_trans_i32")
            if trans is None:
                trans = np.ascontiguousarray(dfa.trans, dtype=np.int32)
                dfa.__dict__["_trans_i32"] = trans
            cls = dfa.__dict__.get("_cls_u8")
            if cls is None:
                cls = np.ascontiguousarray(
                    dfa.byte_class.astype(np.uint8))
                dfa.__dict__["_cls_u8"] = cls
            lib.fsm_walk(
                trans.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int32(trans.shape[1]),
                cls.ctypes.data_as(ctypes.c_void_p),
                self.parent.ctypes.data_as(ctypes.c_void_p),
                self.edge.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(self.n_nodes),
                states.ctypes.data_as(ctypes.c_void_p),
            )
            return states
        cls = dfa.byte_class
        for nodes in self.levels:
            states[nodes] = dfa.trans[
                states[self.parent[nodes]], cls[self.edge[nodes]]
            ]
        return states


# ---------------------------------------------------------------------------
# The constraint object handed to the scheduler


class FSMConstraint:
    """Drives one request's grammar: mask rows + state advance.

    `allowed_mask` → [V] f32 additive bias (0 allowed / -1e30 banned); EOS
    ids are allowed exactly in accepting states. Returns None once the FSM
    has terminally matched (free region after completion is not part of the
    grammar — the scheduler treats None as "anything").
    """

    def __init__(self, dfa: DFA, tokenizer: Any):
        self.dfa = dfa
        self.tokenizer = tokenizer
        self.trie = TokenTrie.for_tokenizer(tokenizer)
        self.vocab_size = tokenizer.vocab_size
        self.eos_ids = sorted(getattr(tokenizer, "eos_ids", set()))
        self.state = dfa.start
        self._done = False
        # per-state caches: mask row and per-token final state (for advance).
        # Shared across all requests using the same (dfa, vocab trie) — the
        # expensive trie walks happen once per state per grammar, not per
        # request. WeakKeyDictionary: when a model's tokenizer (and thus its
        # trie) is unloaded, its [V]-sized rows are collectible, and a new
        # trie can never collide with a dead one's cache.
        import weakref

        shared = dfa.__dict__.setdefault(
            "_vocab_caches", weakref.WeakKeyDictionary()
        )
        cached = shared.get(self.trie)
        if cached is None:
            cached = ({}, {})
            shared[self.trie] = cached
        self._masks, self._finals = cached

    # -- TokenConstraint protocol ----------------------------------------

    def allowed_mask(self) -> Optional[np.ndarray]:
        if self._done:
            return None
        return self._row(self.state)

    def advance(self, token_id: int) -> None:
        if self._done:
            return
        if token_id in self.eos_ids:
            self._done = True
            return
        finals = self._final_states(self.state)
        if not self.trie.token_ok[token_id]:
            log.warning("constraint: non-text token %d sampled", token_id)
            self._done = True
            return
        nxt = int(finals[self.trie.leaf_of_token[token_id]])
        if nxt == DFA.DEAD:
            # Shouldn't happen under masking; fail open so generation ends
            # cleanly rather than wedging the slot.
            log.warning("constraint: token %d left the grammar", token_id)
            self._done = True
            return
        self.state = nxt
        if self.dfa.forced_end(self.state):
            self._done = True

    @property
    def done(self) -> bool:
        return self._done

    # -- internals --------------------------------------------------------

    def _final_states(self, state: int) -> np.ndarray:
        finals = self._finals.get(state)
        if finals is None:
            node_states = self.trie.walk(self.dfa, state)
            finals = node_states
            self._finals[state] = finals
        return finals

    def _row(self, state: int) -> np.ndarray:
        row = self._masks.get(state)
        if row is None:
            finals = self._final_states(state)
            lib = _native_fsm()
            if lib is not None:
                # fused gather+compare+select in C (fsm_walk.c:fsm_mask):
                # no [V] temporaries on a mask-cache miss
                row = np.empty(len(self.trie.leaf_of_token), np.float32)
                ok_u8 = self.trie.__dict__.get("_ok_u8")
                if ok_u8 is None:
                    ok_u8 = np.ascontiguousarray(
                        self.trie.token_ok.astype(np.uint8))
                    self.trie.__dict__["_ok_u8"] = ok_u8
                lib.fsm_mask(
                    finals.ctypes.data_as(ctypes.c_void_p),
                    self.trie.leaf_of_token.ctypes.data_as(
                        ctypes.c_void_p),
                    ok_u8.ctypes.data_as(ctypes.c_void_p),
                    ctypes.c_int64(len(self.trie.leaf_of_token)),
                    ctypes.c_int32(DFA.DEAD),
                    row.ctypes.data_as(ctypes.c_void_p),
                )
            else:
                tok_final = finals[self.trie.leaf_of_token]
                allowed = self.trie.token_ok & (tok_final != DFA.DEAD)
                row = np.where(allowed, np.float32(0.0),
                               NEG).astype(np.float32)
            allowed_any = bool((row == 0.0).any())
            if bool(self.dfa.accept[state]):
                for e in self.eos_ids:
                    row[e] = 0.0
            elif not allowed_any:
                # dead grammar state with nothing allowed: permit EOS so the
                # slot can finish instead of sampling uniformly over -1e30
                for e in self.eos_ids:
                    row[e] = 0.0
            self._masks[state] = row
        return row


# ---------------------------------------------------------------------------
# Convenience constructors


_DFA_CACHE: dict[str, DFA] = {}
_DFA_CACHE_MAX = 128


def cached_dfa(pattern: str) -> DFA:
    """Compile-once cache keyed by pattern text: repeated requests with the
    same toolset skip NFA→DFA construction AND share per-state mask rows
    (they hang off the DFA object)."""
    dfa = _DFA_CACHE.get(pattern)
    if dfa is None:
        dfa = compile_dfa(pattern)
        if len(_DFA_CACHE) >= _DFA_CACHE_MAX:
            _DFA_CACHE.pop(next(iter(_DFA_CACHE)))
        _DFA_CACHE[pattern] = dfa
    return dfa


def constraint_for_regex(pattern: str, tokenizer: Any) -> FSMConstraint:
    c = FSMConstraint(cached_dfa(pattern), tokenizer)
    # retained so worker-backed serving can ship the constraint over the
    # wire (PredictOptions.constraint_regex) and rebuild the FSM remotely
    c.source_regex = pattern
    return c


def constraint_for_schema(schema: dict, tokenizer: Any, *,
                          prop_order: Optional[list[str]] = None,
                          any_depth: int = 3) -> FSMConstraint:
    from localai_tpu.functions.jsonschema import schema_to_regex

    return constraint_for_regex(
        schema_to_regex(schema, prop_order=prop_order, any_depth=any_depth),
        tokenizer,
    )
