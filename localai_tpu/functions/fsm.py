"""Byte-level regex → NFA → DFA engine for constrained decoding.

TPU-era replacement for the reference's BNF grammar pipeline
(/root/reference/pkg/functions/grammars/{json_schema,bnf_rules,rules}.go +
llama.cpp's CPU grammar sampler): instead of handing BNF text to a
per-token CPU sampler, we compile the constraint to a DFA over UTF-8
*bytes* once, and at serve time the only per-token work is an O(1) state
lookup plus a cached [V] mask row (see constraint.py).

The regex dialect is the small subset our own compilers emit
(jsonschema.py): literals, escapes, char classes with ranges/negation,
``(...)``, ``|``, ``* + ?``, ``{m}``/``{m,}``/``{m,n}``, and ``.`` (any
byte). No capture semantics, no anchors (matches are always whole-string),
no backreferences — the language is regular by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import numpy as np

# ---------------------------------------------------------------------------
# AST


@dataclasses.dataclass(frozen=True)
class Lit:
    """Single byte-class step; mask is a frozen 256-bool tuple index set."""

    bytes_mask: bytes  # 256-byte 0/1 mask (hashable, unlike ndarray)


@dataclasses.dataclass(frozen=True)
class Concat:
    parts: tuple["Node", ...]


@dataclasses.dataclass(frozen=True)
class Alt:
    options: tuple["Node", ...]


@dataclasses.dataclass(frozen=True)
class Repeat:
    node: "Node"
    lo: int
    hi: Optional[int]  # None = unbounded


Node = Union[Lit, Concat, Alt, Repeat]

EPSILON = Concat(())


def _mask_of(byte_ids) -> bytes:
    m = bytearray(256)
    for b in byte_ids:
        m[b] = 1
    return bytes(m)


_ANY = _mask_of(range(256))
_DIGIT = _mask_of(range(0x30, 0x3A))
_SPACE = _mask_of(b" \t\n\r\f\v")
_WORD = _mask_of(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F]
)


def _invert(mask: bytes) -> bytes:
    return bytes(1 - b for b in mask)


# ---------------------------------------------------------------------------
# Parser (recursive descent over the emitted dialect)


class RegexError(ValueError):
    pass


class _Parser:
    def __init__(self, pattern: str):
        self.src = pattern.encode("utf-8")
        self.i = 0

    def parse(self) -> Node:
        node = self._alt()
        if self.i != len(self.src):
            raise RegexError(f"trailing input at byte {self.i}")
        return node

    # grammar: alt := concat ('|' concat)* ; concat := repeat* ;
    #          repeat := atom quantifier? ; atom := literal | class | group | .
    def _alt(self) -> Node:
        opts = [self._concat()]
        while self._peek() == 0x7C:  # '|'
            self.i += 1
            opts.append(self._concat())
        return opts[0] if len(opts) == 1 else Alt(tuple(opts))

    def _concat(self) -> Node:
        parts = []
        while True:
            c = self._peek()
            if c is None or c in (0x7C, 0x29):  # '|' ')'
                break
            parts.append(self._repeat())
        if len(parts) == 1:
            return parts[0]
        return Concat(tuple(parts))

    def _repeat(self) -> Node:
        atom = self._atom()
        c = self._peek()
        if c == 0x2A:  # '*'
            self.i += 1
            return Repeat(atom, 0, None)
        if c == 0x2B:  # '+'
            self.i += 1
            return Repeat(atom, 1, None)
        if c == 0x3F:  # '?'
            self.i += 1
            return Repeat(atom, 0, 1)
        if c == 0x7B:  # '{'
            j = self.src.index(b"}", self.i)
            spec = self.src[self.i + 1:j].decode()
            self.i = j + 1
            if "," in spec:
                lo_s, hi_s = spec.split(",", 1)
                lo = int(lo_s or 0)
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(spec)
            if hi is not None and hi < lo:
                raise RegexError(f"bad quantifier {{{spec}}}")
            return Repeat(atom, lo, hi)
        return atom

    def _atom(self) -> Node:
        c = self._peek()
        if c is None:
            raise RegexError("unexpected end of pattern")
        if c == 0x28:  # '('
            self.i += 1
            if self.src[self.i:self.i + 2] == b"?:":
                self.i += 2
            node = self._alt()
            if self._peek() != 0x29:
                raise RegexError("unbalanced group")
            self.i += 1
            return node
        if c == 0x5B:  # '['
            return self._char_class()
        if c == 0x2E:  # '.'
            self.i += 1
            return Lit(_ANY)
        if c == 0x5C:  # '\'
            self.i += 1
            return Lit(self._escape())
        self.i += 1
        return Lit(_mask_of([c]))

    def _escape(self) -> bytes:
        c = self.src[self.i]
        self.i += 1
        table = {0x64: _DIGIT, 0x44: _invert(_DIGIT), 0x73: _SPACE,
                 0x53: _invert(_SPACE), 0x77: _WORD, 0x57: _invert(_WORD)}
        if c in table:
            return table[c]
        literal = {0x6E: 0x0A, 0x74: 0x09, 0x72: 0x0D, 0x66: 0x0C,
                   0x76: 0x0B, 0x30: 0x00}
        if c in literal:
            return _mask_of([literal[c]])
        if c == 0x78:  # \xHH
            h = self.src[self.i:self.i + 2].decode()
            self.i += 2
            return _mask_of([int(h, 16)])
        return _mask_of([c])  # escaped literal (\{ \} \" \\ ...)

    def _char_class(self) -> Node:
        self.i += 1  # '['
        negate = self._peek() == 0x5E  # '^'
        if negate:
            self.i += 1
        mask = bytearray(256)
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexError("unterminated character class")
            if c == 0x5D and not first:  # ']'
                self.i += 1
                break
            first = False
            if c == 0x5C:
                self.i += 1
                sub = self._escape()
                if sum(sub) != 1:  # class escape like \d inside [...]
                    for b in range(256):
                        if sub[b]:
                            mask[b] = 1
                    continue
                lo = sub.index(1)
            else:
                lo = c
                self.i += 1
            if self._peek() == 0x2D and self.src[self.i + 1:self.i + 2] != b"]":
                self.i += 1  # '-'
                hc = self._peek()
                if hc == 0x5C:
                    self.i += 1
                    esc = self._escape()
                    hi = esc.index(1)
                else:
                    hi = hc
                    self.i += 1
                for b in range(lo, hi + 1):
                    mask[b] = 1
            else:
                mask[lo] = 1
        out = bytes(mask)
        return Lit(_invert(out) if negate else out)

    def _peek(self) -> Optional[int]:
        return self.src[self.i] if self.i < len(self.src) else None


def parse(pattern: str) -> Node:
    return _Parser(pattern).parse()


# ---------------------------------------------------------------------------
# Thompson NFA


class _NFA:
    def __init__(self) -> None:
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[bytes, int]]] = []  # (byte mask, target)

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node: Node, src: int, dst: int) -> None:
        if isinstance(node, Lit):
            self.edges[src].append((node.bytes_mask, dst))
        elif isinstance(node, Concat):
            cur = src
            for part in node.parts[:-1] if node.parts else ():
                nxt = self.state()
                self.build(part, cur, nxt)
                cur = nxt
            if node.parts:
                self.build(node.parts[-1], cur, dst)
            else:
                self.eps[src].append(dst)
        elif isinstance(node, Alt):
            for opt in node.options:
                self.build(opt, src, dst)
        elif isinstance(node, Repeat):
            cur = src
            for _ in range(node.lo):
                nxt = self.state()
                self.build(node.node, cur, nxt)
                cur = nxt
            if node.hi is None:
                loop = self.state()
                self.eps[cur].append(loop)
                self.build(node.node, loop, loop)
                self.eps[loop].append(dst)
            else:
                for _ in range(node.hi - node.lo):
                    self.eps[cur].append(dst)
                    nxt = self.state()
                    self.build(node.node, cur, nxt)
                    cur = nxt
                self.eps[cur].append(dst)
        else:  # pragma: no cover
            raise TypeError(node)


# ---------------------------------------------------------------------------
# DFA (subset construction over byte equivalence classes)


@dataclasses.dataclass
class DFA:
    """Dense byte-class DFA. State 0 is always the dead state."""

    trans: np.ndarray        # [n_states, n_classes] int32
    accept: np.ndarray       # [n_states] bool
    byte_class: np.ndarray   # [256] int32
    start: int

    DEAD = 0

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    def step_byte(self, state: int, byte: int) -> int:
        return int(self.trans[state, self.byte_class[byte]])

    def step_bytes(self, state: int, data: bytes) -> int:
        for b in data:
            state = self.step_byte(state, b)
            if state == self.DEAD:
                return state
        return state

    def matches(self, text: Union[str, bytes]) -> bool:
        data = text.encode("utf-8") if isinstance(text, str) else text
        return bool(self.accept[self.step_bytes(self.start, data)])

    def live(self, state: int) -> bool:
        """True if any continuation from `state` can still reach accept."""
        return state != self.DEAD

    def forced_end(self, state: int) -> bool:
        """Accepting state with no live outgoing transition: match complete."""
        return bool(self.accept[state]) and bool(
            (self.trans[state] == self.DEAD).all()
        )


def _byte_classes(masks: list[bytes]) -> np.ndarray:
    """Partition 0..255 into equivalence classes indistinguishable by any
    transition mask — collapses the 256-wide alphabet to typically <64."""
    classes: dict[bytes, int] = {}
    arr = np.zeros((len(masks), 256), dtype=np.uint8)
    for i, m in enumerate(masks):
        arr[i] = np.frombuffer(m, dtype=np.uint8)
    out = np.zeros(256, dtype=np.int32)
    for b in range(256):
        key = arr[:, b].tobytes()
        out[b] = classes.setdefault(key, len(classes))
    return out


def compile_dfa(pattern: Union[str, Node]) -> DFA:
    node = parse(pattern) if isinstance(pattern, str) else pattern
    nfa = _NFA()
    s0 = nfa.state()
    s1 = nfa.state()
    nfa.build(node, s0, s1)

    # epsilon closures (iterative DFS, computed per subset on demand)
    def closure(states: frozenset[int]) -> frozenset[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    masks = [m for edges in nfa.edges for (m, _) in edges]
    if not masks:
        masks = [_ANY]
    byte_class = _byte_classes(masks)
    n_classes = int(byte_class.max()) + 1
    # representative byte per class
    rep = np.zeros(n_classes, dtype=np.int32)
    for c in range(n_classes):
        rep[c] = int(np.argmax(byte_class == c))

    start_set = closure(frozenset([s0]))
    ids: dict[frozenset[int], int] = {frozenset(): DFA.DEAD, start_set: 1}
    order: list[frozenset[int]] = [frozenset(), start_set]
    rows: list[list[int]] = [[DFA.DEAD] * n_classes]
    qi = 1  # BFS over `order` so discovery order == state id order
    while qi < len(order):
        cur = order[qi]
        qi += 1
        row = [DFA.DEAD] * n_classes
        for c in range(n_classes):
            b = int(rep[c])
            targets = set()
            for s in cur:
                for m, t in nfa.edges[s]:
                    if m[b]:
                        targets.add(t)
            if targets:
                nxt = closure(frozenset(targets))
                if nxt not in ids:
                    ids[nxt] = len(order)
                    order.append(nxt)
                row[c] = ids[nxt]
        rows.append(row)
    trans = np.asarray(rows, dtype=np.int32)
    accept = np.zeros(len(order), dtype=bool)
    for subset, sid in ids.items():
        accept[sid] = s1 in subset

    # prune states that can never reach accept (turn them into DEAD) so that
    # `state != DEAD` is exactly "still matchable" — the property the token
    # mask relies on.
    live = accept.copy()
    changed = True
    while changed:
        changed = False
        reaches = live[trans].any(axis=1)
        new_live = live | reaches
        if (new_live != live).any():
            live = new_live
            changed = True
    remap = np.zeros(len(order), dtype=np.int32)
    nxt_id = 1
    for sid in range(1, len(order)):
        if live[sid]:
            remap[sid] = nxt_id
            nxt_id += 1
    new_trans = np.zeros((nxt_id, n_classes), dtype=np.int32)
    new_accept = np.zeros(nxt_id, dtype=bool)
    for sid in range(1, len(order)):
        if live[sid]:
            new_trans[remap[sid]] = np.where(
                live[trans[sid]], remap[trans[sid]], DFA.DEAD
            )
            new_accept[remap[sid]] = accept[sid]
    start = int(remap[1]) if live[1] else DFA.DEAD
    return DFA(trans=new_trans, accept=new_accept,
               byte_class=byte_class, start=start)
