"""OpenAI tools → JSON-schema union → decoding constraint.

Parity: Functions.ToJSONStructure + grammar options
(/root/reference/pkg/functions/functions.go:39,
grammars/options.go, json_schema.go, llama31_schema.go) — re-targeted at
the FSM/logit-mask pipeline (jsonschema.py + constraint.py) instead of
BNF text.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from localai_tpu.config.model_config import FunctionsConfig
from localai_tpu.functions.jsonschema import (
    WS,
    escape_literal,
    schema_to_regex,
    sort_prop_order,
)

NO_ACTION_DESCRIPTION = (
    "use this action to answer the user without performing any other action"
)


def normalize_tools(tools_or_functions: list[dict]) -> list[dict]:
    """Accept both OpenAI `tools` ([{type:function, function:{...}}]) and
    legacy `functions` ([{name,...}]) shapes; return plain function dicts."""
    out = []
    for t in tools_or_functions or []:
        fn = t.get("function") if isinstance(t.get("function"), dict) else t
        if fn.get("name"):
            out.append(fn)
    return out


def inject_no_action(functions: list[dict], cfg: FunctionsConfig) -> list[dict]:
    """Add the default do-nothing tool the LLM uses to answer in prose
    (parity: chat.go no-action injection; disable_no_action skips it)."""
    if cfg.disable_no_action:
        return functions
    name = cfg.no_action_function_name or "answer"
    desc = cfg.no_action_description_name or NO_ACTION_DESCRIPTION
    action = {
        "name": name,
        "description": desc,
        "parameters": {
            "type": "object",
            "properties": {
                "message": {
                    "type": "string",
                    "description": "The message to reply the user with",
                },
            },
            "required": ["message"],
        },
    }
    return list(functions) + [action]


def select_function(functions: list[dict], name: str) -> list[dict]:
    """tool_choice={"name": x} narrowing (parity: Functions.Select)."""
    return [f for f in functions if f.get("name") == name] or list(functions)


def functions_to_schema(
    functions: list[dict],
    *,
    name_key: str = "name",
    arguments_key: str = "arguments",
) -> dict:
    """The call-object union: oneOf {name: const, arguments: {props}}."""
    one_of = []
    defs: dict[str, Any] = {}
    for fn in functions:
        params = fn.get("parameters") or {}
        if isinstance(params.get("$defs"), dict):
            for key, sub in params["$defs"].items():
                if key in defs and defs[key] != sub:
                    raise ValueError(
                        f"conflicting $defs entry {key!r} across tools"
                    )
                defs[key] = sub
        args_schema: dict[str, Any] = {
            "type": "object",
            "properties": params.get("properties") or {},
        }
        if params.get("required") is not None:
            args_schema["required"] = params["required"]
        one_of.append({
            "type": "object",
            "properties": {
                name_key: {"const": fn.get("name", "")},
                arguments_key: args_schema,
            },
        })
    schema: dict[str, Any] = {"oneOf": one_of}
    if defs:
        schema["$defs"] = defs
    return schema


# Free text for mixed mode: anything without a newline start, like the
# reference's freestring rule ([^\x0A\x0D] content).
FREESTRING = r"[^\x0A\x0D][^\x00]*"


@dataclasses.dataclass
class BuiltConstraint:
    """Regex + metadata the chat endpoint needs for the parse side."""

    pattern: str
    functions: list[dict]
    schema: dict
    name_key: str
    arguments_key: str
    schema_type: str  # "json" | "llama3.1"


def build_tool_regex(
    functions: list[dict], cfg: FunctionsConfig
) -> BuiltConstraint:
    """Tools + FunctionsConfig grammar options → the decoding regex.

    Options honored (grammars/options.go parity): parallel_calls (array of
    calls), mixed_mode (free-string alternative), no_mixed_free_string,
    prefix, expect_strings_after_json, properties_order, schema_type
    (json | llama3.1), function_name_key/arguments_key.
    """
    g = cfg.grammar or {}
    name_key = cfg.function_name_key or "name"
    args_key = cfg.function_arguments_key or "arguments"
    prop_order = sort_prop_order(str(g.get("properties_order", ""))) or [
        name_key, args_key
    ]
    schema_type = str(g.get("schema_type", "json") or "json")
    schema = functions_to_schema(
        functions, name_key=name_key, arguments_key=args_key
    )

    if schema_type == "llama3.1":
        # <function=name>{json args}</function> tag form
        alts = []
        for fn in functions:
            params = fn.get("parameters") or {}
            args_schema = {
                "type": "object",
                "properties": params.get("properties") or {},
                **({"required": params["required"]}
                   if params.get("required") is not None else {}),
                **({"$defs": params["$defs"]}
                   if isinstance(params.get("$defs"), dict) else {}),
            }
            args_rx = schema_to_regex(args_schema, prop_order=prop_order)
            fname = escape_literal(fn.get("name", ""))
            alts.append(f"<function={fname}>{args_rx}</function>")
        call = "(" + "|".join(alts) + ")" if alts else FREESTRING
    else:
        call = schema_to_regex(schema, prop_order=prop_order)

    if g.get("parallel_calls"):
        if g.get("disable_parallel_new_lines"):
            sep = f"{WS},{WS}"
        else:
            sep = f"{WS},\\n?{WS}"
        pattern = f"(\\[{WS}{call}({sep}{call})*{WS}\\]|{call})"
    else:
        pattern = call

    prefix = str(g.get("prefix", "") or "")
    if prefix:
        pattern = escape_literal(prefix) + pattern

    if g.get("expect_strings_after_json"):
        pattern = f"{pattern}([^\\x00]*)?"

    if g.get("mixed_mode"):
        if g.get("no_mixed_free_string"):
            pattern = f"({FREESTRING})|({pattern})"
        else:
            # JSON may be surrounded by prose, or the reply is pure prose
            pattern = f"({FREESTRING})|([^\\x00]*{pattern}[^\\x00]*)"

    return BuiltConstraint(
        pattern=pattern,
        functions=functions,
        schema=schema,
        name_key=name_key,
        arguments_key=args_key,
        schema_type=schema_type,
    )


def build_tool_constraint(
    functions: list[dict], cfg: FunctionsConfig, tokenizer: Any
):
    """End-to-end: tools → FSMConstraint ready for GenRequest.constraint.
    Returns (constraint, BuiltConstraint); constraint is None when grammar
    generation is disabled (cfg.grammar['disable'])."""
    built = build_tool_regex(functions, cfg)
    if (cfg.grammar or {}).get("disable"):
        return None, built
    from localai_tpu.functions.constraint import constraint_for_regex

    return constraint_for_regex(built.pattern, tokenizer), built
