"""LLM output → tool calls / text: the parse side of function calling.

Parity: /root/reference/pkg/functions/parse.go —
``cleanup_llm_result`` (ReplaceLLMResult regex substitutions),
``parse_text_content`` (CaptureLLMResult extraction),
``parse_json_objects`` (multi-object tolerant JSON scan),
``parse_function_call`` (JSONRegexMatch → ResponseRegex → JSON decode
pipeline, function_name_key/arguments_key remapping).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import re
from typing import Any

from localai_tpu.config.model_config import FunctionsConfig

log = logging.getLogger(__name__)


@dataclasses.dataclass
class FuncCallResult:
    name: str
    arguments: str  # stringified JSON object (OpenAI wire shape)


def _apply_replacements(text: str, items: list[dict]) -> str:
    for item in items:
        key = item.get("key")
        if not key:  # malformed entry: an empty pattern would match at
            continue  # every position and mangle the whole output
        text = re.sub(key, item.get("value", ""), text)
    return text


def cleanup_llm_result(llmresult: str, cfg: FunctionsConfig) -> str:
    return _apply_replacements(llmresult, cfg.replace_llm_results)


def parse_text_content(llmresult: str, cfg: FunctionsConfig) -> str:
    """Extract the prose part of a tools response via capture_llm_results
    (first capture group of the first matching regex)."""
    for pattern in cfg.capture_llm_results:
        m = re.search(pattern, llmresult, flags=re.DOTALL)
        if m and m.groups():
            return m.group(1).strip()
    return ""


def parse_json_objects(s: str) -> list[Any]:
    """Parse a string holding one or more JSON values with garbage between
    them: `{..} junk {..}` → both objects; a top-level array of objects is
    flattened. Mirrors the reference's offset-skipping ParseJSON."""
    decoder = json.JSONDecoder()
    out: list[Any] = []
    i = 0
    n = len(s)
    while i < n:
        # seek to the next plausible JSON start
        while i < n and s[i] not in "{[":
            i += 1
        if i >= n:
            break
        try:
            obj, end = decoder.raw_decode(s, i)
        except json.JSONDecodeError as e:
            i = max(i + 1, e.pos + 1 if e.pos > i else i + 1)
            continue
        if isinstance(obj, list):
            out.extend(v for v in obj if isinstance(v, dict))
        elif isinstance(obj, dict):
            out.append(obj)
        i = end
    return out


_TAG_CALL = re.compile(r"<function=([\w.-]+)>(.*?)</function>", re.DOTALL)


def parse_function_call(
    llmresult: str, cfg: FunctionsConfig
) -> list[FuncCallResult]:
    """Full pipeline: replacements → JSONRegexMatch extraction →
    ResponseRegex named-group parse | tolerant JSON decode → calls."""
    llmresult = _apply_replacements(llmresult, cfg.replace_function_results)

    name_key = cfg.function_name_key or "name"
    args_key = cfg.function_arguments_key or "arguments"

    candidates: list[str] = []
    if cfg.json_regex_match:
        for pattern in cfg.json_regex_match:
            matches = [
                m.group(1)
                for m in re.finditer(pattern, llmresult, flags=re.DOTALL)
                if m.groups()
            ]
            if matches:
                candidates.extend(matches)
                break

    results: list[FuncCallResult] = []
    if cfg.response_regex:
        for pattern in cfg.response_regex:
            for m in re.finditer(pattern, llmresult, flags=re.DOTALL):
                groups = m.groupdict()
                fname = groups.get(name_key, "")
                if not fname:
                    return results
                results.append(FuncCallResult(
                    name=fname, arguments=groups.get(args_key) or ""
                ))
        return results

    # built-in llama3.1 tag form (the reference handles it via its
    # Llama31 schema + user regexes; we support it out of the box)
    tags = _TAG_CALL.findall(llmresult)
    if tags and not candidates:
        for fname, args in tags:
            args = args.strip() or "{}"
            try:
                json.loads(args)
            except json.JSONDecodeError:
                continue
            results.append(FuncCallResult(name=fname, arguments=args))
        if results:
            return results

    if not candidates:
        candidates = [llmresult]
    for cand in candidates:
        for obj in parse_json_objects(cand):
            fname = obj.get(name_key)
            args = obj.get(args_key)
            if not isinstance(fname, str) or args is None:
                continue
            if isinstance(args, str):
                arg_str = args
            else:
                arg_str = json.dumps(args, separators=(",", ":"),
                                     ensure_ascii=False)
            results.append(FuncCallResult(name=fname, arguments=arg_str))
    return results
