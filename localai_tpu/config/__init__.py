from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader, load_config_file, load_multi_config_file
from localai_tpu.config.model_config import (
    EngineConfig,
    FunctionsConfig,
    ModelConfig,
    PredictionParams,
    ShardingConfig,
    TemplateConfig,
    Usecase,
)

__all__ = [
    "AppConfig",
    "ConfigLoader",
    "EngineConfig",
    "FunctionsConfig",
    "ModelConfig",
    "PredictionParams",
    "ShardingConfig",
    "TemplateConfig",
    "Usecase",
    "load_config_file",
    "load_multi_config_file",
]
