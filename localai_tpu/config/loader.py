"""Model-config registry: load one YAML, a multi-doc YAML, or a whole dir.

Parity: BackendConfigLoader
(/root/reference/core/config/backend_config_loader.go): LoadBackendConfig /
LoadBackendConfigsFromPath / LoadMultipleBackendConfigsSingleFile, plus the
thread-safe registry semantics the HTTP layer relies on.
"""

from __future__ import annotations

import logging
import threading
from pathlib import Path
from typing import Callable, Iterable, Optional

import yaml

from localai_tpu.config.model_config import ModelConfig, Usecase

log = logging.getLogger(__name__)

# files in a models dir that are not servable loose models (parity:
# knownModelsNameSuffixToSkip, /root/reference/pkg/model/loader.go:54-67 —
# weight files like .gguf/.safetensors are NOT skipped there)
_SKIP_SUFFIXES = (".tmpl", ".keep", ".json", ".partial", ".md", ".MD",
                  ".txt", ".jinja", ".tar.gz", ".DS_Store")
_SKIP_FILES = ("MODEL_CARD", "README", "README.md")


def load_config_file(path: str | Path) -> ModelConfig:
    """Load a single-document model YAML (parity: readBackendConfigFromFile)."""
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    cfg = ModelConfig(**data)
    return cfg


def load_multi_config_file(path: str | Path) -> list[ModelConfig]:
    """Load a file holding a LIST of configs (parity:
    LoadMultipleBackendConfigsSingleFile)."""
    with open(path) as f:
        data = yaml.safe_load(f) or []
    if isinstance(data, dict):
        data = [data]
    return [ModelConfig(**d) for d in data]


class ConfigLoader:
    """Thread-safe name→ModelConfig registry."""

    def __init__(self, model_path: str | Path = "models"):
        self.model_path = Path(model_path)
        self._configs: dict[str, ModelConfig] = {}
        self._lock = threading.RLock()

    # -- loading ---------------------------------------------------------

    def load_from_path(self, path: Optional[str | Path] = None,
                       context_size: int = 4096) -> None:
        """Scan a dir for *.yaml/*.yml configs (parity:
        LoadBackendConfigsFromPath, backend_config_loader.go)."""
        root = Path(path or self.model_path)
        if not root.is_dir():
            return
        for entry in sorted(root.iterdir()):
            if not entry.is_file():
                continue
            if entry.suffix not in (".yaml", ".yml"):
                continue
            try:
                cfg = load_config_file(entry)
            except Exception as e:  # noqa: BLE001 — skip malformed, keep loading
                log.warning("skipping malformed config %s: %s", entry, e)
                continue
            if not cfg.name:
                cfg.name = entry.stem
            cfg.set_defaults(context_size=context_size)
            self._autodetect(cfg)
            if cfg.validate_config():
                self.register(cfg)
            else:
                log.warning("invalid config %s, skipping", entry)

    def load_single(self, path: str | Path, context_size: int = 4096) -> ModelConfig:
        cfg = load_config_file(path)
        if not cfg.name:
            cfg.name = Path(path).stem
        cfg.set_defaults(context_size=context_size)
        self._autodetect(cfg)
        self.register(cfg)
        return cfg

    def _autodetect(self, cfg: ModelConfig) -> None:
        """Backend selection for bare `model:` configs by checkpoint sniff
        (the greedy-loader/guesser collapse — models/detect.py)."""
        try:
            from localai_tpu.models.detect import autodetect_config

            autodetect_config(cfg, self.model_path)
        except Exception as e:  # noqa: BLE001 — sniffing must not block load
            log.warning("backend autodetect for %s failed: %s",
                        cfg.name, e)

    # -- registry --------------------------------------------------------

    def register(self, cfg: ModelConfig) -> None:
        with self._lock:
            self._configs[cfg.name] = cfg

    def remove(self, name: str) -> None:
        with self._lock:
            self._configs.pop(name, None)

    def get(self, name: str) -> Optional[ModelConfig]:
        with self._lock:
            return self._configs.get(name)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._configs

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._configs)

    def all(self) -> list[ModelConfig]:
        with self._lock:
            return [self._configs[k] for k in sorted(self._configs)]

    def by_usecase(self, uc: Usecase) -> list[ModelConfig]:
        """Filter (parity: GetBackendConfigsByFilter + usecase flags)."""
        return [c for c in self.all() if c.has_usecase(uc)]

    # -- loose model files ----------------------------------------------

    def loose_files(self) -> list[str]:
        """Model files in the models dir without a YAML config; served with
        default settings (parity: services/list_models.go:17-49 loose-file
        policy + ModelLoader.ListFilesInModelPath skip list
        /root/reference/pkg/model/loader.go:54-67)."""
        if not self.model_path.is_dir():
            return []
        # skip files already claimed by a config — keyed on the config's model
        # filename, not its name (parity: services/list_models.go:28)
        with self._lock:
            claimed = {Path(c.model).name for c in self._configs.values() if c.model}
            claimed |= set(self._configs)
        out = []
        for entry in sorted(self.model_path.iterdir()):
            if not entry.is_file() or entry.name.startswith("."):
                continue
            if entry.suffix in (".yaml", ".yml") or entry.name.endswith(_SKIP_SUFFIXES):
                continue
            if entry.name in _SKIP_FILES or entry.name in claimed:
                continue
            out.append(entry.name)
        return out

    def preload(self, downloader: Optional[Callable[..., None]] = None) -> None:
        """Download model files referenced by configs, sha-verified, with a
        traversal guard on the YAML-supplied filename (parity:
        BackendConfigLoader.Preload, backend_config_loader.go:261-267)."""
        from localai_tpu.utils.downloader import download_uri
        from localai_tpu.utils.paths import verify_path

        dl = downloader or download_uri
        for cfg in self.all():
            for spec in cfg.download_files:
                uri, filename = spec.get("uri"), spec.get("filename")
                if not uri or not filename:
                    continue
                dest = verify_path(filename, self.model_path)
                dest.parent.mkdir(parents=True, exist_ok=True)
                # download_uri skips existing files only when the sha matches
                dl(uri, dest, sha256=spec.get("sha256"))
