"""Application-wide configuration.

Parity: ApplicationConfig + functional options
(/root/reference/core/config/application_config.go) and the CLI flag surface
(/root/reference/core/cli/run.go:19-73). Flags are dataclass fields here;
every field is env-overridable via LOCALAI_<UPPER_NAME> (see from_env).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional


@dataclass
class AppConfig:
    # paths
    model_path: str = "models"
    backend_assets_path: str = "backend-assets"
    upload_path: str = "uploaded_files"
    config_path: str = "configuration"
    audio_path: str = "generated_audio"
    image_path: str = "generated_images"

    # server
    address: str = "0.0.0.0"
    port: int = 8080
    cors: bool = True
    cors_allow_origins: str = "*"
    api_keys: list[str] = field(default_factory=list)
    opaque_errors: bool = False
    disable_webui: bool = False
    csrf: bool = False
    upload_limit_mb: int = 15  # parity: run.go:49 UPLOAD_LIMIT default

    # model management
    galleries: list[dict] = field(default_factory=list)
    autoload_galleries: bool = True
    preload_models: list[str] = field(default_factory=list)
    load_to_memory: list[str] = field(default_factory=list)
    context_size: int = 4096
    parallel_requests: bool = True
    single_active_backend: bool = False
    external_backends: dict[str, str] = field(default_factory=dict)
    worker_env: dict[str, str] = field(default_factory=dict)  # extra env for
                                                # spawned worker processes
                                                # (e.g. device pinning)

    # watchdog (parity: run.go:66-69 defaults 5m busy / 15m idle)
    watchdog_idle: bool = False
    watchdog_busy: bool = False
    watchdog_idle_timeout: float = 15 * 60.0
    watchdog_busy_timeout: float = 5 * 60.0

    # multi-host SPMD (parallel/multihost.py): jax.distributed + the
    # leader's command-mirroring channel
    coordinator_address: str = ""     # host:port for jax.distributed
    num_processes: int = 1
    process_id: int = 0
    mirror_port: int = 0              # leader: broadcast engine calls here
    mirror_followers: int = 0         # block serving until N followers join

    # distributed / federation
    p2p: bool = False
    federated: bool = False           # announce this instance to a router
    federated_router: str = ""        # router base URL to announce to
    federated_advertise: str = ""     # address peers reach us at
                                      # (default http://<hostname>:<port>)
    peer_token: str = ""              # shared secret guarding registration
    swarm_routers: str = ""           # extra comma-separated router URLs the
                                      # swarm UI may query (allowlist)

    # observability
    debug: bool = False
    log_level: str = "info"
    metrics: bool = True

    # SLO observatory + burn-rate load shedding (obs.slo): p95 latency
    # targets in milliseconds, 0 = target disabled. Env-overridable like
    # every field (LOCALAI_SLO_TTFT_P95_MS, ...); CLI: --slo-*-p95-ms.
    # When fast (1m) AND slow (5m) error-budget burn rates exceed
    # slo_burn_threshold, new generation work is refused with 429 +
    # Retry-After until the fast window recovers.
    slo_ttft_p95_ms: float = 0.0
    slo_tpot_p95_ms: float = 0.0
    slo_e2e_p95_ms: float = 0.0
    slo_queue_p95_ms: float = 0.0
    slo_burn_threshold: float = 2.0

    # per-request wall-clock deadline for synchronous generation waits
    # (LOCALAI_REQUEST_DEADLINE_S / --request-deadline-s): expiry CANCELS
    # the generation so the decode slot frees instead of generating into
    # the void, and the client gets 504
    request_deadline_s: float = 600.0

    # offline batch subsystem (localai_tpu.batch): max in-flight batch
    # lines the executor keeps submitted on the scheduler's background
    # lane, and how long a non-terminal job may live before it expires
    # (LOCALAI_BATCH_CONCURRENCY / LOCALAI_BATCH_EXPIRY_H; CLI
    # --batch-concurrency / --batch-expiry-h)
    batch_concurrency: int = 2
    batch_expiry_h: float = 24.0

    # fleet router (localai_tpu.fleet): serve each LLM from N data-parallel
    # engine replicas behind one facade. 0/1 = single engine (today's
    # behavior). Replicas default to spawned worker processes
    # (fleet_backend=worker — crash isolation + device pinning via
    # worker_env); fleet_backend=inprocess builds N in-process engines
    # (CPU tests, CI smoke). fleet_prefill_replicas adds dedicated prefill
    # replicas: prompts >= fleet_disagg_threshold tokens prefill there and
    # hand their KV prefix to a decode replica over TransferPrefix.
    # Env: LOCALAI_FLEET_REPLICAS etc.; CLI: --fleet-replicas etc.
    fleet_replicas: int = 0
    fleet_prefill_replicas: int = 0
    fleet_backend: str = "worker"
    fleet_disagg_threshold: int = 512
    # cross-host fleet: adopt externally managed remote workers at
    # host:port into every fleet pool (LOCALAI_FLEET_HOSTS, comma-
    # separated; CLI --fleet-hosts). Remotes are evicted-with-redial on
    # failure, never respawned — this process does not own their
    # lifecycle. More peers can join at runtime via the token-guarded
    # POST /federated/register on the serving instance.
    fleet_hosts: list[str] = field(default_factory=list)
    # per-reply inactivity deadline on every cross-replica stream and the
    # control-plane RPC bound (LOCALAI_FLEET_RPC_TIMEOUT_S /
    # --fleet-rpc-timeout-s; 0 disables). Size it above worst-case queue
    # wait + TTFT — a cold replica's first-dispatch compile is legitimate
    # silence. Retry count for idempotent cross-host RPCs is env-only:
    # LOCALAI_FLEET_RPC_RETRIES (default 2), as are the redial backoff
    # knobs LOCALAI_FLEET_REDIAL_{BASE,CAP}_S.
    fleet_rpc_timeout_s: float = 120.0

    # elastic capacity (fleet.autoscale): the closed-loop controller that
    # scales each fleet between autoscale_min and autoscale_max decode
    # replicas off queue depth / SLO burn / KV pressure, retires a replica
    # idle past autoscale_in_idle_s, and — when autoscale_zero_idle_s > 0
    # — scales a wholly idle model to ZERO replicas, cold-respawning on
    # the next request (the held request waits, never errors). Overload
    # thresholds and cooldowns are env-only (LOCALAI_AUTOSCALE_OUT_*,
    # LOCALAI_AUTOSCALE_{IN,OUT}_COOLDOWN_S, ...); standby hosts are
    # adopted before spawning when scaling out.
    autoscale: bool = False
    autoscale_min: int = 1
    autoscale_max: int = 4
    autoscale_interval_s: float = 5.0
    autoscale_in_idle_s: float = 120.0
    autoscale_zero_idle_s: float = 0.0
    autoscale_standby_hosts: list[str] = field(default_factory=list)

    # TPU-specific
    mesh_shape: Optional[dict[str, int]] = None   # None = auto from devices
                                                  # (LOCALAI_MESH / --mesh
                                                  # override the topology)
    platform: Optional[str] = None                # force jax platform (tests: cpu)

    # fleet replica device pinning (--fleet-device-pinning /
    # LOCALAI_FLEET_DEVICE_PINNING): auto-derive per-replica worker_env
    # (TPU visible-device slices / JAX_PLATFORMS) so --fleet-replicas N
    # partitions a pod without hand-written env (fleet.pinning)
    fleet_device_pinning: bool = False

    def ensure_dirs(self) -> None:
        """mkdir -p all configured paths (parity: core/startup/startup.go:20-60)."""
        for p in (
            self.model_path,
            self.upload_path,
            self.config_path,
            self.audio_path,
            self.image_path,
        ):
            Path(p).mkdir(parents=True, exist_ok=True)

    @classmethod
    def from_env(cls, **overrides) -> "AppConfig":
        """Build from environment (parity: kong env tags, run.go:22-72)."""
        cfg = cls()
        for name, f in cls.__dataclass_fields__.items():
            env = os.environ.get(f"LOCALAI_{name.upper()}")
            if env is None:
                continue
            typ = str(f.type)
            if typ == "int":
                setattr(cfg, name, int(env))
            elif typ == "float":
                setattr(cfg, name, float(env))
            elif typ == "bool":
                setattr(cfg, name, env.lower() in ("1", "true", "yes", "on"))
            elif typ == "list[str]":
                setattr(cfg, name, [s for s in env.split(",") if s])
            elif typ in ("str", "Optional[str]"):
                setattr(cfg, name, env)
        # LOCALAI_MESH uses the CLI's axis syntax ("data=2,model=4" or
        # "data:2,model:4") — parsed by the ONE parser behind --mesh so
        # the env override and the flag can never drift
        mesh_env = os.environ.get("LOCALAI_MESH")
        if mesh_env is not None:
            from localai_tpu.parallel.mesh import parse_mesh_spec

            cfg.mesh_shape = parse_mesh_spec(mesh_env)
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg
