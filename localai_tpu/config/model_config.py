"""Per-model declarative YAML config.

Parity target: the reference's ``BackendConfig``
(/root/reference/core/config/backend_config.go:28-246) — prediction defaults,
backend choice, prompt-template refs, grammar/function-calling config,
modality-specific sections, and feature flags — re-expressed for a TPU engine:
CUDA/GGUF-specific knobs (gpu_layers, mmap, ...) are replaced by sharding and
dtype/quantization knobs that map onto jax.sharding meshes and XLA.
"""

from __future__ import annotations

import enum
from typing import Any, Optional

from pydantic import BaseModel, ConfigDict, Field, model_validator


class Usecase(str, enum.Enum):
    """Capability flags a model can serve.

    Parity: BackendConfigUsecases bitmask
    (/root/reference/core/config/backend_config.go:"known_usecases").
    """

    CHAT = "chat"
    COMPLETION = "completion"
    EDIT = "edit"
    EMBEDDINGS = "embeddings"
    IMAGE = "image"
    TRANSCRIPT = "transcript"
    TTS = "tts"
    SOUND_GENERATION = "sound_generation"
    RERANK = "rerank"
    TOKENIZE = "tokenize"
    VISION = "vision"


class PredictionParams(BaseModel):
    """Sampling / prediction defaults merged with each request.

    Parity: PredictionOptions (/root/reference/core/schema/prediction.go) and
    the ``parameters:`` YAML section. All sampling runs on-device (see
    localai_tpu.engine.sampling); fields that only make sense for llama.cpp's
    CPU samplers (mirostat, tfz) are accepted and mapped or ignored with a
    warning rather than rejected, so reference YAML files keep loading.
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    max_tokens: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repeat_penalty: Optional[float] = None
    repeat_last_n: Optional[int] = None
    seed: Optional[int] = None
    echo: bool = False
    n: int = 1
    # Accepted-for-compat (llama.cpp-only samplers; engine maps or ignores):
    mirostat: Optional[int] = None
    mirostat_eta: Optional[float] = None
    mirostat_tau: Optional[float] = None
    typical_p: Optional[float] = None
    tfz: Optional[float] = None
    keep: Optional[int] = None

    def merged_with(self, overrides: dict[str, Any]) -> "PredictionParams":
        """Request-over-config merge (parity: updateRequestConfig,
        /root/reference/core/http/endpoints/openai/request.go:51+)."""
        data = self.model_dump(exclude_none=True)
        data.update({k: v for k, v in overrides.items() if v is not None})
        return PredictionParams(**data)


class TemplateConfig(BaseModel):
    """Prompt template references.

    Parity: TemplateConfig (/root/reference/core/config/backend_config.go:
    TemplateConfig struct). Templates here are Jinja2 (the HF ecosystem's
    native format) instead of Go text/template; ``use_tokenizer_template``
    selects the tokenizer's built-in chat template.
    """

    model_config = ConfigDict(extra="allow")

    chat: Optional[str] = None
    chat_message: Optional[str] = None
    completion: Optional[str] = None
    edit: Optional[str] = None
    functions: Optional[str] = None
    multimodal: Optional[str] = None
    use_tokenizer_template: bool = False
    # raw Jinja chat template (messages/add_generation_prompt), overriding
    # the tokenizer's own — filled by the family guesser
    # (config.guesser.guess_chat_defaults) for template-less configs
    chat_template: Optional[str] = None
    join_chat_messages_by_character: Optional[str] = None


class FunctionsConfig(BaseModel):
    """Function-calling / tool-use behavior.

    Parity: FunctionsConfig (/root/reference/pkg/functions/parse.go:15-50).
    On TPU, constrained decoding is token-level logit masking from a compiled
    FSM (localai_tpu.functions) rather than BNF text handed to a CPU sampler.
    """

    model_config = ConfigDict(extra="allow")

    disable_no_action: bool = False
    no_action_function_name: str = "answer"
    no_action_description_name: str = ""
    function_name_key: str = "name"
    function_arguments_key: str = "arguments"
    response_regex: list[str] = Field(default_factory=list)
    json_regex_match: list[str] = Field(default_factory=list)
    replace_function_results: list[dict[str, str]] = Field(default_factory=list)
    replace_llm_results: list[dict[str, str]] = Field(default_factory=list)
    capture_llm_results: list[str] = Field(default_factory=list)
    grammar: dict[str, Any] = Field(default_factory=dict)


class ShardingConfig(BaseModel):
    """How to lay the model over a jax.sharding.Mesh.

    This REPLACES the reference's gpu_layers/tensor_split/main_gpu/rpc_servers
    knobs (/root/reference/core/config/backend_config.go:116-117,151 and
    backend/cpp/llama/grpc-server.cpp:2233-2262): parallelism is compiled via
    pjit over ICI, not proxied over TCP. Axis sizes of 1 collapse; the product
    must divide the available device count (or equal it when data=0 → auto).
    """

    model_config = ConfigDict(extra="forbid")

    tensor_parallel_size: int = 1     # 'model' mesh axis (MXU-friendly TP)
    data_parallel_size: int = 0       # 0 = auto: fill remaining devices
    sequence_parallel_size: int = 1   # 'seq' axis: long-context ring attention
    expert_parallel_size: int = 1     # 'expert' axis for MoE layers
    pipeline_parallel_size: int = 1   # 'pipe' axis (layer stages)


class EngineConfig(BaseModel):
    """TPU serving-engine knobs.

    Replaces llama.cpp slot/cache flags (LLAMACPP_PARALLEL, n_ctx per slot —
    /root/reference/backend/cpp/llama/grpc-server.cpp:176,2223-2231) with
    static-shape equivalents: fixed slot count, paged KV in HBM, bucketed
    prefill lengths to bound XLA recompiles.
    """

    model_config = ConfigDict(extra="allow")

    max_slots: int = 8                # concurrent decode slots (continuous batching)
    page_size: int = 128              # KV page length (tokens); MXU/lane aligned
    prefill_buckets: list[int] = Field(
        default_factory=lambda: [128, 512, 2048, 8192]
    )
    dtype: str = "bfloat16"           # compute/weight dtype
    kv_dtype: str = "bfloat16"        # KV-cache dtype: bfloat16/float32,
                                      # scaled int8, or int4 (paged pools
                                      # only — nibble-packed along head_dim;
                                      # LOCALAI_KV_DTYPE overrides defaults)
    quantization: Optional[str] = None  # "int8" | "int8_w8a8" | "int4"
    donate_kv: bool = True            # buffer donation for in-place KV updates
    decode_steps_per_dispatch: int = 16  # tokens per dispatch (lax.scan) —
                                      # amortizes host→device RTT; lower it
                                      # for tighter streaming cadence
    pipeline_depth: int = 2           # in-flight decode dispatches
    stream_latency_ms: float = 100.0  # SSE delivery-lag bound: with a stream
                                      # attached the scheduler shrinks the
                                      # dispatch size to keep
                                      # steps×depth×step_time under this
    sp_prefill_threshold: int = 1024  # prompts at/above this many tokens
                                      # take the ring-attention prefill when
                                      # the mesh has a 'seq' axis
    attn_impl: str = "auto"           # auto | pallas | pallas_interpret | xla
    # Speculative decoding (parity: DraftModel/NDraft,
    # /root/reference/core/config/backend_config.go:143,
    # backend/backend.proto:210): a small same-vocab model proposes n_draft
    # tokens per window; the target verifies them in one batched forward.
    draft_model: Optional[str] = None
    n_draft: int = 4
    # Block-native speculation lane (localai_tpu.spec). None = auto: ON
    # for paged engines (LOCALAI_SPEC=0 force-disables, =1 has nothing
    # to add), OFF for contiguous engines unless draft_model is set.
    # spec_drafter picks the proposal source: "model" loads draft_model
    # co-located, "ngram" self-drafts via prompt lookup (no second model
    # — the single-model deployment default), "auto" = model when
    # draft_model is configured else ngram. spec_gamma is the window
    # size (draft tokens verified per dispatch; default n_draft, or
    # LOCALAI_SPEC_GAMMA).
    spec: Optional[bool] = None
    spec_drafter: str = "auto"
    spec_gamma: Optional[int] = None
    # Self-extend / group attention (parity: llama.cpp grp_attn_n/grp_attn_w,
    # grpc-server.cpp:210-211): grp_attn_n>1 serves up to
    # max_position_embeddings * grp_attn_n context via grouped positions —
    # see engine/selfextend.py for the TPU formulation.
    grp_attn_n: int = 1
    grp_attn_w: int = 512
    # Paged KV cache (vLLM-style block pool + chunked prefill;
    # engine/paged.py). None = auto: ON for single-device serving without
    # draft/self-extend/multi-host, OFF otherwise. kv_num_blocks sizes the
    # pool (None = the contiguous footprint: max_slots * ceil(ctx/block));
    # smaller pools overcommit HBM — admission then waits for free blocks.
    kv_paged: Optional[bool] = None
    kv_block_tokens: Optional[int] = None   # tokens per block (default 64
                                            # via LOCALAI_KV_BLOCK_TOKENS)
    kv_num_blocks: Optional[int] = None
    prefill_chunk: Optional[int] = None     # chunked-prefill dispatch size
                                            # (tokens; default 512)


class DiffusionConfig(BaseModel):
    """Image-generation section (parity: Diffusers struct,
    /root/reference/core/config/backend_config.go Diffusers section)."""

    model_config = ConfigDict(extra="allow")

    scheduler_type: Optional[str] = None
    cfg_scale: Optional[float] = None
    clip_skip: Optional[int] = None
    pipeline_type: Optional[str] = None
    enable_parameters: Optional[str] = None
    steps: Optional[int] = None
    # ControlNet model ref loaded next to the pipeline (backend.py:192-208)
    control_net: Optional[str] = None
    control_scale: float = 1.0


class TTSConfig(BaseModel):
    """TTS section (parity: TTSConfig,
    /root/reference/core/config/backend_config.go:19-26)."""

    model_config = ConfigDict(extra="allow")

    voice: Optional[str] = None
    audio_path: Optional[str] = None


class ModelConfig(BaseModel):
    """One model's declarative config (a YAML document in the models dir).

    Parity: BackendConfig (/root/reference/core/config/backend_config.go:28+).
    """

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    name: str = ""
    backend: str = ""                       # worker type; "" = auto-select
    description: str = ""
    usage: str = ""
    model: str = ""                         # weights ref: hf repo / local path
    model_path: Optional[str] = None        # resolved absolute path (runtime)
    tokenizer: Optional[str] = None         # override tokenizer ref
    context_size: Optional[int] = None
    embeddings: bool = False
    seed: Optional[int] = None
    mmproj: Optional[str] = None            # vision tower ref (dir or debug:)
    image_token_id: Optional[int] = None    # placeholder id for image spans
                                            # (default: HF image_token_index
                                            # or 0; embeddings are injected
                                            # over these positions anyway)
    download_files: list[dict[str, Any]] = Field(default_factory=list)
    # LoRA adapters merged into base weights at load (parity:
    # backend_config.go:139-141; diffusers backend.py:300-314)
    lora_adapter: str = ""
    lora_base: str = ""                     # unused: merge needs no base copy
    lora_scale: float = 1.0
    # remote-API backends (backend: huggingface — pkg/langchain parity)
    api_token: str = ""
    api_base: str = ""

    parameters: PredictionParams = Field(default_factory=PredictionParams)
    template: TemplateConfig = Field(default_factory=TemplateConfig)
    function: FunctionsConfig = Field(default_factory=FunctionsConfig)
    sharding: ShardingConfig = Field(default_factory=ShardingConfig)
    engine: EngineConfig = Field(default_factory=EngineConfig)
    diffusers: DiffusionConfig = Field(default_factory=DiffusionConfig)
    tts: TTSConfig = Field(default_factory=TTSConfig)

    stopwords: list[str] = Field(default_factory=list)
    cutstrings: list[str] = Field(default_factory=list)
    extract_regex: list[str] = Field(default_factory=list)
    trimspace: list[str] = Field(default_factory=list)
    trimsuffix: list[str] = Field(default_factory=list)

    system_prompt: str = ""
    roles: dict[str, str] = Field(default_factory=dict)

    feature_flags: dict[str, bool] = Field(default_factory=dict)
    known_usecases: Optional[list[Usecase]] = None

    # Compat fields accepted from reference YAMLs and mapped:
    f16: Optional[bool] = None              # → engine.dtype bfloat16 (TPU norm)
    threads: Optional[int] = None           # ignored: XLA owns threading
    gpu_layers: Optional[int] = None        # ignored: no host/device layer split
    tensor_parallel_size: Optional[int] = None  # → sharding.tensor_parallel_size
    low_vram: Optional[bool] = None         # ignored
    mmap: Optional[bool] = None             # ignored
    prompt_cache_path: Optional[str] = None
    prompt_cache_all: bool = False
    prompt_cache_ro: bool = False
    grammar: str = ""                       # raw grammar text (GBNF-compatible)
    rope_scaling: Optional[str] = None      # linear|yarn → models.llama rope
    rope_freq_base: Optional[float] = None
    rope_freq_scale: Optional[float] = None

    @model_validator(mode="after")
    def _apply_compat(self) -> "ModelConfig":
        if self.tensor_parallel_size and self.sharding.tensor_parallel_size == 1:
            self.sharding.tensor_parallel_size = self.tensor_parallel_size
        if self.f16 is False:
            self.engine.dtype = "float32"
        return self

    def set_defaults(self, *, context_size: int = 4096, debug: bool = False) -> None:
        """Fill unset fields (parity: BackendConfig.SetDefaults,
        /root/reference/core/config/backend_config.go)."""
        p = self.parameters
        if p.temperature is None and p.mirostat in (None, 0):
            p.temperature = 0.9
        if p.top_p is None:
            p.top_p = 0.95
        if p.top_k is None:
            p.top_k = 40
        if p.max_tokens is None:
            p.max_tokens = 2048
        if self.context_size is None:
            self.context_size = context_size
        if not self.name and self.model:
            self.name = self.model

    def validate_config(self) -> bool:
        """Minimal sanity validation (parity: BackendConfig.Validate).
        Rejects '..' traversal segments in file refs; absolute paths are
        allowed (they are resolved against verify_path at use sites)."""
        if not self.name:
            return False
        return not any(
            ".." in f.split("/")
            for f in (self.model, self.backend, self.mmproj or "")
        )

    def has_usecase(self, uc: Usecase) -> bool:
        """Usecase gating (parity: HasUsecases/GuessUsecases,
        /root/reference/core/config/backend_config.go known_usecases)."""
        if self.known_usecases is not None:
            return uc in self.known_usecases
        return uc in self.guess_usecases()

    def guess_usecases(self) -> set[Usecase]:
        guessed: set[Usecase] = set()
        name = (self.backend or "").lower()
        if self.embeddings or "embed" in name:
            guessed.add(Usecase.EMBEDDINGS)
        if name in ("", "jax", "jax-llm", "transformers", "worker",
                    "huggingface", "langchain-huggingface", "mamba",
                    "rwkv"):
            guessed |= {
                Usecase.CHAT,
                Usecase.COMPLETION,
                Usecase.EDIT,
                Usecase.TOKENIZE,
            }
            if self.mmproj:
                guessed.add(Usecase.VISION)
            if self.embeddings:
                guessed.add(Usecase.EMBEDDINGS)
        if "diffus" in name or "image" in name:
            guessed.add(Usecase.IMAGE)
        if "whisper" in name:
            guessed.add(Usecase.TRANSCRIPT)
        if "tts" in name or name == "vits":
            guessed.add(Usecase.TTS)
        if "musicgen" in name or "sound" in name:
            guessed.add(Usecase.SOUND_GENERATION)
        if "rerank" in name:
            guessed.add(Usecase.RERANK)
        if self.embeddings:
            # embedding-capable models can score query/document pairs
            guessed.add(Usecase.RERANK)
        return guessed
