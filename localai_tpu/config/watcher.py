"""Dynamic configuration: hot-reload of JSON config files while serving.

Parity: /root/reference/core/startup/config_file_watcher.go — fsnotify
watch over the configuration directory with per-file handlers for
``api_keys.json`` (dynamic API keys appended to the startup keys) and
``external_backends.json`` (name → gRPC address registrations). fsnotify
isn't available here, so a small polling thread diffs mtimes instead —
the observable contract (edit the file, behavior changes without a
restart) is the same.
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path
from typing import Callable, Optional

log = logging.getLogger(__name__)

Handler = Callable[[Optional[bytes]], None]


class ConfigWatcher:
    """Polls a directory of dynamic config files and fires a handler when
    one changes (or disappears — handlers receive None to reset)."""

    def __init__(self, config_dir: str | Path, interval: float = 1.0):
        self.dir = Path(config_dir)
        self.interval = interval
        self._handlers: dict[str, Handler] = {}
        self._mtimes: dict[str, Optional[float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, filename: str, handler: Handler) -> None:
        """Attach a handler for one file (parity: AddConfigFileHandler,
        config_file_watcher.go:53-60 — the handler also runs once at
        registration so pre-existing files apply at boot)."""
        self._handlers[filename] = handler
        self._mtimes[filename] = None
        self._apply(filename)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="config-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval + 2.0)
            self._thread = None

    def poll_once(self) -> None:
        """One poll cycle (exposed for tests and for callers that want
        synchronous application)."""
        for name in list(self._handlers):
            self._apply(name)

    # -- internals ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — watcher must not die
                log.exception("config watcher poll failed")

    def _apply(self, name: str) -> None:
        path = self.dir / name
        try:
            mtime: Optional[float] = path.stat().st_mtime
        except OSError:
            mtime = None
        if mtime == self._mtimes.get(name):
            return
        data: Optional[bytes] = None
        if mtime is not None:
            try:
                data = path.read_bytes()
            except OSError as e:
                # do NOT record the mtime: a transient read failure must be
                # retried on the next poll, not silently dropped forever
                log.warning("cannot read %s: %s", path, e)
                return
        self._mtimes[name] = mtime
        try:
            self._handlers[name](data)
            log.info("dynamic config %s %s", name,
                     "applied" if data is not None else "cleared")
        except Exception:  # noqa: BLE001 — bad file ≠ dead watcher
            log.exception("handler for %s failed", name)


def attach_standard_handlers(watcher: ConfigWatcher, state) -> None:
    """The reference's two built-in dynamic files
    (config_file_watcher.go:139-172), applied to the live AppState:

      * api_keys.json — JSON array of keys, appended to the keys the
        server started with (removing the file restores startup keys).
      * external_backends.json — JSON object name→address, replacing the
        dynamic registrations in AppConfig.external_backends.
    """
    startup_keys = list(state.config.api_keys)
    startup_backends = dict(state.config.external_backends)

    def on_api_keys(data: Optional[bytes]) -> None:
        dynamic: list[str] = []
        if data:
            parsed = json.loads(data)
            if not isinstance(parsed, list):
                raise ValueError("api_keys.json must be a JSON array")
            dynamic = [str(k) for k in parsed if k]
        state.config.api_keys = startup_keys + [
            k for k in dynamic if k not in startup_keys
        ]

    def on_external_backends(data: Optional[bytes]) -> None:
        dynamic: dict[str, str] = {}
        if data:
            parsed = json.loads(data)
            if not isinstance(parsed, dict):
                raise ValueError(
                    "external_backends.json must be a JSON object"
                )
            dynamic = {str(k): str(v) for k, v in parsed.items()}
        merged = dict(startup_backends)
        merged.update(dynamic)
        state.config.external_backends = merged

    watcher.register("api_keys.json", on_api_keys)
    watcher.register("external_backends.json", on_external_backends)
