"""Family-based chat-template/stopword guessing.

Parity: the reference's GGUF guesser (/root/reference/core/config/
guesser.go:13-246) — a template-less config pointing at a checkpoint gets
a usable chat format inferred from the model family. The reference sniffs
GGUF metadata (architecture + special token ids); here the same signals
come from the converted/HF ``config.json`` (utils.gguf.convert_gguf
records bos/eos ids for exactly this), and the emitted defaults are Jinja
chat templates (the repo's template dialect) rather than Go templates.

Families covered (guesser.go identifyFamily): llama3, chatml (qwen2 /
Yi-style llama), phi3, gemma, mistral, command-r, deepseek2.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Optional

log = logging.getLogger(__name__)


def _tmpl(body_per_role: dict[str, str], generation: str,
          prefix: str = "") -> str:
    """Build a messages-loop Jinja chat template from per-role wrappers
    (each with a {content} slot) + the generation prompt tail."""
    branches = []
    first = True
    for role, wrap in body_per_role.items():
        kw = "if" if first else "elif"
        first = False
        branches.append(
            "{%% %s message['role'] == '%s' %%}%s"
            % (kw, role, wrap.replace("{content}", "{{ message['content'] }}"))
        )
    body = "".join(branches) + "{% endif %}"
    return (
        prefix
        + "{% for message in messages %}" + body + "{% endfor %}"
        + "{% if add_generation_prompt %}" + generation + "{% endif %}"
    )


_ROLE_GENERIC = "{content}"

FAMILY_SETTINGS: dict[str, dict[str, Any]] = {
    "llama3": {
        "stopwords": ["<|eot_id|>"],
        "chat_template": _tmpl(
            {r: "<|start_header_id|>" + r + "<|end_header_id|>\n\n"
                "{content}<|eot_id|>" for r in ("system", "user",
                                                "assistant")},
            "<|start_header_id|>assistant<|end_header_id|>\n\n",
            prefix="<|begin_of_text|>",
        ),
    },
    "chatml": {
        "stopwords": ["<|im_end|>"],
        "chat_template": _tmpl(
            {r: "<|im_start|>" + r + "\n{content}<|im_end|>\n"
             for r in ("system", "user", "assistant")},
            "<|im_start|>assistant\n",
        ),
    },
    "phi3": {
        "stopwords": ["<|end|>", "<|endoftext|>"],
        "chat_template": _tmpl(
            {r: "<|" + r + "|>\n{content}<|end|>\n"
             for r in ("system", "user", "assistant")},
            "<|assistant|>\n",
        ),
    },
    "gemma": {
        "stopwords": ["<end_of_turn>", "<start_of_turn>"],
        "chat_template": _tmpl(
            {"user": "<start_of_turn>user\n{content}<end_of_turn>\n",
             "assistant": "<start_of_turn>model\n{content}<end_of_turn>\n",
             "system": "<start_of_turn>user\n{content}<end_of_turn>\n"},
            "<start_of_turn>model\n",
        ),
    },
    "mistral": {
        "stopwords": ["</s>"],
        "chat_template": _tmpl(
            {"user": "[INST] {content} [/INST]",
             "assistant": "{content}</s>",
             "system": "[INST] {content} [/INST]"},
            "",
        ),
    },
    "command-r": {
        "stopwords": ["<|END_OF_TURN_TOKEN|>"],
        "chat_template": _tmpl(
            {"user": "<|START_OF_TURN_TOKEN|><|USER_TOKEN|>{content}"
                     "<|END_OF_TURN_TOKEN|>",
             "system": "<|START_OF_TURN_TOKEN|><|SYSTEM_TOKEN|>{content}"
                       "<|END_OF_TURN_TOKEN|>",
             "assistant": "<|START_OF_TURN_TOKEN|><|CHATBOT_TOKEN|>{content}"
                          "<|END_OF_TURN_TOKEN|>"},
            "<|START_OF_TURN_TOKEN|><|CHATBOT_TOKEN|>",
        ),
    },
    "deepseek2": {
        "stopwords": ["<｜end▁of▁sentence｜>"],
        "chat_template": _tmpl(
            {"user": "User: {content}\n",
             "assistant": "Assistant: {content}<｜end▁of▁sentence｜>",
             "system": "{content}\n"},
            "Assistant: ",
        ),
    },
}


def identify_family(hf: dict, name: str = "") -> Optional[str]:
    """config.json dict (+ model name) → family key, or None.

    Mirrors guesser.go identifyFamily: architecture + special token ids.
    """
    arch = str(hf.get("model_type", ""))
    eos = hf.get("eos_token_id")
    eos = eos[0] if isinstance(eos, list) and eos else eos
    bos = hf.get("bos_token_id")
    lname = name.lower()

    if arch == "deepseek_v2" or arch == "deepseek2":
        return "deepseek2"
    if arch.startswith("gemma") or "gemma" in lname:
        return "gemma"
    if arch == "llama" and eos == 128009:
        return "llama3"
    if arch == "cohere" or (arch == "command-r" and eos == 255001):
        return "command-r"
    if arch in ("phi3", "phi-3"):
        return "phi3"
    if arch == "qwen2":
        return "chatml"
    if arch == "llama" and bos == 1 and eos == 2:
        # Yi-style llama checkpoints ship ChatML formatting (guesser.go
        # isYI); plain llama2 with the same ids is indistinguishable, and
        # the reference makes the same call
        return "chatml"
    if arch == "mistral":
        return "mistral"
    return None


def guess_chat_defaults(cfg, model_path: str | Path) -> None:
    """Fill template.chat_template + stopwords on a template-less config
    whose checkpoint's tokenizer carries no chat template (parity:
    guessDefaultsFromFile, run at config load)."""
    t = cfg.template
    if (t.chat or t.chat_message or t.use_tokenizer_template
            or getattr(t, "chat_template", None)):
        return
    ref = cfg.model or cfg.name
    for cand in (Path(ref), Path(model_path) / ref):
        if not (cand / "config.json").exists():
            continue
        try:
            hf = json.loads((cand / "config.json").read_text())
        except ValueError:
            return
        tok_cfg = cand / "tokenizer_config.json"
        if tok_cfg.exists():
            try:
                own = json.loads(tok_cfg.read_text()).get("chat_template")
            except ValueError:
                own = None
            if own and isinstance(own, str):
                # the checkpoint knows its own format — carry the STRING
                # (converted-GGUF tokenizers are raw tokenizers.Tokenizer
                # objects with no apply_chat_template, so a bare
                # use_tokenizer_template flag would 500 at request time;
                # the explicit template renders through the Jinja fallback)
                t.chat_template = own
                return
        fam = identify_family(hf, cfg.name or "")
        if fam is None:
            return
        st = FAMILY_SETTINGS[fam]
        t.chat_template = st["chat_template"]
        if not cfg.stopwords:
            cfg.stopwords = list(st["stopwords"])
        log.info("model %s: guessed %s chat defaults (family templates)",
                 cfg.name, fam)
        return
