"""Live slot migration: the ticket protocol between the migration caller
and the fleet dispatch thread.

A slot's state between dispatches is a clean movable unit (Orca-style
iteration-level scheduling): the full token record plus the KV rows
``snapshot_prefix``/``load_prefix`` already round-trip layout-
independently. Migration therefore needs no new engine machinery — it is
a choreography:

  1. the caller (operator drain, hot-spot rebalancer, chaos test) stakes
     a :class:`MigrationTicket` on the in-flight handle and asks the
     donor replica to ``migrate_out`` the request: the donor cancels its
     inner stream with the ``migrate_export`` flag set, so the engine's
     release snapshots prompt+generation KV into the replica prefix
     cache, and packs it into TransferPrefix chunks;
  2. the donor's "cancelled" final reply unwinds the fleet dispatch
     pump normally; the dispatch thread sees the staked ticket, waits
     for the chunks, transfers them into the destination replica, and
     re-dispatches a *continuation* request (full token record as the
     prompt, remaining token budget) — the destination admission
     load_prefix-resumes, so generation continues from the exact
     frontier without re-prefilling;
  3. usage accounting is spliced afterwards (donor tokens + destination
     tokens), and every failure leg falls back to a correct full
     re-prefill continuation — a migration can be slow, never lossy.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional


class MigrationTicket:
    """One requested migration, staked on a WorkerGenHandle.

    The caller thread fills the export fields and sets ``ready``; the
    fleet dispatch thread (which owns the request lifecycle) consumes
    them. ``dest_id`` is a preference — the dispatch thread re-validates
    health and may re-route the continuation on fallback."""

    def __init__(self, dest_id: str):
        self.dest_id = dest_id
        self.ready = threading.Event()
        # donor export (filled by the caller thread via migrate_out)
        self.chunks: Optional[list] = None      # TransferPrefix payload
        self.full_tokens: Optional[list[int]] = None  # prompt + generated
        self.donor_tokens = 0                   # tokens generated pre-move
        self.error = ""                         # donor-side failure note
        # outcome (filled by the dispatch thread; tests read it)
        self.completed = threading.Event()
        self.outcome = ""                       # migrated | fallback | ...

    def fail(self, why: str) -> None:
        """Donor export failed: release the waiting dispatch thread with
        the failure recorded (it falls back from the token record)."""
        self.error = why
        self.ready.set()

    def finish(self, outcome: str) -> None:
        self.outcome = outcome
        self.completed.set()


def continuation_request(req: Any, full_tokens: list[int],
                         donor_tokens: int) -> Any:
    """The destination-side request that resumes ``req`` after
    ``donor_tokens`` generated tokens: the full token record becomes the
    prompt (its prefix KV arrives via TransferPrefix, so admission
    resumes instead of prefilling) and the generation budget shrinks by
    what the donor already produced. Sampling state carries over
    trivially for greedy decoding; seeded stochastic sampling restarts
    its stream at the boundary."""
    remaining = max(0, int(req.max_new_tokens or 0) - donor_tokens)
    return dataclasses.replace(
        req, prompt=list(full_tokens), max_new_tokens=remaining,
        mm_embeds=None, mm_positions=None)
