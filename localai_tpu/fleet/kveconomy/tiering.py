"""Host-RAM spill tier for the paged prefix pool (HBM → host tiering).

The paged ``BlockAllocator`` keeps finished prompts' full blocks in a
refcounted HBM prefix pool and LRU-evicts them under pressure. This tier
catches those evictions: the victim block's raw pool rows (whatever the
pool dtype — int4 blocks stay nibble-packed, so they spill at half the
f32 bytes) are gathered to host numpy and parked here, keyed by the same
token-chain hash the pool uses. A later ``match_prefix`` walk that misses
HBM but hits the tier re-onboards the block into a free (or freshly
evicted) pool block and continues the walk — effective prefix-cache
capacity becomes host-RAM-sized instead of HBM-sized.

The tier is a plain byte-budgeted LRU dict of host arrays. It never
touches the device: the allocator owns the pack/load callbacks (wired by
``ModelRunner`` via ``BlockAllocator.attach_tier``), keeping this module
numpy-only and the allocator mesh/topology-blind.

Thread-safety: the allocator calls in from the engine thread under its
own lock; stats scrapes come from API threads — every method takes the
tier lock, and payload dicts are handed over whole (never mutated).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np


def tier_budget_from_env() -> int:
    """``LOCALAI_KV_TIER_MB`` → budget bytes (0 = tiering disabled)."""
    try:
        mb = float(os.environ.get("LOCALAI_KV_TIER_MB", "") or 0)
    except ValueError:
        mb = 0.0
    return max(0, int(mb * (1 << 20)))


def tier_from_env() -> Optional["HostTier"]:
    """A :class:`HostTier` sized by ``LOCALAI_KV_TIER_MB``, or None when
    the knob is unset/zero (tiering off — the seed behavior)."""
    budget = tier_budget_from_env()
    return HostTier(budget) if budget > 0 else None


def payload_nbytes(payload: dict) -> int:
    return sum(int(np.asarray(a).nbytes) for a in payload.values())


class HostTier:
    """Byte-budgeted LRU store of spilled block payloads, keyed by the
    allocator's chain hash (hexdigest)."""

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError("tier budget must be > 0 bytes")
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        # key → (payload, nbytes); LRU order, evicted from the front
        self._entries: "OrderedDict[str, tuple[dict, int]]" = OrderedDict()
        self._bytes = 0
        # lifetime accounting (the allocator layers its own spill/reload
        # counters on top; these are the tier's internal churn)
        self.stores_total = 0
        self.takes_total = 0
        self.budget_drops_total = 0   # LRU-dropped to fit the budget
        self.oversize_rejects_total = 0

    def put(self, key: str, payload: dict) -> bool:
        """Park one spilled block. Evicts tier-LRU entries to fit the
        byte budget; returns False (nothing stored) when the payload
        alone exceeds it."""
        nb = payload_nbytes(payload)
        if nb > self.budget_bytes:
            with self._lock:
                self.oversize_rejects_total += 1
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            while self._entries and self._bytes + nb > self.budget_bytes:
                _, (_, freed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.budget_drops_total += 1
            self._entries[key] = (payload, nb)
            self._bytes += nb
            self.stores_total += 1
        return True

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def take(self, key: str) -> Optional[dict]:
        """Pop ``key``'s payload (reload consumes the spill — a block is
        HBM-resident XOR spilled, never both). None on a miss."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return None
            payload, nb = entry
            self._bytes -= nb
            self.takes_total += 1
            return payload

    def discard(self, key: str) -> None:
        """Drop a stale spill (its chain re-materialized in HBM)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry[1]

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "stores_total": self.stores_total,
                "takes_total": self.takes_total,
                "budget_drops_total": self.budget_drops_total,
                "oversize_rejects_total": self.oversize_rejects_total,
            }
