"""Fleet prefix directory: which replica holds which prefix blocks.

The router's consistent-hash affinity is a *heuristic* — it predicts
where a prefix SHOULD be warm. This directory is the *record* of where
prefixes ARE warm: the fleet scheduler notes (key → replica) on every
completed request, sibling transfer, and migration, keyed by the same
token-chain block hash ``fleet/router.affinity_key`` computes (the paged
allocator's sharing granularity). The router consults it before the ring
walk, so a request whose affinity target changed (ring remap, failover
history, queue override) still lands on known-warm KV; when placement
can't follow the KV, the fleet scheduler uses the directory to pull the
prefix from the holding sibling over TransferPrefix instead of
re-prefilling.

Entries are hints, never load-bearing: a stale holder (replica-side LRU
eviction, respawn that beat the death listener) costs one failed fetch,
after which the caller drops the entry and falls back to a plain
prefill. Replica death/eviction invalidates eagerly via
``drop_replica`` (wired to the pool's death listener).

All methods are thread-safe (routing threads, dispatch threads, and the
pool monitor all call in).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Iterable, Optional


def directory_capacity_from_env() -> int:
    """``LOCALAI_KV_DIR_ENTRIES`` (default 4096) — max tracked keys."""
    try:
        n = int(os.environ.get("LOCALAI_KV_DIR_ENTRIES", "") or 4096)
    except ValueError:
        n = 4096
    return max(16, n)


class PrefixDirectory:
    """LRU map of affinity key → ordered set of replica ids holding it."""

    def __init__(self, max_entries: Optional[int] = None):
        self.max_entries = (directory_capacity_from_env()
                            if max_entries is None else max(1, max_entries))
        self._lock = threading.Lock()
        # key → OrderedDict[rid, None]: most recently confirmed holder
        # LAST (lookup prefers it — freshest KV is least likely evicted)
        self._entries: "OrderedDict[int, OrderedDict[str, None]]" = \
            OrderedDict()
        self.notes = 0
        self.hits = 0
        self.misses = 0
        self.drops = 0            # single stale holders dropped
        self.invalidations = 0    # whole-replica invalidations

    def note(self, key: Optional[int], rid: str) -> None:
        """Record that ``rid`` now holds ``key``'s prefix blocks."""
        if key is None or not rid:
            return
        with self._lock:
            holders = self._entries.get(key)
            if holders is None:
                holders = OrderedDict()
                self._entries[key] = holders
            holders.pop(rid, None)
            holders[rid] = None
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            self.notes += 1

    def lookup(self, key: Optional[int],
               eligible: Iterable[str]) -> Optional[str]:
        """Routing probe: the freshest holder of ``key`` among
        ``eligible`` replica ids, counting hit/miss. None when unknown."""
        if key is None:
            return None
        allowed = set(eligible)
        with self._lock:
            holders = self._entries.get(key)
            if holders:
                for rid in reversed(holders):
                    if rid in allowed:
                        self._entries.move_to_end(key)
                        self.hits += 1
                        return rid
            self.misses += 1
            return None

    def holder(self, key: Optional[int], eligible: Iterable[str],
               exclude: Iterable[str] = ()) -> Optional[str]:
        """Like :meth:`lookup` but counter-silent — the sibling-fetch
        probe, which runs AFTER routing already counted this request."""
        if key is None:
            return None
        allowed = set(eligible) - set(exclude)
        with self._lock:
            holders = self._entries.get(key)
            if holders:
                for rid in reversed(holders):
                    if rid in allowed:
                        return rid
            return None

    def drop(self, key: Optional[int], rid: str) -> None:
        """A fetch against ``rid`` for ``key`` failed — the entry was
        stale (replica-side LRU eviction). Forget that holder."""
        if key is None:
            return
        with self._lock:
            holders = self._entries.get(key)
            if holders is None or rid not in holders:
                return
            del holders[rid]
            if not holders:
                del self._entries[key]
            self.drops += 1

    def drop_replica(self, rid: str) -> int:
        """Replica died/respawned/was evicted: every entry naming it is
        stale at once (a respawned engine boots cold). Returns entries
        touched."""
        touched = 0
        with self._lock:
            dead_keys = []
            for key, holders in self._entries.items():
                if rid in holders:
                    del holders[rid]
                    touched += 1
                    if not holders:
                        dead_keys.append(key)
            for key in dead_keys:
                del self._entries[key]
            if touched:
                self.invalidations += 1
        return touched

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "notes": self.notes,
                "hits": self.hits,
                "misses": self.misses,
                "drops": self.drops,
                "invalidations": self.invalidations,
            }
