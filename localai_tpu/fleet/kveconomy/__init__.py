"""Fleet-wide KV economy: prefix directory, HBM→host tiering, migration.

Three cooperating pieces that turn N replicas' private KV caches into one
fleet-wide pool:

  * :mod:`directory` — the serving instance's map of *which replica holds
    which prefix blocks* (keyed by the same token-chain hashes the
    router's affinity heuristic uses). The router consults it to place
    requests on known-warm KV, and the fleet scheduler uses it to fetch a
    prefix from a sibling over TransferPrefix instead of re-prefilling.
  * :mod:`tiering` — a host-RAM spill tier under the paged
    ``BlockAllocator``'s prefix pool: LRU-evicted HBM blocks park in host
    memory (int4 pools at half the bytes) and re-onboard on a later
    chain match, making effective prefix-cache capacity host-RAM-sized.
  * :mod:`migration` — the ticket protocol for moving an in-flight slot
    between replicas mid-generation (drain-free deploys, hot-spot
    rebalancing), built on the layout-independent
    ``snapshot_prefix``/``load_prefix`` round-trip.
"""

from localai_tpu.fleet.kveconomy.directory import PrefixDirectory
from localai_tpu.fleet.kveconomy.migration import MigrationTicket
from localai_tpu.fleet.kveconomy.tiering import HostTier, tier_from_env

__all__ = ["PrefixDirectory", "HostTier", "tier_from_env",
           "MigrationTicket"]
