"""Fleet router: multi-replica data-parallel serving for one model.

Rebuilds the reference's federation/P2P load-balancing layer (one model
spread over many worker instances) on the worker gRPC tier and the paged
KV engine instead of libp2p:

  * :mod:`localai_tpu.fleet.pool` — ReplicaPool: N engine replicas
    (worker processes or in-process engines), explorer-style health
    dials, respawn-on-death, per-replica stats pulled over RPC.
  * :mod:`localai_tpu.fleet.router` — prompt-prefix-affinity placement
    (token-chain block hash → consistent-hash ring) with least-loaded
    fallback and per-replica burn-rate route-around.
  * :mod:`localai_tpu.fleet.serving` — FleetServingModel/FleetScheduler:
    the ServingModel-shaped facade the API tier serves through, with
    retry-with-failover and the disaggregated prefill→decode handoff.
  * :mod:`localai_tpu.fleet.prefix` — the in-memory prefix cache +
    chunked npz wire format behind the TransferPrefix RPC.
  * :mod:`localai_tpu.fleet.net` — the cross-host RPC discipline:
    explicit deadlines (LOCALAI_FLEET_RPC_TIMEOUT_S), bounded jittered
    retries for idempotent calls, and the stream pump that turns a
    partitioned peer's silence into a prompt failover.
  * :mod:`localai_tpu.fleet.replica` — the replica kinds: spawned
    workers, in-process engines, and adopted remotes (RemoteReplica:
    evicted-with-redial, never respawned).
"""

from localai_tpu.fleet.net import RpcDeadlineExceeded, bounded_stream
from localai_tpu.fleet.pool import ReplicaPool
from localai_tpu.fleet.prefix import PrefixCache, assemble_chunks, pack_chunks
from localai_tpu.fleet.replica import RemoteReplica
from localai_tpu.fleet.router import Router, affinity_key
from localai_tpu.fleet.serving import FleetScheduler, FleetServingModel

__all__ = [
    "FleetScheduler",
    "FleetServingModel",
    "PrefixCache",
    "RemoteReplica",
    "ReplicaPool",
    "Router",
    "RpcDeadlineExceeded",
    "affinity_key",
    "assemble_chunks",
    "bounded_stream",
    "pack_chunks",
]
