"""ReplicaPool: N live engine replicas for one model, kept alive.

Lifecycle parity with the worker tier's single-process management
(worker/process.py) scaled out: every replica is spawned at boot
(concurrently — a cold fleet boots in one model-load, not N), a monitor
thread dial-tests each replica on an interval (explorer-style: timing,
consecutive-failure counting — federation/explorer.py), and a replica
past the failure threshold (or whose process died) is marked ``dead``,
taken out of routing, and respawned in the background; it rejoins the
ring only after its respawn passes health + LoadModel again. Per-replica
engine stats are pulled over the metrics RPC for /v1/fleet and the
``localai_fleet_*`` gauges — the decode hot path never waits on a stats
pull."""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, Optional

from localai_tpu.faults import registry as _faults
from localai_tpu.fleet.replica import (DEAD, EVICTED, HEALTHY, RESPAWNING,
                                       BaseReplica)
from localai_tpu.obs.metrics import REGISTRY

log = logging.getLogger(__name__)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ReplicaPool:
    def __init__(self, model: str,
                 factory: Callable[[str, str], BaseReplica],
                 *, replicas: int = 2, prefill_replicas: int = 0,
                 remotes: Optional[list[BaseReplica]] = None,
                 health_interval: float = 5.0,
                 failure_threshold: int = 3,
                 dial_timeout: float = 2.0,
                 track_queue_depth: bool = False):
        self.model = model
        self.factory = factory
        self.health_interval = health_interval
        self.failure_threshold = failure_threshold
        self.dial_timeout = dial_timeout
        # refresh each healthy replica's reported decode queue depth on
        # the monitor sweep (one bounded stats pull per replica per
        # interval) — opt-in: only the router's queue-override hint reads
        # it, and fleets without the hint shouldn't pay the RPCs
        self.track_queue_depth = track_queue_depth
        self.replicas: list[BaseReplica] = []
        for i in range(replicas):
            self.replicas.append(factory(f"{model}/r{i}", "decode"))
        for i in range(prefill_replicas):
            self.replicas.append(factory(f"{model}/p{i}", "prefill"))
        # runtime-spawn id minting (autoscale scale-out, hot swap): ids
        # only ever advance — a retired r0's name is never reused, so
        # directory entries, SLO windows, and backoff books keyed on the
        # old id can never be mistaken for the newcomer's
        self._next_index = {"decode": replicas, "prefill": prefill_replicas}
        self._lock = threading.Lock()
        self._respawning: set[str] = set()
        # death listeners: called with the replica id once per
        # DEAD/EVICTED transition (the kveconomy prefix directory hooks
        # here to invalidate every entry naming the replica — a respawn
        # comes back with COLD HBM, so the old entries are lies)
        self._death_listeners: list[Callable[[str], None]] = []
        self.respawns = 0
        # remote lifecycle accounting, distinct from local respawn: a
        # failed remote is EVICTED from routing and REDIALED on backoff —
        # this process never (re)spawns a peer it does not own
        self.evictions = 0
        self.redials = 0
        self.adoptions = 0
        # respawn pacing: a replica whose respawn keeps failing is retried
        # on jittered exponential backoff (base doubled per consecutive
        # failure, capped) instead of hammering a dead host every sweep;
        # a successful rejoin resets the clock. Exported per replica as
        # localai_fleet_respawn_backoff_s (locals) /
        # localai_fleet_redial_backoff_s (remotes).
        self.respawn_backoff_base = _env_float(
            "LOCALAI_FLEET_RESPAWN_BASE_S", 1.0)
        self.respawn_backoff_cap = _env_float(
            "LOCALAI_FLEET_RESPAWN_CAP_S", 60.0)
        self.redial_backoff_base = _env_float(
            "LOCALAI_FLEET_REDIAL_BASE_S", self.respawn_backoff_base)
        self.redial_backoff_cap = _env_float(
            "LOCALAI_FLEET_REDIAL_CAP_S", self.respawn_backoff_cap)
        self._respawn_failures: dict[str, int] = {}
        self._respawn_after: dict[str, float] = {}
        self.respawn_backoff_s: dict[str, float] = {}
        self.redial_backoff_s: dict[str, float] = {}
        self._started = False
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # statically adopted remote replicas (LOCALAI_FLEET_HOSTS) ride
        # the same adopt() path as runtime joins — one counting surface,
        # one duplicate guard — and boot with the locals in start()
        for r in remotes or []:
            self.adopt(r)

    # -- boot / teardown ---------------------------------------------------

    def start(self) -> None:
        """Spawn every replica concurrently (worker spawns take tens of
        seconds; serialized boot would multiply that by N), then start the
        health monitor. A replica that fails to boot is marked dead and
        left to the monitor's respawn path — one bad replica must not
        abort the fleet."""
        errors: dict[str, Exception] = {}

        def boot(r: BaseReplica) -> None:
            try:
                r.start()
                r.dial(self.dial_timeout)
            except Exception as e:  # noqa: BLE001
                errors[r.id] = e
                # an unreachable remote at boot is evicted-with-redial
                # like any other remote failure, never left "dead" —
                # and it COUNTS: the runbook (and alerting) watch the
                # eviction series for boot-time partitions too
                r.state = DEAD if r.respawnable else EVICTED
                if not r.respawnable:
                    with self._lock:
                        self.evictions += 1
                    REGISTRY.fleet_evictions.inc(
                        model=self.model, replica=r.id)

        members = self.members()
        threads = [threading.Thread(target=boot, args=(r,),
                                    name=f"fleet-boot-{r.id}", daemon=True)
                   for r in members]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for rid, e in errors.items():
            log.warning("fleet %s: replica %s failed to boot: %s",
                        self.model, rid, e)
        if not any(r.state == HEALTHY for r in members):
            # reap whatever DID spawn — without a monitor nothing else
            # will, and a retried load would stack orphaned workers
            for r in members:
                try:
                    r.stop()
                except Exception:  # noqa: BLE001 — teardown must finish
                    log.exception("stopping replica %s failed", r.id)
            raise RuntimeError(
                f"fleet {self.model}: no replica came up "
                f"({ {k: str(v) for k, v in errors.items()} })")
        self._monitor = threading.Thread(
            target=self._run_monitor, name=f"fleet-monitor-{self.model}",
            daemon=True)
        self._monitor.start()
        self._started = True

    def members(self) -> list[BaseReplica]:
        """Locked snapshot of the replica list. The list is append-only
        (adopt() under ``_lock``); every reader iterates a copy so a
        mid-traffic registry join can never invalidate an iteration."""
        with self._lock:
            return list(self.replicas)

    def adopt(self, replica: BaseReplica, *, wait: bool = False) -> bool:
        """Add ``replica`` to the pool at runtime (federation-registry
        join / operator action). Returns False on a duplicate id. Before
        ``start()`` the replica just rides the normal concurrent boot;
        after it, the dial+load runs on a background thread (``wait=True``
        runs it inline — the registration endpoint wants the verdict) and
        a failed boot lands in the eviction/redial (remote) or respawn
        (local) path instead of aborting anything. The router's
        consistent-hash ring picks the newcomer up on its next route —
        only ~1/N of the affinity keyspace remaps."""
        with self._lock:
            if any(r.id == replica.id for r in self.replicas):
                return False
            self.replicas.append(replica)
            self.adoptions += 1
        REGISTRY.fleet_adoptions.inc(model=self.model)
        if not self._started:
            return True

        def boot() -> None:
            try:
                replica.start()
                if not replica.dial(self.dial_timeout):
                    raise RuntimeError(
                        f"adopted replica {replica.id} failed its first "
                        "dial")
                log.info("fleet %s: adopted replica %s joined",
                         self.model, replica.id)
            except Exception as e:  # noqa: BLE001 — join ≠ fleet health
                log.warning("fleet %s: adopted replica %s failed to boot: "
                            "%s", self.model, replica.id, e)
                replica.failures = max(replica.failures,
                                       self.failure_threshold)
                self._mark_dead(replica)

        if wait:
            boot()
        else:
            threading.Thread(target=boot, daemon=True,
                             name=f"fleet-adopt-{replica.id}").start()
        return True

    def spawn(self, role: str = "decode", *,
              wait: bool = True) -> Optional[str]:
        """Mint a brand-new locally owned replica through the pool's
        factory and adopt it (autoscale scale-out / hot swap / cold
        re-onboard). Returns the new replica id, or None when the boot
        failed — the failed newcomer stays in the pool's respawn loop,
        so capacity still arrives once whatever blocked the spawn clears."""
        with self._lock:
            prefix = "r" if role == "decode" else "p"
            idx = self._next_index.get(role, 0)
            self._next_index[role] = idx + 1
        rid = f"{self.model}/{prefix}{idx}"
        replica = self.factory(rid, role)
        self.adopt(replica, wait=wait)
        if wait and replica.state != HEALTHY:
            return None
        return rid

    def remove(self, rid: str, *, stop: bool = True) -> bool:
        """Retire ``rid`` out of the pool (autoscale scale-in, hot swap).
        The replica leaves the member list (routing loses it on the next
        ring rebuild), its respawn/backoff books are cleared, and its
        ``retired`` flag parks any in-flight respawn thread. The caller
        owns the drain — this only removes and stops."""
        with self._lock:
            replica = next((r for r in self.replicas if r.id == rid), None)
            if replica is None:
                return False
            replica.retired = True
            self.replicas = [r for r in self.replicas if r.id != rid]
            self._respawn_failures.pop(rid, None)
            self._respawn_after.pop(rid, None)
            self.respawn_backoff_s.pop(rid, None)
            self.redial_backoff_s.pop(rid, None)
        if stop:
            try:
                replica.stop()
            except Exception:  # noqa: BLE001 — removal must finish
                log.exception("stopping retired replica %s failed", rid)
        log.info("fleet %s: replica %s retired from the pool",
                 self.model, rid)
        return True

    def shutdown(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(self.health_interval * 2)
            self._monitor = None
        for r in self.members():
            try:
                r.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.exception("stopping replica %s failed", r.id)

    # -- routing surface ---------------------------------------------------

    def healthy(self, role: str = "decode") -> list[BaseReplica]:
        return [r for r in self.members()
                if r.state == HEALTHY and r.role == role]

    def get(self, rid: str) -> Optional[BaseReplica]:
        for r in self.members():
            if r.id == rid:
                return r
        return None

    def least_loaded(self, role: str = "prefill") -> Optional[BaseReplica]:
        live = self.healthy(role)
        return min(live, key=lambda r: r.load) if live else None

    def add_death_listener(self, fn: Callable[[str], None]) -> None:
        """Register ``fn(replica_id)`` to run on every DEAD/EVICTED
        transition (once per incident — _mark_dead is idempotent)."""
        with self._lock:
            self._death_listeners.append(fn)

    def note_failure(self, replica: BaseReplica) -> None:
        """A request-level transport failure on ``replica`` (called by the
        dispatch thread). A dead process is marked dead IMMEDIATELY —
        subsequent requests route around it without waiting for the next
        monitor sweep — and its respawn starts in the background."""
        if replica.state != HEALTHY:
            return
        if not replica.process_alive() or not replica.dial(self.dial_timeout):
            replica.failures = max(replica.failures, self.failure_threshold)
            self._mark_dead(replica)

    # -- monitor -----------------------------------------------------------

    def _run_monitor(self) -> None:
        while not self._stop.wait(self.health_interval):
            self.poll_once()

    def poll_once(self) -> None:
        """One dial-test sweep (the testable unit)."""
        for r in self.members():
            if r.state == RESPAWNING or self._stop.is_set():
                continue
            if r.state in (DEAD, EVICTED):
                with self._lock:
                    hold = self._respawn_after.get(r.id, 0.0)
                if time.monotonic() >= hold:
                    self._spawn_respawn(r)
                continue
            ok = r.process_alive() and r.dial(self.dial_timeout)
            if ok and self.track_queue_depth and r.role == "decode":
                # only decode placement reads the hint — prefill replicas
                # shouldn't pay the extra metrics RPC per sweep
                m = r.metrics()
                if "queue_depth" in m:
                    r.queue_depth = int(m.get("queue_depth") or 0)
                else:
                    # failed scrape (the RPC error dict): a stale high
                    # reading must not strip affinity traffic forever
                    r.queue_depth = 0
            if not ok and r.failures >= self.failure_threshold:
                self._mark_dead(r)
            elif not ok and not r.process_alive():
                # no process left to dial back to life — don't burn the
                # remaining threshold sweeps on a corpse
                r.failures = max(r.failures, self.failure_threshold)
                self._mark_dead(r)

    def _mark_dead(self, r: BaseReplica) -> None:
        # check-and-transition atomically: a dispatch thread's
        # note_failure can race the monitor sweep (or another dispatch)
        # here, and the eviction accounting must move once per incident
        with self._lock:
            if r.state in (DEAD, EVICTED):
                return
            r.state = DEAD if r.respawnable else EVICTED
            if not r.respawnable:
                self.evictions += 1
            listeners = list(self._death_listeners)
        for fn in listeners:
            try:
                fn(r.id)
            except Exception:  # noqa: BLE001 — bookkeeping ≠ recovery
                log.exception("death listener failed for %s", r.id)
        if r.respawnable:
            log.warning("fleet %s: replica %s marked dead "
                        "(%d consecutive dial failures)",
                        self.model, r.id, r.failures)
        else:
            # a remote's failure is the NETWORK's (or the peer's) — evict
            # it from routing and redial on backoff; there is no process
            # here to respawn
            log.warning("fleet %s: remote replica %s evicted "
                        "(%d consecutive dial failures)",
                        self.model, r.id, r.failures)
            REGISTRY.fleet_evictions.inc(model=self.model, replica=r.id)
        self._spawn_respawn(r)

    def _spawn_respawn(self, r: BaseReplica) -> None:
        """Bring a dead local replica (respawn) or an evicted remote
        (redial) back: same retry skeleton, different semantics — a
        remote is never stopped-and-spawned, its ``start()`` is a fresh
        dial + LoadModel-if-empty, and it keeps state ``evicted`` (not
        ``respawning``) while the attempt runs."""
        with self._lock:
            if r.id in self._respawning:
                return
            self._respawning.add(r.id)
        down_state = DEAD if r.respawnable else EVICTED
        if r.respawnable:
            r.state = RESPAWNING

        def respawn() -> None:
            try:
                if self._stop.is_set() or r.retired:
                    r.state = down_state
                    return
                try:
                    r.stop()
                except Exception:  # noqa: BLE001
                    pass
                if _faults.ACTIVE and r.respawnable:
                    # chaos: a respawn that keeps failing (remotes
                    # exercise fleet.dial on the post-start dial instead)
                    _faults.apply("fleet.respawn", key=r.id)
                r.start()
                if self._stop.is_set() or r.retired:
                    # shutdown (or a scale-in removal) raced the spawn:
                    # its stop() sweep already ran, so reap the worker we
                    # just brought up
                    try:
                        r.stop()
                    except Exception:  # noqa: BLE001
                        pass
                    r.state = down_state
                    return
                # rejoin routing only after a real dial passes (start()
                # already health-gated the spawn; this records the timing
                # and flips STARTING/RESPAWNING/EVICTED → HEALTHY)
                if r.dial(self.dial_timeout):
                    with self._lock:
                        if r.respawnable:
                            self.respawns += 1
                        else:
                            self.redials += 1
                    if not r.respawnable:
                        r.state = HEALTHY  # dial() only flips from
                        #                    STARTING/RESPAWNING
                        REGISTRY.fleet_redials.inc(
                            model=self.model, replica=r.id)
                    self._note_rejoined(r)
                    log.info("fleet %s: replica %s %s", self.model, r.id,
                             "respawned" if r.respawnable else "redialed")
                else:
                    r.state = down_state
                    self._note_respawn_failed(r)
            except Exception as e:  # noqa: BLE001
                r.state = down_state
                backoff = self._note_respawn_failed(r)
                log.warning("fleet %s: %s of %s failed: %s "
                            "(retrying in %.1fs)", self.model,
                            "respawn" if r.respawnable else "redial",
                            r.id, e, backoff)
            finally:
                with self._lock:
                    self._respawning.discard(r.id)

        threading.Thread(target=respawn, name=f"fleet-respawn-{r.id}",
                         daemon=True).start()

    def _note_respawn_failed(self, r: BaseReplica) -> float:
        """Advance the replica's jittered exponential respawn (local) or
        redial (remote) backoff: base × 2^consecutive-failures, ±25%
        jitter, capped. The next sweep skips the replica until the hold
        expires. Returns the applied delay (logging/tests)."""
        if r.respawnable:
            base_s, cap = self.respawn_backoff_base, self.respawn_backoff_cap
            gauge = REGISTRY.fleet_respawn_backoff
        else:
            base_s, cap = self.redial_backoff_base, self.redial_backoff_cap
            gauge = REGISTRY.fleet_redial_backoff
        with self._lock:
            book = (self.respawn_backoff_s if r.respawnable
                    else self.redial_backoff_s)
            n = self._respawn_failures.get(r.id, 0)
            self._respawn_failures[r.id] = n + 1
            base = min(cap, base_s * (2 ** n))
            delay = min(cap, base * (0.75 + 0.5 * random.random()))
            book[r.id] = delay
            self._respawn_after[r.id] = time.monotonic() + delay
        gauge.set(delay, model=self.model, replica=r.id)
        return delay

    def _note_rejoined(self, r: BaseReplica) -> None:
        """A respawn/redial passed health + LoadModel: the backoff clock
        resets so the next incident starts from the base again."""
        gauge = (REGISTRY.fleet_respawn_backoff if r.respawnable
                 else REGISTRY.fleet_redial_backoff)
        with self._lock:
            self._respawn_failures.pop(r.id, None)
            self._respawn_after.pop(r.id, None)
            self.respawn_backoff_s.pop(r.id, None)
            self.redial_backoff_s.pop(r.id, None)
        gauge.set(0.0, model=self.model, replica=r.id)

    # -- observability -----------------------------------------------------

    def states(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for r in self.members():
            out[r.state] = out.get(r.state, 0) + 1
        return out

    def snapshot(self, *, with_metrics: bool = False) -> dict:
        reps = []
        for r in self.members():
            snap = r.snapshot()
            if not r.respawnable:
                snap["remote"] = True
                snap["address"] = getattr(r, "address", None)
            if with_metrics and r.state == HEALTHY:
                m = r.metrics()
                # step-time percentiles + spec accept ride along so
                # /v1/fleet explains route-around decisions per replica
                snap["engine"] = {
                    k: m.get(k) for k in (
                        "occupancy", "queue_depth", "kv_utilization",
                        "total_generated_tokens", "step_ms_p50",
                        "step_ms_p99", "spec_accept_rate",
                        "spec_tokens_per_dispatch", "error",
                    ) if k in m
                }
            reps.append(snap)
        with self._lock:
            respawns = self.respawns
            evictions = self.evictions
            redials = self.redials
            adoptions = self.adoptions
            backoff = dict(self.respawn_backoff_s)
            redial_backoff = dict(self.redial_backoff_s)
        return {
            "model": self.model,
            "states": self.states(),
            "respawns": respawns,
            "evictions": evictions,
            "redials": redials,
            "adoptions": adoptions,
            "respawn_backoff_s": backoff,
            "redial_backoff_s": redial_backoff,
            "health_interval_s": self.health_interval,
            "failure_threshold": self.failure_threshold,
            "replicas": reps,
        }
