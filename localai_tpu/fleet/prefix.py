"""In-memory KV-prefix cache + the chunked wire format between replicas.

The disaggregated handoff deliberately reuses the disk prompt-cache
machinery end to end (engine/promptcache.py): a prefill replica's
scheduler *stores* the finished prompt prefix into a :class:`PrefixCache`
(the same ``store(tokens, pack_prefix(...))`` call the disk tier gets), the
fleet router relays the packed arrays over the TransferPrefix RPC, and the
decode replica's scheduler finds them via ``lookup()`` at admission and
``load_prefix``-resumes — the exact code path the disk cache already
proves byte-identical greedy resumption for. No new engine state, no new
admission semantics; the cache is just RAM-resident and fed over the wire
instead of from npz files.

``pack_chunks``/``assemble_chunks`` are the wire codec: one npz blob
(numpy's own container — the same serialization the disk tier uses) split
into bounded ``PrefixChunk`` fragments so a long prompt's KV export
streams instead of materializing one giant message.
"""

from __future__ import annotations

import io
import os
import threading
import uuid
from collections import OrderedDict
from typing import Iterable, Iterator, Optional

import numpy as np

from localai_tpu.engine.promptcache import CacheHit

# 1 MiB fragments: far under the 256 MiB channel cap, big enough that a
# multi-MB prefix ships in a handful of messages
CHUNK_BYTES = 1 << 20


def _default_max_bytes() -> int:
    """LOCALAI_FLEET_PREFIX_CACHE_MB (default 1024). A packed prefix for a
    production-size model is hundreds of MB, so an entry-count bound alone
    would let the cache grow to many GB of host RAM."""
    try:
        mb = float(os.environ.get("LOCALAI_FLEET_PREFIX_CACHE_MB", "") or 1024)
    except ValueError:
        mb = 1024.0
    return max(1, int(mb * (1 << 20)))


class PrefixUnavailable(RuntimeError):
    """Prefill ran, but no exportable prefix materialized (prompt beyond
    context, or the scheduler's export path is disabled)."""


class PrefixCache:
    """PromptKVCache-shaped, RAM-resident, signalling store.

    Presents exactly the surface ``engine.scheduler.Scheduler`` expects of
    a prompt cache (``lookup``/``store``/``stats``/``read_only``/
    ``min_prefix``) plus ``wait_for()`` — the prefill-export path stores
    asynchronously (the scheduler's prompt-cache writer thread), so the
    PrefillPrefix RPC handler blocks on the store event rather than
    polling."""

    def __init__(self, *, max_entries: int = 16, min_prefix: int = 16,
                 max_bytes: Optional[int] = None, fallthrough=None):
        self.read_only = False
        self.min_prefix = min_prefix
        self.max_entries = max_entries
        self.max_bytes = max_bytes if max_bytes is not None \
            else _default_max_bytes()
        # optional second tier (a configured disk PromptKVCache): stores
        # forward to it, RAM-missed lookups fall through to it — a fleet
        # replica with a disk prompt cache keeps BOTH the disk reuse and
        # the store-signalling surface the disaggregation export needs
        self.fallthrough = fallthrough
        self._lock = threading.Lock()
        # key → (tokens, arrays, nbytes); LRU order, evicted from the front
        self._entries: "OrderedDict[tuple, tuple[list[int], dict, int]]" = \
            OrderedDict()
        self._total_bytes = 0
        self._stored = threading.Condition(self._lock)
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.hit_tokens = 0

    @staticmethod
    def _key(tokens: list[int]) -> tuple:
        return tuple(int(t) for t in tokens)

    def store(self, tokens: list[int], arrays: dict) -> None:
        n = int(arrays["k"].shape[2])
        if n < self.min_prefix:
            return
        key = self._key(tokens)
        nbytes = sum(int(np.asarray(a).nbytes) for a in arrays.values())
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total_bytes -= old[2]
            self._entries[key] = (list(map(int, tokens)), arrays, nbytes)
            self._total_bytes += nbytes
            # evict LRU past either budget — but always keep the entry just
            # stored, even if it alone exceeds max_bytes (the exporter is
            # blocked on it in wait_for)
            while len(self._entries) > 1 and (
                    len(self._entries) > self.max_entries
                    or self._total_bytes > self.max_bytes):
                _, (_, _, freed) = self._entries.popitem(last=False)
                self._total_bytes -= freed
            self.stores += 1
            self._stored.notify_all()
        ft = self.fallthrough
        if ft is not None and not ft.read_only:
            # disk IO stays outside our lock; the disk tier is its own
            # synchronization domain
            ft.store(tokens, arrays)

    def wait_for(self, tokens: list[int],
                 timeout: float = 30.0) -> Optional[dict]:
        """Block until ``tokens`` lands (the prefill replica's scheduler
        stores off-thread); returns its packed arrays or None on timeout."""
        key = self._key(tokens)
        with self._lock:
            if self._stored.wait_for(lambda: key in self._entries, timeout):
                return self._entries[key][1]
            return None

    def lookup(self, prompt: list[int]) -> Optional[CacheHit]:
        """Entry with the longest common prefix ≥ min_prefix, or None —
        the same contract (and the same last-token-recompute clip) as the
        disk tier. Runs fully under the lock (≤ max_entries short scans)
        so a concurrent store() cannot evict the winner mid-selection."""
        with self._lock:
            best_key: Optional[tuple] = None
            best: Optional[tuple[list[int], dict, int]] = None
            best_lcp = 0
            for key, entry in self._entries.items():
                lcp = 0
                for a, b in zip(entry[0], prompt):
                    if a != b:
                        break
                    lcp += 1
                if lcp > best_lcp:
                    best_key, best, best_lcp = key, entry, lcp
            best_lcp = min(best_lcp, len(prompt) - 1)
            if best is not None and best_lcp >= self.min_prefix:
                self._entries.move_to_end(best_key, last=True)
                tokens, arrays, _ = best
                n = int(arrays["k"].shape[2])
                self.hits += 1
                self.hit_tokens += n
                return CacheHit(tokens=list(tokens), arrays=arrays, n=n)
            self.misses += 1
        # the disk tier's IO runs outside our lock
        if self.fallthrough is not None:
            return self.fallthrough.lookup(prompt)
        return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._total_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "hit_tokens": self.hit_tokens,
            }


# -- the two halves of the handoff, shared by both replica kinds -------------
# (worker/server.py's PrefillPrefix/TransferPrefix handlers and
# fleet/replica.py's InProcessReplica wrap these; keeping the threshold
# checks, the one-token prefill trick, and the export wait here means the
# gRPC and in-process paths cannot drift)


def export_prefix(sm, gr, cache: PrefixCache,
                  *, prefill_timeout: float = 600.0,
                  export_timeout: float = 60.0) -> tuple[list[int], dict]:
    """Prefill-replica half: run ``gr``'s prefill (one sampled token, then
    the slot retires through the normal release path, which snapshots the
    prompt prefix into ``cache`` — engine/scheduler._release), wait for
    the off-thread export, return ``(prompt, packed arrays)``.

    Raises ValueError on a prompt below the export minimum,
    PrefixUnavailable when prefill finished but nothing exported, and
    RuntimeError when the prefill itself failed."""
    if len(gr.prompt) <= cache.min_prefix:
        raise ValueError(
            f"prompt of {len(gr.prompt)} tokens is below the "
            f"{cache.min_prefix}-token export minimum")
    gr.max_new_tokens = 1
    gr.stream = False
    prompt = list(gr.prompt)
    handle = sm.scheduler.submit(gr)
    try:
        handle.result(timeout=prefill_timeout)
    finally:
        if handle.finish_reason is None:
            handle.cancel()
    if handle.finish_reason not in ("stop", "length"):
        raise RuntimeError(f"prefill finished {handle.finish_reason!r}")
    arrays = cache.wait_for(prompt, timeout=export_timeout)
    if arrays is None:
        raise PrefixUnavailable(
            "prefill finished but no prefix was exported (prompt beyond "
            "context, or the export path is disabled)")
    return prompt, arrays


def import_prefix(cache: PrefixCache, chunks: Iterable) -> int:
    """Decode-replica half: assemble the streamed chunks, enforce the
    import minimum, seed ``cache``. Returns the KV-row count. Raises
    ValueError on a malformed stream or an undersized prefix."""
    tokens, arrays = assemble_chunks(chunks)
    n = int(arrays["k"].shape[2])
    if n < cache.min_prefix:
        raise ValueError(
            f"{n} transferred rows is below the {cache.min_prefix}-"
            "token import minimum")
    cache.store(tokens, arrays)
    return n


# -- wire codec --------------------------------------------------------------


def pack_chunks(tokens: list[int], arrays: dict,
                *, chunk_bytes: int = CHUNK_BYTES,
                transfer_id: str = "") -> Iterator[dict]:
    """(tokens, packed arrays) → bounded PrefixChunk-shaped dicts.

    ``arrays`` must already be host numpy (``ModelRunner.pack_prefix``
    output); the payload is one npz blob split at ``chunk_bytes``."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob = buf.getvalue()
    tid = transfer_id or uuid.uuid4().hex
    n_tokens = int(arrays["k"].shape[2])
    total = max(1, -(-len(blob) // chunk_bytes))
    for i in range(total):
        frag = blob[i * chunk_bytes:(i + 1) * chunk_bytes]
        yield {
            "transfer_id": tid,
            "seq": i,
            "data": frag,
            "last": i == total - 1,
            # identity rides the first fragment only (the rest are payload)
            "tokens": list(map(int, tokens)) if i == 0 else [],
            "n_tokens": n_tokens if i == 0 else 0,
        }


def assemble_chunks(chunks: Iterable) -> tuple[list[int], dict]:
    """PrefixChunk stream (protos or pack_chunks dicts) → (tokens, arrays).

    Raises ValueError on an empty, unordered or truncated stream — the
    TransferPrefix handler maps that to INVALID_ARGUMENT."""
    tokens: list[int] = []
    frags: list[bytes] = []
    done = False
    for c in chunks:
        get = (lambda k, _c=c: _c[k]) if isinstance(c, dict) \
            else (lambda k, _c=c: getattr(_c, k))
        if int(get("seq")) != len(frags):
            raise ValueError(
                f"out-of-order prefix chunk: seq {get('seq')} "
                f"(expected {len(frags)})")
        if not frags:
            tokens = list(get("tokens"))
        frags.append(bytes(get("data")))
        if get("last"):
            done = True
            break
    if not frags or not done:
        raise ValueError("truncated prefix transfer (no final chunk)")
    if not tokens:
        raise ValueError("prefix transfer carries no token identity")
    try:
        with np.load(io.BytesIO(b"".join(frags))) as z:
            arrays = {name: z[name] for name in z.files}
    except Exception as e:  # zipfile.BadZipFile, OSError, ... — all mean
        # the same thing to the caller: the payload is not a prefix export
        raise ValueError(f"corrupt prefix transfer payload: {e}") from e
    if "k" not in arrays:
        raise ValueError("prefix transfer payload misses KV rows")
    return tokens, arrays
