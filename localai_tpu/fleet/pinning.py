"""Per-replica device pinning presets (--fleet-device-pinning).

``--fleet-replicas N`` with worker-backed replicas spawns N engine
processes — but without device pinning every worker initializes the SAME
accelerators and the second LoadModel dies on a held TPU chip. The manual
escape is hand-writing ``worker_env`` per deployment; this module derives
it instead: the host's visible devices are partitioned into N contiguous
equal slices (ICI-contiguous in ``jax.devices()`` order, so each replica's
chips form a ring for its own auto-mesh) and each replica's spawn env pins
its slice.

Env derivation by platform:

  * **tpu** — ``TPU_VISIBLE_DEVICES=<ids>`` (libtpu claims only those
    chips) plus ``TPU_PROCESS_BOUNDS``/``TPU_CHIPS_PER_PROCESS_BOUNDS``
    cleared to single-process defaults so a pod-sliced parent env can't
    leak multi-process topology into the worker.
  * **cpu** — ``JAX_PLATFORMS=cpu`` plus
    ``XLA_FLAGS=--xla_force_host_platform_device_count=<per>`` (virtual
    CPU devices; the CI/test shape).
  * anything else (gpu plugins) — ``JAX_PLATFORMS`` passthrough only; no
    portable visible-device convention to derive, so pinning is a no-op
    and the operator keeps ``worker_env``.

The pure core (:func:`pinning_env`) takes platform/device-count
explicitly so tests pin the partition math without touching a backend.
On a real fleet host declare the topology with
``LOCALAI_FLEET_PIN_PLATFORM=tpu LOCALAI_FLEET_PIN_DEVICES=8`` — the
API server process must not probe (and thereby claim) the accelerators
its workers are about to be pinned to (see :func:`derive_pinning_env`).
"""

from __future__ import annotations

import logging
from typing import Optional

log = logging.getLogger(__name__)


def pinning_env(index: int, replicas: int, *, platform: str,
                n_devices: int) -> dict[str, str]:
    """Spawn-env additions for replica ``index`` of ``replicas`` on a host
    with ``n_devices`` ``platform`` accelerators. Pure — no jax import.

    Devices partition into ``replicas`` contiguous slices of
    ``n_devices // replicas`` (device order is ICI-contiguous, so a slice
    is a valid ring for the replica's own auto-mesh); the remainder
    devices stay unused rather than skewing one replica. Returns {} when
    the partition is impossible (fewer devices than replicas) or the
    platform has no pinning convention."""
    if not 0 <= index < replicas:
        raise ValueError(f"replica index {index} outside fleet size "
                         f"{replicas}")
    per = n_devices // replicas
    if per < 1:
        log.warning(
            "device pinning: %d replicas over %d %s device(s) — cannot "
            "partition; replicas spawn unpinned", replicas, n_devices,
            platform)
        return {}
    if n_devices % replicas:
        log.warning(
            "device pinning: %d %s devices do not divide evenly over %d "
            "replicas; %d device(s) stay unused", n_devices, platform,
            replicas, n_devices % replicas)
    ids = range(index * per, (index + 1) * per)
    if platform == "tpu":
        return {
            "TPU_VISIBLE_DEVICES": ",".join(str(i) for i in ids),
            # single-process topology inside the slice: a pod-sliced
            # parent env must not leak its process bounds into the worker
            "TPU_PROCESS_BOUNDS": "",
            "TPU_CHIPS_PER_PROCESS_BOUNDS": "",
        }
    if platform == "cpu":
        return {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={per}",
        }
    log.warning(
        "device pinning: no visible-device convention for platform %r; "
        "replica %d spawns unpinned (set worker_env explicitly)",
        platform, index)
    return {}


def derive_pinning_env(index: int, replicas: int) -> dict[str, str]:
    """:func:`pinning_env` for this host's accelerators.

    Topology comes from ``LOCALAI_FLEET_PIN_PLATFORM`` +
    ``LOCALAI_FLEET_PIN_DEVICES`` when set — the operator-declared truth
    for fleet deployments where the API server itself must not touch the
    accelerators (the recommended worker-fleet setup runs the server
    under ``--platform cpu`` so it never holds a TPU chip; probing
    jax.devices() there would both report the WRONG platform and, on an
    unforced server, initialize libtpu in the parent and claim every
    chip the workers need). Falls back to the parent's live backend only
    when the env is absent — correct for in-process experiments, logged
    so a misconfigured fleet is diagnosable."""
    import os

    platform = os.environ.get("LOCALAI_FLEET_PIN_PLATFORM", "")
    nd = os.environ.get("LOCALAI_FLEET_PIN_DEVICES", "")
    if platform and nd:
        return pinning_env(index, replicas, platform=platform,
                           n_devices=int(nd))
    import jax

    devs = jax.devices()
    log.info(
        "device pinning: LOCALAI_FLEET_PIN_PLATFORM/_DEVICES unset; "
        "deriving from this process's backend (%d %s device(s)) — on a "
        "TPU host declare the topology via env so the server process "
        "never initializes (and holds) the chips itself",
        len(devs), devs[0].platform)
    return pinning_env(index, replicas, platform=devs[0].platform,
                       n_devices=len(devs))


def pinned_worker_env(base: Optional[dict], index: int,
                      replicas: int) -> dict[str, str]:
    """Merge the derived pinning slice over the operator's worker_env
    (explicit keys win — an operator pinning by hand keeps their layout,
    and the derived keys fill only the gaps)."""
    derived = derive_pinning_env(index, replicas)
    out = dict(derived)
    out.update(base or {})
    return out
