"""Cross-host RPC discipline: deadlines, bounded retries, stream pumps.

Once replicas live across a real network, the network is a failure domain
of its own — a partitioned peer does not refuse connections, it silently
eats packets, and a slow link delivers every byte *eventually*. Neither
failure shape raises; both hang. So every cross-host interaction in the
fleet tier goes through this module:

  * **explicit deadlines** — :func:`rpc_timeout_s` is the one knob
    (``LOCALAI_FLEET_RPC_TIMEOUT_S``, default 120 s) bounding
    control-plane RPCs and, via :func:`bounded_stream`, the per-reply
    *inactivity* of dispatch/prefill streams (a generation may
    legitimately run for minutes; what may never happen is silence
    between replies);
  * **bounded jittered retry** — :func:`call_with_retries` for RPCs that
    are idempotent by construction (stats pulls, prefix imports, load
    checks). Dispatch streams are NOT retried here: the fleet scheduler
    owns failover, which is a routing decision, not a transport one;
  * **fault surface** — the ``fleet.transport`` injection site fires on
    the stream pump (per message, keyed by replica id), so partitions
    (``raise``) and slow links (``sleep``) are emulated at exactly the
    layer a real NIC would fail.
"""

from __future__ import annotations

import logging
import os
import queue
import random
import threading
import time
from typing import Callable, Iterator, Optional, TypeVar

from localai_tpu.faults import registry as _faults

log = logging.getLogger(__name__)

T = TypeVar("T")

# deliberately generous: the deadline must sit ABOVE worst-case queue
# wait + TTFT — a first-dispatch XLA compile on a cold replica is minutes
# of legitimate silence, and a too-tight default would cascade spurious
# failovers (each one landing on another cold replica). Operators with
# warmed fleets tighten it; the keepalive pings already catch truly dead
# peers in ~40 s regardless.
DEFAULT_RPC_TIMEOUT_S = 120.0
DEFAULT_RPC_RETRIES = 2


def rpc_timeout_s() -> float:
    """The fleet's cross-host RPC deadline (``LOCALAI_FLEET_RPC_TIMEOUT_S``,
    default 120 s; 0 disables deadline enforcement). Control-plane unary
    RPCs use it directly; streams use it as the per-reply inactivity
    bound."""
    try:
        return float(os.environ.get("LOCALAI_FLEET_RPC_TIMEOUT_S", "")
                     or DEFAULT_RPC_TIMEOUT_S)
    except ValueError:
        return DEFAULT_RPC_TIMEOUT_S


def rpc_retries() -> int:
    """Max retry attempts for idempotent cross-host RPCs
    (``LOCALAI_FLEET_RPC_RETRIES``, default 2)."""
    try:
        return int(os.environ.get("LOCALAI_FLEET_RPC_RETRIES", "")
                   or DEFAULT_RPC_RETRIES)
    except ValueError:
        return DEFAULT_RPC_RETRIES


class RpcDeadlineExceeded(RuntimeError):
    """A cross-host RPC (or one reply of a stream) blew its deadline."""

    def __init__(self, rid: str, timeout: float, what: str = "reply"):
        super().__init__(
            f"no {what} from {rid or 'peer'} within {timeout:.1f}s "
            "(LOCALAI_FLEET_RPC_TIMEOUT_S)")
        self.rid = rid
        self.timeout = timeout


def call_with_retries(fn: Callable[[], T], *, retries: Optional[int] = None,
                      base_delay: float = 0.1, cap_delay: float = 2.0,
                      rid: str = "", what: str = "rpc") -> T:
    """Run ``fn`` with up to ``retries`` bounded, jittered-exponential
    retries. ONLY for idempotent RPCs — re-running must be a no-op on the
    peer (health, stats, tokenize, prefix import). Every retry is counted
    in ``localai_fleet_rpc_retries_total`` so a flaky link shows up in the
    exposition before it shows up as an incident."""
    n = rpc_retries() if retries is None else retries
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transport errors retry
            if attempt >= n:
                raise
            delay = min(cap_delay, base_delay * (2 ** attempt))
            delay *= 0.75 + 0.5 * random.random()
            attempt += 1
            from localai_tpu.obs.metrics import REGISTRY

            REGISTRY.fleet_rpc_retries.inc(rpc=what)
            log.warning("fleet rpc %s to %s failed (%s); retry %d/%d in "
                        "%.2fs", what, rid or "peer", e, attempt, n, delay)
            time.sleep(delay)


# sentinel marking normal end-of-stream on the pump queue
_DONE = object()


def bounded_stream(replies: Iterator[T], timeout: float, *,
                   rid: str = "") -> Iterator[T]:
    """Pump ``replies`` on a reader thread and re-yield each item, raising
    :class:`RpcDeadlineExceeded` when the upstream goes silent for more
    than ``timeout`` seconds (0 = no deadline, pure pump).

    This is how a *dead or partitioned* remote surfaces promptly: a
    SIGKILLed host never RSTs an established TCP stream, so the gRPC
    iterator would block until its (generation-scale) total deadline —
    hanging the dispatch thread and the request with it. The pump turns
    that silence into an exception the fleet scheduler can fail over on.

    The ``fleet.transport`` fault site fires per message *inside the
    pump*, upstream of the deadline check — so an injected ``sleep`` is
    indistinguishable from a slow link and an injected ``raise`` from a
    mid-stream connection reset.
    """
    if timeout <= 0 and not _faults.ACTIVE:
        # deadline disabled and nothing armed: no pump thread, no queue
        # hop — the stream flows as it did pre-cross-host. (The ACTIVE
        # flag is sampled at stream start; a schedule armed mid-stream
        # catches the next dispatch.)
        yield from replies
        return
    q: "queue.Queue" = queue.Queue(maxsize=64)
    abandoned = threading.Event()

    def pump() -> None:
        payload: object = _DONE
        try:
            for item in replies:
                if _faults.ACTIVE:
                    _faults.apply("fleet.transport", key=rid)
                while not abandoned.is_set():
                    try:
                        q.put(item, timeout=0.25)
                        break
                    except queue.Full:
                        continue
                if abandoned.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            payload = e
        finally:
            if abandoned.is_set():
                # the consumer is gone: release whatever the upstream
                # holds. Closing a generator is only legal from the
                # thread that runs its frame — that is THIS thread.
                close = getattr(replies, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — teardown only
                        pass
            else:
                while not abandoned.is_set():
                    try:
                        q.put(payload, timeout=0.25)
                        break
                    except queue.Full:
                        continue

    t = threading.Thread(target=pump, daemon=True,
                         name=f"fleet-pump-{rid or 'stream'}")
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=timeout if timeout > 0 else None)
            except queue.Empty:
                raise RpcDeadlineExceeded(rid, timeout) from None
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        abandoned.set()
        # only a cross-thread-safe cancel here: a gRPC call's cancel()
        # unblocks the pump's next(); a plain generator is closed by the
        # pump itself (closing it from this thread could hit "generator
        # already executing")
        cancel = getattr(replies, "cancel", None)
        if cancel is not None:
            try:
                cancel()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
