"""Cache-aware request placement over a replica fleet.

Placement is the lever that makes N replicas worth more than N× the
hardware (DistServe/Mooncake): the paged engine's whole-block prefix pool
and the disk prompt cache only pay off if requests sharing a prompt prefix
keep landing on the SAME replica. So the router keys placement on the
prompt's first K token-chain blocks (the identical block granularity the
paged allocator shares KV at — engine/paged.py) and maps that key onto a
consistent-hash ring over the live replicas: adding or losing a replica
remaps only ~1/N of the keyspace instead of reshuffling every prompt's
affinity.

Fallbacks, in order: a short prompt (no full block) routes least-loaded;
a replica the per-replica SLO tracker marks shedding is routed AROUND
(next ring candidate) unless every replica is shedding — per-replica
burn is a placement signal here, while true model-level overload stays
the API admission gate's job (obs.slo + 429); a replica that dies
mid-stream is excluded and the retry routes with reason ``failover``."""

from __future__ import annotations

import bisect
import hashlib
import logging
import threading
from typing import Iterable, Optional

import numpy as np

log = logging.getLogger(__name__)

# ring points per replica: enough that one replica's share of the
# keyspace stays within ~2x of fair for small fleets
VNODES = 64
# affinity covers the first K full blocks — enough to separate prompt
# families without making every long shared preamble one hot spot
AFFINITY_BLOCKS = 4


def affinity_key(prompt: list[int], *, block_tokens: int = 64,
                 blocks: int = AFFINITY_BLOCKS) -> Optional[int]:
    """Hash of the prompt's first ``min(blocks, full blocks)`` token-chain
    blocks (the paged allocator's sharing granularity), or None when the
    prompt doesn't fill one block — those route least-loaded."""
    if block_tokens <= 0:
        return None
    nb = min(blocks, len(prompt) // block_tokens)
    if nb <= 0:
        return None
    h = hashlib.sha1()
    h.update(np.asarray(prompt[:nb * block_tokens], np.int64).tobytes())
    return int.from_bytes(h.digest()[:8], "big")


class _Ring:
    """Consistent-hash ring: replica ids → VNODES points each."""

    def __init__(self, ids: Iterable[str], vnodes: int = VNODES):
        pts = []
        for rid in ids:
            for v in range(vnodes):
                d = hashlib.sha1(f"{rid}#{v}".encode()).digest()
                pts.append((int.from_bytes(d[:8], "big"), rid))
        self.points = sorted(pts)
        # the ring is cached across requests (Router._ring), so the
        # per-route cost is one bisect + a short walk, not a rebuild
        self._hashes = [h for h, _ in self.points]
        self._n_ids = len({rid for _, rid in self.points})

    def ordered(self, key: int) -> list[str]:
        """Distinct replica ids in clockwise ring order from ``key`` —
        the failover/route-around preference order for this prompt."""
        if not self.points:
            return []
        start = bisect.bisect_left(self._hashes, key) % len(self.points)
        out: list[str] = []
        seen = set()
        for i in range(len(self.points)):
            rid = self.points[(start + i) % len(self.points)][1]
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
                if len(out) == self._n_ids:
                    break
        return out


class FleetUnavailable(RuntimeError):
    """No healthy replica can take this request right now."""


class Router:
    """Stateless-per-request placement over a ReplicaPool."""

    def __init__(self, pool, slo=None, *, block_tokens: int = 64,
                 affinity_blocks: int = AFFINITY_BLOCKS,
                 queue_override: int = 0, directory=None):
        self.pool = pool
        self.slo = slo                  # per-replica SLOTracker (optional)
        self.block_tokens = block_tokens
        self.affinity_blocks = affinity_blocks
        # fleet prefix directory (kveconomy.PrefixDirectory, optional):
        # the RECORD of which replica holds which prefix blocks, checked
        # before the ring heuristic — a known-warm holder beats where
        # the hash says the prefix should be
        self.directory = directory
        # decode-admission hint (LOCALAI_FLEET_QUEUE_OVERRIDE, 0 = off):
        # when the affinity target's last reported decode queue depth
        # exceeds this, placement degrades to least-loaded — cache
        # affinity is worth little behind a queue that long, and the
        # monitor-refreshed depth costs the hot path nothing
        self.queue_override = queue_override
        self._ring_cache: tuple[tuple, _Ring] = ((), _Ring(()))
        # observability (snapshot into /v1/fleet) — every request routes
        # from its own dispatch thread, so the counters take a lock
        self._lock = threading.Lock()
        self.routed = {"affinity": 0, "least_loaded": 0, "failover": 0,
                       "queue_override": 0, "directory": 0}
        self.routed_around = 0          # shed replicas skipped on the ring

    def _ring(self, ids: tuple) -> _Ring:
        cached_ids, ring = self._ring_cache
        if cached_ids != ids:
            ring = _Ring(ids)
            self._ring_cache = (ids, ring)
        return ring

    def _shedding(self, rid: str) -> bool:
        return self.slo is not None and self.slo.shedding(rid)

    def route(self, prompt: list[int], *, role: str = "decode",
              exclude: Optional[set] = None,
              failover: bool = False):
        """→ (replica, reason). ``exclude`` holds replica ids that already
        failed this request; ``failover=True`` tags the re-dispatch."""
        exclude = exclude or set()
        live = [r for r in self.pool.healthy(role) if r.id not in exclude]
        if not live:
            raise FleetUnavailable(
                f"no healthy {role} replica available "
                f"(excluded: {sorted(exclude) or 'none'})")
        byid = {r.id: r for r in live}
        eligible = [r for r in live if not self._shedding(r.id)]
        skipped = len(live) - len(eligible)
        if not eligible:
            # every replica is burning budget: routing around all of them
            # would 503 traffic the model-level admission gate chose to
            # admit — degrade to least-loaded instead
            eligible = live
            skipped = 0
        with self._lock:
            self.routed_around += skipped

        key = affinity_key(prompt, block_tokens=self.block_tokens,
                           blocks=self.affinity_blocks)
        if key is not None and self.directory is not None:
            # directory first: a replica KNOWN to hold this prefix's
            # blocks (noted at completion/transfer time) beats the ring's
            # prediction — e.g. after a failover or a ring remap moved
            # the heuristic target away from the warm KV
            rid = self.directory.lookup(key, (r.id for r in eligible))
            if rid is not None:
                target = byid[rid]
                if not (self.queue_override
                        and getattr(target, "queue_depth", 0)
                        > self.queue_override):
                    reason = "failover" if failover else "directory"
                    with self._lock:
                        self.routed[reason] += 1
                    return target, reason
                # holder is drowning in queued decodes: fall through to
                # the ring/least-loaded placement — the fleet scheduler's
                # sibling fetch moves the KV to wherever we land instead
        if key is not None:
            ring = self._ring(tuple(sorted(byid)))
            eligible_ids = {r.id for r in eligible}
            for rid in ring.ordered(key):
                if rid not in eligible_ids:
                    continue
                target = byid[rid]
                if (self.queue_override
                        and getattr(target, "queue_depth", 0)
                        > self.queue_override):
                    # the affinity target is drowning in queued decodes:
                    # prefix locality saves a prefill, not a queue wait —
                    # place least-loaded instead (monitor-refreshed depth,
                    # so the check is a field read)
                    choice = min(eligible, key=lambda r: r.load)
                    if choice.id != rid:
                        reason = ("failover" if failover
                                  else "queue_override")
                        with self._lock:
                            self.routed[reason] += 1
                        return choice, reason
                reason = "failover" if failover else "affinity"
                with self._lock:
                    self.routed[reason] += 1
                return target, reason
        # no affinity signal (short prompt) or empty ring: least loaded
        choice = min(eligible, key=lambda r: r.load)
        reason = "failover" if failover else "least_loaded"
        with self._lock:
            self.routed[reason] += 1
        return choice, reason

    def snapshot(self) -> dict:
        with self._lock:
            routed = dict(self.routed)
            routed_around = self.routed_around
        return {
            "routed": routed,
            "routed_around": routed_around,
            "affinity_blocks": self.affinity_blocks,
            "block_tokens": self.block_tokens,
            "queue_override": self.queue_override,
            "vnodes": VNODES,
        }
