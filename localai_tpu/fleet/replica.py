"""One engine replica: lifecycle state, dial bookkeeping, dispatch surface.

Two implementations behind one duck-typed surface:

  * :class:`WorkerReplica` — a spawned gRPC worker process
    (worker/process.py WorkerProcess + worker/client.py WorkerClient), the
    production shape: crash isolation per replica, device pinning via the
    spawn env, KV prefixes crossing the wire as PrefixChunk streams.
  * :class:`RemoteReplica` — an externally managed worker dialed at
    ``host:port`` across the network (static ``LOCALAI_FLEET_HOSTS``
    adoption or a federation-registry join): same WorkerClient transport
    as WorkerReplica, but NOT respawnable — this process does not own the
    remote's lifecycle, so a failed remote is *evicted* from routing and
    *redialed* on jittered exponential backoff instead of respawned.
  * :class:`InProcessReplica` — a full engine (build_serving_model) inside
    this process: the CPU-testable shape the router/pool/disaggregation
    tests and the CI telemetry smoke drive, with the same reply/chunk
    schema (worker.server.gen_request_from_options decodes requests for
    both, so the two kinds cannot drift).

States: ``starting`` → ``healthy`` ⇄ ``dead`` → ``respawning`` →
``healthy`` for locally owned replicas; remotes flip ``healthy`` ⇄
``evicted`` (redial instead of respawn). "Shedding" is not a stored state
— it is derived per route from the fleet's per-replica SLO tracker
(router.py)."""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Iterator, Optional

from localai_tpu.faults import registry as _faults

log = logging.getLogger(__name__)

STARTING = "starting"
HEALTHY = "healthy"
DEAD = "dead"
RESPAWNING = "respawning"
# a remote replica out of routing after failed dials: the pool redials it
# on backoff but never tries to (re)spawn a process it does not own
EVICTED = "evicted"


class _Reply:
    """pb.Reply-shaped streaming element from an in-process replica."""

    __slots__ = ("message", "tokens", "prompt_tokens", "finish_reason")

    def __init__(self, message: bytes = b"", tokens: int = 0,
                 prompt_tokens: int = 0, finish_reason: str = ""):
        self.message = message
        self.tokens = tokens
        self.prompt_tokens = prompt_tokens
        self.finish_reason = finish_reason


class BaseReplica:
    """Shared lifecycle/accounting; subclasses provide transport."""

    # False on replicas whose process this server does not own (remotes):
    # the pool evicts-and-redials them instead of stop()+respawn
    respawnable = True

    def __init__(self, rid: str, role: str):
        self.id = rid
        self.role = role                  # "decode" | "prefill"
        self.state = STARTING
        self._lock = threading.Lock()
        self.inflight = 0
        self.dispatched = 0               # lifetime requests routed here
        self.errors = 0                   # request-level failures
        self.failures = 0                 # consecutive dial failures
        self.dial_seconds: Optional[float] = None
        self.checked_mono: Optional[float] = None
        self.started_at = time.monotonic()
        # deliberately removed from the pool (autoscale scale-in, hot
        # swap): an in-flight respawn thread must park the corpse instead
        # of resurrecting a replica the operator just drained away
        self.retired = False
        # last-dispatch clock pair: monotonic drives the idle_s policy
        # signal (never jumps), wall time is the human-readable export in
        # GET /v1/fleet. A replica that never served reads idle since
        # boot — an unused fleet is exactly as scale-in-eligible as a
        # quiesced one.
        self.last_dispatch_mono = self.started_at
        self.last_dispatch_wall: Optional[float] = None
        # last reported decode queue depth (monitor-refreshed when the
        # pool tracks it — router.py's queue-override admission hint
        # reads this as a plain field, never an RPC)
        self.queue_depth = 0

    # -- accounting (router reads these for least-loaded) ------------------

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1
            self.dispatched += 1
            self.last_dispatch_mono = time.monotonic()
            self.last_dispatch_wall = time.time()

    def done(self, *, error: bool = False) -> None:
        with self._lock:
            self.inflight -= 1
            if error:
                self.errors += 1

    @property
    def load(self) -> tuple[int, int]:
        """Least-loaded sort key: (inflight, lifetime dispatched)."""
        with self._lock:
            return (self.inflight, self.dispatched)

    # -- health dial (explorer-style: consecutive failures, dial timing) --

    def dial(self, timeout: float = 2.0) -> bool:
        t0 = time.monotonic()
        try:
            if _faults.ACTIVE:
                # chaos: an unreachable/refusing peer as the monitor sees
                # it — the injected raise is a failed dial, exactly like a
                # real partition (keyed by replica id so a schedule can
                # partition one peer)
                _faults.apply("fleet.dial", key=self.id)
            ok = self._dial(timeout)
        except Exception:  # noqa: BLE001 — a dial failing IS the signal
            ok = False
        self.dial_seconds = round(time.monotonic() - t0, 4)
        self.checked_mono = time.monotonic()
        if ok:
            self.failures = 0
            if self.state in (STARTING, RESPAWNING):
                self.state = HEALTHY
        else:
            self.failures += 1
        return ok

    def idle_s(self) -> float:
        """Seconds since the last request was dispatched here (or since
        boot, for a replica that never served) — the autoscale policy's
        scale-in/scale-to-zero signal. 0 while anything is in flight: a
        slow generation is work, not idleness."""
        with self._lock:
            if self.inflight > 0:
                return 0.0
            return max(0.0, time.monotonic() - self.last_dispatch_mono)

    def snapshot(self) -> dict:
        with self._lock:
            inflight, dispatched = self.inflight, self.dispatched
            errors = self.errors
            last_wall = self.last_dispatch_wall
            idle = (0.0 if inflight > 0
                    else max(0.0, time.monotonic() - self.last_dispatch_mono))
        return {
            "id": self.id,
            "role": self.role,
            "state": self.state,
            "inflight": inflight,
            "dispatched": dispatched,
            "errors": errors,
            "idle_s": round(idle, 1),
            "last_dispatch": last_wall,
            "dial_failures": self.failures,
            "dial_seconds": self.dial_seconds,
            "checked_age_s": (
                round(time.monotonic() - self.checked_mono, 1)
                if self.checked_mono is not None else None),
            "age_s": round(time.monotonic() - self.started_at, 1),
        }

    # -- transport (subclass responsibility) -------------------------------

    def start(self) -> None:
        raise NotImplementedError

    def _dial(self, timeout: float) -> bool:
        raise NotImplementedError

    def predict_stream(self, opts: Any, trace_id: str = "",
                       tenant: str = "") -> Iterator:
        raise NotImplementedError

    def prefill_prefix(self, opts: Any, trace_id: str = "") -> Iterator:
        raise NotImplementedError

    def transfer_prefix(self, chunks: Iterator, trace_id: str = "",
                        timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def export_cached(self, prompt: list,
                      trace_id: str = "") -> Optional[list]:
        """Sibling-fetch donor half: this replica's ALREADY-CACHED prefix
        rows for ``prompt`` as TransferPrefix chunks, without running any
        prefill — or None when nothing matching is cached. Default None:
        client-backed replicas have no remote cache-peek RPC, so the
        fleet scheduler falls back to ``prefill_prefix`` for them (cheap
        on the donor — its paged prefix pool makes the re-prefill mostly
        block reuse)."""
        return None

    def migrate_out(self, corr_id: str,
                    timeout: float = 30.0) -> Optional[dict]:
        """Live-migration donor half: cancel the in-flight request with
        the KV-export flag set and return ``{"tokens": full token
        record, "generated": n, "chunks": TransferPrefix payload or
        None}`` — or None when the request is unknown here / the kind
        doesn't support migration (client-backed replicas would need a
        dedicated RPC)."""
        return None

    def metrics(self) -> dict:
        raise NotImplementedError

    def telemetry(self, trace_id: str = "", since: float = 0.0,
                  limit: int = 256, recent: int = 20) -> dict:
        """This replica's observability pane (obs.fleetview payload:
        trace spans + flight snapshot + metrics). Never raises — a
        wedged/partitioned replica returns ``{"error", "unreachable"}``
        so the caller degrades that replica's pane, not the endpoint."""
        raise NotImplementedError

    def process_alive(self) -> bool:
        """Cheap no-RPC liveness (worker: process poll)."""
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError


class _ClientReplica(BaseReplica):
    """Transport shared by every WorkerClient-backed replica (spawned
    worker processes AND adopted remote workers): the streaming dispatch,
    both halves of the disaggregated prefix handoff, bounded stats pulls,
    and the LoadModel handshake. Subclasses own lifecycle (spawn vs dial)
    and set ``self._client``."""

    mcfg = None
    app = None
    _client = None

    def _load_model(self) -> None:
        import yaml

        doc = self.mcfg.model_dump(exclude_none=True, exclude_defaults=True)
        doc["name"] = self.mcfg.name
        doc["model"] = self.mcfg.model or self.mcfg.name
        doc.pop("backend", None)  # the replica itself runs in-process
        res = self._client.load_model(
            config_yaml=yaml.safe_dump(doc),
            model_path=str(self.app.model_path),
        )
        if not res.success:
            raise RuntimeError(
                f"replica {self.id} LoadModel failed: {res.message}")

    def _dial(self, timeout: float) -> bool:
        return self._client is not None and self._client.health(timeout)

    def predict_stream(self, opts, trace_id: str = "",
                       tenant: str = "") -> Iterator:
        return self._client.predict_stream(opts, trace_id=trace_id,
                                           tenant=tenant)

    def prefill_prefix(self, opts, trace_id: str = "") -> Iterator:
        return self._client.prefill_prefix(opts, trace_id=trace_id)

    def transfer_prefix(self, chunks, trace_id: str = "",
                        timeout: Optional[float] = None):
        from localai_tpu.fleet import net
        from localai_tpu.worker import backend_pb2 as pb

        def as_protos():
            for c in chunks:
                yield c if not isinstance(c, dict) else pb.PrefixChunk(**c)

        # explicit deadline: the transfer moves bulk KV rows, so it gets
        # headroom (4×) over the per-reply bound — but never hangs a
        # partitioned peer's dispatch thread for the 600 s stream
        # default. The caller (FleetScheduler) passes its CONFIGURED
        # timeout so --fleet-rpc-timeout-s governs this path too; the
        # env read is only the no-caller fallback.
        t = net.rpc_timeout_s() if timeout is None else timeout
        return self._client.transfer_prefix(
            as_protos(), timeout=(t * 4 if t > 0 else 600.0),
            trace_id=trace_id)

    def metrics(self) -> dict:
        try:
            # short deadline: this is the scrape/status path, and a wedged
            # replica must cost seconds, not the full RPC default
            return self._client.metrics(timeout=3.0)
        except Exception as e:  # noqa: BLE001 — stats pull ≠ serving
            return {"error": str(e)}

    def telemetry(self, trace_id: str = "", since: float = 0.0,
                  limit: int = 256, recent: int = 20) -> dict:
        from localai_tpu.fleet import net

        try:
            # the harvest carries the fleet RPC deadline — one bounded
            # pull, no retries: a wedged peer must degrade its pane in one
            # deadline, not three (the read is idempotent; the NEXT pane
            # refresh is the retry)
            t = net.rpc_timeout_s()
            return self._client.get_telemetry(
                trace_id=trace_id, since=since, limit=limit, recent=recent,
                timeout=t if t > 0 else 60.0)
        except Exception as e:  # noqa: BLE001 — telemetry pull ≠ serving
            return {"error": str(e), "unreachable": True}


class WorkerReplica(_ClientReplica):
    """A replica backed by its own spawned gRPC worker process."""

    def __init__(self, rid: str, role: str, mcfg, app,
                 *, env: Optional[dict] = None):
        super().__init__(rid, role)
        self.mcfg = mcfg
        self.app = app
        self._env = dict(env or {})
        self._wp = None
        self._client = None

    def start(self) -> None:
        from localai_tpu.worker.process import WorkerProcess

        self._wp = WorkerProcess(self.id, env=self._env or None)
        self._client = self._wp.start()
        self._load_model()

    def process_alive(self) -> bool:
        return self._wp is not None and self._wp.alive

    def kill(self) -> None:
        """SIGKILL the worker (tests / operator surface)."""
        if self._wp is not None and self._wp.proc is not None:
            self._wp.proc.kill()

    def stop(self) -> None:
        if self._wp is not None:
            self._wp.stop()
            self._wp = None
            self._client = None


class RemoteReplica(_ClientReplica):
    """A replica served by an externally managed worker at ``host:port``
    — another box entirely. Adopted from the static ``LOCALAI_FLEET_HOSTS``
    list or a ``POST /federated/register`` join; this process does NOT own
    the remote's lifecycle, so ``respawnable = False``: on failed dials
    the pool evicts it from routing and redials on backed-off holds
    instead of respawning. ``stop()`` only closes the channel."""

    respawnable = False

    def __init__(self, rid: str, role: str, address: str,
                 mcfg=None, app=None, *, dial_timeout: float = 5.0):
        super().__init__(rid, role)
        self.address = address
        self.mcfg = mcfg
        self.app = app
        self.dial_timeout = dial_timeout
        self._client = None

    def start(self) -> None:
        """Dial (or redial) the remote: a fresh channel, a health gate,
        and — because a redial may find a *rebooted, empty* worker — a
        Status check that re-issues LoadModel when the peer lost the
        model. Raises when the peer is unreachable; the pool turns that
        into eviction + backed-off redial, never a respawn."""
        from localai_tpu.worker.client import WorkerClient

        if self._client is not None:
            self._client.close()
        self._client = WorkerClient(self.address)
        if not self._client.health(self.dial_timeout):
            raise RuntimeError(
                f"remote replica {self.id} at {self.address} is "
                "unreachable")
        if self.mcfg is not None:
            self._ensure_loaded()

    def _ensure_loaded(self) -> None:
        from localai_tpu.fleet import net
        from localai_tpu.worker import backend_pb2 as pb

        # idempotent status probe: bounded retry absorbs a peer that just
        # came up and is still binding its servicer. NOTE: Status carries
        # no model identity (a worker process holds exactly ONE model),
        # so READY is trusted as "holds THIS pool's model" — the
        # registration layer enforces that a peer is only ever adopted
        # into one model's pool (api.localai.fleet_register refuses an
        # ambiguous join).
        st = net.call_with_retries(
            lambda: self._client.status(timeout=self.dial_timeout),
            rid=self.id, what="status")
        if st.state in (pb.StatusResponse.READY, pb.StatusResponse.BUSY):
            return
        self._load_model()

    def process_alive(self) -> bool:
        """No local process to poll — the health dial is the only truth
        about a peer across a network."""
        return self._client is not None

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class InProcessReplica(BaseReplica):
    """A replica owning a full in-process engine (factory →
    models.manager.ServingModel). The CPU-testable twin of WorkerReplica:
    same opts/reply/chunk schema, no processes, no sockets."""

    def __init__(self, rid: str, role: str, factory):
        super().__init__(rid, role)
        self._factory = factory
        self.sm = None
        self._killed = False
        # correlation id → inner GenHandle while its stream is being
        # pumped: the live-migration surface (migrate_out) finds the
        # in-flight request here. Plain dict: writes are
        # insert/pop-by-key from the dispatch thread, reads are a
        # single get() from the migration caller — GIL-atomic.
        self._streaming: dict = {}

    def start(self) -> None:
        from localai_tpu.fleet.prefix import PrefixCache

        self._killed = False
        self.sm = self._factory()
        # both halves of the disaggregated handoff run through this cache
        # (export at release on prefill replicas, import at admission on
        # decode replicas) — attach it up front; a configured disk cache
        # is layered under it rather than replaced (layer=True)
        self.sm.scheduler.attach_prompt_cache(PrefixCache(
            min_prefix=getattr(self.sm.runner, "prefix_reuse_min", 16)),
            layer=True)

    def _cache(self):
        return self.sm.scheduler.prompt_cache

    def _dial(self, timeout: float) -> bool:
        return (not self._killed and self.sm is not None
                and self.sm.scheduler._thread.is_alive())

    def predict_stream(self, opts, trace_id: str = "",
                       tenant: str = "") -> Iterator:
        from localai_tpu.worker.server import gen_request_from_options

        if self._killed:
            raise RuntimeError(f"replica {self.id} is dead")
        sm = self.sm
        # ``tenant`` is accepted for surface parity and deliberately
        # DROPPED: this engine shares the front door's process, and the
        # fleet dispatch thread already feeds the usage ledger for the
        # front-door request — stamping the inner resubmit too would
        # double-count every fleet token ("whoever stamped the tenant
        # owns the feed", obs.ledger)
        gr = gen_request_from_options(opts, sm, trace_id=trace_id)
        handle = sm.scheduler.submit(gr)
        if gr.correlation_id:
            self._streaming[gr.correlation_id] = handle
        try:
            while True:
                try:
                    # bounded wait so a kill() mid-stream surfaces as a
                    # transport error (exactly like a SIGKILLed worker)
                    # instead of parking on a queue the dead engine thread
                    # will never feed again
                    item = handle._q.get(timeout=0.25)
                except queue.Empty:
                    if self._killed:
                        raise RuntimeError(
                            f"replica {self.id} died mid-stream")
                    continue
                if self._killed:
                    raise RuntimeError(f"replica {self.id} died mid-stream")
                if _faults.ACTIVE:
                    # same chaos surface as the gRPC worker stream: an
                    # injected error/slowdown mid-stream, keyed by the
                    # replica id so a schedule can target one replica
                    _faults.apply("worker.stream", key=self.id)
                if item.finish_reason is not None:
                    yield _Reply(b"", handle.completion_tokens,
                                 handle.prompt_tokens, item.finish_reason)
                    break
                if item.delta:
                    yield _Reply(item.delta.encode("utf-8"))
        finally:
            if gr.correlation_id:
                self._streaming.pop(gr.correlation_id, None)
            if handle.finish_reason is None:
                handle.cancel()

    def prefill_prefix(self, opts, trace_id: str = "") -> Iterator:
        from localai_tpu.fleet.prefix import export_prefix, pack_chunks
        from localai_tpu.worker.server import gen_request_from_options

        if self._killed:
            raise RuntimeError(f"replica {self.id} is dead")
        sm = self.sm
        gr = gen_request_from_options(opts, sm, trace_id=trace_id)
        prompt, arrays = export_prefix(sm, gr, self._cache())
        yield from pack_chunks(prompt, arrays)

    def transfer_prefix(self, chunks, trace_id: str = "",
                        timeout: Optional[float] = None):
        # timeout accepted for surface parity with the client-backed
        # kinds; an in-process import has no wire to bound
        from types import SimpleNamespace

        from localai_tpu.fleet.prefix import import_prefix

        if self._killed:
            raise RuntimeError(f"replica {self.id} is dead")
        n = import_prefix(self._cache(), chunks)
        return SimpleNamespace(success=True, message=f"{n} rows")

    def export_cached(self, prompt: list,
                      trace_id: str = "") -> Optional[list]:
        from localai_tpu.fleet.prefix import pack_chunks

        if self._killed or self.sm is None:
            return None
        cache = self._cache()
        if cache is None:
            return None
        hit = cache.lookup(list(prompt))
        # the LCP winner must be a TRUE prefix of the prompt: lookup can
        # return an entry that diverges past the common prefix, and its
        # arrays cover the entry's rows, not the LCP
        if hit is None or list(hit.tokens) != list(prompt)[:len(hit.tokens)]:
            return None
        return list(pack_chunks(hit.tokens, hit.arrays,
                                transfer_id=trace_id))

    def migrate_out(self, corr_id: str,
                    timeout: float = 30.0) -> Optional[dict]:
        from localai_tpu.fleet.prefix import pack_chunks

        ih = self._streaming.get(corr_id)
        if ih is None or self._killed or self.sm is None:
            return None
        # flag first, then cancel: the engine's release reads the flag,
        # keeps the generated tail, and snapshots prompt+generation KV
        # into this replica's prefix cache (scheduler._release)
        ih.migrate_export = True
        ih.cancel()
        try:
            ih.result(timeout)
        except TimeoutError:
            return None
        full = list(ih.request.prompt) + list(ih.token_ids)
        out = {"tokens": full, "generated": len(ih.token_ids),
               "chunks": None}
        cache = self._cache()
        if cache is None or len(full) < cache.min_prefix:
            return out  # nothing exportable: destination re-prefills
        # the export lands off-thread (prompt-cache writer); the stored
        # key is the full token record (migration keeps the generation)
        arrays = cache.wait_for(full, timeout=min(timeout, 10.0))
        tokens = full
        if arrays is None:
            # context-cap edge (or a racing store): take the longest
            # cached true prefix instead — the destination re-prefills
            # only the uncovered tail
            hit = cache.lookup(full)
            if hit is not None and list(hit.tokens) == full[:len(hit.tokens)]:
                tokens, arrays = list(hit.tokens), hit.arrays
        if arrays is not None:
            out["chunks"] = list(pack_chunks(tokens, arrays))
        return out

    def metrics(self) -> dict:
        if self.sm is None:
            return {"error": "not started"}
        return self.sm.scheduler.metrics()

    def telemetry(self, trace_id: str = "", since: float = 0.0,
                  limit: int = 256, recent: int = 20) -> dict:
        # same payload builder the gRPC servicer uses (obs.fleetview), so
        # the wire and in-process panes cannot drift. NOTE: in-process
        # engines share the front door's trace STORE — the stitcher
        # dedupes harvested traces it already holds locally.
        from localai_tpu.obs.fleetview import telemetry_payload

        if self._killed or self.sm is None:
            return {"error": f"replica {self.id} is dead",
                    "unreachable": True}
        try:
            payload = telemetry_payload(
                self.sm.scheduler, trace_id=trace_id, since=since,
                limit=limit, recent=recent)
            # the stitcher must dedupe ONLY panes that share the caller's
            # store: request ids are per-process counters, so a worker's
            # "model-0" legitimately coexists with the front door's —
            # only an in-process replica's traces are literally the same
            # records
            payload["shared_store"] = True
            return payload
        except Exception as e:  # noqa: BLE001 — telemetry pull ≠ serving
            return {"error": str(e), "unreachable": True}

    def process_alive(self) -> bool:
        return self._dial(0.0)

    def kill(self) -> None:
        """Simulate a replica crash: in-flight streams raise, dials fail,
        the engine thread stops (tests / failover drills)."""
        self._killed = True
        if self.sm is not None:
            self.sm.scheduler.shutdown(timeout=2.0)

    def stop(self) -> None:
        if self.sm is not None:
            self.sm.scheduler.shutdown()
            self.sm = None
