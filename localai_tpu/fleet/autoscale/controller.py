"""The closed capacity loop: harvest signals, decide, actuate.

One daemon per fleet-served model. Scale-out prefers adopting a
configured standby host (instant capacity, no boot) and falls back to
spawning a fresh replica through the pool's own factory — worker
replicas ride device pinning and the BENCH weight cache exactly like
boot-time replicas, because it IS the boot-time path (pool.spawn).
Scale-in is drain-based: live-migrate every in-flight slot off the
victim (FleetScheduler.drain), then retire it from the pool — zero lost
requests by construction, and the scale-in is simply deferred when a
request can't be moved yet.

Scale-to-zero parks ``request_capacity`` on the scheduler's ``on_cold``
hook: when routing finds no healthy replica, the dispatch thread calls
it and *waits* for a cold re-onboard instead of erroring — the held
request is served by the replica its own arrival booted.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from localai_tpu.fleet.autoscale import density
from localai_tpu.fleet.autoscale.policy import (ACTIONS, AutoscaleConfig,
                                                AutoscalePolicy, Decision,
                                                ReplicaSignals)
from localai_tpu.obs.history import HISTORY
from localai_tpu.obs.metrics import REGISTRY

log = logging.getLogger(__name__)


class AutoscaleController:
    """Telemetry-driven replica lifecycle for one FleetServingModel."""

    def __init__(self, fm, *, config: Optional[AutoscaleConfig] = None,
                 manager=None):
        self.fm = fm
        self.pool = fm.pool
        self.cfg = config or AutoscaleConfig.from_app(fm.app)
        self.policy = AutoscalePolicy(self.cfg)
        #: ModelManager, when attached — enables the density reaper
        self.manager = manager
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()        # serialises actuation
        self._cold_lock = threading.Lock()   # single-flight cold boot
        self.decisions = {a: 0 for a in ACTIONS}
        self.last_decision: Optional[dict] = None
        self.evictions: list = []
        self.target = len(self.pool.healthy("decode")) or fm.app.fleet_replicas
        fm.scheduler.on_cold = self.request_capacity

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"autoscale:{self.fm.name}", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        if self.fm.scheduler.on_cold is self.request_capacity:
            self.fm.scheduler.on_cold = None

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("autoscale %s: tick failed", self.fm.name)

    # -- signal harvest -----------------------------------------------------

    def signals(self) -> list:
        """Per-decode-replica policy input off the live pool: lifecycle
        state + idle clock always, engine telemetry and SLO burn for the
        healthy ones."""
        out = []
        for r in self.pool.members():
            if r.role != "decode":
                continue
            sig = ReplicaSignals(rid=r.id, state=r.state,
                                 inflight=r.inflight, idle_s=r.idle_s())
            if r.state == "healthy":
                try:
                    m = r.metrics()
                except Exception:  # noqa: BLE001 — telemetry ≠ serving
                    m = {}
                sig.queue_depth = float(m.get("queue_depth") or 0.0)
                sig.kv_util = float(m.get("kv_utilization") or 0.0)
                sig.step_p99_ms = float(m.get("step_ms_p99") or 0.0)
                sig.burn_1m = self.fm.slo.burn_rate(r.id, "1m")
                sig.burn_5m = self.fm.slo.burn_rate(r.id, "5m")
            out.append(sig)
        return out

    # -- the loop body ------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        decision = self.policy.decide(self.signals(), now)
        applied = self._apply(decision, now)
        action = decision.action if applied or decision.action == "none" \
            else "none"
        self.decisions[action] += 1
        self.target = decision.target if applied else \
            len(self.pool.healthy("decode"))
        self.last_decision = {
            "action": action, "reason": decision.reason,
            "target": decision.target, "rid": decision.rid,
            "applied": applied,
        }
        REGISTRY.autoscale_decisions.inc(model=self.fm.name, action=action)
        REGISTRY.fleet_target_replicas.set(self.target, model=self.fm.name)
        HISTORY.record(f"fleet_target_replicas.{self.fm.name}", self.target)
        if self.manager is not None:
            evicted = density.evict_lru_model(
                self.manager, keep=(self.fm.name,),
                threshold=self.cfg.hbm_threshold)
            if evicted:
                self.evictions.append(evicted)
        return decision

    def _apply(self, decision: Decision, now: float) -> bool:
        if decision.action == "none":
            return False
        # the actuation lock is held across the drain-and-poll on purpose:
        # it serialises capacity mutations (daemon tick vs. manual tick vs.
        # cold start), and nothing latency-sensitive ever waits on it
        with self._lock:
            if decision.action == "scale_out":
                ok = self._scale_out()
            elif decision.action == "scale_in":
                ok = self._scale_in(decision.rid)  # jaxlint: disable=blocking-under-lock
            elif decision.action == "scale_to_zero":
                ok = self._scale_to_zero()  # jaxlint: disable=blocking-under-lock
            else:
                ok = False
        if ok:
            self.policy.note(decision.action, now)
            log.info("autoscale %s: %s (%s) → target %d", self.fm.name,
                     decision.action, decision.reason, decision.target)
        return ok

    # -- actuation ----------------------------------------------------------

    def _scale_out(self) -> bool:
        for addr in self.cfg.standby_hosts:
            rid = f"{self.fm.name}/{addr}"
            if self.pool.get(rid) is not None:
                continue  # already adopted (possibly evicted/redialing)
            res = self.fm.adopt_remote(addr)
            if res.get("state") == "healthy":
                log.info("autoscale %s: adopted standby %s", self.fm.name,
                         addr)
                return True
        return self.pool.spawn("decode", wait=True) is not None

    def _scale_in(self, rid: Optional[str]) -> bool:
        replica = self.pool.get(rid) if rid else None
        if replica is None:
            return False
        self.fm.scheduler.drain(rid)
        deadline = time.monotonic() + 10.0
        while replica.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        if replica.inflight > 0:
            # a request neither migrated nor finished — keep the replica,
            # the next tick retries; never lose a request to a scale-in
            log.warning("autoscale %s: %s still busy after drain; "
                        "deferring scale-in", self.fm.name, rid)
            return False
        return self.pool.remove(rid)

    def _scale_to_zero(self) -> bool:
        ok = True
        for r in list(self.pool.healthy("decode")):
            ok = self._scale_in(r.id) and ok
        return ok and not self.pool.healthy("decode")

    # -- cold start (scale-to-zero wakeup) ----------------------------------

    def request_capacity(self) -> bool:
        """FleetScheduler.on_cold hook: routing found no healthy replica.
        Boot one (single-flight — concurrent held requests queue on the
        lock and find the capacity the first caller brought up), then
        wait for it within the cold-start budget. True → the scheduler
        re-routes; False → the request errors as before."""
        deadline = time.monotonic() + self.cfg.cold_timeout_s
        started = False
        with self._cold_lock:
            if not self.pool.healthy("decode"):
                log.info("autoscale %s: cold start — replica requested by "
                         "held traffic", self.fm.name)
                self._scale_out()
                started = True
        if started:
            self.decisions["cold_start"] += 1
            REGISTRY.autoscale_decisions.inc(
                model=self.fm.name, action="cold_start")
        while time.monotonic() < deadline:
            if self.pool.healthy("decode"):
                self.target = max(self.target, 1)
                REGISTRY.fleet_target_replicas.set(
                    self.target, model=self.fm.name)
                return True
            if self._stop.is_set():
                return False
            time.sleep(0.05)
        return False

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "target": self.target,
            "min": self.cfg.min_replicas,
            "max": self.cfg.max_replicas,
            "interval_s": self.cfg.interval_s,
            "zero_idle_s": self.cfg.zero_idle_s,
            "decisions": dict(self.decisions),
            "last_decision": self.last_decision,
            "density_evictions": list(self.evictions),
            "standby_hosts": list(self.cfg.standby_hosts),
        }
