"""Multi-model density on one host: whole-model LRU eviction under HBM
pressure, and hot weight swap as the deploy primitive.

The manager's idle watchdog already evicts a *single engine* that sat
unused too long; the density reaper generalizes that to the fleet tier —
when live HBM occupancy crosses the threshold, the least-recently-used
non-busy model is shut down wholesale (every replica, via the manager's
own graceful path), freeing block pools and weights for whoever is
actually serving.

Hot swap turns a checkpoint rollout into a routing event instead of a
restart: boot a replacement replica per live local replica on the new
checkpoint (the pool factory reads the fleet's mutable config holder, so
runtime spawns pick the new weights up), let the router's consistent-
hash ring shift traffic to the newcomers, then drain and retire the old
generation — in-flight requests live-migrate, nothing 5xxes.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from localai_tpu.obs.metrics import REGISTRY

log = logging.getLogger(__name__)


def hbm_fraction() -> Optional[float]:
    """Worst-device HBM occupancy fraction, or None when the platform
    exposes no memory stats (CPU). ``LOCALAI_AUTOSCALE_HBM_FRACTION``
    overrides for tests and CPU smoke."""
    override = os.environ.get("LOCALAI_AUTOSCALE_HBM_FRACTION")
    if override:
        try:
            return float(override)
        except ValueError:
            return None
    try:
        import jax

        fracs = []
        for d in jax.local_devices():
            stats_fn = getattr(d, "memory_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if not stats:
                continue
            limit = stats.get("bytes_limit") or 0
            if limit:
                fracs.append((stats.get("bytes_in_use") or 0) / limit)
        return max(fracs) if fracs else None
    except Exception:  # noqa: BLE001 — density is advisory, never fatal
        return None


def evict_lru_model(manager, *, keep=(), threshold: float = 0.92,
                    fraction: Optional[float] = None) -> Optional[str]:
    """Under HBM pressure, evict the least-recently-used non-busy model
    through the manager's graceful shutdown. ``keep`` protects the
    caller's own model; returns the evicted name or None."""
    frac = hbm_fraction() if fraction is None else fraction
    if frac is None or frac < threshold:
        return None
    with manager._lock:
        items = list(manager._models.items())
    candidates = [(name, sm) for name, sm in items
                  if name not in keep and not sm.busy]
    if not candidates:
        return None
    name, _ = min(candidates,
                  key=lambda kv: getattr(kv[1], "last_used", 0.0))
    log.warning("density: HBM at %.0f%% — evicting LRU model %s",
                frac * 100.0, name)
    manager.shutdown_model(name, force=False, wait=5.0)
    return name


def hot_swap(fm, checkpoint: Optional[str] = None, *,
             timeout: float = 30.0) -> dict:
    """Swap every healthy local replica of ``fm`` for a freshly booted
    one (optionally on a new ``checkpoint``). Aborts cleanly — the old
    generation keeps serving — if any replacement fails to boot."""
    pool = fm.pool
    olds = [r for r in pool.members()
            if r.respawnable and r.state == "healthy"]
    if not olds:
        return {"ok": False,
                "error": "no healthy local replicas to swap"}
    prev_cfg = fm.cfg_ref["mcfg"]
    if checkpoint:
        fm.cfg_ref["mcfg"] = prev_cfg.model_copy(
            update={"model": checkpoint})
        fm.config = fm.cfg_ref["mcfg"]
    spawned = []
    for old in olds:
        rid = pool.spawn(old.role, wait=True)
        if rid is None:
            # the new checkpoint doesn't boot: tear the replacements down
            # and rebind the old config — the rollout failed, serving
            # didn't
            for nid in spawned:
                pool.remove(nid)
            if checkpoint:
                fm.cfg_ref["mcfg"] = prev_cfg
                fm.config = prev_cfg
            log.error("hot swap %s: replacement for %s failed to boot; "
                      "aborted", fm.name, old.id)
            return {"ok": False, "spawned_then_removed": spawned,
                    "error": f"replacement for {old.id} failed to boot"}
        spawned.append(rid)
    drained = {}
    for old in olds:
        drained[old.id] = fm.scheduler.drain(old.id)
        deadline = time.monotonic() + timeout
        while old.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        if old.inflight > 0:
            log.warning("hot swap %s: %s still busy after drain+%.0fs; "
                        "retiring anyway", fm.name, old.id, timeout)
        pool.remove(old.id)
    REGISTRY.model_swaps.inc(model=fm.name)
    REGISTRY.autoscale_decisions.inc(model=fm.name, action="swap")
    auto = getattr(fm, "autoscaler", None)
    if auto is not None:
        auto.decisions["swap"] += 1
    log.info("hot swap %s: %s → %s (%s)", fm.name,
             [r.id for r in olds], spawned,
             checkpoint or "same checkpoint")
    return {"ok": True, "checkpoint": checkpoint,
            "old": [r.id for r in olds], "new": spawned,
            "drained": drained}
