"""Pure capacity policy: per-replica telemetry in, one decision out.

The policy is deliberately thread-free and side-effect-free — ``decide``
is a function of (signals, now, cooldown state), so the whole decision
table unit-tests without a fleet, a clock, or a daemon (the controller
owns all of those). Scale-out and scale-in read *different* thresholds
with *separate* cooldowns: the hysteresis gap is what keeps a fleet
sitting near one threshold from flapping a replica up and down every
interval.

Signal sources (all already harvested by the fleet tier):

* queue depth + step-time p99 — the honest continuous-batching load
  signals (Orca, Yu et al. OSDI 2022), per replica via GetTelemetry
* KV/block utilization — PagedAttention block-pool pressure
* SLO burn windows — the per-replica observatory (obs.slo)
* idle seconds — BaseReplica.idle_s(), 0 while anything is in flight
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

#: every label ``localai_autoscale_decisions_total{action=...}`` can carry
#: (cold_start is controller-originated, swap is operator-originated; the
#: rest come out of ``decide``)
ACTIONS = ("scale_out", "scale_in", "scale_to_zero", "cold_start",
           "swap", "none")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class AutoscaleConfig:
    """Knobs. Replica bounds/idle horizons ride AppConfig (CLI +
    ``LOCALAI_AUTOSCALE_*`` via from_env); the overload thresholds are
    env-only tuning knobs with defaults that match the engine's own
    admission behaviour."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 5.0
    #: a replica this idle (and the fleet above min) is scale-in bait
    in_idle_s: float = 120.0
    #: ALL replicas this idle → scale the model to zero (0 disables)
    zero_idle_s: float = 0.0
    #: mean decode queue depth per healthy replica that means "add one"
    out_queue_depth: float = 4.0
    #: mean KV block-pool utilization that means "add one"
    out_kv_util: float = 0.85
    #: worst-replica step p99 that means "add one" (0 disables)
    out_step_p99_ms: float = 0.0
    #: worst-replica fast-window SLO burn that means "add one"
    out_burn: float = 2.0
    out_cooldown_s: float = 30.0
    in_cooldown_s: float = 60.0
    #: how long a held request waits for a cold re-onboard before erroring
    cold_timeout_s: float = 120.0
    #: HBM fraction above which the density reaper evicts the LRU model
    hbm_threshold: float = 0.92
    standby_hosts: list = field(default_factory=list)

    @classmethod
    def from_app(cls, app) -> "AutoscaleConfig":
        return cls(
            min_replicas=max(0, app.autoscale_min),
            max_replicas=max(1, app.autoscale_max),
            interval_s=max(0.05, app.autoscale_interval_s),
            in_idle_s=app.autoscale_in_idle_s,
            zero_idle_s=app.autoscale_zero_idle_s,
            standby_hosts=list(app.autoscale_standby_hosts or []),
            out_queue_depth=_env_float("LOCALAI_AUTOSCALE_OUT_QUEUE", 4.0),
            out_kv_util=_env_float("LOCALAI_AUTOSCALE_OUT_KV", 0.85),
            out_step_p99_ms=_env_float(
                "LOCALAI_AUTOSCALE_OUT_STEP_P99_MS", 0.0),
            out_burn=_env_float("LOCALAI_AUTOSCALE_OUT_BURN", 2.0),
            out_cooldown_s=_env_float(
                "LOCALAI_AUTOSCALE_OUT_COOLDOWN_S", 30.0),
            in_cooldown_s=_env_float(
                "LOCALAI_AUTOSCALE_IN_COOLDOWN_S", 60.0),
            cold_timeout_s=_env_float(
                "LOCALAI_AUTOSCALE_COLD_TIMEOUT_S", 120.0),
            hbm_threshold=_env_float(
                "LOCALAI_AUTOSCALE_HBM_THRESHOLD", 0.92),
        )


@dataclass
class ReplicaSignals:
    """One decode replica's slice of the policy input."""

    rid: str
    state: str = "healthy"
    inflight: int = 0
    idle_s: float = 0.0
    queue_depth: float = 0.0
    kv_util: float = 0.0
    step_p99_ms: float = 0.0
    burn_1m: float = 0.0
    burn_5m: float = 0.0


@dataclass
class Decision:
    action: str
    reason: str
    #: decode replica count the fleet should converge on
    target: int
    #: the replica to drain, for scale_in
    rid: Optional[str] = None


class AutoscalePolicy:
    """Holds the cooldown clocks; ``decide`` itself never mutates them —
    the controller calls ``note`` only after a decision actually applied,
    so a failed spawn doesn't burn the cooldown."""

    def __init__(self, cfg: AutoscaleConfig):
        self.cfg = cfg
        self.last_out_at = float("-inf")
        self.last_in_at = float("-inf")

    def note(self, action: str, now: float) -> None:
        if action == "scale_out":
            self.last_out_at = now
        elif action in ("scale_in", "scale_to_zero"):
            self.last_in_at = now

    # -- the decision table -------------------------------------------------

    def _overloaded(self, healthy: list) -> tuple[bool, str]:
        if not healthy:
            return False, ""
        cfg = self.cfg
        mean_q = sum(r.queue_depth for r in healthy) / len(healthy)
        if cfg.out_queue_depth > 0 and mean_q >= cfg.out_queue_depth:
            return True, "queue_depth"
        if cfg.out_burn > 0 \
                and max(r.burn_1m for r in healthy) >= cfg.out_burn:
            return True, "slo_burn"
        mean_kv = sum(r.kv_util for r in healthy) / len(healthy)
        if cfg.out_kv_util > 0 and mean_kv >= cfg.out_kv_util:
            return True, "kv_pressure"
        if cfg.out_step_p99_ms > 0 and max(
                r.step_p99_ms for r in healthy) >= cfg.out_step_p99_ms:
            return True, "step_p99"
        return False, ""

    def decide(self, replicas: list, now: float) -> Decision:
        """Map the decode fleet's signals to one action. Precedence:
        below-min floor (bypasses cooldown) > overload scale-out >
        overload holds capacity (burn overrides idle) > scale-to-zero >
        single idle scale-in > none."""
        cfg = self.cfg
        healthy = [r for r in replicas if r.state == "healthy"]
        booting = [r for r in replicas
                   if r.state in ("starting", "respawning")]
        n, pending = len(healthy), len(booting)
        total = n + pending

        if total < cfg.min_replicas:
            # self-heal below the floor regardless of load or cooldown
            return Decision("scale_out", "below_min", total + 1)

        overloaded, why = self._overloaded(healthy)
        if overloaded:
            if pending:
                return Decision("none", f"boot_pending:{why}", total)
            if total >= cfg.max_replicas:
                return Decision("none", f"at_max:{why}", total)
            if now - self.last_out_at < cfg.out_cooldown_s:
                return Decision("none", f"out_cooldown:{why}", total)
            return Decision("scale_out", why, total + 1)

        quiet = all(r.inflight == 0 and r.queue_depth == 0
                    for r in healthy)
        if (cfg.zero_idle_s > 0 and n > 0 and not pending and quiet
                and all(r.idle_s >= cfg.zero_idle_s for r in healthy)):
            if now - self.last_in_at < cfg.in_cooldown_s:
                return Decision("none", "in_cooldown", total)
            return Decision("scale_to_zero", "idle_to_zero", 0)

        # single-replica scale-in retires SURPLUS capacity only — the
        # last replica leaves through scale_to_zero or not at all
        if n > 0 and total > max(cfg.min_replicas, 1) \
                and cfg.in_idle_s > 0:
            idlest = max(healthy, key=lambda r: r.idle_s)
            if (idlest.inflight == 0 and idlest.queue_depth == 0
                    and idlest.idle_s >= cfg.in_idle_s):
                if now - self.last_in_at < cfg.in_cooldown_s:
                    return Decision("none", "in_cooldown", total)
                return Decision("scale_in", "idle", total - 1,
                                rid=idlest.rid)
        return Decision("none", "steady", total)
