"""Elastic capacity (ROADMAP item 4): a closed-loop capacity controller
over the existing ReplicaPool/FleetScheduler — telemetry-driven scale
out/in, scale-to-zero with cold re-onboard, hot weight swap, and
multi-model density under HBM pressure."""

from localai_tpu.fleet.autoscale.controller import AutoscaleController
from localai_tpu.fleet.autoscale.density import (evict_lru_model,
                                                 hbm_fraction, hot_swap)
from localai_tpu.fleet.autoscale.policy import (ACTIONS, AutoscaleConfig,
                                                AutoscalePolicy, Decision,
                                                ReplicaSignals)

__all__ = [
    "ACTIONS",
    "AutoscaleConfig",
    "AutoscaleController",
    "AutoscalePolicy",
    "Decision",
    "ReplicaSignals",
    "evict_lru_model",
    "hbm_fraction",
    "hot_swap",
]
