"""FleetServingModel: one model served by N replicas behind one facade.

The ServingModel-shaped surface the API tier already speaks (tokenizer/
templates local, ``scheduler.submit`` → GenHandle) — but submit() routes
each request across the replica fleet:

  * placement: prompt-prefix affinity over a consistent-hash ring, with
    least-loaded fallback and per-replica burn-rate route-around
    (fleet/router.py);
  * retry-with-failover: a replica dying mid-request is marked dead, and
    the request re-dispatches to the next candidate as long as nothing
    was streamed to the client yet (a half-streamed completion cannot be
    transparently resumed — it finishes ``error`` and the API tier maps
    that to a clean 5xx);
  * disaggregation: long prompts prefill on a dedicated prefill replica,
    whose packed KV prefix streams over TransferPrefix into the decode
    replica's prefix cache — the decode replica's admission then
    load_prefix-resumes, so long prompts never occupy decode slots for
    prefill (DistServe/Mooncake shape on the paged-KV block transfer).

Every request records lifecycle spans under its API trace id (queued →
route → prefix_transfer? → rpc), with the replica-side engine spans
grouping under the same id via the gRPC metadata propagation the worker
tier already does."""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Callable, Optional

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.faults import registry as _faults
from localai_tpu.fleet import net
from localai_tpu.fleet.kveconomy import MigrationTicket, PrefixDirectory
from localai_tpu.fleet.kveconomy.migration import continuation_request
from localai_tpu.fleet.pool import ReplicaPool
from localai_tpu.fleet.router import FleetUnavailable, Router, affinity_key
from localai_tpu.obs import EngineTelemetry
from localai_tpu.obs import ledger as obs_ledger
from localai_tpu.obs import watchdog as obs_watchdog
from localai_tpu.obs.metrics import REGISTRY
from localai_tpu.obs.slo import SLOTracker, targets_from_config
from localai_tpu.worker.serving import (WorkerGenHandle, consume_stream,
                                        predict_options)

log = logging.getLogger(__name__)


class FleetScheduler:
    """The scheduler-shaped surface of a replica fleet: submit() routes,
    dispatches on a daemon thread, and fails over on replica death."""

    def __init__(self, owner: "FleetServingModel", pool: ReplicaPool,
                 router: Router, slo: SLOTracker,
                 *, disagg_threshold: int = 512, max_failovers: int = 2,
                 rpc_timeout_s: Optional[float] = None):
        self._owner = owner
        self.pool = pool
        self.router = router
        self.slo = slo                      # per-REPLICA observatory
        self.disagg_threshold = disagg_threshold
        self.max_failovers = max_failovers
        # per-reply inactivity deadline on every cross-replica stream
        # (fleet.net.bounded_stream): a partitioned peer never RSTs, so
        # silence — not an error — is how a dead remote presents; the
        # deadline turns it into a prompt failover instead of a hung
        # dispatch thread (0 disables)
        self.rpc_timeout_s = (net.rpc_timeout_s() if rpc_timeout_s is None
                              else rpc_timeout_s)
        self._ids = itertools.count()
        self._inflight = 0
        self._lock = threading.Lock()
        # autoscale cold-start hook (AutoscaleController.request_capacity):
        # when routing finds no healthy replica, the dispatch thread calls
        # this and, on True, re-routes — a scaled-to-zero model serves the
        # held request off its cold re-onboard instead of erroring
        self.on_cold: Optional[Callable[[], bool]] = None
        # fleet prefix directory (shared with the router, which probes it
        # for placement; the scheduler writes it and fetches against it)
        self.directory: Optional[PrefixDirectory] = router.directory
        # handle.id → (handle, replica currently serving it): the live-
        # migration surface (migrate_inflight/drain) finds in-flight
        # requests here. Plain dict — per-key insert/pop from the owning
        # dispatch thread, point get() from callers — GIL-atomic.
        self._active: dict[int, tuple] = {}
        self.telemetry = EngineTelemetry(model=owner.name)
        self.watchdog = obs_watchdog.WATCHDOG
        self._wd_channel = f"fleet:{owner.name}"
        self.watchdog.start()
        self.shed_total = 0                 # API-tier SLO 429 mirror
        self.failovers = 0
        self.prefix_transfers = 0
        self.prefix_transfer_bytes = 0
        self.disagg_fallbacks = 0
        self.sibling_transfers = 0          # directory-driven KV pulls
        self.sibling_transfer_bytes = 0
        self.sibling_fallbacks = 0          # stale entry → re-prefill
        self.migrations = 0                 # live slot moves completed
        self.migration_fallbacks = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def note_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def submit(self, gr: GenRequest) -> GenHandle:
        handle = WorkerGenHandle(gr, next(self._ids))
        if not gr.correlation_id:
            # migration must be able to address the replica-side stream of
            # any request (replica._streaming keys on correlation id) —
            # mint one when the API tier didn't; never overwrite a
            # caller-set id
            gr.correlation_id = f"fleet:{self._owner.name}:{handle.id}"
        handle._migration = None            # staked by migrate_inflight
        handle.trace = self.telemetry.queued(handle)
        if gr.mm_embeds is not None:
            self.telemetry.finished(handle.trace, handle, "error")
            handle._finish("error")
            log.error("fleet-served models do not support multimodal input")
            return handle
        with self._lock:
            self._inflight += 1
        threading.Thread(
            target=self._run, args=(handle,), daemon=True,
            name=f"fleet-req-{handle.id}",
        ).start()
        return handle

    # -- dispatch ----------------------------------------------------------

    def _run(self, handle: WorkerGenHandle) -> None:
        tr = handle.trace
        req = handle.request
        self.watchdog.arm(self._wd_channel)
        try:
            if tr is not None:
                tr.end("queued")
            exclude: set = set()
            attempt = 0
            cold_started = False
            while True:
                try:
                    if tr is not None:
                        tr.begin("route")
                    replica, reason = self.router.route(
                        req.prompt, exclude=exclude,
                        failover=attempt > 0)
                    if tr is not None:
                        tr.end("route", replica=replica.id, reason=reason)
                        # trace-level replica id (overwritten on failover
                        # → the replica that actually served): the
                        # telemetry harvest reads this to know WHOSE pane
                        # holds the other half of the waterfall
                        tr.annotate(replica=replica.id)
                except FleetUnavailable as e:
                    # scale-to-zero wakeup: the autoscaler parks a hook
                    # here; the held request waits out the cold boot (one
                    # attempt) and re-routes instead of erroring
                    if not cold_started and self.on_cold is not None:
                        cold_started = True
                        if tr is not None:
                            tr.end("route", error=str(e), cold_start=True)
                        if self.on_cold():
                            continue
                    elif tr is not None:
                        tr.end("route", error=str(e))
                    log.error("fleet %s: %s", self._owner.name, e)
                    self.telemetry.finished(tr, handle, "error")
                    handle._finish("error")
                    return
                REGISTRY.fleet_routed.inc(
                    model=self._owner.name, reason=reason)
                # submit() already rejected multimodal requests, so every
                # request here is plain-text and disagg-eligible by length
                if (attempt == 0
                        and len(req.prompt) >= self.disagg_threshold):
                    self._disaggregate(req, replica, tr)
                    if replica.state != "healthy":
                        # the handoff exposed a dead decode replica —
                        # re-route now instead of burning a dispatch on it
                        exclude.add(replica.id)
                        attempt += 1
                        with self._lock:
                            self.failovers += 1
                        self._note_failover_waste(req)
                        continue
                elif reason not in ("affinity", "directory"):
                    # placement could not follow the warm KV (queue
                    # override, failover, ring miss): if the directory
                    # knows a sibling holding this prefix, pull it over
                    # TransferPrefix instead of re-prefilling here
                    self._sibling_fetch(req, replica, tr)
                t_dispatch = time.monotonic()
                self._active[handle.id] = (handle, replica)
                try:
                    finish = self._dispatch(handle, replica, tr)
                except Exception as e:  # noqa: BLE001 — replica ≠ fleet
                    if isinstance(e, net.RpcDeadlineExceeded):
                        # silence past the inactivity bound — a partition
                        # or a link too slow to serve from
                        REGISTRY.fleet_rpc_deadlines.inc(
                            model=self._owner.name)
                    self.slo.observe(replica.id, error=True)
                    self.pool.note_failure(replica)
                    ticket = getattr(handle, "_migration", None)
                    if ticket is not None:
                        # the donor died mid-migration: resolve the ticket
                        # so migrate_inflight's wait returns instead of
                        # timing out; the normal failover path takes over
                        ticket.ready.set()
                        ticket.finish("error")
                        handle._migration = None
                    streamed = handle.t_first_token is not None
                    log.warning(
                        "fleet %s: replica %s failed request %d (%s); "
                        "%s", self._owner.name, replica.id, handle.id, e,
                        "failing (already streamed)" if streamed
                        else "failing over" if attempt < self.max_failovers
                        else "out of failover attempts")
                    if not streamed and attempt < self.max_failovers:
                        exclude.add(replica.id)
                        attempt += 1
                        with self._lock:
                            self.failovers += 1
                        self._note_failover_waste(req)
                        continue
                    self.telemetry.finished(tr, handle, "error")
                    handle._finish("error")
                    return
                ticket = getattr(handle, "_migration", None)
                if finish == "cancelled" and ticket is not None:
                    # not a client cancel: migrate_out cancelled the donor
                    # stream — finish the request on the destination
                    finish = self._migrate_continue(
                        handle, ticket, tr, donor=replica)
                    handle._migration = None
                elif finish in ("stop", "length"):
                    # the replica now holds this prompt's prefix KV (the
                    # engine stores it at release) — record the fact so
                    # later placement follows it
                    self._note_prefix(req.prompt, replica.id)
                now = time.monotonic()
                self.slo.observe(
                    replica.id,
                    ttft_ms=((handle.t_first_token - t_dispatch) * 1e3
                             if handle.t_first_token is not None else None),
                    e2e_ms=(now - t_dispatch) * 1e3,
                    error=finish == "error",
                )
                self.telemetry.finished(tr, handle, finish)
                handle._finish(finish)
                return
        finally:
            self._active.pop(handle.id, None)
            self.watchdog.disarm(self._wd_channel)
            with self._lock:
                self._inflight -= 1

    def _note_failover_waste(self, req: GenRequest) -> None:
        """Waste decomposition (obs.ledger): a failover throws away the
        failed replica's prefill work — the re-dispatch re-prefills the
        whole prompt somewhere else. Charged in prompt tokens to the
        request's tenant (which also stamps the front-door feed, so this
        drill-down never double-counts delivered tokens)."""
        obs_ledger.LEDGER.note_waste(
            "failover_reprefill", tokens=len(req.prompt),
            model=self._owner.name, tenant=req.tenant, requests=1)

    def _dispatch(self, handle: WorkerGenHandle, replica, tr,
                  req: Optional[GenRequest] = None) -> str:
        """One streaming attempt against one replica. Raises on transport
        failure (the caller decides whether failover is still safe).
        ``req`` overrides the handle's request (migration continuations
        dispatch a rewritten request through the original handle)."""
        req = handle.request if req is None else req
        opts = predict_options(req)
        replica.begin()
        error = True
        try:
            if tr is not None:
                tr.begin("rpc", replica=replica.id)
            # every dispatch stream — local or remote — runs through the
            # bounded pump: explicit per-reply deadline, and the
            # fleet.transport chaos site fires at the same layer a real
            # NIC would fail
            finish, got_final = consume_stream(
                handle,
                net.bounded_stream(
                    replica.predict_stream(
                        opts, trace_id=req.trace_id or req.correlation_id,
                        tenant=req.tenant),
                    self.rpc_timeout_s, rid=replica.id),
                watchdog=self.watchdog, channel=self._wd_channel, tr=tr)
            if not got_final:
                # the stream went away without a final usage reply — a
                # dying replica, not a completed generation
                raise RuntimeError(
                    f"stream from {replica.id} ended without a final reply")
            error = finish == "error"
            return finish
        finally:
            if tr is not None:
                tr.end("rpc")
            replica.done(error=error)

    def _disaggregate(self, req: GenRequest, decode, tr) -> bool:
        """Prefill replica → TransferPrefix → decode replica's cache. Best
        effort: any failure falls back to a plain dispatch (the decode
        replica prefills itself, exactly as without disaggregation)."""
        pre = self.pool.least_loaded("prefill")
        if pre is None:
            return False
        opts = predict_options(req)
        trace_id = req.trace_id or req.correlation_id
        nbytes = 0
        if tr is not None:
            tr.begin("prefix_transfer", prefill=pre.id, decode=decode.id)
            # disagg requests span TWO replicas — record the prefill half
            # so the harvest stitches both panes into one waterfall
            tr.annotate(prefill_replica=pre.id)
        ok = False
        # the export is materialized before the decode-side call so a
        # failure is charged to the replica that actually failed: lazy
        # relaying would surface a dying prefill iterator as a transfer
        # RPC error on the decode side (and vice versa). The buffered
        # chunks are the same arrays the prefill replica already holds in
        # its prefix cache — one transient copy, bounded by the export.
        blame = pre
        try:
            pre.begin()
            pre_err = True
            try:
                chunks = []
                # bounded pump: a partitioned prefill replica surfaces as
                # RpcDeadlineExceeded here (charged to `pre`), never as a
                # silently hung handoff
                for c in net.bounded_stream(
                        pre.prefill_prefix(opts, trace_id=trace_id),
                        self.rpc_timeout_s, rid=pre.id):
                    nbytes += len(
                        c["data"] if isinstance(c, dict) else c.data)
                    self.watchdog.pulse(self._wd_channel)
                    chunks.append(c)
                pre_err = False
            finally:
                pre.done(error=pre_err)
            blame = decode
            # importing a prefix is idempotent (a re-store of the same
            # rows is a no-op on the peer), so the transfer gets the
            # bounded jittered retry a flaky link deserves — the buffered
            # chunk list re-streams cleanly
            res = net.call_with_retries(
                lambda: decode.transfer_prefix(iter(chunks),
                                               trace_id=trace_id,
                                               timeout=self.rpc_timeout_s),
                rid=decode.id, what="transfer_prefix")
            ok = bool(getattr(res, "success", False))
        except Exception as e:  # noqa: BLE001 — disagg is an optimization
            if isinstance(e, net.RpcDeadlineExceeded):
                REGISTRY.fleet_rpc_deadlines.inc(model=self._owner.name)
            log.warning(
                "fleet %s: disaggregated prefill %s→%s failed on %s (%s); "
                "falling back to direct dispatch",
                self._owner.name, pre.id, decode.id, blame.id, e)
            self.slo.observe(blame.id, error=True)
            self.pool.note_failure(blame)
        finally:
            if tr is not None:
                tr.end("prefix_transfer", ok=ok, bytes=nbytes)
        if ok:
            with self._lock:
                self.prefix_transfers += 1
                self.prefix_transfer_bytes += nbytes
            REGISTRY.fleet_prefix_transfers.inc(model=self._owner.name)
            REGISTRY.fleet_prefix_transfer_bytes.inc(
                nbytes, model=self._owner.name)
            # the decode replica now holds the transferred prefix
            self._note_prefix(req.prompt, decode.id)
        else:
            with self._lock:
                self.disagg_fallbacks += 1
        return ok

    # -- KV economy: directory, sibling fetch, live migration -------------

    def _note_prefix(self, prompt: list, rid: str) -> None:
        """Record in the fleet directory that ``rid`` holds ``prompt``'s
        prefix KV (same key granularity the router's affinity uses)."""
        if self.directory is None:
            return
        self.directory.note(
            affinity_key(prompt, block_tokens=self.router.block_tokens,
                         blocks=self.router.affinity_blocks), rid)

    def _sibling_fetch(self, req: GenRequest, target, tr) -> bool:
        """Directory-driven warm-up: when placement lands a request away
        from its warm KV, pull the prefix from the holding sibling over
        TransferPrefix before dispatching — one bulk copy instead of a
        re-prefill. Best effort: a stale directory entry (replica-side
        LRU eviction, a dying donor) costs one failed fetch, after which
        the entry is dropped and the plain dispatch prefills as usual —
        never a request error."""
        if self.directory is None:
            return False
        key = affinity_key(req.prompt, block_tokens=self.router.block_tokens,
                           blocks=self.router.affinity_blocks)
        if key is None:
            return False
        donor_id = self.directory.holder(
            key, (r.id for r in self.pool.healthy("decode")),
            exclude=(target.id,))
        if donor_id is None:
            return False
        donor = self.pool.get(donor_id)
        if donor is None or donor.state != "healthy":
            return False
        trace_id = req.trace_id or req.correlation_id
        nbytes = 0
        ok = False
        if tr is not None:
            tr.begin("sibling_fetch", donor=donor.id, target=target.id)
        try:
            if _faults.ACTIVE:
                # chaos: the donor dies mid-fetch — this leg must degrade
                # to a plain re-prefill, never fail the request
                _faults.apply("fleet.sibling", key=donor.id)
            chunks = donor.export_cached(req.prompt, trace_id=trace_id)
            if chunks is None:
                # no cache-peek surface (client-backed donor) or the
                # cached entry diverged: re-prefill ON THE DONOR — its
                # paged prefix pool makes this mostly block reuse — and
                # stream the rows over, same as the disagg export
                opts = predict_options(req)
                donor.begin()
                derr = True
                try:
                    chunks = []
                    for c in net.bounded_stream(
                            donor.prefill_prefix(opts, trace_id=trace_id),
                            self.rpc_timeout_s, rid=donor.id):
                        self.watchdog.pulse(self._wd_channel)
                        chunks.append(c)
                    derr = False
                finally:
                    donor.done(error=derr)
            if not chunks:
                raise RuntimeError("donor exported no prefix chunks")
            nbytes = sum(len(c["data"] if isinstance(c, dict) else c.data)
                         for c in chunks)
            res = net.call_with_retries(
                lambda: target.transfer_prefix(iter(chunks),
                                               trace_id=trace_id,
                                               timeout=self.rpc_timeout_s),
                rid=target.id, what="transfer_prefix")
            ok = bool(getattr(res, "success", False))
            if not ok:
                raise RuntimeError("target refused the prefix transfer")
        except Exception as e:  # noqa: BLE001 — the fetch is an optimization
            if isinstance(e, net.RpcDeadlineExceeded):
                REGISTRY.fleet_rpc_deadlines.inc(model=self._owner.name)
            log.warning(
                "fleet %s: sibling prefix fetch %s→%s failed (%s); "
                "dropping directory entry, falling back to local prefill",
                self._owner.name, donor.id, target.id, e)
            self.directory.drop(key, donor.id)
            with self._lock:
                self.sibling_fallbacks += 1
            REGISTRY.fleet_sibling_fallbacks.inc(model=self._owner.name)
        finally:
            if tr is not None:
                tr.end("sibling_fetch", ok=ok, bytes=nbytes)
        if ok:
            with self._lock:
                self.sibling_transfers += 1
                self.sibling_transfer_bytes += nbytes
            REGISTRY.fleet_sibling_transfers.inc(model=self._owner.name)
            REGISTRY.fleet_sibling_transfer_bytes.inc(
                nbytes, model=self._owner.name)
            self.directory.note(key, target.id)
        return ok

    def migrate_inflight(self, handle: WorkerGenHandle,
                         dest_id: Optional[str] = None,
                         timeout: float = 30.0) -> bool:
        """Move an in-flight request to another replica at its next
        dispatch boundary (operator drain, rebalancing, chaos drills).
        Blocks until the migration resolves; True only when the request
        actually continued on the destination. Safe to call from any
        thread — the dispatch thread owns the request lifecycle
        throughout."""
        req = handle.request
        if req.constraint is not None:
            # the destination would recompile the grammar FSM from
            # position 0 over a prompt that already contains donor
            # generations — constrained requests stay put
            return False
        entry = self._active.get(handle.id)
        if entry is None or handle.finish_reason is not None:
            return False
        donor = entry[1]
        dests = [r for r in self.pool.healthy("decode") if r.id != donor.id]
        if dest_id is not None:
            dests = [r for r in dests if r.id == dest_id]
        if not dests:
            return False
        dest = min(dests, key=lambda r: r.load)
        ticket = MigrationTicket(dest.id)
        # stake BEFORE cancelling: the dispatch thread must find the
        # ticket when the donor's "cancelled" final reply unwinds
        handle._migration = ticket
        out = None
        try:
            out = donor.migrate_out(req.correlation_id, timeout=timeout)
        except Exception as e:  # noqa: BLE001 — donor export ≠ request
            ticket.fail(str(e))
        if out is not None:
            ticket.chunks = out.get("chunks")
            ticket.full_tokens = out.get("tokens")
            ticket.donor_tokens = int(out.get("generated") or 0)
            ticket.ready.set()
        elif not ticket.error:
            # the donor doesn't know this request (already finished, or a
            # replica kind without a migration surface) and nothing was
            # cancelled: unstake so a later genuine client cancel isn't
            # misread as a migration
            handle._migration = None
            return False
        if not ticket.completed.wait(timeout):
            return False
        return ticket.outcome == "migrated"

    def drain(self, rid: str, timeout: float = 30.0) -> dict:
        """Migrate every in-flight request off replica ``rid`` (drain-free
        shutdown / rebalancing). Returns {"moved": n, "failed": n}."""
        moved = failed = 0
        for _, entry in list(self._active.items()):
            handle, replica = entry
            if replica.id != rid or handle.finish_reason is not None:
                continue
            if self.migrate_inflight(handle, timeout=timeout):
                moved += 1
            else:
                failed += 1
        return {"moved": moved, "failed": failed}

    def _migrate_continue(self, handle: WorkerGenHandle,
                          ticket: MigrationTicket, tr, donor) -> str:
        """Dispatch-thread half of a live migration: the donor stream just
        unwound "cancelled" with ``ticket`` staked. Transfer the exported
        KV into the destination and re-dispatch a continuation (full token
        record as prompt, remaining budget); every failure leg falls back
        to a correct full re-prefill — slow, never lossy. Returns the
        request's final finish reason."""
        req = handle.request
        if tr is not None:
            tr.begin("migrate", donor=donor.id, dest=ticket.dest_id)
        try:
            if not ticket.ready.wait(30.0) or ticket.error \
                    or not ticket.full_tokens:
                return self._migration_fallback(
                    handle, ticket, tr,
                    ticket.error or "donor export timed out")
            cont = continuation_request(req, ticket.full_tokens,
                                        ticket.donor_tokens)
            if cont.max_new_tokens <= 0:
                # the donor spent the whole budget before the boundary:
                # the move is complete with nothing left to generate
                self._finish_migration(handle, ticket, req, 0)
                return "length"
            dest = self.pool.get(ticket.dest_id)
            targets = ([dest] if dest is not None
                       and dest.state == "healthy" else [])
            # the continuation is self-contained (full token record), so
            # any healthy sibling can finish it if the preferred
            # destination died between staking and transfer
            targets += [r for r in self.pool.healthy("decode")
                        if r.id != donor.id
                        and all(r.id != t.id for t in targets)]
            trace_id = req.trace_id or req.correlation_id
            for dest in targets[:2]:
                n_text = len(handle.text)
                try:
                    if ticket.chunks:
                        # best effort: a failed import only costs the
                        # destination a re-prefill of the token record
                        try:
                            dest.transfer_prefix(
                                iter(ticket.chunks), trace_id=trace_id,
                                timeout=self.rpc_timeout_s)
                        except Exception as e:  # noqa: BLE001
                            log.warning(
                                "fleet %s: migration KV transfer to %s "
                                "failed (%s); destination will re-prefill",
                                self._owner.name, dest.id, e)
                    self._active[handle.id] = (handle, dest)
                    finish = self._dispatch(handle, dest, tr, req=cont)
                    self._finish_migration(
                        handle, ticket, req,
                        getattr(handle, "_completion_override", None) or 0)
                    self._note_prefix(req.prompt, dest.id)
                    return finish
                except Exception as e:  # noqa: BLE001 — dest ≠ request
                    self.slo.observe(dest.id, error=True)
                    self.pool.note_failure(dest)
                    log.warning(
                        "fleet %s: migration continuation on %s failed "
                        "(%s)", self._owner.name, dest.id, e)
                    if len(handle.text) > n_text:
                        # this continuation streamed deltas before dying —
                        # a retry would replay text
                        ticket.finish("error")
                        return "error"
            return self._migration_fallback(
                handle, ticket, tr, "no destination could continue")
        finally:
            if tr is not None:
                tr.end("migrate", outcome=ticket.outcome)

    def _finish_migration(self, handle: WorkerGenHandle,
                          ticket: MigrationTicket, req: GenRequest,
                          cont_tokens: int) -> None:
        """Splice usage across the boundary: the client sees ONE request
        — donor tokens + destination tokens, and the ORIGINAL prompt
        length (the continuation's inflated prompt is an implementation
        detail)."""
        handle._completion_override = ticket.donor_tokens + cont_tokens
        handle.prompt_tokens = len(req.prompt)
        with self._lock:
            self.migrations += 1
        REGISTRY.fleet_migrations.inc(model=self._owner.name)
        ticket.finish("migrated")

    def _migration_fallback(self, handle: WorkerGenHandle,
                            ticket: MigrationTicket, tr, why: str) -> str:
        """The migration could not complete. If nothing reached the
        client yet the original request re-dispatches from scratch
        (correct, just slower); a half-streamed request cannot be
        replayed and finishes ``error``."""
        with self._lock:
            self.migration_fallbacks += 1
        REGISTRY.fleet_migration_fallbacks.inc(model=self._owner.name)
        # waste decomposition (obs.ledger): the fallback throws the
        # donor's exported KV away and re-prefills the prompt from scratch
        obs_ledger.LEDGER.note_waste(
            "migration_reprefill", tokens=len(handle.request.prompt),
            model=self._owner.name, tenant=handle.request.tenant,
            requests=1)
        log.warning("fleet %s: live migration of request %d fell back "
                    "(%s)", self._owner.name, handle.id, why)
        ticket.finish("fallback")
        if handle.t_first_token is not None:
            return "error"
        try:
            replica, _ = self.router.route(handle.request.prompt,
                                           failover=True)
            REGISTRY.fleet_routed.inc(model=self._owner.name,
                                      reason="failover")
            self._active[handle.id] = (handle, replica)
            return self._dispatch(handle, replica, tr)
        except Exception as e:  # noqa: BLE001
            log.warning("fleet %s: post-migration re-dispatch failed (%s)",
                        self._owner.name, e)
            return "error"

    # -- observability / lifecycle ----------------------------------------

    def metrics(self) -> dict:
        """Aggregate engine metrics across healthy replicas (the shape
        update_engine_gauges understands) + the fleet's own stats. Pulls
        one stats RPC per replica — scrape-path only, never the dispatch
        path."""
        totals = {"total_prompt_tokens": 0, "total_generated_tokens": 0,
                  "queue_depth": 0, "dispatches": 0, "preemptions": 0,
                  "prefix_tokens_reused": 0}
        # host-RAM KV tier roll-up (only exported when some replica has a
        # tier attached — worker dicts without the keys stay invisible)
        tier = {"kv_tier_blocks": 0, "kv_tier_bytes": 0,
                "kv_tier_spills": 0, "kv_tier_reloads": 0}
        tiered = False
        occ = []
        kvu = []
        per_replica: dict[str, dict] = {}
        for r in self.pool.members():
            if r.state != "healthy":
                per_replica[r.id] = {"state": r.state}
                continue
            m = r.metrics()
            per_replica[r.id] = m
            if "error" in m and len(m) == 1:
                continue
            for k in totals:
                totals[k] += m.get(k, 0) or 0
            if "kv_tier_spills" in m:
                tiered = True
                for k in tier:
                    tier[k] += m.get(k, 0) or 0
            if m.get("occupancy") is not None:
                occ.append(m["occupancy"])
            if m.get("kv_utilization") is not None:
                kvu.append(m["kv_utilization"])
        if tiered:
            totals.update(tier)
        with self._lock:
            fleet = {
                "replicas": self.pool.states(),
                "respawns": self.pool.respawns,
                "failovers": self.failovers,
                "prefix_transfers": self.prefix_transfers,
                "prefix_transfer_bytes": self.prefix_transfer_bytes,
                "disagg_fallbacks": self.disagg_fallbacks,
                "sibling_transfers": self.sibling_transfers,
                "sibling_transfer_bytes": self.sibling_transfer_bytes,
                "sibling_fallbacks": self.sibling_fallbacks,
                "migrations": self.migrations,
                "migration_fallbacks": self.migration_fallbacks,
                **self.router.snapshot(),
            }
            shed = self.shed_total
        if self.directory is not None:
            fleet["directory"] = self.directory.stats()
        return {
            **totals,
            "occupancy": sum(occ) / len(occ) if occ else 0.0,
            "kv_utilization": sum(kvu) / len(kvu) if kvu else 0.0,
            "shed_total": shed,
            "fleet": fleet,
            "replica_metrics": per_replica,
        }

    def export_gauges(self) -> None:
        """Scrape-time refresh of the fleet gauge family."""
        states = self.pool.states()
        for state in ("starting", "healthy", "dead", "respawning",
                      "evicted"):
            REGISTRY.fleet_replicas.set(
                states.get(state, 0), model=self._owner.name, state=state)
        auto = getattr(self._owner, "autoscaler", None)
        if auto is not None:
            REGISTRY.fleet_target_replicas.set(
                auto.target, model=self._owner.name)
        if self.directory is not None:
            st = self.directory.stats()
            REGISTRY.fleet_directory_entries.set(
                st["entries"], model=self._owner.name)
            REGISTRY.fleet_directory_hits.set_total(
                st["hits"], model=self._owner.name)
            REGISTRY.fleet_directory_misses.set_total(
                st["misses"], model=self._owner.name)
            REGISTRY.fleet_directory_drops.set_total(
                st["drops"] + st["invalidations"],
                model=self._owner.name)

    def shutdown(self, timeout: float = 10.0) -> None:
        self.pool.shutdown()


class FleetServingModel:
    """ServingModel facade over a replica fleet (the multi-replica
    counterpart of worker.serving.WorkerServingModel)."""

    def __init__(self, mcfg: ModelConfig, app: AppConfig, factory,
                 *, replicas: int, prefill_replicas: int = 0,
                 disagg_threshold: Optional[int] = None,
                 remote_hosts: Optional[list[str]] = None,
                 rpc_timeout_s: Optional[float] = None):
        from localai_tpu.models.registry import resolve_tokenizer
        from localai_tpu.templates.cache import TemplateCache

        self.name = mcfg.name
        self.config = mcfg
        self.app = app
        self.tokenizer = resolve_tokenizer(
            mcfg.model or mcfg.name, app.model_path)
        self.templates = TemplateCache(app.model_path)
        self.vision = None
        self.image_token_id = 0
        if mcfg.mmproj:
            log.warning(
                "model %s: mmproj is not supported on fleet-served models "
                "yet; images will be ignored", mcfg.name)
        # per-replica SLO observatory driving route-around: app-config
        # latency targets when set; otherwise an error-rate-only objective
        # (events are bad only on transport/engine errors, so a replica
        # sheds from routing when >threshold× its error budget burns)
        targets = targets_from_config(app) or {"e2e_ms": float("inf")}
        self.slo = SLOTracker(targets=targets)
        # decode-admission hint: affinity placement degrades to
        # least-loaded when the target replica's monitor-reported decode
        # queue depth exceeds LOCALAI_FLEET_QUEUE_OVERRIDE (0 = off)
        try:
            queue_override = int(os.environ.get(
                "LOCALAI_FLEET_QUEUE_OVERRIDE", "0") or 0)
        except ValueError:
            queue_override = 0
        # cross-host: every `host:port` in remote_hosts (default: the
        # app's fleet_hosts / LOCALAI_FLEET_HOSTS list) is adopted as a
        # RemoteReplica — same routing surface, but evicted-with-redial
        # on failure instead of respawned (we do not own the peer)
        from localai_tpu.fleet.replica import RemoteReplica

        hosts = (remote_hosts if remote_hosts is not None
                 else list(getattr(app, "fleet_hosts", []) or []))
        remotes = [
            RemoteReplica(f"{mcfg.name}/{host}", "decode", host, mcfg, app)
            for host in hosts
        ]
        self.pool = ReplicaPool(
            mcfg.name, factory,
            replicas=replicas, prefill_replicas=prefill_replicas,
            remotes=remotes,
            track_queue_depth=queue_override > 0,
        )
        self.pool.start()
        from localai_tpu.engine.paged import block_tokens_default

        bt = mcfg.engine.kv_block_tokens or block_tokens_default()
        # fleet prefix directory: the RECORD of which replica holds which
        # prefix blocks (kveconomy). The router probes it for placement;
        # the scheduler writes it and pulls KV from siblings against it;
        # replica death invalidates every entry naming the corpse (a
        # respawned engine boots cold — the old entries are lies)
        self.directory = PrefixDirectory()
        self.pool.add_death_listener(self.directory.drop_replica)
        self.router = Router(self.pool, self.slo, block_tokens=bt,
                             queue_override=queue_override,
                             directory=self.directory)
        self.scheduler = FleetScheduler(
            self, self.pool, self.router, self.slo,
            disagg_threshold=(disagg_threshold
                              if disagg_threshold is not None
                              else app.fleet_disagg_threshold),
            rpc_timeout_s=(rpc_timeout_s if rpc_timeout_s is not None
                           else getattr(app, "fleet_rpc_timeout_s", None)),
        )
        # hot-swap surface: the pool factory reads its model config
        # through this mutable holder (manager rebinds it here), so a
        # runtime spawn after a checkpoint swap boots the NEW weights;
        # the autoscaler is attached by the manager when enabled
        self.cfg_ref = {"mcfg": mcfg}
        self.autoscaler = None
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()

    def touch(self) -> None:
        self.last_used = time.monotonic()

    def adopt_remote(self, address: str, role: str = "decode") -> dict:
        """Adopt a remote worker at ``address`` into this fleet's pool
        (the federation-registry join path: POST /federated/register on
        the serving instance). Dial + LoadModel run inline so the caller
        gets the verdict; a peer that registers and then fails its first
        dial lands straight in the eviction/redial loop — offline-
        eviction parity with the federation router's registry."""
        from localai_tpu.fleet.replica import RemoteReplica

        rid = f"{self.name}/{address}"
        replica = RemoteReplica(rid, role, address, self.config, self.app)
        adopted = self.pool.adopt(replica, wait=True)
        current = self.pool.get(rid)
        return {
            "id": rid,
            "adopted": adopted,
            "state": current.state if current is not None else "unknown",
        }

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        """The fleet self-heals dead replicas; the facade only dies when
        its monitor is gone (manager then rebuilds the whole fleet)."""
        mon = self.pool._monitor
        return mon is not None and mon.is_alive()

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()

    def fleet_status(self) -> dict:
        """The /v1/fleet payload for this model."""
        return {
            **self.pool.snapshot(with_metrics=True),
            "router": self.router.snapshot(),
            "disagg_threshold": self.scheduler.disagg_threshold,
            "failovers": self.scheduler.failovers,
            "prefix_transfers": self.scheduler.prefix_transfers,
            "prefix_transfer_bytes": self.scheduler.prefix_transfer_bytes,
            "disagg_fallbacks": self.scheduler.disagg_fallbacks,
            "directory": self.directory.stats(),
            "sibling_transfers": self.scheduler.sibling_transfers,
            "sibling_transfer_bytes":
                self.scheduler.sibling_transfer_bytes,
            "sibling_fallbacks": self.scheduler.sibling_fallbacks,
            "migrations": self.scheduler.migrations,
            "migration_fallbacks": self.scheduler.migration_fallbacks,
            "shedding": {
                r.id: self.slo.shedding(r.id) for r in self.pool.members()
            },
            "autoscale": (self.autoscaler.snapshot()
                          if self.autoscaler is not None
                          else {"enabled": False}),
        }

    def swap(self, checkpoint: Optional[str] = None,
             *, timeout: float = 30.0) -> dict:
        """Hot weight swap (POST /v1/fleet/{model}/swap): boot fresh
        replicas — on ``checkpoint`` when given — shift traffic, drain
        and retire the old generation."""
        from localai_tpu.fleet.autoscale import hot_swap

        return hot_swap(self, checkpoint, timeout=timeout)

    def close(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.pool.shutdown()
