"""FleetServingModel: one model served by N replicas behind one facade.

The ServingModel-shaped surface the API tier already speaks (tokenizer/
templates local, ``scheduler.submit`` → GenHandle) — but submit() routes
each request across the replica fleet:

  * placement: prompt-prefix affinity over a consistent-hash ring, with
    least-loaded fallback and per-replica burn-rate route-around
    (fleet/router.py);
  * retry-with-failover: a replica dying mid-request is marked dead, and
    the request re-dispatches to the next candidate as long as nothing
    was streamed to the client yet (a half-streamed completion cannot be
    transparently resumed — it finishes ``error`` and the API tier maps
    that to a clean 5xx);
  * disaggregation: long prompts prefill on a dedicated prefill replica,
    whose packed KV prefix streams over TransferPrefix into the decode
    replica's prefix cache — the decode replica's admission then
    load_prefix-resumes, so long prompts never occupy decode slots for
    prefill (DistServe/Mooncake shape on the paged-KV block transfer).

Every request records lifecycle spans under its API trace id (queued →
route → prefix_transfer? → rpc), with the replica-side engine spans
grouping under the same id via the gRPC metadata propagation the worker
tier already does."""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Optional

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.fleet import net
from localai_tpu.fleet.pool import ReplicaPool
from localai_tpu.fleet.router import FleetUnavailable, Router
from localai_tpu.obs import EngineTelemetry
from localai_tpu.obs import watchdog as obs_watchdog
from localai_tpu.obs.metrics import REGISTRY
from localai_tpu.obs.slo import SLOTracker, targets_from_config
from localai_tpu.worker.serving import (WorkerGenHandle, consume_stream,
                                        predict_options)

log = logging.getLogger(__name__)


class FleetScheduler:
    """The scheduler-shaped surface of a replica fleet: submit() routes,
    dispatches on a daemon thread, and fails over on replica death."""

    def __init__(self, owner: "FleetServingModel", pool: ReplicaPool,
                 router: Router, slo: SLOTracker,
                 *, disagg_threshold: int = 512, max_failovers: int = 2,
                 rpc_timeout_s: Optional[float] = None):
        self._owner = owner
        self.pool = pool
        self.router = router
        self.slo = slo                      # per-REPLICA observatory
        self.disagg_threshold = disagg_threshold
        self.max_failovers = max_failovers
        # per-reply inactivity deadline on every cross-replica stream
        # (fleet.net.bounded_stream): a partitioned peer never RSTs, so
        # silence — not an error — is how a dead remote presents; the
        # deadline turns it into a prompt failover instead of a hung
        # dispatch thread (0 disables)
        self.rpc_timeout_s = (net.rpc_timeout_s() if rpc_timeout_s is None
                              else rpc_timeout_s)
        self._ids = itertools.count()
        self._inflight = 0
        self._lock = threading.Lock()
        self.telemetry = EngineTelemetry(model=owner.name)
        self.watchdog = obs_watchdog.WATCHDOG
        self._wd_channel = f"fleet:{owner.name}"
        self.watchdog.start()
        self.shed_total = 0                 # API-tier SLO 429 mirror
        self.failovers = 0
        self.prefix_transfers = 0
        self.prefix_transfer_bytes = 0
        self.disagg_fallbacks = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def note_shed(self) -> None:
        with self._lock:
            self.shed_total += 1

    def submit(self, gr: GenRequest) -> GenHandle:
        handle = WorkerGenHandle(gr, next(self._ids))
        handle.trace = self.telemetry.queued(handle)
        if gr.mm_embeds is not None:
            self.telemetry.finished(handle.trace, handle, "error")
            handle._finish("error")
            log.error("fleet-served models do not support multimodal input")
            return handle
        with self._lock:
            self._inflight += 1
        threading.Thread(
            target=self._run, args=(handle,), daemon=True,
            name=f"fleet-req-{handle.id}",
        ).start()
        return handle

    # -- dispatch ----------------------------------------------------------

    def _run(self, handle: WorkerGenHandle) -> None:
        tr = handle.trace
        req = handle.request
        self.watchdog.arm(self._wd_channel)
        try:
            if tr is not None:
                tr.end("queued")
            exclude: set = set()
            attempt = 0
            while True:
                try:
                    if tr is not None:
                        tr.begin("route")
                    replica, reason = self.router.route(
                        req.prompt, exclude=exclude,
                        failover=attempt > 0)
                    if tr is not None:
                        tr.end("route", replica=replica.id, reason=reason)
                        # trace-level replica id (overwritten on failover
                        # → the replica that actually served): the
                        # telemetry harvest reads this to know WHOSE pane
                        # holds the other half of the waterfall
                        tr.annotate(replica=replica.id)
                except FleetUnavailable as e:
                    if tr is not None:
                        tr.end("route", error=str(e))
                    log.error("fleet %s: %s", self._owner.name, e)
                    self.telemetry.finished(tr, handle, "error")
                    handle._finish("error")
                    return
                REGISTRY.fleet_routed.inc(
                    model=self._owner.name, reason=reason)
                # submit() already rejected multimodal requests, so every
                # request here is plain-text and disagg-eligible by length
                if (attempt == 0
                        and len(req.prompt) >= self.disagg_threshold):
                    self._disaggregate(req, replica, tr)
                    if replica.state != "healthy":
                        # the handoff exposed a dead decode replica —
                        # re-route now instead of burning a dispatch on it
                        exclude.add(replica.id)
                        attempt += 1
                        with self._lock:
                            self.failovers += 1
                        continue
                t_dispatch = time.monotonic()
                try:
                    finish = self._dispatch(handle, replica, tr)
                except Exception as e:  # noqa: BLE001 — replica ≠ fleet
                    if isinstance(e, net.RpcDeadlineExceeded):
                        # silence past the inactivity bound — a partition
                        # or a link too slow to serve from
                        REGISTRY.fleet_rpc_deadlines.inc(
                            model=self._owner.name)
                    self.slo.observe(replica.id, error=True)
                    self.pool.note_failure(replica)
                    streamed = handle.t_first_token is not None
                    log.warning(
                        "fleet %s: replica %s failed request %d (%s); "
                        "%s", self._owner.name, replica.id, handle.id, e,
                        "failing (already streamed)" if streamed
                        else "failing over" if attempt < self.max_failovers
                        else "out of failover attempts")
                    if not streamed and attempt < self.max_failovers:
                        exclude.add(replica.id)
                        attempt += 1
                        with self._lock:
                            self.failovers += 1
                        continue
                    self.telemetry.finished(tr, handle, "error")
                    handle._finish("error")
                    return
                now = time.monotonic()
                self.slo.observe(
                    replica.id,
                    ttft_ms=((handle.t_first_token - t_dispatch) * 1e3
                             if handle.t_first_token is not None else None),
                    e2e_ms=(now - t_dispatch) * 1e3,
                    error=finish == "error",
                )
                self.telemetry.finished(tr, handle, finish)
                handle._finish(finish)
                return
        finally:
            self.watchdog.disarm(self._wd_channel)
            with self._lock:
                self._inflight -= 1

    def _dispatch(self, handle: WorkerGenHandle, replica, tr) -> str:
        """One streaming attempt against one replica. Raises on transport
        failure (the caller decides whether failover is still safe)."""
        req = handle.request
        opts = predict_options(req)
        replica.begin()
        error = True
        try:
            if tr is not None:
                tr.begin("rpc", replica=replica.id)
            # every dispatch stream — local or remote — runs through the
            # bounded pump: explicit per-reply deadline, and the
            # fleet.transport chaos site fires at the same layer a real
            # NIC would fail
            finish, got_final = consume_stream(
                handle,
                net.bounded_stream(
                    replica.predict_stream(
                        opts, trace_id=req.trace_id or req.correlation_id),
                    self.rpc_timeout_s, rid=replica.id),
                watchdog=self.watchdog, channel=self._wd_channel, tr=tr)
            if not got_final:
                # the stream went away without a final usage reply — a
                # dying replica, not a completed generation
                raise RuntimeError(
                    f"stream from {replica.id} ended without a final reply")
            error = finish == "error"
            return finish
        finally:
            if tr is not None:
                tr.end("rpc")
            replica.done(error=error)

    def _disaggregate(self, req: GenRequest, decode, tr) -> bool:
        """Prefill replica → TransferPrefix → decode replica's cache. Best
        effort: any failure falls back to a plain dispatch (the decode
        replica prefills itself, exactly as without disaggregation)."""
        pre = self.pool.least_loaded("prefill")
        if pre is None:
            return False
        opts = predict_options(req)
        trace_id = req.trace_id or req.correlation_id
        nbytes = 0
        if tr is not None:
            tr.begin("prefix_transfer", prefill=pre.id, decode=decode.id)
            # disagg requests span TWO replicas — record the prefill half
            # so the harvest stitches both panes into one waterfall
            tr.annotate(prefill_replica=pre.id)
        ok = False
        # the export is materialized before the decode-side call so a
        # failure is charged to the replica that actually failed: lazy
        # relaying would surface a dying prefill iterator as a transfer
        # RPC error on the decode side (and vice versa). The buffered
        # chunks are the same arrays the prefill replica already holds in
        # its prefix cache — one transient copy, bounded by the export.
        blame = pre
        try:
            pre.begin()
            pre_err = True
            try:
                chunks = []
                # bounded pump: a partitioned prefill replica surfaces as
                # RpcDeadlineExceeded here (charged to `pre`), never as a
                # silently hung handoff
                for c in net.bounded_stream(
                        pre.prefill_prefix(opts, trace_id=trace_id),
                        self.rpc_timeout_s, rid=pre.id):
                    nbytes += len(
                        c["data"] if isinstance(c, dict) else c.data)
                    self.watchdog.pulse(self._wd_channel)
                    chunks.append(c)
                pre_err = False
            finally:
                pre.done(error=pre_err)
            blame = decode
            # importing a prefix is idempotent (a re-store of the same
            # rows is a no-op on the peer), so the transfer gets the
            # bounded jittered retry a flaky link deserves — the buffered
            # chunk list re-streams cleanly
            res = net.call_with_retries(
                lambda: decode.transfer_prefix(iter(chunks),
                                               trace_id=trace_id,
                                               timeout=self.rpc_timeout_s),
                rid=decode.id, what="transfer_prefix")
            ok = bool(getattr(res, "success", False))
        except Exception as e:  # noqa: BLE001 — disagg is an optimization
            if isinstance(e, net.RpcDeadlineExceeded):
                REGISTRY.fleet_rpc_deadlines.inc(model=self._owner.name)
            log.warning(
                "fleet %s: disaggregated prefill %s→%s failed on %s (%s); "
                "falling back to direct dispatch",
                self._owner.name, pre.id, decode.id, blame.id, e)
            self.slo.observe(blame.id, error=True)
            self.pool.note_failure(blame)
        finally:
            if tr is not None:
                tr.end("prefix_transfer", ok=ok, bytes=nbytes)
        if ok:
            with self._lock:
                self.prefix_transfers += 1
                self.prefix_transfer_bytes += nbytes
            REGISTRY.fleet_prefix_transfers.inc(model=self._owner.name)
            REGISTRY.fleet_prefix_transfer_bytes.inc(
                nbytes, model=self._owner.name)
        else:
            with self._lock:
                self.disagg_fallbacks += 1
        return ok

    # -- observability / lifecycle ----------------------------------------

    def metrics(self) -> dict:
        """Aggregate engine metrics across healthy replicas (the shape
        update_engine_gauges understands) + the fleet's own stats. Pulls
        one stats RPC per replica — scrape-path only, never the dispatch
        path."""
        totals = {"total_prompt_tokens": 0, "total_generated_tokens": 0,
                  "queue_depth": 0, "dispatches": 0, "preemptions": 0,
                  "prefix_tokens_reused": 0}
        occ = []
        kvu = []
        per_replica: dict[str, dict] = {}
        for r in self.pool.members():
            if r.state != "healthy":
                per_replica[r.id] = {"state": r.state}
                continue
            m = r.metrics()
            per_replica[r.id] = m
            if "error" in m and len(m) == 1:
                continue
            for k in totals:
                totals[k] += m.get(k, 0) or 0
            if m.get("occupancy") is not None:
                occ.append(m["occupancy"])
            if m.get("kv_utilization") is not None:
                kvu.append(m["kv_utilization"])
        with self._lock:
            fleet = {
                "replicas": self.pool.states(),
                "respawns": self.pool.respawns,
                "failovers": self.failovers,
                "prefix_transfers": self.prefix_transfers,
                "prefix_transfer_bytes": self.prefix_transfer_bytes,
                "disagg_fallbacks": self.disagg_fallbacks,
                **self.router.snapshot(),
            }
            shed = self.shed_total
        return {
            **totals,
            "occupancy": sum(occ) / len(occ) if occ else 0.0,
            "kv_utilization": sum(kvu) / len(kvu) if kvu else 0.0,
            "shed_total": shed,
            "fleet": fleet,
            "replica_metrics": per_replica,
        }

    def export_gauges(self) -> None:
        """Scrape-time refresh of the fleet gauge family."""
        states = self.pool.states()
        for state in ("starting", "healthy", "dead", "respawning",
                      "evicted"):
            REGISTRY.fleet_replicas.set(
                states.get(state, 0), model=self._owner.name, state=state)

    def shutdown(self, timeout: float = 10.0) -> None:
        self.pool.shutdown()


class FleetServingModel:
    """ServingModel facade over a replica fleet (the multi-replica
    counterpart of worker.serving.WorkerServingModel)."""

    def __init__(self, mcfg: ModelConfig, app: AppConfig, factory,
                 *, replicas: int, prefill_replicas: int = 0,
                 disagg_threshold: Optional[int] = None,
                 remote_hosts: Optional[list[str]] = None,
                 rpc_timeout_s: Optional[float] = None):
        from localai_tpu.models.registry import resolve_tokenizer
        from localai_tpu.templates.cache import TemplateCache

        self.name = mcfg.name
        self.config = mcfg
        self.app = app
        self.tokenizer = resolve_tokenizer(
            mcfg.model or mcfg.name, app.model_path)
        self.templates = TemplateCache(app.model_path)
        self.vision = None
        self.image_token_id = 0
        if mcfg.mmproj:
            log.warning(
                "model %s: mmproj is not supported on fleet-served models "
                "yet; images will be ignored", mcfg.name)
        # per-replica SLO observatory driving route-around: app-config
        # latency targets when set; otherwise an error-rate-only objective
        # (events are bad only on transport/engine errors, so a replica
        # sheds from routing when >threshold× its error budget burns)
        targets = targets_from_config(app) or {"e2e_ms": float("inf")}
        self.slo = SLOTracker(targets=targets)
        # decode-admission hint: affinity placement degrades to
        # least-loaded when the target replica's monitor-reported decode
        # queue depth exceeds LOCALAI_FLEET_QUEUE_OVERRIDE (0 = off)
        try:
            queue_override = int(os.environ.get(
                "LOCALAI_FLEET_QUEUE_OVERRIDE", "0") or 0)
        except ValueError:
            queue_override = 0
        # cross-host: every `host:port` in remote_hosts (default: the
        # app's fleet_hosts / LOCALAI_FLEET_HOSTS list) is adopted as a
        # RemoteReplica — same routing surface, but evicted-with-redial
        # on failure instead of respawned (we do not own the peer)
        from localai_tpu.fleet.replica import RemoteReplica

        hosts = (remote_hosts if remote_hosts is not None
                 else list(getattr(app, "fleet_hosts", []) or []))
        remotes = [
            RemoteReplica(f"{mcfg.name}/{host}", "decode", host, mcfg, app)
            for host in hosts
        ]
        self.pool = ReplicaPool(
            mcfg.name, factory,
            replicas=replicas, prefill_replicas=prefill_replicas,
            remotes=remotes,
            track_queue_depth=queue_override > 0,
        )
        self.pool.start()
        from localai_tpu.engine.paged import block_tokens_default

        bt = mcfg.engine.kv_block_tokens or block_tokens_default()
        self.router = Router(self.pool, self.slo, block_tokens=bt,
                             queue_override=queue_override)
        self.scheduler = FleetScheduler(
            self, self.pool, self.router, self.slo,
            disagg_threshold=(disagg_threshold
                              if disagg_threshold is not None
                              else app.fleet_disagg_threshold),
            rpc_timeout_s=(rpc_timeout_s if rpc_timeout_s is not None
                           else getattr(app, "fleet_rpc_timeout_s", None)),
        )
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()

    def touch(self) -> None:
        self.last_used = time.monotonic()

    def adopt_remote(self, address: str, role: str = "decode") -> dict:
        """Adopt a remote worker at ``address`` into this fleet's pool
        (the federation-registry join path: POST /federated/register on
        the serving instance). Dial + LoadModel run inline so the caller
        gets the verdict; a peer that registers and then fails its first
        dial lands straight in the eviction/redial loop — offline-
        eviction parity with the federation router's registry."""
        from localai_tpu.fleet.replica import RemoteReplica

        rid = f"{self.name}/{address}"
        replica = RemoteReplica(rid, role, address, self.config, self.app)
        adopted = self.pool.adopt(replica, wait=True)
        current = self.pool.get(rid)
        return {
            "id": rid,
            "adopted": adopted,
            "state": current.state if current is not None else "unknown",
        }

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        """The fleet self-heals dead replicas; the facade only dies when
        its monitor is gone (manager then rebuilds the whole fleet)."""
        mon = self.pool._monitor
        return mon is not None and mon.is_alive()

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()

    def fleet_status(self) -> dict:
        """The /v1/fleet payload for this model."""
        return {
            **self.pool.snapshot(with_metrics=True),
            "router": self.router.snapshot(),
            "disagg_threshold": self.scheduler.disagg_threshold,
            "failovers": self.scheduler.failovers,
            "prefix_transfers": self.scheduler.prefix_transfers,
            "prefix_transfer_bytes": self.scheduler.prefix_transfer_bytes,
            "disagg_fallbacks": self.scheduler.disagg_fallbacks,
            "shedding": {
                r.id: self.slo.shedding(r.id) for r in self.pool.members()
            },
        }

    def close(self) -> None:
        self.pool.shutdown()
