"""WAV I/O + resampling on the host.

Parity: the reference shells out to ffmpeg to coerce uploads to 16-kHz wav
(/root/reference/pkg/utils/ffmpeg.go) before whisper.cpp consumes them.
ffmpeg isn't in this image; stdlib ``wave`` + polyphase resampling covers
the wav path, and non-wav containers raise a clear error.
"""

from __future__ import annotations

import io
import wave

import numpy as np


def read_wav(data: bytes, target_rate: int = 16000) -> np.ndarray:
    """Decode wav bytes → mono float32 [-1, 1] at ``target_rate``."""
    try:
        with wave.open(io.BytesIO(data)) as w:
            rate = w.getframerate()
            n_ch = w.getnchannels()
            width = w.getsampwidth()
            frames = w.readframes(w.getnframes())
    except (wave.Error, EOFError) as e:
        raise ValueError(
            f"could not parse audio as WAV ({e}); convert to 16-bit PCM wav"
        ) from e
    if width == 2:
        x = np.frombuffer(frames, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(frames, np.int32).astype(np.float32) / 2147483648.0
    elif width == 1:
        x = (np.frombuffer(frames, np.uint8).astype(np.float32) - 128) / 128.0
    else:
        raise ValueError(f"unsupported wav sample width: {width}")
    if n_ch > 1:
        x = x.reshape(-1, n_ch).mean(axis=1)
    if rate != target_rate:
        from scipy.signal import resample_poly
        from math import gcd

        g = gcd(rate, target_rate)
        x = resample_poly(x, target_rate // g, rate // g).astype(np.float32)
    return x


def write_wav(samples: np.ndarray, rate: int = 16000) -> bytes:
    """mono float32 [-1, 1] → 16-bit PCM wav bytes."""
    x = np.clip(np.asarray(samples, np.float32), -1.0, 1.0)
    pcm = (x * 32767.0).astype(np.int16)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()
