"""MusicGen-class generative audio: codebook LM + EnCodec SEANet decoder.

Parity target: the reference's transformers-musicgen backend
(/root/reference/backend/python/transformers-musicgen/backend.py:1-176 —
SoundGeneration RPC → MusicgenForConditionalGeneration.generate →
EnCodec decode). This is a faithful JAX port of the two generative stages:

  * ``lm_forward`` — MusicGen's decoder LM (transformers
    ``MusicgenForCausalLM``): K codebook embeddings summed, sinusoidal
    positions, pre-LN self+cross attention layers (bias-free projections),
    K lm heads. Verified layer-for-layer against the torch implementation
    on tiny random checkpoints (tests/test_musicgen.py — the same strategy
    test_vits.py uses for the VITS port).
  * ``encodec_decode`` — EnCodec's RVQ codebook decode + SEANet decoder
    (causal convs with reflect padding + weight-norm folding, 2-layer LSTM
    residual, transposed-conv upsampling, residual blocks), verified
    against transformers ``EncodecModel``.
  * ``generate_codes`` — the delay-pattern autoregressive sampler
    (codebook k trails k steps) as one ``lax.scan`` with an explicit
    per-layer KV cache: one compiled program per (frames, text) bucket.

Serving uses a deterministic random-weight debug preset (zero-egress
environment — BASELINE.md); real Musicgen/EnCodec checkpoints load through
the same ``*_from_torch`` weight adapters the tests use.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


@dataclasses.dataclass(frozen=True)
class MusicgenConfig:
    vocab_size: int = 64          # per-codebook acoustic vocab
    num_codebooks: int = 4
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 2
    ffn_dim: int = 128
    max_positions: int = 2048
    # EnCodec decoder side
    codebook_dim: int = 32
    num_filters: int = 8
    upsampling_ratios: tuple = (8, 5, 4)   # 160× → 100 Hz frames @16 kHz
    num_residual_layers: int = 1
    num_lstm_layers: int = 2
    kernel_size: int = 7
    last_kernel_size: int = 7
    residual_kernel_size: int = 3
    dilation_growth_rate: int = 2
    compress: int = 2
    sampling_rate: int = 16000

    @property
    def pad_id(self) -> int:  # BOS/PAD sentinel (embed tables have V+1 rows)
        return self.vocab_size

    @property
    def frame_rate(self) -> float:
        return self.sampling_rate / math.prod(self.upsampling_ratios)


# ---------------------------------------------------------------------------
# LM building blocks (MusicgenForCausalLM parity)
# ---------------------------------------------------------------------------


def _ln(x, p):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) / jnp.sqrt(v + 1e-5) * p["w"] + p["b"]


def sinusoidal_positions(n: int, dim: int) -> jnp.ndarray:
    """[n, dim] — tensor2tensor layout: [cos | sin] halves (matches
    MusicgenSinusoidalPositionalEmbedding.get_embedding)."""
    half = dim // 2
    freq = jnp.exp(jnp.arange(half) * (-math.log(10000.0) / (half - 1)))
    ang = jnp.arange(n)[:, None] * freq[None, :]
    emb = jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=1)
    if dim % 2:
        emb = jnp.concatenate([emb, jnp.zeros((n, 1))], axis=1)
    return emb


def _mha(q_x, kv_x, p, heads: int, mask=None):
    """Bias-free multi-head attention (MusicgenAttention)."""
    D = q_x.shape[-1]
    hd = D // heads
    q = (q_x @ p["q"].T) * (hd ** -0.5)
    k = kv_x @ p["k"].T
    v = kv_x @ p["v"].T

    def split(t):
        return t.reshape(*t.shape[:-1], heads, hd)

    scores = jnp.einsum("qhd,khd->hqk", split(q), split(k))
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hqk,khd->qhd", probs, split(v)).reshape(q_x.shape[0], D)
    return out @ p["o"].T


def lm_forward(cfg: MusicgenConfig, params: PyTree, codes: jnp.ndarray,
               memory: Optional[jnp.ndarray] = None,
               offset: int = 0) -> jnp.ndarray:
    """Teacher-forced decoder pass. codes [K, T] (pad_id = BOS) →
    logits [K, T, V]. ``memory`` [S, D] enables cross-attention."""
    T = codes.shape[1]
    x = sum(params["embed"][k][codes[k]] for k in range(cfg.num_codebooks))
    x = x + sinusoidal_positions(offset + T, cfg.hidden_size)[offset:]
    causal = jnp.tril(jnp.ones((T, T), bool))[None]
    for lp in params["layers"]:
        h = _ln(x, lp["ln1"])
        x = x + _mha(h, h, lp["self"], cfg.num_heads, causal)
        if memory is not None:
            h = _ln(x, lp["ln2"])
            x = x + _mha(h, memory, lp["cross"], cfg.num_heads)
        h = _ln(x, lp["ln3"])
        x = x + jax.nn.gelu(h @ lp["fc1"].T, approximate=False) @ lp["fc2"].T
    x = _ln(x, params["final_ln"])
    return jnp.stack([x @ params["heads"][k].T
                      for k in range(cfg.num_codebooks)])


# ---------------------------------------------------------------------------
# Delay-pattern generation (one lax.scan, explicit KV cache)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("cfg", "frames", "top_k"))
def generate_codes(cfg: MusicgenConfig, params: PyTree, memory: jnp.ndarray,
                   key: jax.Array, *, frames: int,
                   temperature=1.0, top_k: int = 64,
                   memory_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sample [K, frames] acoustic codes with MusicGen's delay pattern
    (codebook k trails k steps; BOS until a codebook's first frame).

    ``temperature`` is traced (a sweep of values reuses one compiled
    program; <=0 means greedy); ``memory_mask`` [S] marks real rows when
    the conditioning memory is padded to a length bucket."""
    K, D, L = cfg.num_codebooks, cfg.hidden_size, cfg.num_layers
    heads = cfg.num_heads
    hd = D // heads
    T_total = frames + K
    pos_tab = sinusoidal_positions(T_total, D)

    # cross-attention K/V precomputed once per layer
    cross_kv = [
        (memory @ lp["cross"]["k"].T, memory @ lp["cross"]["v"].T)
        for lp in params["layers"]
    ]

    def step(carry, t):
        kc, vc, tok_col, key = carry  # kc/vc [L, T_total, H, hd]
        x = sum(params["embed"][k][tok_col[k]] for k in range(K)) + pos_tab[t]
        x = x[None]  # [1, D]
        new_kc, new_vc = kc, vc
        for li, lp in enumerate(params["layers"]):
            h = _ln(x, lp["ln1"])
            q = (h @ lp["self"]["q"].T).reshape(1, heads, hd) * (hd ** -0.5)
            k_new = (h @ lp["self"]["k"].T).reshape(heads, hd)
            v_new = (h @ lp["self"]["v"].T).reshape(heads, hd)
            new_kc = new_kc.at[li, t].set(k_new)
            new_vc = new_vc.at[li, t].set(v_new)
            keys, vals = new_kc[li], new_vc[li]          # [T_total, H, hd]
            scores = jnp.einsum("qhd,khd->hqk", q, keys)
            valid = (jnp.arange(T_total) <= t)[None, None, :]
            probs = jax.nn.softmax(jnp.where(valid, scores, -1e30), -1)
            att = jnp.einsum("hqk,khd->qhd", probs, vals).reshape(1, D)
            x = x + att @ lp["self"]["o"].T
            # cross-attention
            h = _ln(x, lp["ln2"])
            qc = (h @ lp["cross"]["q"].T).reshape(1, heads, hd) * (hd ** -0.5)
            ck, cv = cross_kv[li]
            cs = jnp.einsum("qhd,khd->hqk", qc,
                            ck.reshape(-1, heads, hd))
            if memory_mask is not None:
                cs = jnp.where(memory_mask[None, None, :], cs, -1e30)
            cp = jax.nn.softmax(cs, -1)
            catt = jnp.einsum("hqk,khd->qhd", cp,
                              cv.reshape(-1, heads, hd)).reshape(1, D)
            x = x + catt @ lp["cross"]["o"].T
            h = _ln(x, lp["ln3"])
            x = x + jax.nn.gelu(h @ lp["fc1"].T,
                                approximate=False) @ lp["fc2"].T
        x = _ln(x, params["final_ln"])[0]
        logits = jnp.stack([x @ params["heads"][k].T for k in range(K)])

        key, sub = jax.random.split(key)
        kk = min(top_k, cfg.vocab_size)
        temp = jnp.asarray(temperature, jnp.float32)
        vals_k, idx_k = jax.lax.top_k(
            logits / jnp.maximum(temp, 1e-6), kk)
        choice = jax.random.categorical(sub, vals_k, axis=-1)
        # traced temperature: greedy is a select, not a program variant
        choice = jnp.where(temp <= 0, 0, choice)
        sampled = jnp.take_along_axis(idx_k, choice[:, None], 1)[:, 0]
        # delay pattern: codebook k stays BOS until step t+1 > k
        next_col = jnp.where(t + 1 > jnp.arange(K), sampled, cfg.pad_id)
        next_col = next_col.astype(jnp.int32)
        return (new_kc, new_vc, next_col, key), sampled.astype(jnp.int32)

    kc0 = jnp.zeros((L, T_total, heads, hd), jnp.float32)
    vc0 = jnp.zeros((L, T_total, heads, hd), jnp.float32)
    bos = jnp.full((K,), cfg.pad_id, jnp.int32)
    (_, _, _, _), cols = jax.lax.scan(
        step, (kc0, vc0, bos, key), jnp.arange(T_total)
    )  # cols [T_total, K] — sampled at each step
    # un-delay: codebook k's frame f was sampled at step f + k
    frames_idx = jnp.arange(frames)
    codes = jnp.stack([
        cols[frames_idx + k, k] for k in range(K)
    ])
    return jnp.clip(codes, 0, cfg.vocab_size - 1)


# ---------------------------------------------------------------------------
# EnCodec decoder (SEANet) — EncodecModel.decode parity
# ---------------------------------------------------------------------------


def _causal_conv1d(x, w, b, stride: int = 1, dilation: int = 1):
    """x [C, T] with EnCodec causal reflect padding; w [out, in, k]."""
    k = w.shape[-1]
    pad_total = (k - 1) * dilation + 1 - stride
    length = x.shape[-1]
    n_frames = (length - ((k - 1) * dilation + 1) + pad_total) / stride + 1
    ideal = (math.ceil(n_frames) - 1) * stride + ((k - 1) * dilation + 1) \
        - pad_total
    extra = ideal - length
    # reflect needs width > pad; EnCodec zero-extends first in that case
    if length <= pad_total:
        x = jnp.pad(x, ((0, 0), (0, pad_total - length + 1)))
    x = jnp.pad(x, ((0, 0), (pad_total, 0)), mode="reflect")
    if extra > 0:
        x = jnp.pad(x, ((0, 0), (0, extra)))
    out = jax.lax.conv_general_dilated(
        x[None], w, (stride,), "VALID", rhs_dilation=(dilation,),
        dimension_numbers=("NCH", "OIH", "NCH"),
    )[0]
    return out + b[:, None]


def _conv_transpose1d(x, w, b, stride: int):
    """torch ConvTranspose1d (padding=0) + EnCodec causal right-trim.
    w torch layout [in, out, k]."""
    k = w.shape[-1]
    w_flip = jnp.flip(w, -1).transpose(1, 0, 2)  # [out, in, k]
    out = jax.lax.conv_general_dilated(
        x[None], w_flip, (1,), [(k - 1, k - 1)], lhs_dilation=(stride,),
        dimension_numbers=("NCH", "OIH", "NCH"),
    )[0] + b[:, None]
    pad_total = k - stride
    right = math.ceil(pad_total * 1.0)  # trim_right_ratio = 1.0 (causal)
    left = pad_total - right
    return out[:, left: out.shape[-1] - right]


def _lstm_stack(x, layers):
    """EncodecLSTM: stacked torch-layout LSTM over time + residual.
    x [C, T] → [C, T]."""
    h_seq = x.T  # [T, C]
    for lw in layers:
        wi, wh, bi, bh = lw  # [4H, in], [4H, H], [4H], [4H]
        H = wh.shape[1]

        def cell(carry, xt):
            h, c = carry
            g = wi @ xt + wh @ h + bi + bh
            i, f, gg, o = jnp.split(g, 4)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gg)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        (_, _), h_seq = jax.lax.scan(
            cell, (jnp.zeros(H), jnp.zeros(H)), h_seq
        )
    return x + h_seq.T


def encodec_decode(cfg: MusicgenConfig, dparams: PyTree,
                   codes: jnp.ndarray) -> jnp.ndarray:
    """RVQ codes [K, T] → waveform [T × prod(ratios)] float32 mono."""
    # residual VQ decode: sum the codebook vectors
    emb = sum(dparams["codebooks"][k][codes[k]]
              for k in range(cfg.num_codebooks))   # [T, codebook_dim]
    x = emb.T  # [C, T]
    x = _causal_conv1d(x, *dparams["conv_in"])
    x = _lstm_stack(x, dparams["lstm"])
    for up in dparams["ups"]:
        x = jax.nn.elu(x)
        x = _conv_transpose1d(x, up["w"], up["b"], up["stride"])
        for rb in up["res"]:
            y = jax.nn.elu(x)
            y = _causal_conv1d(y, *rb["c1"], dilation=rb["dilation"])
            y = jax.nn.elu(y)
            y = _causal_conv1d(y, *rb["c2"])
            sc = rb.get("shortcut")
            x = (x if sc is None else _causal_conv1d(x, *sc)) + y
    x = jax.nn.elu(x)
    x = _causal_conv1d(x, *dparams["conv_out"])
    return x[0]


# ---------------------------------------------------------------------------
# Weight adapters (torch state_dict → param pytrees)
# ---------------------------------------------------------------------------


def lm_params_from_torch(state: dict, cfg: MusicgenConfig) -> PyTree:
    """transformers MusicgenForCausalLM state_dict → lm param pytree."""
    g = lambda n: jnp.asarray(np.asarray(state[n]), jnp.float32)  # noqa: E731
    layers = []
    for i in range(cfg.num_layers):
        p = f"model.decoder.layers.{i}."
        layers.append({
            "self": {x: g(p + f"self_attn.{x}_proj.weight")
                     for x in "qkvo" if x != "o"} |
                    {"o": g(p + "self_attn.out_proj.weight")},
            "cross": {x: g(p + f"encoder_attn.{x}_proj.weight")
                      for x in "qkvo" if x != "o"} |
                     {"o": g(p + "encoder_attn.out_proj.weight")},
            "ln1": {"w": g(p + "self_attn_layer_norm.weight"),
                    "b": g(p + "self_attn_layer_norm.bias")},
            "ln2": {"w": g(p + "encoder_attn_layer_norm.weight"),
                    "b": g(p + "encoder_attn_layer_norm.bias")},
            "ln3": {"w": g(p + "final_layer_norm.weight"),
                    "b": g(p + "final_layer_norm.bias")},
            "fc1": g(p + "fc1.weight"),
            "fc2": g(p + "fc2.weight"),
        })
    return {
        "embed": [g(f"model.decoder.embed_tokens.{k}.weight")
                  for k in range(cfg.num_codebooks)],
        "heads": [g(f"lm_heads.{k}.weight")
                  for k in range(cfg.num_codebooks)],
        "final_ln": {"w": g("model.decoder.layer_norm.weight"),
                     "b": g("model.decoder.layer_norm.bias")},
        "layers": layers,
    }


def _fold_weight_norm(state: dict, prefix: str):
    """weight_norm(v, g): w = g · v / ‖v‖ over (in, k) per out channel."""
    g0 = np.asarray(state[prefix + ".parametrizations.weight.original0"])
    v = np.asarray(state[prefix + ".parametrizations.weight.original1"])
    norm = np.sqrt((v ** 2).sum(axis=(1, 2), keepdims=True))
    w = g0 * v / np.maximum(norm, 1e-12)
    b = np.asarray(state[prefix + ".bias"])
    return jnp.asarray(w, jnp.float32), jnp.asarray(b, jnp.float32)


def encodec_params_from_torch(state: dict, cfg: MusicgenConfig) -> PyTree:
    """transformers EncodecModel state_dict → SEANet decoder pytree.

    Layer indices follow EncodecDecoder's ModuleList layout: conv_in=0,
    lstm=1, then per ratio [ELU, convtranspose, res×R], final [ELU, conv]."""
    idx = 0
    out: dict = {}
    out["codebooks"] = [
        jnp.asarray(np.asarray(
            state[f"quantizer.layers.{k}.codebook.embed"]), jnp.float32)
        for k in range(cfg.num_codebooks)
    ]
    out["conv_in"] = _fold_weight_norm(state, f"decoder.layers.{idx}.conv")
    idx += 1
    out["lstm"] = [
        tuple(jnp.asarray(np.asarray(
            state[f"decoder.layers.{idx}.lstm.{n}_l{li}"]), jnp.float32)
            for n in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"))
        for li in range(cfg.num_lstm_layers)
    ]
    idx += 1
    ups = []
    for ratio in cfg.upsampling_ratios:
        idx += 1  # ELU
        w, b = _fold_weight_norm(state, f"decoder.layers.{idx}.conv")
        idx += 1
        res = []
        for j in range(cfg.num_residual_layers):
            p = f"decoder.layers.{idx}"
            c1 = _fold_weight_norm(state, p + ".block.1.conv")
            c2 = _fold_weight_norm(state, p + ".block.3.conv")
            rb = {"c1": c1, "c2": c2,
                  "dilation": cfg.dilation_growth_rate ** j}
            if f"{p}.shortcut.conv.bias" in state:
                rb["shortcut"] = _fold_weight_norm(state, p + ".shortcut.conv")
            res.append(rb)
            idx += 1
        ups.append({"w": w, "b": b, "stride": ratio, "res": res})
    idx += 1  # final ELU
    out["ups"] = ups
    out["conv_out"] = _fold_weight_norm(state, f"decoder.layers.{idx}.conv")
    return out


# ---------------------------------------------------------------------------
# Random init (debug preset) + serving entry
# ---------------------------------------------------------------------------


def init_params(rng: jax.Array, cfg: MusicgenConfig) -> tuple[PyTree, PyTree]:
    """(lm_params, decoder_params) with random weights — the zero-download
    serving preset (same role as registry.DEBUG_PRESETS for the LLM)."""
    keys = jax.random.split(rng, 64)
    ki = iter(keys)

    def w(shape, scale=0.08):
        return jax.random.normal(next(ki), shape, jnp.float32) * scale

    D, F, K, V = (cfg.hidden_size, cfg.ffn_dim, cfg.num_codebooks,
                  cfg.vocab_size)
    ln = lambda: {"w": jnp.ones(D), "b": jnp.zeros(D)}  # noqa: E731
    layers = []
    for _ in range(cfg.num_layers):
        layers.append({
            "self": {c: w((D, D)) for c in "qkvo"},
            "cross": {c: w((D, D)) for c in "qkvo"},
            "ln1": ln(), "ln2": ln(), "ln3": ln(),
            "fc1": w((F, D)), "fc2": w((D, F)),
        })
    lm = {
        "embed": [w((V + 1, D)) for _ in range(K)],
        "heads": [w((V, D)) for _ in range(K)],
        "final_ln": ln(),
        "layers": layers,
    }

    C = cfg.codebook_dim
    scaling = 2 ** len(cfg.upsampling_ratios)
    ch = scaling * cfg.num_filters
    dec: dict = {
        "codebooks": [w((V, C), 0.5) for _ in range(K)],
        "conv_in": (w((ch, C, cfg.kernel_size), 0.2), jnp.zeros(ch)),
        "lstm": [
            tuple(w(s, 0.15) for s in
                  ((4 * ch, ch), (4 * ch, ch), (4 * ch,), (4 * ch,)))
            for _ in range(cfg.num_lstm_layers)
        ],
    }
    ups = []
    for ratio in cfg.upsampling_ratios:
        nxt = ch // 2
        res = []
        hidden = nxt // cfg.compress
        for j in range(cfg.num_residual_layers):
            res.append({
                "c1": (w((hidden, nxt, cfg.residual_kernel_size), 0.2),
                       jnp.zeros(hidden)),
                "c2": (w((nxt, hidden, 1), 0.2), jnp.zeros(nxt)),
                "dilation": cfg.dilation_growth_rate ** j,
                "shortcut": (w((nxt, nxt, 1), 0.2), jnp.zeros(nxt)),
            })
        ups.append({"w": w((ch, nxt, ratio * 2), 0.2), "b": jnp.zeros(nxt),
                    "stride": ratio, "res": res})
        ch = nxt
    dec["ups"] = ups
    dec["conv_out"] = (w((1, cfg.num_filters, cfg.last_kernel_size), 0.3),
                       jnp.zeros(1))
    return lm, dec


class MusicGenerator:
    """Text-conditioned audio generation (SoundGeneration parity engine).

    Conditioning: UTF-8 bytes → learned byte embeddings + sinusoidal
    positions form the cross-attention memory (the debug-preset stand-in
    for MusicGen's T5 encoder; a loaded checkpoint can supply its own
    memory via ``generate(memory=...)``)."""

    def __init__(self, cfg: Optional[MusicgenConfig] = None, seed: int = 0):
        self.cfg = cfg or MusicgenConfig()
        key = jax.random.key(seed)
        self.lm, self.dec = init_params(key, self.cfg)
        self.text_embed = jax.random.normal(
            jax.random.key(seed + 1), (256, self.cfg.hidden_size),
            jnp.float32) * 0.3

    def text_memory(self, text: str,
                    max_len: int = 64) -> tuple[jnp.ndarray, jnp.ndarray]:
        """(memory [B, D], mask [B]) padded to a fixed bucket so every text
        length shares one compiled generation program."""
        ids = np.frombuffer(text.encode()[:max_len], np.uint8)
        if not len(ids):
            ids = np.zeros(1, np.uint8)
        padded = np.zeros(max_len, np.uint8)
        padded[: len(ids)] = ids
        mem = self.text_embed[jnp.asarray(padded)]
        mem = mem + sinusoidal_positions(max_len, self.cfg.hidden_size)
        return mem, jnp.arange(max_len) < len(ids)

    def generate(self, text: str, duration: float = 3.0,
                 temperature: float = 1.0,
                 memory: Optional[jnp.ndarray] = None) -> np.ndarray:
        cfg = self.cfg
        frames = int(min(max(duration, 0.25), 30.0) * cfg.frame_rate)
        # bucket frames so repeated durations reuse compiled programs
        bucket = 32
        while bucket < frames:
            bucket *= 2
        seed = int.from_bytes(
            hashlib.sha256(text.encode()).digest()[:4], "little")
        if memory is None:
            memory, mask = self.text_memory(text)
        else:
            mask = None
        codes = generate_codes(
            cfg, self.lm, memory,
            jax.random.key(seed), frames=bucket,
            temperature=max(float(temperature), 0.0),
            memory_mask=mask,
        )[:, :frames]
        audio = np.asarray(encodec_decode(cfg, self.dec, codes), np.float32)
        peak = np.abs(audio).max()
        return (audio / max(peak, 1e-6) * 0.7).astype(np.float32)
