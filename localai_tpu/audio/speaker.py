"""Speaker encoder + prosody extraction — the voice-cloning front end.

Parity target: the reference's voice-cloning audio path — vall-e-x's
``audio_path`` reference-voice config (/root/reference/core/config/
backend_config.go:19-26) and the openvoice backend
(/root/reference/backend/python/openvoice/backend.py), both of which turn a
reference recording into conditioning for synthesis.

Two conditioning signals are extracted from a reference waveform:

  * ``SpeakerEncoder.embed`` — an identity embedding from engineered
    voice features: voiced autocorrelation pitch profile + log-mel
    envelope statistics, seeded linear projection, L2-normalize. One
    jitted program over a fixed 3-s window; trained projection weights
    load via ``load``/npz. Distances in the embedding space separate
    voices (tests/test_voice_clone.py).
  * ``estimate_pitch`` — median F0 via frame autocorrelation, used by the
    parametric synthesizer to match the reference speaker's pitch when no
    neural voice checkpoint is loaded.

VITS conditioning: ``project`` maps the embedding onto a checkpoint's
``speaker_embedding_size`` axis with a deterministic orthogonal-ish
projection so any multi-speaker VITS checkpoint accepts cloned voices.
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.audio.mel import mel_filterbank

RATE = 16000
N_FFT = 400
HOP = 160
N_MELS = 40
WINDOW_S = 3.0                      # reference window (pad/truncate)
FRAMES = int(WINDOW_S * RATE) // HOP


def _frame_mels(audio: jnp.ndarray, filters: jnp.ndarray) -> jnp.ndarray:
    """audio [WINDOW samples] → log-mel [FRAMES, N_MELS]."""
    window = (0.5 * (1.0 - jnp.cos(
        2.0 * jnp.pi * jnp.arange(N_FFT) / N_FFT))).astype(jnp.float32)
    pad = N_FFT // 2
    x = jnp.pad(audio, (pad, pad), mode="reflect")
    idx = jnp.arange(FRAMES)[:, None] * HOP + jnp.arange(N_FFT)[None, :]
    frames = x[idx] * window[None, :]
    power = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** 2
    mel = power @ filters.T
    return jnp.log10(jnp.maximum(mel, 1e-10))


_AC_FRAME = 640   # 40 ms pitch-analysis frames
_AC_HOP = 320
_AC_LO = RATE // 400   # 60–400 Hz lag band
_AC_HI = RATE // 60


class SpeakerEncoder:
    """Reference waveform → L2-normalized identity embedding [dim].

    The frame features are engineered to be text-invariant and
    voice-discriminative WITHOUT training (an untrained conv/GRU stack
    collapses to content similarity — measured, not assumed): the voiced
    autocorrelation pitch profile over the 60–400 Hz lag band (harmonic
    spacing — the dominant speaker cue) concatenated with log-mel
    mean/std statistics (spectral envelope), then a seeded linear
    projection to ``dim``. ``load`` replaces the projection with trained
    weights when a real encoder checkpoint is available."""

    def __init__(self, dim: int = 192, seed: int = 0):
        self.dim = dim
        self.filters = jnp.asarray(
            mel_filterbank(n_mels=N_MELS, n_fft=N_FFT, rate=RATE)
        )
        feat = (_AC_HI - _AC_LO) + 2 * N_MELS
        self.params = {
            "proj": jax.random.normal(
                jax.random.key(seed), (dim, feat), jnp.float32
            ) / np.sqrt(feat),
        }
        self._embed = jax.jit(self._embed_fn)

    def load(self, path) -> None:
        """Load trained projection weights (npz with key 'proj')."""
        with np.load(path) as z:
            self.params = {k: jnp.asarray(z[k]) for k in z.files}

    def _embed_fn(self, audio, length):
        # --- pitch profile: voiced-frame mean autocorrelation band ------
        n_ac = (audio.shape[0] - _AC_FRAME) // _AC_HOP
        idx = (jnp.arange(n_ac)[:, None] * _AC_HOP
               + jnp.arange(_AC_FRAME)[None, :])
        frames = audio[idx]
        frames = frames - frames.mean(axis=1, keepdims=True)
        spec = jnp.fft.rfft(frames, n=2 * _AC_FRAME, axis=1)
        ac = jnp.fft.irfft(spec * jnp.conj(spec), axis=1)[:, :_AC_FRAME]
        ac = ac / jnp.maximum(ac[:, :1], 1e-8)
        band = ac[:, _AC_LO:_AC_HI]                    # [n_ac, lags]
        in_range = (jnp.arange(n_ac) * _AC_HOP + _AC_FRAME) <= length
        voiced = (band.max(axis=1) > 0.3) & in_range
        w = voiced[:, None].astype(jnp.float32)
        profile = (band * w).sum(0) / jnp.maximum(w.sum(), 1.0)
        profile = profile / jnp.maximum(jnp.linalg.norm(profile), 1e-8)

        # --- spectral envelope statistics -------------------------------
        mels = _frame_mels(audio, self.filters)        # [FRAMES, M]
        n_frames = jnp.minimum(length // HOP + 1, FRAMES)
        valid = (jnp.arange(FRAMES) < n_frames)[:, None].astype(jnp.float32)
        denom = jnp.maximum(valid.sum(), 1.0)
        mean = (mels * valid).sum(0) / denom
        var = ((mels - mean) ** 2 * valid).sum(0) / denom
        stats = jnp.concatenate([mean, jnp.sqrt(var + 1e-8)])
        stats = stats / jnp.maximum(jnp.linalg.norm(stats), 1e-8)

        # pitch dominates (it is the stronger untrained cue)
        feats = jnp.concatenate([2.0 * profile, stats])
        emb = self.params["proj"] @ feats
        return emb / jnp.maximum(jnp.linalg.norm(emb), 1e-8)

    def embed(self, audio: np.ndarray) -> np.ndarray:
        """audio float32 @16 kHz (any length) → [dim] unit vector."""
        n = int(WINDOW_S * RATE)
        buf = np.zeros(n, np.float32)
        a = np.asarray(audio, np.float32)[:n]
        buf[: len(a)] = a
        return np.asarray(
            self._embed(jnp.asarray(buf), jnp.int32(min(len(a), n)))
        )

    def project(self, emb: np.ndarray, size: int) -> np.ndarray:
        """Map [dim] → [size] with a fixed seeded projection (so any
        multi-speaker VITS checkpoint accepts cloned embeddings)."""
        if size == self.dim:
            return emb
        proj = np.asarray(jax.random.normal(
            jax.random.key(1234), (size, self.dim)) / np.sqrt(self.dim))
        out = proj @ emb
        return (out / max(np.linalg.norm(out), 1e-8)).astype(np.float32)


@partial(jax.jit, static_argnames=())
def _autocorr_pitch(audio: jnp.ndarray) -> jnp.ndarray:
    """Median frame F0 (Hz) over voiced frames via autocorrelation."""
    frame_len = 640  # 40 ms
    hop = 320
    n_frames = (audio.shape[0] - frame_len) // hop
    idx = jnp.arange(n_frames)[:, None] * hop + jnp.arange(frame_len)[None, :]
    frames = audio[idx]
    frames = frames - frames.mean(axis=1, keepdims=True)
    # autocorrelation via FFT
    spec = jnp.fft.rfft(frames, n=2 * frame_len, axis=1)
    ac = jnp.fft.irfft(spec * jnp.conj(spec), axis=1)[:, :frame_len]
    ac = ac / jnp.maximum(ac[:, :1], 1e-8)
    lo, hi = RATE // 400, RATE // 60        # 60–400 Hz band
    band = ac[:, lo:hi]
    lag = jnp.argmax(band, axis=1) + lo
    strength = jnp.max(band, axis=1)
    f0 = RATE / lag
    voiced = strength > 0.3
    # median over voiced frames (fall back to 140 Hz when none)
    f0_sorted = jnp.sort(jnp.where(voiced, f0, jnp.nan))  # NaNs sort last
    count = voiced.sum()
    med = f0_sorted[jnp.maximum((count - 1) // 2, 0)]
    return jnp.where(count > 0, med, 140.0)


def estimate_pitch(audio: np.ndarray) -> float:
    """Median F0 (Hz) of a reference recording (60–400 Hz band)."""
    a = np.asarray(audio, np.float32)
    if len(a) < 1600:
        return 140.0
    buf = np.zeros(RATE * 10, np.float32)  # fixed shape → one compile
    a = a[: RATE * 10]
    buf[: len(a)] = a
    return float(_autocorr_pitch(jnp.asarray(buf)))


_encoder = None
_encoder_lock = threading.Lock()


def get_speaker_encoder() -> SpeakerEncoder:
    """Process-wide encoder (weights are deterministic by seed, so all
    callers agree on the embedding space)."""
    global _encoder
    if _encoder is None:
        with _encoder_lock:
            if _encoder is None:
                _encoder = SpeakerEncoder()
    return _encoder
