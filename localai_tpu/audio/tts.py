"""Built-in parametric TTS + sound-generation engine, jitted.

Role parity: the reference's TTS tier (go-piper cgo backend,
/root/reference/backend/go/tts/piper.go:20-49, plus the Python TTS
backends) behind the TTS/SoundGeneration RPCs and /v1/audio/speech,
/tts, Elevenlabs routes. Piper-class neural voices are external models;
this built-in engine is the zero-download path: a deterministic formant
synthesizer (phoneme-ish classes → pitch/formant/duration tracks →
harmonic + noise bank) producing intelligible-cadence speech audio
entirely as vectorized JAX ops. Neural voices are served by the VITS
engine (localai_tpu.audio.vits — piper's architecture, loading HF
VitsModel checkpoints); this module remains the fallback for models
without a vits checkpoint.

The synthesis is one jitted program over fixed-size frame tracks, so a
request costs one device dispatch.
"""

from __future__ import annotations

import hashlib
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

RATE = 16000
FRAME = 160                      # 10 ms frames
MAX_FRAMES = 3000                # 30 s ceiling per request

_VOWELS = {
    # vowel → (F1, F2) formant pair (rough adult averages, Hz)
    "a": (800, 1200), "e": (500, 1900), "i": (320, 2300),
    "o": (500, 900), "u": (330, 800), "y": (300, 2100),
}
_VOICED = set("bdgjlmnrvwz")
_SIBILANT = set("szcfxh")


def _char_params(ch: str) -> tuple[float, float, float, int]:
    """char → (f1, f2, noise_mix, frames)."""
    c = ch.lower()
    if c in _VOWELS:
        f1, f2 = _VOWELS[c]
        return f1, f2, 0.05, 9
    if c in _SIBILANT:
        return 2500.0, 4000.0, 0.95, 6
    if c in _VOICED:
        return 300.0, 1400.0, 0.35, 6
    if c.isalpha() or c.isdigit():
        return 400.0, 1800.0, 0.6, 5
    if c in ".,;:!?":
        return 0.0, 0.0, 0.0, 12   # pause
    return 0.0, 0.0, 0.0, 6        # space/other → short pause


def _voice_seed(voice: str) -> tuple[float, float]:
    """voice name → (base pitch Hz, vibrato rate) — distinct, stable."""
    h = int.from_bytes(hashlib.sha256(voice.encode()).digest()[:4], "little")
    pitch = 95.0 + (h % 120)            # 95–215 Hz
    vib = 4.0 + (h >> 8) % 4
    return pitch, float(vib)


@partial(jax.jit, static_argnames=("n_frames",))
def _synth(f1_track, f2_track, noise_track, voiced_track, pitch_track,
           key, n_frames: int):
    """Frame tracks [n_frames] → audio [n_frames * FRAME]."""
    n = n_frames * FRAME
    t = jnp.arange(n) / RATE
    up = lambda tr: jnp.repeat(tr, FRAME)  # noqa: E731

    pitch = up(pitch_track)
    phase = jnp.cumsum(pitch) / RATE * 2 * jnp.pi
    # harmonic source: fundamental + 2 overtones, formant-weighted
    f1 = up(f1_track)
    f2 = up(f2_track)
    src = (jnp.sin(phase)
           + 0.5 * jnp.sin(2 * phase)
           + 0.25 * jnp.sin(3 * phase))
    # crude formant colouring: ring-modulate toward the formant bands
    form = (jnp.sin(2 * jnp.pi * f1 * t) * 0.6
            + jnp.sin(2 * jnp.pi * f2 * t) * 0.4)
    voiced = src * (0.65 + 0.35 * form)
    noise = jax.random.normal(key, (n,))
    mix = up(noise_track)
    amp = up(voiced_track)
    audio = amp * ((1 - mix) * voiced + mix * 0.5 * noise)
    # 5-ms attack/decay per frame boundary smoothing via moving average
    kernel = jnp.ones(81) / 81
    audio = jnp.convolve(audio, kernel, mode="same")
    peak = jnp.max(jnp.abs(audio))
    return audio / jnp.maximum(peak, 1e-6) * 0.7


def synthesize(text: str, voice: str = "alloy",
               speed: float = 1.0,
               ref_audio: "np.ndarray | None" = None) -> np.ndarray:
    """text → mono float32 speech-like audio at 16 kHz.

    ``ref_audio`` is the parametric voice-cloning path (vall-e-x
    audio_path parity): the synthesized voice takes its pitch from the
    reference recording (audio.speaker.estimate_pitch) instead of the
    name-hash, so output prosody tracks the reference speaker."""
    pitch0, vib = _voice_seed(voice or "alloy")
    if ref_audio is not None and len(ref_audio):
        from localai_tpu.audio.speaker import estimate_pitch

        pitch0 = estimate_pitch(ref_audio)
    f1s, f2s, mixes, amps, pitches = [], [], [], [], []
    for i, ch in enumerate(text[:2000]):
        f1, f2, mix, frames = _char_params(ch)
        frames = max(1, int(round(frames / max(speed, 0.25))))
        silent = f1 == 0.0
        for j in range(frames):
            f1s.append(f1)
            f2s.append(f2)
            mixes.append(mix)
            amps.append(0.0 if silent else 1.0)
            # gentle declination + per-char vibrato gives sentence cadence
            frac = i / max(len(text), 1)
            pitches.append(pitch0 * (1.12 - 0.18 * frac)
                           + vib * np.sin(0.7 * i + j))
    if not f1s:
        f1s, f2s, mixes, amps, pitches = [0], [0], [0], [0], [pitch0]
    n_frames = min(len(f1s), MAX_FRAMES)
    # pad to power-of-two frame buckets so varying text lengths reuse a
    # handful of compiled programs (amps are 0 in the padding → silence)
    bucket = 64
    while bucket < n_frames:
        bucket *= 2
    bucket = min(bucket, MAX_FRAMES)

    def pad(xs):
        arr = np.zeros(bucket, np.float32)
        arr[:len(xs[:n_frames])] = xs[:n_frames]
        return jnp.asarray(arr)

    key = jax.random.key(
        int.from_bytes(hashlib.sha256(
            (voice + text).encode()).digest()[:4], "little")
    )
    audio = _synth(pad(f1s), pad(f2s), pad(mixes), pad(amps), pad(pitches),
                   key, bucket)
    return np.asarray(audio, np.float32)[:n_frames * FRAME]


_music_gen = None
_music_gen_lock = threading.Lock()


def generate_sound(text: str, duration: float = 3.0,
                   temperature: float = 1.0) -> np.ndarray:
    """Model-generated text-conditioned audio (SoundGeneration RPC parity —
    the reference fans out to transformers-musicgen). Runs the MusicGen-class
    codebook LM + EnCodec decoder (audio.musicgen, torch-verified); the
    debug-preset weights are the zero-download default, real checkpoints
    load through the same adapters."""
    global _music_gen
    if _music_gen is None:
        with _music_gen_lock:
            if _music_gen is None:
                from localai_tpu.audio.musicgen import MusicGenerator

                _music_gen = MusicGenerator()
    return _music_gen.generate(text, duration, temperature)
