"""Audio: wav I/O, log-mel frontend, transcription engine glue, TTS.

Parity: the reference's audio tier — whisper.cpp transcription
(/root/reference/backend/go/transcribe/whisper/), piper TTS
(backend/go/tts/), musicgen sound generation (backend/python/
transformers-musicgen) — rebuilt as JAX models + jitted DSP.
"""

from localai_tpu.audio.wav import read_wav, write_wav
