"""VITS text-to-speech in functional JAX: the neural voice path.

Parity: the reference's piper TTS backend (/root/reference/backend/go/tts
— piper runs VITS-architecture voices) and the coqui/parler neural-TTS
python backends. This implements VITS inference — text encoder with
windowed relative attention, stochastic/deterministic duration predictor
(rational-quadratic-spline conv flows), residual-coupling flow, and the
HiFi-GAN decoder — natively in JAX, loading HuggingFace `VitsModel`
checkpoints (model_type "vits": facebook/mms-tts-*, kakao-enterprise
vits variants). Numerics mirror transformers' torch implementation
layer-for-layer (verified in tests/test_vits.py against torch on random
tiny checkpoints); weight-normed convs are fused at load.

TPU notes: synthesis is one batched pass dominated by the HiFi-GAN
transposed convs — MXU-friendly dense convs, all stax-free functional
code. Shapes depend on text length and predicted durations, so the
forward runs eagerly (one synthesis ≈ one dispatch chain); bucketing
would only matter for high-QPS TTS serving.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class VitsConfig:
    vocab_size: int = 38
    hidden_size: int = 192
    num_layers: int = 6
    num_heads: int = 2
    window_size: int = 4
    use_bias: bool = True
    ffn_dim: int = 768
    ffn_kernel_size: int = 3
    layer_norm_eps: float = 1e-5
    flow_size: int = 192
    spectrogram_bins: int = 513
    prior_encoder_num_flows: int = 4
    prior_encoder_num_wavenet_layers: int = 4
    wavenet_kernel_size: int = 5
    wavenet_dilation_rate: int = 1
    use_stochastic_duration_prediction: bool = True
    duration_predictor_num_flows: int = 4
    duration_predictor_kernel_size: int = 3
    duration_predictor_filter_channels: int = 256
    duration_predictor_flow_bins: int = 10
    duration_predictor_tail_bound: float = 5.0
    depth_separable_channels: int = 2
    depth_separable_num_layers: int = 3
    upsample_initial_channel: int = 512
    upsample_rates: tuple = (8, 8, 2, 2)
    upsample_kernel_sizes: tuple = (16, 16, 4, 4)
    resblock_kernel_sizes: tuple = (3, 7, 11)
    resblock_dilation_sizes: tuple = ((1, 3, 5), (1, 3, 5), (1, 3, 5))
    leaky_relu_slope: float = 0.1
    num_speakers: int = 1
    speaker_embedding_size: int = 0
    sampling_rate: int = 16000
    speaking_rate: float = 1.0
    noise_scale: float = 0.667
    noise_scale_duration: float = 0.8
    pad_token_id: int = 0
    add_blank: bool = True

    @classmethod
    def from_hf(cls, hf: dict) -> "VitsConfig":
        aliases = {"num_layers": "num_hidden_layers",
                   "num_heads": "num_attention_heads"}
        kw = {}
        for f in dataclasses.fields(cls):
            src = aliases.get(f.name, f.name)
            if src in hf:
                v = hf[src]
                if isinstance(v, list):
                    v = tuple(tuple(x) if isinstance(x, list) else x
                              for x in v)
                kw[f.name] = v
        return cls(**kw)


# ---------------------------------------------------------------------------
# primitives (all tensors [B, C, L] to mirror the torch layouts 1:1)


def conv1d(x, w, b=None, *, stride=1, dilation=1, padding=0, groups=1):
    """torch.nn.Conv1d semantics: x [B,C,L], w [O,I/g,k]."""
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(padding, padding)],
        rhs_dilation=(dilation,), feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if b is not None:
        out = out + b[None, :, None]
    return out


def conv_transpose1d(x, w, b=None, *, stride=1, padding=0):
    """torch.nn.ConvTranspose1d semantics: w [I,O,k].

    Expressed as the equivalent fractionally-strided conv — dilate the
    input by `stride`, run a regular conv with the spatially-flipped,
    in/out-swapped kernel and padding k-1-p. Output length matches
    torch's (L-1)*stride - 2p + k exactly."""
    k = w.shape[-1]
    w_conv = jnp.flip(w, axis=-1).transpose(1, 0, 2)  # [O,I,k]
    out = jax.lax.conv_general_dilated(
        x, w_conv, window_strides=(1,),
        padding=[(k - 1 - padding, k - 1 - padding)],
        lhs_dilation=(stride,),
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if b is not None:
        out = out + b[None, :, None]
    return out


def layer_norm_cl(x, g, b, eps):
    """LayerNorm over the channel dim of [B,C,L] (torch transposes to
    channels-last; normalizing axis 1 directly is equivalent)."""
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.var(x, axis=1, keepdims=True)
    return ((x - mu) * jax.lax.rsqrt(var + eps)) * g[None, :, None] \
        + b[None, :, None]


def leaky_relu(x, slope):
    return jnp.where(x >= 0, x, x * slope)


class _P:
    """Flat HF-named tensor dict with weight-norm fusion on read."""

    def __init__(self, tensors: dict[str, np.ndarray]):
        self.t = tensors

    def __contains__(self, k):
        return (k in self.t or f"{k}_g" in self.t
                or f"{k.rsplit('.', 1)[0]}.parametrizations.weight."
                    "original0" in self.t)

    def get(self, name: str) -> jnp.ndarray:
        if name in self.t:
            return jnp.asarray(self.t[name])
        # weight-norm storage: weight_g/weight_v or parametrizations
        if name.endswith(".weight"):
            base = name[: -len(".weight")]
            pairs = (
                (f"{base}.weight_g", f"{base}.weight_v"),
                (f"{base}.parametrizations.weight.original0",
                 f"{base}.parametrizations.weight.original1"),
            )
            for gk, vk in pairs:
                if gk in self.t:
                    g = np.asarray(self.t[gk], np.float32)
                    v = np.asarray(self.t[vk], np.float32)
                    norm = np.sqrt(
                        (v ** 2).sum(axis=tuple(range(1, v.ndim)),
                                     keepdims=True)
                    )
                    return jnp.asarray(g * v / np.maximum(norm, 1e-12))
        raise KeyError(name)

    def opt(self, name: str):
        try:
            return self.get(name)
        except KeyError:
            return None


# ---------------------------------------------------------------------------
# text encoder (relative-position attention — VitsAttention parity)


def _relative_embeddings(rel, window, length):
    pad = max(length - (window + 1), 0)
    if pad > 0:
        rel = jnp.pad(rel, ((0, 0), (pad, pad), (0, 0)))
    start = max((window + 1) - length, 0)
    return rel[:, start: start + 2 * length - 1]


def _rel_to_abs(x):
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    xf = x.reshape(bh, length * 2 * length)
    xf = jnp.pad(xf, ((0, 0), (0, length - 1)))
    return xf.reshape(bh, length + 1, 2 * length - 1)[:, :length,
                                                      length - 1:]


def _abs_to_rel(x):
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, length - 1)))
    xf = x.reshape(bh, length * (2 * length - 1))
    xf = jnp.pad(xf, ((0, 0), (length, 0)))
    return xf.reshape(bh, length, 2 * length)[:, :, 1:]


def _attention(p: _P, pre: str, cfg: VitsConfig, x, attn_mask):
    """x [B,L,H] → [B,L,H] (channels-last like the torch module)."""
    B, L, H = x.shape
    nh = cfg.num_heads
    hd = H // nh
    scale = hd ** -0.5

    def proj(name):
        w = p.get(f"{pre}.{name}.weight")
        out = x @ w.T
        b = p.opt(f"{pre}.{name}.bias")
        return out + b if b is not None else out

    q = (proj("q_proj") * scale).reshape(B, L, nh, hd).transpose(
        0, 2, 1, 3).reshape(B * nh, L, hd)
    k = proj("k_proj").reshape(B, L, nh, hd).transpose(
        0, 2, 1, 3).reshape(B * nh, L, hd)
    v = proj("v_proj").reshape(B, L, nh, hd).transpose(
        0, 2, 1, 3).reshape(B * nh, L, hd)
    weights = q @ k.transpose(0, 2, 1)
    if cfg.window_size:
        rel_k = _relative_embeddings(
            p.get(f"{pre}.emb_rel_k"), cfg.window_size, L)
        weights = weights + _rel_to_abs(q @ rel_k.transpose(0, 2, 1))
    if attn_mask is not None:
        weights = jnp.where(
            attn_mask.reshape(1, 1, 1, L), weights.reshape(B, nh, L, L),
            -1e9,
        ).reshape(B * nh, L, L)
    probs = jax.nn.softmax(weights, axis=-1)
    out = probs @ v
    if cfg.window_size:
        rel_v = _relative_embeddings(
            p.get(f"{pre}.emb_rel_v"), cfg.window_size, L)
        out = out + _abs_to_rel(probs) @ rel_v
    out = out.reshape(B, nh, L, hd).transpose(0, 2, 1, 3).reshape(B, L, H)
    w_o = p.get(f"{pre}.out_proj.weight")
    out = out @ w_o.T
    b_o = p.opt(f"{pre}.out_proj.bias")
    return out + b_o if b_o is not None else out


def _ln_last(x, g, b, eps):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _feed_forward(p: _P, pre: str, cfg: VitsConfig, x, pad_cl):
    """x [B,L,H]; pad_cl [B,L,1] — VitsFeedForward parity (asymmetric
    conv padding)."""
    h = (x * pad_cl).transpose(0, 2, 1)
    mask = pad_cl.transpose(0, 2, 1)
    k = cfg.ffn_kernel_size
    if k > 1:
        h = jnp.pad(h, ((0, 0), (0, 0), ((k - 1) // 2, k // 2)))
    h = conv1d(h, p.get(f"{pre}.conv_1.weight"),
               p.get(f"{pre}.conv_1.bias"))
    h = jax.nn.relu(h)
    h = h * mask
    if k > 1:
        h = jnp.pad(h, ((0, 0), (0, 0), ((k - 1) // 2, k // 2)))
    h = conv1d(h, p.get(f"{pre}.conv_2.weight"),
               p.get(f"{pre}.conv_2.bias"))
    return (h * mask).transpose(0, 2, 1)


def text_encoder(p: _P, cfg: VitsConfig, ids, pad_mask):
    """ids [B,L]; pad_mask [B,L] → (hidden [B,H,L], m_p, logs_p [B,F,L])."""
    x = jnp.take(p.get("text_encoder.embed_tokens.weight"), ids, axis=0)
    x = x * math.sqrt(cfg.hidden_size)
    pad_cl = pad_mask[:, :, None].astype(x.dtype)
    x = x * pad_cl
    for i in range(cfg.num_layers):
        pre = f"text_encoder.encoder.layers.{i}"
        attn = _attention(p, f"{pre}.attention", cfg, x, pad_mask)
        x = _ln_last(x + attn, p.get(f"{pre}.layer_norm.weight"),
                     p.get(f"{pre}.layer_norm.bias"), cfg.layer_norm_eps)
        ff = _feed_forward(p, f"{pre}.feed_forward", cfg, x, pad_cl)
        x = _ln_last(x + ff, p.get(f"{pre}.final_layer_norm.weight"),
                     p.get(f"{pre}.final_layer_norm.bias"),
                     cfg.layer_norm_eps)
    x = x * pad_cl
    stats = conv1d(x.transpose(0, 2, 1),
                   p.get("text_encoder.project.weight"),
                   p.get("text_encoder.project.bias"))
    stats = stats * pad_cl.transpose(0, 2, 1)
    m_p, logs_p = jnp.split(stats, 2, axis=1)
    return x.transpose(0, 2, 1), m_p, logs_p


# ---------------------------------------------------------------------------
# WaveNet + residual coupling flow (reverse only — inference)


def _wavenet(p: _P, pre: str, cfg: VitsConfig, x, pad, num_layers,
             cond=None):
    """VitsWaveNet parity: x [B,H,L]."""
    H = cfg.hidden_size
    if cond is not None:
        cond = conv1d(cond, p.get(f"{pre}.cond_layer.weight"),
                      p.get(f"{pre}.cond_layer.bias"))
    outputs = jnp.zeros_like(x)
    for i in range(num_layers):
        dilation = cfg.wavenet_dilation_rate ** i
        padding = (cfg.wavenet_kernel_size * dilation - dilation) // 2
        h = conv1d(x, p.get(f"{pre}.in_layers.{i}.weight"),
                   p.get(f"{pre}.in_layers.{i}.bias"),
                   dilation=dilation, padding=padding)
        if cond is not None:
            off = i * 2 * H
            h = h + cond[:, off: off + 2 * H]
        acts = jnp.tanh(h[:, :H]) * jax.nn.sigmoid(h[:, H:])
        rs = conv1d(acts, p.get(f"{pre}.res_skip_layers.{i}.weight"),
                    p.get(f"{pre}.res_skip_layers.{i}.bias"))
        if i < num_layers - 1:
            x = (x + rs[:, :H]) * pad
            outputs = outputs + rs[:, H:]
        else:
            outputs = outputs + rs
    return outputs * pad


def flow_reverse(p: _P, cfg: VitsConfig, z, pad, cond=None):
    """VitsResidualCouplingBlock reverse (inference direction)."""
    half = cfg.flow_size // 2
    for i in reversed(range(cfg.prior_encoder_num_flows)):
        z = jnp.flip(z, axis=1)
        pre = f"flow.flows.{i}"
        first, second = z[:, :half], z[:, half:]
        h = conv1d(first, p.get(f"{pre}.conv_pre.weight"),
                   p.get(f"{pre}.conv_pre.bias")) * pad
        h = _wavenet(p, f"{pre}.wavenet", cfg, h, pad,
                     cfg.prior_encoder_num_wavenet_layers, cond)
        mean = conv1d(h, p.get(f"{pre}.conv_post.weight"),
                      p.get(f"{pre}.conv_post.bias")) * pad
        second = (second - mean) * pad
        z = jnp.concatenate([first, second], axis=1)
    return z


# ---------------------------------------------------------------------------
# duration predictors


def _dds(p: _P, pre: str, cfg: VitsConfig, x, pad, cond=None):
    """VitsDilatedDepthSeparableConv parity."""
    if cond is not None:
        x = x + cond
    k = cfg.duration_predictor_kernel_size
    for i in range(cfg.depth_separable_num_layers):
        dilation = k ** i
        padding = (k * dilation - dilation) // 2
        h = conv1d(x * pad, p.get(f"{pre}.convs_dilated.{i}.weight"),
                   p.get(f"{pre}.convs_dilated.{i}.bias"),
                   dilation=dilation, padding=padding,
                   groups=x.shape[1])
        h = layer_norm_cl(h, p.get(f"{pre}.norms_1.{i}.weight"),
                          p.get(f"{pre}.norms_1.{i}.bias"),
                          cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        h = conv1d(h, p.get(f"{pre}.convs_pointwise.{i}.weight"),
                   p.get(f"{pre}.convs_pointwise.{i}.bias"))
        h = layer_norm_cl(h, p.get(f"{pre}.norms_2.{i}.weight"),
                          p.get(f"{pre}.norms_2.{i}.bias"),
                          cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        x = x + h
    return x * pad


def _rq_spline_reverse(inputs, uw, uh, ud, tail_bound):
    """_unconstrained_rational_quadratic_spline (reverse) — vectorized
    with masking instead of boolean indexing."""
    min_bin_width = min_bin_height = min_derivative = 1e-3
    inside = (inputs >= -tail_bound) & (inputs <= tail_bound)
    num_bins = uw.shape[-1]
    constant = math.log(math.exp(1 - min_derivative) - 1)
    ud = jnp.pad(ud, [(0, 0)] * (ud.ndim - 1) + [(1, 1)],
                 constant_values=constant)

    widths = jax.nn.softmax(uw, axis=-1)
    widths = min_bin_width + (1 - min_bin_width * num_bins) * widths
    cumw = jnp.cumsum(widths, -1)
    cumw = jnp.pad(cumw, [(0, 0)] * (cumw.ndim - 1) + [(1, 0)])
    cumw = 2 * tail_bound * cumw - tail_bound
    cumw = cumw.at[..., 0].set(-tail_bound)
    cumw = cumw.at[..., -1].set(tail_bound)
    widths = cumw[..., 1:] - cumw[..., :-1]

    derivs = min_derivative + jax.nn.softplus(ud)

    heights = jax.nn.softmax(uh, axis=-1)
    heights = min_bin_height + (1 - min_bin_height * num_bins) * heights
    cumh = jnp.cumsum(heights, -1)
    cumh = jnp.pad(cumh, [(0, 0)] * (cumh.ndim - 1) + [(1, 0)])
    cumh = 2 * tail_bound * cumh - tail_bound
    cumh = cumh.at[..., 0].set(-tail_bound)
    cumh = cumh.at[..., -1].set(tail_bound)
    heights = cumh[..., 1:] - cumh[..., :-1]

    # reverse mode bins locate on the height axis
    locs = cumh.at[..., -1].add(1e-6)
    safe_in = jnp.clip(inputs, -tail_bound, tail_bound)
    bin_idx = jnp.sum(
        (safe_in[..., None] >= locs).astype(jnp.int32), axis=-1
    ) - 1
    bin_idx = jnp.clip(bin_idx, 0, num_bins - 1)[..., None]

    def g(a):
        return jnp.take_along_axis(a, bin_idx, axis=-1)[..., 0]

    in_cumw = g(cumw)
    in_w = g(widths)
    in_cumh = g(cumh)
    delta = heights / widths
    in_delta = g(delta)
    in_d = g(derivs)
    in_d1 = g(derivs[..., 1:])
    in_h = g(heights)

    i1 = in_d + in_d1 - 2 * in_delta
    i2 = safe_in - in_cumh
    i3 = i2 * i1
    a = in_h * (in_delta - in_d) + i3
    b = in_h * in_d - i3
    c = -in_delta * i2
    disc = b ** 2 - 4 * a * c
    root = (2 * c) / (-b - jnp.sqrt(jnp.maximum(disc, 0.0)))
    outputs = root * in_w + in_cumw
    return jnp.where(inside, outputs, inputs)


def _conv_flow_reverse(p: _P, pre: str, cfg: VitsConfig, z, pad, cond):
    half = cfg.depth_separable_channels // 2
    first, second = z[:, :half], z[:, half:]
    h = conv1d(first, p.get(f"{pre}.conv_pre.weight"),
               p.get(f"{pre}.conv_pre.bias"))
    h = _dds(p, f"{pre}.conv_dds", cfg, h, pad, cond)
    h = conv1d(h, p.get(f"{pre}.conv_proj.weight"),
               p.get(f"{pre}.conv_proj.bias")) * pad
    B, C, L = first.shape
    nb = cfg.duration_predictor_flow_bins
    h = h.reshape(B, C, -1, L).transpose(0, 1, 3, 2)
    scale = math.sqrt(cfg.hidden_size)
    second = _rq_spline_reverse(
        second, h[..., :nb] / scale, h[..., nb: 2 * nb] / scale,
        h[..., 2 * nb:], cfg.duration_predictor_tail_bound,
    )
    return jnp.concatenate([first, second], axis=1) * pad


def stochastic_duration_reverse(p: _P, cfg: VitsConfig, x, pad,
                                noise, cond=None):
    """VitsStochasticDurationPredictor reverse → log durations [B,1,L].
    ``noise`` is the [B,2,L] latent draw (0 → deterministic)."""
    pre = "duration_predictor"
    x = conv1d(x, p.get(f"{pre}.conv_pre.weight"),
               p.get(f"{pre}.conv_pre.bias"))
    if cond is not None:
        x = x + conv1d(cond, p.get(f"{pre}.cond.weight"),
                       p.get(f"{pre}.cond.bias"))
    x = _dds(p, f"{pre}.conv_dds", cfg, x, pad)
    x = conv1d(x, p.get(f"{pre}.conv_proj.weight"),
               p.get(f"{pre}.conv_proj.bias")) * pad

    # flows reversed, dropping the "useless vflow" (modeling_vits.py:792)
    n = cfg.duration_predictor_num_flows
    latents = noise
    # order: flows[n] .. flows[2], then flows[0] (ElementwiseAffine)
    for idx in list(range(n, 1, -1)) + [0]:
        latents = jnp.flip(latents, axis=1)
        fp = f"{pre}.flows.{idx}"
        if idx == 0:
            tr = p.get(f"{fp}.translate")
            ls = p.get(f"{fp}.log_scale")
            latents = (latents - tr[None]) * jnp.exp(-ls[None]) * pad
        else:
            latents = _conv_flow_reverse(p, fp, cfg, latents, pad, x)
    log_duration = latents[:, :1]
    return log_duration


def duration_predictor(p: _P, cfg: VitsConfig, x, pad, cond=None):
    """Deterministic VitsDurationPredictor → log durations [B,1,L]."""
    pre = "duration_predictor"
    if cond is not None:
        x = x + conv1d(cond, p.get(f"{pre}.cond.weight"),
                       p.get(f"{pre}.cond.bias"))
    k = cfg.duration_predictor_kernel_size
    h = conv1d(x * pad, p.get(f"{pre}.conv_1.weight"),
               p.get(f"{pre}.conv_1.bias"), padding=k // 2)
    h = layer_norm_cl(jax.nn.relu(h), p.get(f"{pre}.norm_1.weight"),
                      p.get(f"{pre}.norm_1.bias"), cfg.layer_norm_eps)
    h = conv1d(h * pad, p.get(f"{pre}.conv_2.weight"),
               p.get(f"{pre}.conv_2.bias"), padding=k // 2)
    h = layer_norm_cl(jax.nn.relu(h), p.get(f"{pre}.norm_2.weight"),
                      p.get(f"{pre}.norm_2.bias"), cfg.layer_norm_eps)
    return conv1d(h * pad, p.get(f"{pre}.proj.weight"),
                  p.get(f"{pre}.proj.bias")) * pad


# ---------------------------------------------------------------------------
# HiFi-GAN decoder


def hifigan(p: _P, cfg: VitsConfig, spec, cond=None):
    """spec [B,F,L] → waveform [B, L*prod(upsample_rates)]."""
    x = conv1d(spec, p.get("decoder.conv_pre.weight"),
               p.get("decoder.conv_pre.bias"), padding=3)
    if cond is not None:
        x = x + conv1d(cond, p.get("decoder.cond.weight"),
                       p.get("decoder.cond.bias"))
    nk = len(cfg.resblock_kernel_sizes)
    for i, (rate, k) in enumerate(zip(cfg.upsample_rates,
                                      cfg.upsample_kernel_sizes)):
        x = leaky_relu(x, cfg.leaky_relu_slope)
        x = conv_transpose1d(
            x, p.get(f"decoder.upsampler.{i}.weight"),
            p.get(f"decoder.upsampler.{i}.bias"),
            stride=rate, padding=(k - rate) // 2,
        )
        acc = None
        for j in range(nk):
            rb = f"decoder.resblocks.{i * nk + j}"
            ks = cfg.resblock_kernel_sizes[j]
            h = x
            for ci, dil in enumerate(cfg.resblock_dilation_sizes[j]):
                r = leaky_relu(h, cfg.leaky_relu_slope)
                r = conv1d(r, p.get(f"{rb}.convs1.{ci}.weight"),
                           p.get(f"{rb}.convs1.{ci}.bias"),
                           dilation=dil,
                           padding=(ks * dil - dil) // 2)
                r = leaky_relu(r, cfg.leaky_relu_slope)
                r = conv1d(r, p.get(f"{rb}.convs2.{ci}.weight"),
                           p.get(f"{rb}.convs2.{ci}.bias"),
                           padding=(ks - 1) // 2)
                h = h + r
            acc = h if acc is None else acc + h
        x = acc / nk
    x = leaky_relu(x, 0.01)  # torch F.leaky_relu default slope
    x = conv1d(x, p.get("decoder.conv_post.weight"), padding=3)
    return jnp.tanh(x)[:, 0]


# ---------------------------------------------------------------------------
# tokenizer + model


class VitsCharTokenizer:
    """HF VitsTokenizer behavior: char → id via vocab.json, optional
    lowercasing and blank interspersal (tokenizer_config.json)."""

    def __init__(self, model_dir: Path):
        self.vocab = json.loads(
            (model_dir / "vocab.json").read_text()
        )
        tc = {}
        tc_path = model_dir / "tokenizer_config.json"
        if tc_path.exists():
            tc = json.loads(tc_path.read_text())
        self.do_lower = tc.get("do_lower_case", True)
        self.add_blank = tc.get("add_blank", True)
        self.pad_id = self.vocab.get(tc.get("pad_token", "<pad>"), 0)

    def encode(self, text: str) -> list[int]:
        if self.do_lower:
            text = text.lower()
        ids = [self.vocab[ch] for ch in text if ch in self.vocab]
        if not ids:
            ids = [self.pad_id]
        if self.add_blank:
            out = [self.pad_id] * (2 * len(ids) + 1)
            out[1::2] = ids
            return out
        return ids


class VitsTTS:
    """One loaded VITS voice: text → waveform."""

    def __init__(self, cfg: VitsConfig, params: _P, tokenizer: Any):
        self.cfg = cfg
        self.p = params
        self.tokenizer = tokenizer

    def synthesize(self, text: str, *, speaker_id: Optional[int] = None,
                   speaker_embedding: Optional[np.ndarray] = None,
                   noise_scale: Optional[float] = None,
                   noise_scale_duration: Optional[float] = None,
                   speaking_rate: Optional[float] = None,
                   seed: int = 0) -> np.ndarray:
        """float32 waveform in [-1, 1] at cfg.sampling_rate.

        ``speaker_embedding`` conditions the flow/decoder/duration nets on
        a CONTINUOUS [speaker_embedding_size] vector — the voice-cloning
        path (audio.speaker.SpeakerEncoder output), bypassing the trained
        speaker table. Takes precedence over ``speaker_id``."""
        cfg = self.cfg
        ids = np.asarray([self.tokenizer.encode(text)], np.int32)
        pad_mask = np.ones_like(ids, np.float32)
        wav = self._forward(
            ids, pad_mask,
            noise_scale=cfg.noise_scale if noise_scale is None
            else noise_scale,
            noise_scale_duration=cfg.noise_scale_duration
            if noise_scale_duration is None else noise_scale_duration,
            speaking_rate=cfg.speaking_rate if speaking_rate is None
            else speaking_rate,
            speaker_id=speaker_id, speaker_embedding=speaker_embedding,
            seed=seed,
        )
        return np.asarray(wav[0], np.float32)

    def _forward(self, ids, pad_mask, *, noise_scale,
                 noise_scale_duration, speaking_rate, speaker_id, seed,
                 speaker_embedding=None):
        cfg, p = self.cfg, self.p
        key = jax.random.key(seed)
        pad = pad_mask[:, None, :]  # [B,1,L]
        cond = None
        if speaker_embedding is not None and cfg.speaker_embedding_size:
            emb = np.asarray(speaker_embedding, np.float32)
            if emb.shape != (cfg.speaker_embedding_size,):
                raise ValueError(
                    f"speaker_embedding must be [{cfg.speaker_embedding_size}]"
                    f", got {emb.shape}"
                )
            # match the trained table's scale so the conditioning convs see
            # in-distribution magnitudes
            tab = p.get("embed_speaker.weight")
            if tab is not None:
                emb = emb * float(np.linalg.norm(
                    np.asarray(tab), axis=1).mean())
            cond = jnp.asarray(emb)[None, :, None]
        elif cfg.num_speakers > 1 and speaker_id is not None:
            emb = p.get("embed_speaker.weight")[speaker_id]
            cond = jnp.asarray(emb)[None, :, None]
        hidden, m_p, logs_p = text_encoder(p, cfg, jnp.asarray(ids),
                                           jnp.asarray(pad_mask))
        if cfg.use_stochastic_duration_prediction:
            k1, key = jax.random.split(key)
            noise = jax.random.normal(
                k1, (ids.shape[0], 2, ids.shape[1])
            ) * noise_scale_duration
            log_d = stochastic_duration_reverse(
                p, cfg, hidden, pad, noise, cond)
        else:
            log_d = duration_predictor(p, cfg, hidden, pad, cond)
        durations = np.ceil(
            np.asarray(jnp.exp(log_d)) * np.asarray(pad)
            / speaking_rate
        )[:, 0]  # [B,L]
        total = max(int(durations.sum()), 1)

        # length regulation: repeat each text position by its duration
        # (host-side — output length is data-dependent)
        reps = durations[0].astype(np.int64)
        gather = np.repeat(np.arange(ids.shape[1]), reps)
        if gather.size == 0:
            gather = np.zeros(1, np.int64)
        m_up = jnp.asarray(np.asarray(m_p)[:, :, gather])
        logs_up = jnp.asarray(np.asarray(logs_p)[:, :, gather])
        out_pad = jnp.ones((1, 1, m_up.shape[-1]), m_up.dtype)

        k2, key = jax.random.split(key)
        prior = m_up + jax.random.normal(k2, m_up.shape) \
            * jnp.exp(logs_up) * noise_scale
        latents = flow_reverse(p, cfg, prior, out_pad, cond)
        wav = hifigan(p, cfg, latents * out_pad, cond)
        del total
        return wav


def load_hf_vits(model_dir: str | Path) -> VitsTTS:
    """HF VitsModel checkpoint dir (config.json model_type "vits" +
    safetensors + vocab.json) → VitsTTS."""
    model_dir = Path(model_dir)
    hf = json.loads((model_dir / "config.json").read_text())
    cfg = VitsConfig.from_hf(hf)
    from localai_tpu.models.loader import _get, _open_safetensors

    raw = _open_safetensors(model_dir)
    tensors = {name: np.asarray(_get(raw, name), np.float32)
               for name in raw}
    return VitsTTS(cfg, _P(tensors), VitsCharTokenizer(model_dir))
