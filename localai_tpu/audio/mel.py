"""Whisper-compatible log-mel spectrogram frontend, in JAX.

Replaces whisper.cpp's C mel extraction (consumed via the cgo backend,
/root/reference/backend/go/transcribe/whisper/whisper.go:21-105) with a
jitted STFT + slaney-scale mel filterbank: frame, window, rFFT, magnitude²,
mel project, log10, clamp — all fused by XLA, so the frontend runs on
device alongside the encoder instead of on the host.

Constants match OpenAI whisper (n_fft=400, hop=160, 80 mels @ 16 kHz) so
real checkpoint weights see the distribution they were trained on.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160
N_MELS = 80
CHUNK_SECONDS = 30
CHUNK_SAMPLES = SAMPLE_RATE * CHUNK_SECONDS
CHUNK_FRAMES = CHUNK_SAMPLES // HOP  # 3000


def _hz_to_mel(f: np.ndarray) -> np.ndarray:
    """Slaney scale (librosa default, what whisper uses)."""
    f = np.asarray(f, np.float64)
    mel = f / (200.0 / 3)
    log_step = np.log(6.4) / 27.0
    brk = 1000.0
    brk_mel = brk / (200.0 / 3)
    safe = np.maximum(f, 1e-10)
    return np.where(f >= brk, brk_mel + np.log(safe / brk) / log_step, mel)


def _mel_to_hz(m: np.ndarray) -> np.ndarray:
    m = np.asarray(m, np.float64)
    log_step = np.log(6.4) / 27.0
    brk_mel = 15.0
    f = m * (200.0 / 3)
    return np.where(m >= brk_mel, 1000.0 * np.exp(log_step * (m - brk_mel)), f)


def mel_filterbank(n_mels: int = N_MELS, n_fft: int = N_FFT,
                   rate: int = SAMPLE_RATE) -> np.ndarray:
    """[n_mels, n_fft//2 + 1] slaney-normalized triangular filters."""
    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, rate / 2, n_freqs)
    mel_pts = np.linspace(_hz_to_mel(np.array(0.0)),
                          _hz_to_mel(np.array(rate / 2.0)), n_mels + 2)
    hz_pts = _mel_to_hz(mel_pts)
    fb = np.zeros((n_mels, n_freqs))
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
        fb[i] *= 2.0 / (hi - lo)  # slaney area normalization
    return fb.astype(np.float32)


@partial(jax.jit, static_argnames=("n_mels",))
def log_mel(audio: jax.Array, filters: jax.Array,
            n_mels: int = N_MELS) -> jax.Array:
    """audio [CHUNK_SAMPLES] f32 → log-mel [n_mels, CHUNK_FRAMES]."""
    # periodic Hann (torch.hann_window), NOT the symmetric jnp.hanning —
    # whisper checkpoints were trained with the periodic variant
    window = (0.5 * (1.0 - jnp.cos(
        2.0 * jnp.pi * jnp.arange(N_FFT) / N_FFT))).astype(jnp.float32)
    pad = N_FFT // 2
    x = jnp.pad(audio, (pad, pad), mode="reflect")
    n_frames = CHUNK_FRAMES
    idx = jnp.arange(n_frames)[:, None] * HOP + jnp.arange(N_FFT)[None, :]
    frames = x[idx] * window[None, :]
    spec = jnp.fft.rfft(frames, axis=-1)
    power = jnp.abs(spec) ** 2                    # [frames, n_freqs]
    mel = power @ filters.T                       # [frames, n_mels]
    logspec = jnp.log10(jnp.maximum(mel, 1e-10))
    logspec = jnp.maximum(logspec, jnp.max(logspec) - 8.0)
    logspec = (logspec + 4.0) / 4.0
    return logspec.T                              # [n_mels, frames]


def chunk_audio(audio: np.ndarray) -> list[np.ndarray]:
    """Split/pad into 30-s chunks (whisper's fixed receptive field)."""
    chunks = []
    for off in range(0, max(len(audio), 1), CHUNK_SAMPLES):
        c = audio[off:off + CHUNK_SAMPLES]
        if len(c) < CHUNK_SAMPLES:
            c = np.pad(c, (0, CHUNK_SAMPLES - len(c)))
        chunks.append(c.astype(np.float32))
    return chunks
