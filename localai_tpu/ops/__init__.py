"""TPU kernels (Pallas) and their selection policy.

``resolve_attn_impl`` decides the attention implementation for the engine:
  * "pallas"  — flash kernels (ops.attention), the default on real TPU
  * "xla"     — pure-XLA grouped attention (models.llama._grouped_attn),
                the default off-TPU and the numerical reference
  * "pallas_interpret" — flash kernels in interpreter mode (CPU tests)

Override with env ``LOCALAI_ATTN_IMPL`` or per-runner ``attn_impl=``.
"""

from __future__ import annotations

import os

import jax

from localai_tpu.ops.attention import decode_attention, prefill_attention

__all__ = [
    "decode_attention",
    "prefill_attention",
    "resolve_attn_impl",
]


def resolve_attn_impl(requested: str = "auto") -> tuple[str, bool]:
    """Returns (impl, interpret) with impl in {"xla", "pallas"}."""
    impl = requested
    if impl in ("auto", ""):
        # env only overrides the default, never an explicit per-runner choice
        impl = os.environ.get("LOCALAI_ATTN_IMPL", "") or "auto"
    if impl in ("auto", ""):
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas_interpret":
        return "pallas", True
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown attention impl {impl!r}")
    return impl, impl == "pallas" and jax.default_backend() != "tpu"
