"""TPU kernels (Pallas) and their selection policy.

``resolve_attn_impl`` decides the attention implementation for the engine:
  * "pallas"  — flash kernels (ops.attention), the default on real TPU
  * "xla"     — pure-XLA grouped attention (models.llama._grouped_attn),
                the default off-TPU and the numerical reference
  * "pallas_interpret" — flash kernels in interpreter mode (CPU tests)

Override with env ``LOCALAI_ATTN_IMPL`` or per-runner ``attn_impl=``.
"""

from __future__ import annotations

import os

import jax

from localai_tpu.ops.attention import (
    decode_attention,
    paged_decode_attention,
    paged_decode_attention_ref,
    prefill_attention,
)

__all__ = [
    "decode_attention",
    "paged_decode_attention",
    "paged_decode_attention_ref",
    "prefill_attention",
    "resolve_attn_impl",
    "select_paged_attn_impl",
]


def resolve_attn_impl(requested: str = "auto",
                      backend: str | None = None) -> tuple[str, bool]:
    """Returns (impl, interpret) with impl in {"xla", "pallas"}."""
    backend = backend or jax.default_backend()
    impl = requested
    if impl in ("auto", ""):
        # env only overrides the default, never an explicit per-runner choice
        impl = os.environ.get("LOCALAI_ATTN_IMPL", "") or "auto"
    if impl in ("auto", ""):
        impl = "pallas" if backend == "tpu" else "xla"
    if impl == "pallas_interpret":
        return "pallas", True
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown attention impl {impl!r}")
    return impl, impl == "pallas" and backend != "tpu"


def select_attn_impl(requested: str, *, num_heads: int, num_kv_heads: int,
                     head_dim: int, max_ctx: int, tp: int = 1,
                     backend: str | None = None) -> tuple[str, bool, str]:
    """The FULL engine attention-impl decision — resolve_attn_impl plus
    every fallback gate ModelRunner applies, as one pure function so CI can
    assert which path a given (model, mesh) lands on at hardware shapes
    (VERDICT r4 #9: a silent Pallas→XLA fallback regression must fail a
    test, not just slow the bench).

    Returns (impl, interpret, reason) — reason is "" when no fallback
    fired, else a human-readable explanation.
    """
    impl, interpret = resolve_attn_impl(requested, backend)
    if impl == "pallas" and tp > 1 and (num_heads % tp or num_kv_heads % tp):
        # under a mesh the flash kernels run per-device via shard_map
        # (slots on 'data', heads on 'model') — head groups must split
        # evenly or the kernel's GQA grouping would misalign
        return "xla", False, (
            f"heads ({num_heads} q / {num_kv_heads} kv) not divisible by "
            f"tensor_parallel {tp}")
    if impl == "pallas" and not interpret and (head_dim % 128
                                               or max_ctx % 128):
        # Mosaic lane tiling is 128-wide; unaligned head_dim/ctx (tiny
        # debug models, hd-64 families) take the XLA path on real TPU
        return "xla", False, (
            f"head_dim={head_dim} ctx={max_ctx} not 128-aligned")
    return impl, interpret, ""


def select_paged_attn_impl(requested: str, *, num_heads: int,
                           num_kv_heads: int, head_dim: int,
                           block_tokens: int, tp: int = 1,
                           kv_dtype: str = "bfloat16",
                           backend: str | None = None,
                           tuned=None) -> tuple[str, bool, str]:
    """Attention-impl decision for the PAGED decode path (the paged analogue
    of ``select_attn_impl``). Returns (impl, interpret, reason).

    The Pallas paged kernel DMAs one [block_tokens, head_dim] physical
    block per online-softmax step, so on hardware it needs Mosaic-tileable
    blocks: head_dim 128-aligned and block_tokens covering the dtype's
    sublane minimum (32 covers int8, the narrowest full-width pool dtype).
    int4 pools are nibble-packed along head_dim, so their DMA'd last dim
    is head_dim/2 — on hardware that needs head_dim 256-aligned to stay
    lane-tileable (hd-128 int4 models take the gather fallback unless a
    tuned or env override proves the kernel). The ``gather + XLA``
    fallback (ops.paged_decode_attention_ref wired through the paged
    write policies) has no shape constraints and is the CPU/test path.

    Precedence: an explicit ``requested`` wins; then the
    ``LOCALAI_PAGED_ATTN_IMPL`` env override; then a tuned entry from the
    per-shape tuning table (ops.tuning, keyed by head_dim / kv heads /
    kv_dtype / tp — pass ``tuned`` to reuse an entry the caller already
    looked up and skip the second lookup receipt); then the backend
    default. Hard shape gates apply to every source except the explicit
    env override-to-xla (tuned "pallas" on an untileable shape still
    falls back, with the reason reported). A tuned "pallas" is honored
    ONLY on a real TPU backend: off-TPU that impl would mean the Pallas
    *interpreter* — orders of magnitude slower — and the table is an
    automatic source, not a user's explicit interpret opt-in.
    """
    backend = backend or jax.default_backend()
    impl = requested
    if impl in ("auto", ""):
        impl = os.environ.get("LOCALAI_PAGED_ATTN_IMPL", "") or "auto"
    if impl in ("auto", ""):
        if tuned is None:
            from localai_tpu.ops import tuning

            tuned = tuning.lookup(head_dim, num_kv_heads, kv_dtype, tp)
        if tuned is not None and tuned.impl and (
                tuned.impl != "pallas" or backend == "tpu"):
            impl = tuned.impl
    if impl in ("auto", ""):
        impl = "pallas" if backend == "tpu" else "xla"
    if impl not in ("pallas", "pallas_interpret", "xla"):
        raise ValueError(f"unknown paged attention impl {impl!r}")
    if (impl in ("pallas", "pallas_interpret") and tp > 1
            and (num_heads % tp or num_kv_heads % tp)):
        # under a mesh the paged kernel runs per-device via shard_map
        # (tables/slots on 'data', heads on 'model') — both head counts
        # must split evenly or the per-shard GQA grouping misaligns (a
        # replicated-KV pool has no per-shard head group to walk)
        return "xla", False, (
            f"heads ({num_heads} q / {num_kv_heads} kv) not divisible by "
            f"tensor_parallel {tp}")
    if impl == "pallas_interpret":
        return "pallas", True, ""
    interpret = impl == "pallas" and backend != "tpu"
    if impl == "pallas" and not interpret:
        if head_dim % 128 or block_tokens % 32:
            return "xla", False, (
                f"head_dim={head_dim} block_tokens={block_tokens} not "
                f"Mosaic-tileable (need hd%128==0, bt%32==0)")
        if kv_dtype == "int4" and head_dim % 256:
            # the nibble-packed pool's DMA'd last dim is head_dim/2
            return "xla", False, (
                f"int4 pool packs head_dim to {head_dim // 2} lanes "
                f"(need hd%256==0 for the packed Mosaic tiling)")
        if num_heads % num_kv_heads:
            return "xla", False, (
                f"heads ({num_heads} q / {num_kv_heads} kv) not grouped")
    return impl, interpret, ""
