"""Per-shape kernel tuning table for the paged decode hot path.

The paged attention dispatch has real tuning freedom — kernel impl
(Pallas flash vs gather+XLA), pool block size, DMA buffer depth — and the
best point depends on the shape tuple ``(head_dim, kv_heads, kv_dtype,
tensor_parallel)`` and on the hardware generation, not on anything
decidable statically. ``tools/autotune.py`` sweeps those knobs on real
timings and persists the winners here; ``ops.select_paged_attn_impl`` and
``engine.runner.ModelRunner`` consult the table at construction so a tuned
box serves the measured-fastest configuration without config changes.

The table is a flat JSON file at ``LOCALAI_TUNE_CACHE`` (default
``~/.cache/localai_tpu/tuning.json``):

    {"hd128_kv8_int8_tp1": {"impl": "pallas", "block_tokens": 64,
                            "num_buffers": 3, "us": 412.0}, ...}

Failure policy: a missing, corrupt, or partially-written file silently
degrades to built-in defaults (one warning, never an error — tuning is an
optimization, not a dependency). Every lookup emits a
``localai_autotune_lookups_total{result=hit|miss}`` receipt so a fleet
where the table silently stopped matching its shapes is visible on the
dashboard.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
from typing import Optional

log = logging.getLogger(__name__)

ENV_CACHE = "LOCALAI_TUNE_CACHE"
_DEFAULT_PATH = "~/.cache/localai_tpu/tuning.json"

_IMPLS = ("", "pallas", "xla")


def cache_path() -> str:
    """Resolved tuning-table path (``LOCALAI_TUNE_CACHE``; "0" disables)."""
    p = os.environ.get(ENV_CACHE, "")
    if p == "0":
        return ""
    return os.path.expanduser(p or _DEFAULT_PATH)


def shape_key(head_dim: int, kv_heads: int, kv_dtype: str, tp: int) -> str:
    """The tuning key: per-(head_dim, kv-head count, KV dtype, tensor-
    parallel width) — the parameters that change the kernel's memory
    traffic pattern. Slot count and context length deliberately excluded:
    they scale the grid, not the per-block schedule."""
    return f"hd{int(head_dim)}_kv{int(kv_heads)}_{kv_dtype}_tp{int(tp)}"


@dataclasses.dataclass
class TuneEntry:
    """One tuned configuration. Zero-valued fields mean "no preference —
    keep the engine default"."""

    impl: str = ""          # "pallas" | "xla" | "" (auto)
    block_tokens: int = 0   # pool block size; 0 = LOCALAI_KV_BLOCK_TOKENS
    num_buffers: int = 0    # flash-loop DMA depth; 0 = 2 (ping-pong)
    us: float = 0.0         # best measured microseconds per dispatch

    @staticmethod
    def from_dict(d: object) -> Optional["TuneEntry"]:
        """Validated parse; None on any malformed field (one bad entry
        must not poison the rest of the table)."""
        if not isinstance(d, dict):
            return None
        try:
            e = TuneEntry(
                impl=str(d.get("impl", "")),
                block_tokens=int(d.get("block_tokens", 0)),
                num_buffers=int(d.get("num_buffers", 0)),
                us=float(d.get("us", 0.0)),
            )
        except (TypeError, ValueError):
            return None
        if e.impl not in _IMPLS:
            return None
        if e.block_tokens < 0 or e.num_buffers < 0:
            return None
        return e

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v not in ("", 0, 0.0)}


class TuningTable:
    """In-memory view of one tuning-cache file."""

    def __init__(self, entries: Optional[dict[str, TuneEntry]] = None,
                 path: str = ""):
        self.entries: dict[str, TuneEntry] = dict(entries or {})
        self.path = path

    @staticmethod
    def load(path: str) -> "TuningTable":
        """Parse ``path``; corrupt or unreadable files degrade to an empty
        table with one warning (defaults keep serving)."""
        table = TuningTable(path=path)
        if not path or not os.path.exists(path):
            return table
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(raw).__name__}")
        except (OSError, ValueError) as e:
            log.warning("tuning cache %s unreadable (%s); using defaults",
                        path, e)
            return table
        for key, val in raw.items():
            entry = TuneEntry.from_dict(val)
            if entry is None:
                log.warning("tuning cache %s: dropping malformed entry %r",
                            path, key)
                continue
            table.entries[str(key)] = entry
        return table

    def lookup(self, key: str) -> Optional[TuneEntry]:
        return self.entries.get(key)

    def put(self, key: str, entry: TuneEntry) -> None:
        self.entries[key] = entry

    def save(self, path: Optional[str] = None) -> str:
        """Atomic JSON write; returns the path written."""
        path = path or self.path or cache_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({k: e.to_dict() for k, e in self.entries.items()},
                      f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


# process-wide table, lazily loaded per LOCALAI_TUNE_CACHE value (tests
# flip the env between runners; serving reads it once per path)
_lock = threading.Lock()
_loaded: Optional[TuningTable] = None
_loaded_path: Optional[str] = None


def table() -> TuningTable:
    global _loaded, _loaded_path
    path = cache_path()
    with _lock:
        if _loaded is None or _loaded_path != path:
            _loaded = TuningTable.load(path)
            _loaded_path = path
            _set_entries_gauge(len(_loaded.entries))
        return _loaded


def reset() -> None:
    """Drop the cached table (tests; a rewritten cache file re-loads on
    the next lookup)."""
    global _loaded, _loaded_path
    with _lock:
        _loaded = None
        _loaded_path = None


def lookup(head_dim: int, kv_heads: int, kv_dtype: str,
           tp: int = 1) -> Optional[TuneEntry]:
    """Tuned entry for one shape, with a hit/miss metric receipt."""
    entry = table().lookup(shape_key(head_dim, kv_heads, kv_dtype, tp))
    _note_lookup("hit" if entry is not None else "miss")
    return entry


def _note_lookup(result: str) -> None:
    try:
        from localai_tpu.obs.metrics import REGISTRY

        REGISTRY.autotune_lookups.inc(result=result)
    except Exception:  # noqa: BLE001 — metrics must never break tuning
        pass


def _set_entries_gauge(n: int) -> None:
    try:
        from localai_tpu.obs.metrics import REGISTRY

        REGISTRY.autotune_entries.set(n)
    except Exception:  # noqa: BLE001
        pass
