"""Pallas TPU flash-attention kernels for the serving engine.

The XLA path (models.llama._grouped_attn) materializes the full score tensor
[S, Hkv, g, T, L] in float32 — at long context that is the HBM-bandwidth
bottleneck of decode. These kernels keep K/V in HBM and stream them through
VMEM in ``block_k`` chunks with double-buffered async DMA and an online
softmax (flash attention), so per (slot, kv-head) the VMEM working set is
O(block_k · hd) regardless of context length, and only blocks inside the
[sliding-window, causal/length] frontier are ever fetched.

Replaces (TPU-era) the reference's per-slot CPU attention inside llama.cpp's
``llama_decode`` hot loop (/root/reference/backend/cpp/llama/
grpc-server.cpp:1546-1990). Two shapes of the same kernel:

  * ``decode_attention`` — q is one token per slot, KV is the slot cache
    head-major [S, Hkv, C, hd] (so per-head DMA slices are (context, hd) —
    the (sublane, lane) tiling Mosaic requires); grid (S, Hkv); the GQA
    group (g = Hq/Hkv queries) forms the row dimension of the MXU matmul.
    Masking comes from per-slot write positions, not a materialized mask.
  * ``prefill_attention`` — single-sequence causal attention [T, ...];
    grid (Hkv, T/block_q); rows are (q-position × group) pairs; KV blocks
    beyond the causal frontier or the real prompt length are not fetched.

Both run under ``interpret=True`` on CPU for tests (tests/test_ops.py) and
compile to Mosaic on real TPU. Sliding-window (Mistral) masking is supported
statically.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the in-kernel dequant step for int4 KV pools: the ONE halves-layout
# unpacker (pure VPU shifts — models.quant has no ops imports at module
# level, so no cycle) shared with the pool writers; a drifted second
# copy would make the kernels silently dequantize differently from the
# scatter that packed the rows
from localai_tpu.models.quant import (
    unpack_int4_lastdim as _unpack_nibbles,
)

_NEG_INF = -1e30


def _pick_block(total: int, target: int) -> int:
    """Largest divisor of ``total`` that is ≤ target (keeps grids exact)."""
    b = min(total, target)
    while total % b:
        b -= 1
    return b


def _pick_block_aligned(total: int, target: int) -> int:
    """Like _pick_block, but when ``total`` is 128-divisible the block is
    too, so every dynamic DMA offset (i·block) stays lane/sublane-aligned
    for Mosaic (e.g. total=640: plain _pick_block gives 320 — offset 320 is
    not 128-aligned; this gives 128). Unaligned totals only reach the
    kernels in interpret mode (the runner gates max_ctx%128 on hardware)."""
    if total % 128:
        return _pick_block(total, target)
    b = (min(total, max(target, 128)) // 128) * 128
    while total % b:
        b -= 128
    return b




def _flash_loop(q, kv_slice, kbuf, vbuf, ksem, vsem, lo, nb, block_k,
                mask_for_block, scales=None, scale_dma=None, depth: int = 2,
                unpack: bool = False):
    """Online-softmax loop over KV blocks [lo, nb) with ``depth``-deep
    double-buffered DMA (depth 2 = classic ping-pong; 3 keeps one extra
    block in flight for gather-latency-bound paged pools — autotunable via
    ops.tuning).

    q: [rows, hd] f32 (pre-scaled). ``kv_slice(hbm_ref, i)`` yields the
    [block_k, hd] HBM slice for block i; ``mask_for_block(i)`` the
    [rows or 1, block_k] keep-mask. Returns the attention output [rows, hd].

    ``scales`` fuses scaled-int8 KV dequantization into the loop:
    (ks_block, vs_block) functions yielding block i's [block_k] f32
    per-position scales (read from VMEM-resident scale rows). The dequant
    never materializes K/V in bf16 — per-position K scales distribute over
    the score matmul columns (q·(k·s) = (q·k)·s) and V scales over the
    probability columns (p@(v·s) = (p·s)@v), so both apply as [1, block_k]
    row multiplies on the VPU while the MXU matmuls stay int8-sourced.

    ``scale_dma`` is the paged-kernel variant of ``scales``: scale rows
    live per-block in HBM (pool layout, no per-head VMEM residency), so
    they ride the same double-buffered DMA as K/V. A tuple
    (ks_hbm(i), vs_hbm(i), ksbuf, vsbuf, kssem, vssem) — block i's [1,
    block_k] HBM slices plus their [depth, 1, block_k] scratch and
    semaphores. Mutually exclusive with ``scales``.

    ``unpack=True`` fuses int4 KV dequantization: the buffered blocks are
    nibble-packed int8 ([block_k, hd/2], models.quant.quantize_lastdim4)
    and unpack in VMEM right after the DMA wait — HALF the int8 path's
    HBM bytes moved per block, with the same per-position scale fusion.
    """
    k_hbm, v_hbm = kv_slice
    rows, hd = q.shape
    if scales is not None:
        ks_block, vs_block = scales
    if scale_dma is not None:
        ks_hbm, vs_hbm, ksbuf, vsbuf, kssem, vssem = scale_dma

    def start(i, slot):
        pltpu.make_async_copy(k_hbm(i), kbuf.at[slot], ksem.at[slot]).start()
        pltpu.make_async_copy(v_hbm(i), vbuf.at[slot], vsem.at[slot]).start()
        if scale_dma is not None:
            pltpu.make_async_copy(
                ks_hbm(i), ksbuf.at[slot], kssem.at[slot]).start()
            pltpu.make_async_copy(
                vs_hbm(i), vsbuf.at[slot], vssem.at[slot]).start()

    def wait(i, slot):
        pltpu.make_async_copy(k_hbm(i), kbuf.at[slot], ksem.at[slot]).wait()
        pltpu.make_async_copy(v_hbm(i), vbuf.at[slot], vsem.at[slot]).wait()
        if scale_dma is not None:
            pltpu.make_async_copy(
                ks_hbm(i), ksbuf.at[slot], kssem.at[slot]).wait()
            pltpu.make_async_copy(
                vs_hbm(i), vsbuf.at[slot], vssem.at[slot]).wait()

    # prime the pipeline: depth-1 blocks in flight before the first fold
    # (the loop body keeps exactly depth-1 ahead of the block in hand)
    start(lo, 0)
    for j in range(1, depth - 1):
        @pl.when(lo + j < nb)
        def _prime(j=j):
            start(lo + j, j)

    def body(i, carry):
        m, l, acc = carry
        slot = lax.rem(i - lo, depth)

        @pl.when(i + depth - 1 < nb)
        def _prefetch():
            start(i + depth - 1, lax.rem(i + depth - 1 - lo, depth))

        wait(i, slot)
        if unpack:
            k = _unpack_nibbles(kbuf[slot]).astype(jnp.float32)
            v = _unpack_nibbles(vbuf[slot]).astype(jnp.float32)
        else:
            k = kbuf[slot].astype(jnp.float32)
            v = vbuf[slot].astype(jnp.float32)
        s = q @ k.T  # [rows, block_k] — MXU
        if scales is not None:
            s = s * ks_block(i)[None, :]
        elif scale_dma is not None:
            s = s * ksbuf[slot]
        s = jnp.where(mask_for_block(i), s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # denominator sums the raw probabilities; V scales touch only the
        # weighted-value numerator
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if scales is not None:
            p = p * vs_block(i)[None, :]
        elif scale_dma is not None:
            p = p * vsbuf[slot]
        acc_new = acc * alpha + p @ v
        return m_new, l_new, acc_new

    m0 = jnp.full((rows, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((rows, 1), jnp.float32)
    acc0 = jnp.zeros((rows, hd), jnp.float32)
    m, l, acc = lax.fori_loop(lo, nb, body, (m0, l0, acc0))
    return acc / jnp.maximum(l, 1e-30)


# ---------------------------------------------------------------------------
# decode: one token per slot over the slot KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, block_k: int,
                   sm_scale: float, sliding_window: Optional[int],
                   quantized: bool):
    # k_ref/v_ref are the FULL [S, Hkv, C, hd] cache in HBM (Mosaic only
    # allows whole-array ANY refs); slot/head are picked in the DMA slice.
    # When quantized, ks/vs_ref are this (slot, head)'s [C] f32 scale rows,
    # auto-loaded into VMEM by their BlockSpec (a scale row is ≤32 KB even
    # at 8k context — no manual DMA needed).
    if quantized:
        ks_ref, vs_ref, o_ref, kbuf, vbuf, ksem, vsem = rest
    else:
        o_ref, kbuf, vbuf, ksem, vsem = rest
    s_idx = pl.program_id(0)
    h_idx = pl.program_id(1)
    pos = pos_ref[s_idx]
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [g, hd]
    ctx = k_ref.shape[2]

    nb = jnp.minimum(pos // block_k + 1, ctx // block_k)
    lo = jnp.int32(0)
    if sliding_window is not None:
        lo = jnp.maximum((pos - sliding_window + 1) // block_k, 0)

    def slice_of(ref):
        return lambda i: ref.at[s_idx, h_idx, pl.ds(i * block_k, block_k), :]

    def mask_for_block(i):
        idx = i * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        keep = idx <= pos
        if sliding_window is not None:
            keep &= idx > pos - sliding_window
        return keep

    scales = None
    if quantized:
        scales = (lambda i: ks_ref[0, 0, pl.ds(i * block_k, block_k)],
                  lambda i: vs_ref[0, 0, pl.ds(i * block_k, block_k)])
    out = _flash_loop(q, (slice_of(k_ref), slice_of(v_ref)),
                      kbuf, vbuf, ksem, vsem, lo, nb, block_k, mask_for_block,
                      scales=scales)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def decode_attention(
    q: jax.Array,            # [S, Hq, hd]
    k_cache: jax.Array,      # [S, Hkv, C, hd] head-major slot cache
    v_cache: jax.Array,      # [S, Hkv, C, hd]
    positions: jax.Array,    # [S] i32 — current token's KV write position
    k_scale: Optional[jax.Array] = None,  # [S, Hkv, C] f32 (scaled-int8 KV)
    v_scale: Optional[jax.Array] = None,
    *,
    sliding_window: Optional[int] = None,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Flash GQA decode attention over the slot cache. Returns [S, Hq, hd].

    With ``k_scale``/``v_scale`` the cache is scaled int8 and dequantization
    fuses into the flash loop (scores/probs column scaling) — decode reads
    half the KV bytes of bf16 and never materializes a dequantized cache.
    """
    S, Hq, hd = q.shape
    Hkv, C = k_cache.shape[1], k_cache.shape[2]
    g = Hq // Hkv
    bk = _pick_block_aligned(C, block_k)
    qg = q.reshape(S, Hkv, g, hd)
    quantized = k_scale is not None

    kernel = functools.partial(
        _decode_kernel, block_k=bk, sm_scale=hd ** -0.5,
        sliding_window=sliding_window, quantized=quantized,
    )
    in_specs = [
        # SMEM blocks must cover the whole array; index by slot inside
        pl.BlockSpec((S,), lambda s, h: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, g, hd), lambda s, h: (s, h, 0, 0)),
        # K/V stay whole in HBM (ANY refs must be unblocked); the
        # kernel DMAs block_k slices per (slot, head) itself
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch = [
        pltpu.VMEM((2, bk, hd), k_cache.dtype),
        pltpu.VMEM((2, bk, hd), v_cache.dtype),
    ]
    args = [positions.astype(jnp.int32), qg, k_cache, v_cache]
    if quantized:
        # scale rows ride normal VMEM blocks — one [C] f32 row per
        # (slot, head) grid step (≤32 KB at 8k context)
        in_specs += [pl.BlockSpec((1, 1, C), lambda s, h: (s, h, 0)),
                     pl.BlockSpec((1, 1, C), lambda s, h: (s, h, 0))]
        args += [k_scale, v_scale]
    scratch += [pltpu.SemaphoreType.DMA((2,))] * 2
    out = pl.pallas_call(
        kernel,
        grid=(S, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, hd), lambda s, h: (s, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S, Hkv, g, hd), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    return out.reshape(S, Hq, hd)


# ---------------------------------------------------------------------------
# prefill: single-sequence causal attention
# ---------------------------------------------------------------------------


def _prefill_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                    kbuf, vbuf, ksem, vsem, *, block_q: int, block_k: int,
                    groups: int, sm_scale: float,
                    sliding_window: Optional[int]):
    h_idx = pl.program_id(0)
    length = len_ref[0]
    qi = pl.program_id(1)
    hd = q_ref.shape[3]
    T = k_ref.shape[1]
    rows = block_q * groups
    q = q_ref[:, 0].astype(jnp.float32).reshape(rows, hd) * sm_scale
    # row r ↦ absolute q position
    qpos = qi * block_q + lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // groups

    # trip range: causal frontier ∧ real length, minus sub-window blocks
    nb_causal = ((qi + 1) * block_q + block_k - 1) // block_k
    nb_len = (length + block_k - 1) // block_k
    nb = jnp.minimum(jnp.minimum(nb_causal, nb_len), T // block_k)
    lo = jnp.int32(0)
    if sliding_window is not None:
        lo = jnp.maximum((qi * block_q - sliding_window + 1) // block_k, 0)
    # rows entirely past `length` are garbage either way; keep the loop
    # non-empty so the DMA pipeline stays well-formed
    nb = jnp.maximum(nb, lo + 1)

    def slice_of(ref):
        return lambda i: ref.at[h_idx, pl.ds(i * block_k, block_k), :]

    def mask_for_block(i):
        kj = i * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        keep = (kj <= qpos) & (kj < length)
        if sliding_window is not None:
            keep &= kj > qpos - sliding_window
        return keep

    out = _flash_loop(q, (slice_of(k_ref), slice_of(v_ref)),
                      kbuf, vbuf, ksem, vsem, lo, nb, block_k, mask_for_block)
    o_ref[:] = out.reshape(block_q, 1, groups, hd).astype(o_ref.dtype)


def prefill_attention(
    q: jax.Array,         # [T, Hq, hd]
    k: jax.Array,         # [Hkv, T, hd] head-major chunk
    v: jax.Array,         # [Hkv, T, hd]
    length: jax.Array,    # scalar i32 — real (unpadded) sequence length
    *,
    sliding_window: Optional[int] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Flash causal GQA prefill attention. Returns [T, Hq, hd]."""
    T, Hq, hd = q.shape
    Hkv = k.shape[0]
    g = Hq // Hkv
    bq = _pick_block_aligned(T, block_q)
    bk = _pick_block_aligned(T, block_k)
    qg = q.reshape(T, Hkv, g, hd)

    kernel = functools.partial(
        _prefill_kernel, block_q=bq, block_k=bk, groups=g,
        sm_scale=hd ** -0.5, sliding_window=sliding_window,
    )
    out = pl.pallas_call(
        kernel,
        grid=(Hkv, T // bq),
        in_specs=[
            pl.BlockSpec((1,), lambda h, i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bq, 1, g, hd), lambda h, i: (i, h, 0, 0)),
            # K/V whole in HBM; the kernel DMAs per-head block_k slices
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((bq, 1, g, hd), lambda h, i: (i, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((T, Hkv, g, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, bk, hd), k.dtype),
            pltpu.VMEM((2, bk, hd), v.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(jnp.reshape(length, (1,)).astype(jnp.int32), qg, k, v)
    return out.reshape(T, Hq, hd)


# ---------------------------------------------------------------------------
# paged decode: one token per slot over a block pool via block tables
# ---------------------------------------------------------------------------


def gather_blocks(cache: jax.Array, tables: jax.Array) -> jax.Array:
    """[N, H, bt, hd] block pool + [S, MB] i32 tables -> [S, H, MB*bt, hd]
    logical context rows — THE pool-gather used by the pure-lax paged
    attention path and the paged KV write policies (engine.kvcache)."""
    S, MB = tables.shape
    _, H, bt, hd = cache.shape
    g = cache[tables]                              # [S, MB, H, bt, hd]
    return g.transpose(0, 2, 1, 3, 4).reshape(S, H, MB * bt, hd)


def gather_block_scales(scales: jax.Array, tables: jax.Array) -> jax.Array:
    """[N, H, bt] scale pool + [S, MB] tables -> [S, H, MB*bt]."""
    S, MB = tables.shape
    _, H, bt = scales.shape
    g = scales[tables]                             # [S, MB, H, bt]
    return g.transpose(0, 2, 1, 3).reshape(S, H, MB * bt)


def _paged_decode_kernel(pos_ref, tbl_ref, q_ref, k_ref, v_ref, *rest,
                         block_tokens: int, sm_scale: float,
                         sliding_window: Optional[int], quantized: bool,
                         int4: bool, num_buffers: int):
    # k_ref/v_ref are the FULL [N, Hkv, bt, hd] block pool in HBM; the
    # block walked at loop step i is tbl_ref[slot, i] (SMEM block table),
    # so the DMA gathers physically-scattered blocks in logical order.
    # Scale rows ([N, Hkv, bt] f32 for int8/int4 pools) are per-block in
    # HBM and ride the same buffered DMA (scale_dma in _flash_loop). int4
    # pools arrive nibble-packed [N, Hkv, bt, hd/2] and unpack in VMEM
    # after the DMA wait — half the int8 path's bytes per block.
    if quantized:
        (ks_ref, vs_ref, o_ref, kbuf, vbuf, ksbuf, vsbuf,
         ksem, vsem, kssem, vssem) = rest
    else:
        o_ref, kbuf, vbuf, ksem, vsem = rest
    s_idx = pl.program_id(0)
    h_idx = pl.program_id(1)
    pos = pos_ref[s_idx]
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [g, hd]
    bt = block_tokens

    nb = jnp.minimum(pos // bt + 1, tbl_ref.shape[1])
    lo = jnp.int32(0)
    if sliding_window is not None:
        lo = jnp.maximum((pos - sliding_window + 1) // bt, 0)

    def slice_of(ref):
        return lambda i: ref.at[tbl_ref[s_idx, i], h_idx]

    def mask_for_block(i):
        idx = i * bt + lax.broadcasted_iota(jnp.int32, (1, bt), 1)
        keep = idx <= pos
        if sliding_window is not None:
            keep &= idx > pos - sliding_window
        return keep

    def scale_slice_of(ref):
        # keep the head axis as a size-1 slice so src/dst ranks match the
        # [1, bt] scratch rows (and the DMA stays 2-D for Mosaic tiling)
        return lambda i: ref.at[tbl_ref[s_idx, i], pl.ds(h_idx, 1)]

    scale_dma = None
    if quantized:
        scale_dma = (scale_slice_of(ks_ref), scale_slice_of(vs_ref),
                     ksbuf, vsbuf, kssem, vssem)
    out = _flash_loop(q, (slice_of(k_ref), slice_of(v_ref)),
                      kbuf, vbuf, ksem, vsem, lo, nb, bt, mask_for_block,
                      scale_dma=scale_dma, depth=num_buffers, unpack=int4)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,            # [S, Hq, hd]
    k_cache: jax.Array,      # [N, Hkv, bt, hd] block pool
                             # (int4: nibble-packed [N, Hkv, bt, hd/2])
    v_cache: jax.Array,      # [N, Hkv, bt, hd]
    tables: jax.Array,       # [S, MB] i32 per-slot block tables
    positions: jax.Array,    # [S] i32 — current token's KV write position
    k_scale: Optional[jax.Array] = None,  # [N, Hkv, bt] f32 (int8/int4)
    v_scale: Optional[jax.Array] = None,
    *,
    sliding_window: Optional[int] = None,
    interpret: bool = False,
    num_buffers: int = 2,
) -> jax.Array:
    """Flash GQA decode attention over a paged block pool. Returns
    [S, Hq, hd]. The kernel walks each slot's block table in SMEM and
    DMAs one [bt, hd] physical block per online-softmax step — identical
    math to ``decode_attention``, with the contiguous slot row replaced
    by gather-over-block-table.

    Under a mesh the runner wraps this in ``shard_map`` with slots (q,
    tables, positions) on 'data' and head groups (q, pool) on 'model':
    the body is then the per-device single-chip kernel, so the pool's
    block axis must arrive WHOLE on every device (table values are
    global physical block ids) and both head counts must divide the
    'model' width (``ops.select_paged_attn_impl`` gates that)."""
    S, Hq, hd = q.shape
    Hkv, bt = k_cache.shape[1], k_cache.shape[2]
    MB = tables.shape[1]
    g = Hq // Hkv
    qg = q.reshape(S, Hkv, g, hd)
    quantized = k_scale is not None
    # an int4 pool is self-describing: its last dim is the packed hd/2
    int4 = quantized and k_cache.shape[-1] * 2 == hd
    depth = max(2, int(num_buffers))

    kernel = functools.partial(
        _paged_decode_kernel, block_tokens=bt, sm_scale=hd ** -0.5,
        sliding_window=sliding_window, quantized=quantized,
        int4=int4, num_buffers=depth,
    )
    in_specs = [
        pl.BlockSpec((S,), lambda s, h: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((S, MB), lambda s, h: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, g, hd), lambda s, h: (s, h, 0, 0)),
        # the pool stays whole in HBM; blocks are gathered by table DMA
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    args = [positions.astype(jnp.int32), tables.astype(jnp.int32), qg,
            k_cache, v_cache]
    scratch = [
        # int4 pools buffer the packed [bt, hd/2] bytes — unpack happens
        # after the DMA wait, so the scratch mirrors the pool's last dim
        pltpu.VMEM((depth, bt, k_cache.shape[-1]), k_cache.dtype),
        pltpu.VMEM((depth, bt, v_cache.shape[-1]), v_cache.dtype),
    ]
    if quantized:
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        args += [k_scale, v_scale]
        scratch += [pltpu.VMEM((depth, 1, bt), jnp.float32),
                    pltpu.VMEM((depth, 1, bt), jnp.float32)]
    scratch += [pltpu.SemaphoreType.DMA((depth,))] * (4 if quantized else 2)
    out = pl.pallas_call(
        kernel,
        grid=(S, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, hd), lambda s, h: (s, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S, Hkv, g, hd), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    return out.reshape(S, Hq, hd)


def paged_decode_attention_ref(
    q: jax.Array,            # [S, Hq, hd]
    k_cache: jax.Array,      # [N, Hkv, bt, hd]
    v_cache: jax.Array,
    tables: jax.Array,       # [S, MB] i32
    positions: jax.Array,    # [S]
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    *,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Pure-lax paged decode attention (gather + masked softmax): the CPU
    fallback and the numerical reference the Pallas kernel is tested
    against. Handles f32/bf16, scaled-int8 and nibble-packed int4 pools
    (int4 detected from the pool's packed hd/2 last dim). Returns
    [S, Hq, hd]."""
    S, Hq, hd = q.shape
    Hkv, bt = k_cache.shape[1], k_cache.shape[2]
    MB = tables.shape[1]
    g = Hq // Hkv
    int4 = k_scale is not None and k_cache.shape[-1] * 2 == hd

    keys = gather_blocks(k_cache, tables)
    values = gather_blocks(v_cache, tables)
    if int4:
        keys = _unpack_nibbles(keys)
        values = _unpack_nibbles(values)
    keys = keys.astype(jnp.float32)
    values = values.astype(jnp.float32)
    if k_scale is not None:
        keys = keys * gather_block_scales(k_scale, tables)[..., None]
        values = values * gather_block_scales(v_scale, tables)[..., None]
    qg = q.reshape(S, Hkv, g, hd).astype(jnp.float32) * hd ** -0.5
    scores = jnp.einsum("skgh,sklh->skgl", qg, keys)
    idx = jnp.arange(MB * bt)[None, None, None, :]
    pos = positions[:, None, None, None]
    keep = idx <= pos
    if sliding_window is not None:
        keep &= idx > pos - sliding_window
    scores = jnp.where(keep, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("skgl,sklh->skgh", probs, values)
    return out.reshape(S, Hq, hd).astype(q.dtype)
