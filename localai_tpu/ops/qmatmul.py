"""Pallas TPU kernel: int8-weight matmul with in-kernel dequant.

The decode hot loop is weight-HBM-bound: every step streams every weight
once. The XLA 'w8' path (models.quant.matmul: ``(x @ q.astype(bf16)) *
scale``) leaves XLA free to materialize the casted bf16 weight as its own
fusion — when it does, the weight bytes cross HBM ~3x (read int8, write
bf16, read bf16) and int8 serving loses its entire bandwidth advantage
(the r4 roofline-gap suspect, VERDICT #2). This kernel removes the
ambiguity: int8 blocks stream HBM→VMEM once, the cast to the activation
dtype happens in-register, the MXU runs the bf16 dot, and the
per-output-channel scale lands in the accumulator epilogue.

Layout: grid (N/bn, K/bk) with K minor (sequential accumulation into a
f32 VMEM scratch); weight blocks (bk, bn) int8 respect Mosaic's (32, 128)
int8 tiling; M pads to the bf16 sublane (16). ``transpose_w=True`` serves
the tied-embedding lm_head (x @ W.T with per-row scales) by swapping the
block index map and contracting on the weight block's minor axis — the
int8 table is still read in its native row-major layout.

Enabled from models.quant.matmul via LOCALAI_W8_KERNEL=1 (opt-in until
hardware measurement picks the default; bench_micro.py measures both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int,
            transpose_w: bool):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...].astype(x.dtype)
    if transpose_w:
        # w block [bn, bk]: contract x's K with the block's minor axis
        acc_ref[...] += jax.lax.dot_general(
            x, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        acc_ref[...] += jnp.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _done():
        scale = s_ref[...].astype(jnp.float32)[None, :]
        o_ref[...] = (acc_ref[...] * scale).astype(o_ref.dtype)


def _pick(total: int, target: int, quantum: int) -> int:
    b = min(total, target)
    b -= b % quantum
    while b > quantum and total % b:
        b -= quantum
    return b if b and total % b == 0 else total


@functools.partial(jax.jit, static_argnames=("transpose_w", "interpret"))
def w8_matmul(x: jax.Array, q: jax.Array, scale: jax.Array, *,
              transpose_w: bool = False,
              interpret: bool = False) -> jax.Array:
    """x [M, K] (bf16/f32) x int8 weight → [M, N] in x.dtype.

    ``transpose_w=False``: q [K, N], scale [N] (per output column).
    ``transpose_w=True``:  q [N, K], scale [N] (per row — the tied
    lm_head table), computing x @ q.T.
    """
    M, K = x.shape
    N = q.shape[1] if not transpose_w else q.shape[0]
    # pad M to the bf16 sublane so tiny decode batches stay Mosaic-legal
    Mp = max(16, ((M + 15) // 16) * 16)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    bk = _pick(K, 512, 128)
    bn = _pick(N, 512, 128)
    n_k, n_n = K // bk, N // bn

    if transpose_w:
        w_spec = pl.BlockSpec((bn, bk), lambda n, k: (n, k))
    else:
        w_spec = pl.BlockSpec((bk, bn), lambda n, k: (k, n))

    out = pl.pallas_call(
        functools.partial(_kernel, n_k=n_k, transpose_w=transpose_w),
        grid=(n_n, n_k),
        in_specs=[
            pl.BlockSpec((Mp, bk), lambda n, k: (0, k)),
            w_spec,
            pl.BlockSpec((bn,), lambda n, k: (n,)),
        ],
        out_specs=pl.BlockSpec((Mp, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((Mp, bn), jnp.float32)],
        interpret=interpret,
    )(x, q, scale)
    return out[:M]


def _w4_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int,
               group: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                # [M, bk]
    w = q_ref[...].astype(x.dtype)                # [bk, bn]
    s = s_ref[...].astype(jnp.float32)            # [bk//group, bn]
    gc = w.shape[0] // group
    # per-group scaled partial dots: y = sum_g (x_g @ w_g) * s_g — the
    # group count per block is small and static (e.g. 512/128 = 4)
    for gi in range(gc):
        part = jnp.dot(
            x[:, gi * group:(gi + 1) * group],
            w[gi * group:(gi + 1) * group],
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] += part * s[gi][None, :]

    @pl.when(k == n_k - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def w4_matmul(x: jax.Array, q: jax.Array, scale: jax.Array, *,
              interpret: bool = False) -> jax.Array:
    """x [M, K] x group-wise int4 weight q [K, N] (scale [K/group, N]) →
    [M, N]. The int4 blocks stream HBM packed (two nibbles per byte),
    dequantizing per group in-register — int8's bandwidth halved again.
    The group size derives from the q/scale shapes (single source of
    truth for every caller)."""
    M, K = x.shape
    N = q.shape[1]
    group = K // scale.shape[0]
    Mp = max(16, ((M + 15) // 16) * 16)
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    bk = _pick(K, 512, group)
    bn = _pick(N, 512, 128)
    n_k, n_n = K // bk, N // bn

    out = pl.pallas_call(
        functools.partial(_w4_kernel, n_k=n_k, group=group),
        grid=(n_n, n_k),
        in_specs=[
            pl.BlockSpec((Mp, bk), lambda n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
            pl.BlockSpec((bk // group, bn), lambda n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((Mp, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((Mp, bn), jnp.float32)],
        interpret=interpret,
    )(x, q, scale)
    return out[:M]


def w4_eligible(x_shape: tuple, q: jax.Array, scale: jax.Array) -> bool:
    """Gates for the grouped-int4 kernel: 2-D NATIVE-int4 weight, 2-D scale
    whose group size is 128-aligned and divides the K block, decode-sized
    M. The dtype gate mirrors ``eligible``'s int8 check: a mode='w4'
    tensor stored as int8 (e.g. an imported GGUF q4 kept unpacked) has
    different Mosaic tiling and must take the XLA path."""
    if q.ndim != 2 or scale.ndim != 2 or q.dtype != jnp.int4:
        return False
    K, N = q.shape
    if scale.shape[1] != N or K % scale.shape[0]:
        return False
    group = K // scale.shape[0]
    M = 1
    for d in x_shape[:-1]:
        M *= d
    return (x_shape[-1] == K and group % 128 == 0 and K % 128 == 0
            and N % 128 == 0 and M <= 256)


def eligible(x_shape: tuple, q: jax.Array, scale: jax.Array,
             transpose_w: bool) -> bool:
    """Shape gates: 2-D int8 weight, 128-aligned dims, 1-D scale, small M
    (decode/small-batch — prefill matmuls are compute-bound and stay XLA)."""
    if q.ndim != 2 or scale.ndim != 1 or q.dtype != jnp.int8:
        return False
    K = q.shape[1] if transpose_w else q.shape[0]
    N = q.shape[0] if transpose_w else q.shape[1]
    M = 1
    for d in x_shape[:-1]:
        M *= d
    return (x_shape[-1] == K and K % 128 == 0 and N % 128 == 0
            and M <= 256)
