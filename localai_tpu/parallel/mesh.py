"""Device-mesh construction and axis conventions.

This layer REPLACES the reference's entire distributed plumbing — llama.cpp
RPC weight-sharding over libp2p tunnels (/root/reference/backend/cpp/llama/
grpc-server.cpp:2233-2236, core/p2p/p2p.go:137-173) and vLLM
tensor_parallel_size passthrough (backend/python/vllm/backend.py:102-103) —
with compiled SPMD: a jax.sharding.Mesh over ICI, shardings annotated on
params/activations, XLA inserting the collectives.

Axis conventions (sizes of 1 are legal and collapse at trace time):

  data    — request/batch data parallelism (DP)
  seq     — sequence/context parallelism for long-context (SP, ring attention)
  pipe    — pipeline stages (PP)
  expert  — MoE expert parallelism (EP)
  model   — tensor parallelism (TP; Megatron-style head/ffn split)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "seq", "pipe", "expert", "model")


@dataclass(frozen=True)
class MeshPlan:
    """Validated logical mesh shape. Product must equal the device count."""

    data: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1
    model: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.seq, self.pipe, self.expert, self.model)

    def size(self) -> int:
        return math.prod(self.shape)


def plan_from_sharding_config(
    tensor_parallel_size: int = 1,
    data_parallel_size: int = 0,
    sequence_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    n_devices: Optional[int] = None,
) -> MeshPlan:
    """Turn ShardingConfig knobs into a concrete MeshPlan.

    data_parallel_size=0 means "fill whatever devices remain" (the TPU
    analogue of the reference auto-detecting GPU count,
    /root/reference/pkg/model/initializers.go:185-267).
    """
    nd = n_devices if n_devices is not None else len(jax.devices())
    fixed = (
        tensor_parallel_size
        * sequence_parallel_size
        * expert_parallel_size
        * pipeline_parallel_size
    )
    if nd % fixed != 0:
        raise ValueError(
            f"device count {nd} not divisible by tp*sp*ep*pp={fixed}"
        )
    dp = data_parallel_size or nd // fixed
    plan = MeshPlan(
        data=dp,
        seq=sequence_parallel_size,
        pipe=pipeline_parallel_size,
        expert=expert_parallel_size,
        model=tensor_parallel_size,
    )
    if plan.size() != nd:
        raise ValueError(
            f"mesh {plan.shape} (={plan.size()}) != device count {nd}"
        )
    return plan


def build_mesh(
    plan: Optional[MeshPlan] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the Mesh. Device order follows jax.devices(), which for TPU
    slices is ICI-contiguous — 'model' is the fastest-varying axis so TP
    collectives ride the shortest ICI rings."""
    devs = list(devices if devices is not None else jax.devices())
    if plan is None:
        plan = MeshPlan(model=len(devs))
    arr = np.array(devs).reshape(plan.shape)
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshPlan(), devices=jax.devices()[:1])


def parse_mesh_spec(spec: str) -> Optional[dict]:
    """``"data=2,model=4"`` (or ``data:2,model:4``) → axis dict for
    MeshPlan. The one parser behind ``--mesh`` and ``LOCALAI_MESH`` so the
    CLI flag and the env override can never drift. Unknown axes raise —
    a typo'd axis name must not silently serve an unsharded layout."""
    if not spec:
        return None
    out: dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        sep = "=" if "=" in part else ":"
        k, _, v = part.partition(sep)
        k = k.strip()
        if k not in AXES:
            raise ValueError(
                f"unknown mesh axis {k!r} in {spec!r}; have {AXES}")
        out[k] = int(v)
    return out or None


def default_tensor_parallel(n_devices: int, num_heads: int) -> int:
    """The auto-mesh TP width for one host: all visible devices when the
    q-head count allows (``model=all``, ISSUE 8 / ROADMAP item 3),
    otherwise the widest divisor of the device count that splits the
    heads evenly. KV heads narrower than TP are legal (kv_spec/
    paged_kv_spec replicate the cache) but the flash kernels need the
    q-head groups aligned, so only ``num_heads`` gates here. Returns 1
    when no split works (callers then skip the mesh entirely)."""
    for tp in range(min(n_devices, num_heads), 0, -1):
        if n_devices % tp == 0 and num_heads % tp == 0:
            return tp
    return 1


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
