"""Ring attention: sequence/context parallelism for long-context prefill.

The reference has NO sequence parallelism — long context is a single-device
concern handled by RoPE scaling and self-extend inside llama.cpp
(SURVEY.md §5.7, /root/reference/backend/cpp/llama/grpc-server.cpp:1884-1886).
On TPU, context length scales across the 'seq' mesh axis instead: the
sequence is chunked over devices, each device computes blockwise attention
between its query chunk and a rotating KV chunk, and the KV chunks travel
the ICI ring via ``lax.ppermute`` (Ring Attention, arXiv:2310.01889-style;
the blockwise online-softmax merge is the same math as the Pallas flash
kernels in ops.attention).

Communication pattern per layer: n_seq - 1 ppermute hops of one KV chunk
(2 · Tc · Hkv · hd elements) fully overlapped with the chunk attention
matmuls by XLA's latency-hiding scheduler; no all-to-all, no gather of the
full sequence on any device.

``sp_prefill_forward`` runs the whole llama trunk under shard_map with
activations sharded on 'seq', reusing models.llama._layer so the math stays
in one place. Params are replicated across 'seq' but may be 'model'-sharded
(TP×SP composition — see sp_prefill_forward's docstring); the returned
per-layer K/V is 'seq'-sharded (and head-sharded under TP), ready for
slot-cache insertion.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from localai_tpu.models import llama as mdl
from localai_tpu.models import quant as qnt
from localai_tpu.models.llama import LlamaConfig
from localai_tpu.utils.jaxcompat import shard_map

_NEG_INF = -1e30


def ring_attention(
    q: jax.Array,          # [Tc, Hq, hd] — this device's query chunk
    k: jax.Array,          # [Tc, Hkv, hd] — this device's KV chunk
    v: jax.Array,          # [Tc, Hkv, hd]
    length: jax.Array,     # scalar i32 — real (unpadded) global length
    *,
    n_chunks: int,         # static: size of the 'seq' axis
    axis_name: str = "seq",
    sliding_window: int | None = None,
) -> jax.Array:
    """Causal GQA ring attention inside shard_map. Returns [Tc, Hq, hd].

    The q-chunk's global offset is derived from ``lax.axis_index`` — chunk
    layout and mask can never disagree.
    """
    Tc, Hq, hd = q.shape
    Hkv = k.shape[1]
    g = Hq // Hkv
    i = lax.axis_index(axis_name)

    qg = q.reshape(Tc, Hkv, g, hd).astype(jnp.float32) * hd ** -0.5
    qpos = i * Tc + jnp.arange(Tc, dtype=jnp.int32)
    perm = [(p, (p + 1) % n_chunks) for p in range(n_chunks)]

    def update(s, k_c, v_c, m, l, acc):
        j = lax.rem(i - s + n_chunks, n_chunks)  # owner of the chunk in hand
        kpos = j * Tc + jnp.arange(Tc, dtype=jnp.int32)
        scores = jnp.einsum(
            "tkgh,lkh->kgtl", qg, k_c.astype(jnp.float32)
        )
        keep = (kpos[None, :] <= qpos[:, None]) & (kpos < length)[None, :]
        if sliding_window is not None:
            keep &= kpos[None, :] > qpos[:, None] - sliding_window
        scores = jnp.where(keep[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "kgtl,lkh->kgth", p, v_c.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((Hkv, g, Tc, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, g, Tc, 1), jnp.float32)
    acc0 = jnp.zeros((Hkv, g, Tc, hd), jnp.float32)
    # local chunk first, then exactly n_chunks-1 ring hops: each body
    # iteration rotates the KV chunk one device along ICI, then folds it in
    carry = (k, v) + update(0, k, v, m0, l0, acc0)

    def body(s, carry):
        k_c, v_c, m, l, acc = carry
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        return (k_c, v_c) + update(s, k_c, v_c, m, l, acc)

    _, _, _, l, acc = lax.fori_loop(1, n_chunks, body, carry)
    out = acc / jnp.maximum(l, 1e-30)            # [Hkv, g, Tc, hd]
    out = out.transpose(2, 0, 1, 3).reshape(Tc, Hq, hd)
    return out.astype(q.dtype)


def _tp_param_specs(cfg: LlamaConfig, mesh: Mesh, params: Any) -> Any:
    """Per-leaf PartitionSpecs for the trunk params under TP ('model' axis)
    — the shared helper in parallel.sharding (also used by the
    parallel.overlap decode path)."""
    from localai_tpu.parallel import sharding as shd

    return shd.tp_param_specs(cfg, mesh, params)


def sp_prefill_forward(
    cfg: LlamaConfig,
    params: Any,
    tokens: jax.Array,     # [T] i32, T divisible by mesh 'seq' size
    length: jax.Array,     # scalar i32
    mesh: Mesh,
    rope: tuple[jax.Array, jax.Array],
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """Sequence/context-parallel prefill of one long sequence, composed
    with tensor parallelism when the mesh's 'model' axis is >1.

    Composition (SURVEY §5.7 "sequence-sharded prefill over ICI"):
      * activations shard over 'seq' (each device owns a token chunk);
      * weights shard over 'model' exactly as in decode (Megatron layout,
        parallel.sharding.param_specs) — each device computes its local
        head group / ffn slice and the two row-parallel products psum over
        'model' (models.llama._layer's ``reduce`` hook);
      * ring attention rotates KV chunks over the 'seq' ICI ring per local
        head group — the two axes compose orthogonally (KV hops carry
        Hkv/tp heads, so TP also shrinks ring traffic per device);
      * a vocab-sharded embedding gathers locally and psums over 'model'.

    Returns (hidden [1, T, D], (k, v) each [L, T, Hkv, hd]) with T sharded
    on 'seq' and Hkv sharded on 'model'. NOTE: the slot cache
    (engine.kvcache) is head-major [L, S, Hkv, C, hd] — transpose the
    returned stacks to [L, Hkv, T, hd] before inserting into a slot.
    """
    n = mesh.shape["seq"]
    tp = mesh.shape.get("model", 1)
    T = tokens.shape[0]
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by seq={n}")
    if tp > 1 and (cfg.num_heads % tp or cfg.num_kv_heads % tp
                   or cfg.intermediate_size % tp):
        # intermediate_size matters too: _sanitize would silently REPLICATE
        # an indivisible ffn weight while the manual psum still assumes
        # partial sums — multiplying the MLP branch by tp
        raise ValueError(
            f"heads ({cfg.num_heads} q / {cfg.num_kv_heads} kv) or "
            f"intermediate_size ({cfg.intermediate_size}) not divisible "
            f"by tensor_parallel {tp}"
        )
    if cfg.num_experts and mesh.shape.get("expert", 1) > 1:
        raise ValueError(
            "expert-parallel MoE prefill runs on the GSPMD path, not the "
            "manual ring shard_map (runner gates SP off for this mesh)"
        )
    Tc = T // n
    dtype = jnp.dtype(cfg.dtype)
    reduce = (lambda t: lax.psum(t, "model")) if tp > 1 else None

    if tp > 1:
        pspec = _tp_param_specs(cfg, mesh, params)
        embed_sharded = tuple(pspec["embed"].q if hasattr(pspec["embed"], "q")
                              else pspec["embed"])[:1] == ("model",)
    else:
        pspec = jax.tree.map(lambda _: P(), params)
        embed_sharded = False

    def embed_local(table, ids):
        """Token gather under a vocab-sharded table: local rows + psum."""
        v_local = table.shape[0]
        offset = lax.axis_index("model") * v_local
        local = jnp.clip(ids - offset, 0, v_local - 1)
        rows = qnt.embed_rows(table, local, dtype)
        in_range = ((ids >= offset) & (ids < offset + v_local))[..., None]
        return lax.psum(jnp.where(in_range, rows, 0), "model")

    def local_fn(params, tokens_c, length, cos_t, sin_t):
        i = lax.axis_index("seq")
        positions = i * Tc + jnp.arange(Tc, dtype=jnp.int32)
        cos = cos_t[positions][None, :, None, :]
        sin = sin_t[positions][None, :, None, :]
        if embed_sharded:
            x = embed_local(params["embed"], tokens_c[None])
        else:
            x = qnt.embed_rows(params["embed"], tokens_c[None], dtype)

        def body(carry, lp):
            def attend(q, k_new, v_new):
                out = ring_attention(
                    q[0], k_new[0], v_new[0], length,
                    n_chunks=n, sliding_window=cfg.sliding_window,
                )
                return out[None], (k_new[0], v_new[0])

            return mdl._layer(cfg, carry, lp, cos, sin, attend, reduce=reduce)

        x, kvs = lax.scan(body, x, params["layers"])
        x = mdl.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        return x, kvs

    kv_heads = "model" if tp > 1 else None
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(pspec, P("seq"), P(), P(), P()),
        out_specs=(
            P(None, "seq", None),
            (P(None, "seq", kv_heads, None), P(None, "seq", kv_heads, None)),
        ),
        check_vma=False,
    )
    return fn(params, tokens, length, rope[0], rope[1])
