"""Pipeline (layer-sharded) parallelism over the 'pipe' mesh axis.

Parity: llama.cpp's layer split mode — its default multi-GPU layout
(``--split-mode layer`` / tensor_split, /root/reference/backend/cpp/llama/
grpc-server.cpp:2240-2262 plumbs the split knobs): each device holds a
contiguous block of layers and activations flow device→device. The point
is HBM CAPACITY scaling — a model whose weights+KV exceed one chip serves
from P chips at params/P per chip — not throughput: decode is
weight-bandwidth-bound and the stage chain reads the same total bytes.

TPU formulation: the stacked layer weights and the KV cache shard their
leading L axis over 'pipe' via shard_map. One forward runs P ticks: every
device applies ITS layer block to whatever activation it holds, then the
activations rotate one hop along the 'pipe' ICI ring (ppermute). Real
data enters at stage 0 and exits stage P-1 after P ticks; KV writes gate
on ``tick == axis_index`` so off-turn (garbage) passes never touch the
cache. v1 runs the 'pipe' axis alone ('data'/'model'/'seq'/'expert' stay
1 — the runner gates; the KV-write closures capture global slot indices,
so slot-sharding composition needs a closure-free rework first).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from localai_tpu.models import llama as mdl
from localai_tpu.models import quant as qnt
from localai_tpu.models.llama import LlamaConfig

from localai_tpu.utils.jaxcompat import shard_map


def _pipe_spec(ndim: int) -> P:
    """Leading-axis-on-'pipe' spec — the one formula for layer-stacked
    weights and the KV stack (pp_forward in_specs, pp_param_specs,
    shard_params_pp all share it so they can never drift)."""
    return P(*(("pipe",) + (None,) * (ndim - 1)))


def pp_forward(
    cfg: LlamaConfig,
    params: Any,
    tokens: jax.Array,      # [B, T] i32
    positions: jax.Array,   # [B, T] i32
    kv_write: Any,          # fn(layer_kv, k, v) -> (new_layer_kv, keys, vals)
    kv_stack: Any,          # stacked KV pytree, L axis 'pipe'-sharded
    mask: jax.Array,        # [B, T, Lk] bool
    rope: tuple[jax.Array, jax.Array],
    mesh: Mesh,
    embeds: Optional[jax.Array] = None,
) -> tuple[jax.Array, Any]:
    """models.llama.forward over a 'pipe'-sharded mesh (v1: pipe alone).

    Same contract as forward(): returns (hidden [B, T, D] replicated
    across 'pipe', updated kv_stack still 'pipe'-sharded).
    """
    n_pipe = mesh.shape["pipe"]
    dtype = jnp.dtype(cfg.dtype)
    cos_t, sin_t = rope

    def local_fn(layers_local, kv_local, embed, final_norm, tokens,
                 positions, mask, emb_in):
        p = lax.axis_index("pipe")
        cos = cos_t[positions][:, :, None, :]
        sin = sin_t[positions][:, :, None, :]
        if emb_in is None:
            x = qnt.embed_rows(embed, tokens, dtype)
        else:
            x = emb_in.astype(dtype)

        def block(x, kv_block, write_real):
            """My layer block over x; KV updates applied only when
            ``write_real`` (this tick carries my real activations)."""

            def body(carry, layer_in):
                lp, layer_kv = layer_in

                def attend(q, k_new, v_new):
                    new_kv, keys, values = kv_write(layer_kv, k_new, v_new)
                    out = mdl._grouped_attn(cfg, q, keys, values, mask)
                    return out, new_kv

                y, new_kv = mdl._layer(cfg, carry, lp, cos, sin, attend)
                new_kv = jax.tree.map(
                    lambda new, old: jnp.where(write_real, new, old),
                    new_kv, layer_kv,
                )
                return y, new_kv

            return lax.scan(body, x, (layers_local, kv_block))

        def tick(carry, s):
            x, kv = carry
            y, new_kv = block(x, kv, write_real=(s == p))
            # keep OFF-TURN (garbage) activations finite so they can't
            # poison the chain with inf/nan before the real data arrives;
            # the on-turn output propagates untouched — genuine overflow
            # must stay visible, exactly as on a single device
            y = jnp.where(
                s == p, y,
                jnp.nan_to_num(y, nan=0.0, posinf=0.0, neginf=0.0))
            y = lax.ppermute(
                y, "pipe", [(i, (i + 1) % n_pipe) for i in range(n_pipe)])
            return (y, new_kv), None

        (x, kv_local), _ = lax.scan(
            tick, (x, kv_local), jnp.arange(n_pipe))
        # after P ticks + rotations the real output sits on stage 0 —
        # broadcast it so every device returns the same hidden state
        x = lax.psum(jnp.where(p == 0, x, jnp.zeros_like(x)), "pipe")
        x = mdl.rms_norm(x, final_norm, cfg.rms_norm_eps)
        return x, kv_local

    lp_specs = jax.tree.map(lambda a: _pipe_spec(a.ndim), params["layers"])
    kv_specs = jax.tree.map(lambda a: _pipe_spec(a.ndim), kv_stack)
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(lp_specs, kv_specs, P(), P(),
                  P(), P(), P(),
                  (P() if embeds is not None else None)),
        out_specs=(P(), kv_specs),
        check_vma=False,
    )
    hidden, new_kv = fn(
        params["layers"], kv_stack, params["embed"], params["final_norm"],
        tokens, positions, mask, embeds,
    )
    return hidden, new_kv


def pp_param_specs(cfg: LlamaConfig, mesh: Mesh) -> dict:
    """PartitionSpecs for pipeline-sharded placement: stacked layer
    weights shard L over 'pipe'; embed/norm/lm_head replicate."""
    from localai_tpu.models.llama import param_shapes

    shapes = param_shapes(cfg)
    specs: dict = {
        "embed": P(),
        "final_norm": P(),
        # no _sanitize: a non-dividing layer count must FAIL placement
        # loudly (the runner validates first) — pp_forward's in_specs use
        # the same unsanitized formula, so placement and execution can
        # never disagree about what is sharded
        "layers": {
            k: _pipe_spec(len(s)) for k, s in shapes["layers"].items()
        },
    }
    if "lm_head" in shapes:
        specs["lm_head"] = P()
    return specs


def shard_params_pp(params: Any, cfg: LlamaConfig, mesh: Mesh) -> Any:
    from jax.sharding import NamedSharding

    from localai_tpu.parallel.sharding import expand_quantized_spec

    specs = pp_param_specs(cfg, mesh)

    def put(spec_leaf, arr):
        spec = expand_quantized_spec(spec_leaf, arr, mesh)
        return jax.tree.map(
            lambda s, a: jax.device_put(a, NamedSharding(mesh, s)),
            spec, arr, is_leaf=lambda x: isinstance(x, P),
        )

    return jax.tree.map(
        put, specs, params, is_leaf=lambda x: isinstance(x, P)
    )
