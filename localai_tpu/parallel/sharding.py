"""Sharding rules: how the llama engine lays out over the device mesh.

This module is the compiled-SPMD replacement for the reference's entire
multi-device story — llama.cpp tensor_split/main_gpu
(/root/reference/core/config/backend_config.go:116-117, backend/cpp/llama/
grpc-server.cpp:2240-2262), the RPC weight-sharding worker mode
(grpc-server.cpp:2233-2236), and vLLM's tensor_parallel_size passthrough
(backend/python/vllm/backend.py:102-103). Instead of shipping tensors over
TCP, we annotate NamedShardings and let XLA insert ICI collectives.

Layout (Megatron-style TP on the 'model' axis, slots on 'data'):

  wq/wk/wv  [L, D, H*hd]   → P(None, None, 'model')   column-parallel
  wo        [L, H*hd, D]   → P(None, 'model', None)   row-parallel
  w_gate/up [L, D, F]      → P(None, None, 'model')
  w_down    [L, F, D]      → P(None, 'model', None)
  embed     [V, D]         → P('model', None)         vocab-sharded
  lm_head   [D, V]         → P(None, 'model')         vocab-sharded logits
  norms                    → replicated
  KV cache  [L, S, Hkv, C, hd] → P(None, 'data', 'model', None, None)
  counts/bias [S, V]       → P('data', 'model')

With this layout one decode step needs exactly two psums per layer (after
attention-out and after mlp-down) plus one all-gather for sampled logits'
top-k — the standard Megatron inference communication pattern, riding ICI.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from localai_tpu.models.llama import LlamaConfig

log = logging.getLogger(__name__)


def _sanitize(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Drop mesh axes whose size does not divide the tensor dim (replicate
    that dim instead) — keeps odd vocab/ffn sizes loadable on any mesh."""
    out = []
    for i, axis in enumerate(spec):
        if axis is None:
            out.append(None)
            continue
        size = mesh.shape[axis]
        if shape[i] % size != 0:
            log.warning(
                "dim %d of shape %s not divisible by mesh axis %r (%d); "
                "replicating", i, shape, axis, size,
            )
            out.append(None)
        else:
            out.append(axis)
    return P(*out)


def param_specs(
    cfg: LlamaConfig, mesh: Mesh, shapes: Optional[dict] = None
) -> dict:
    """PartitionSpec pytree matching models.llama.param_shapes (divisibility-
    sanitized against the mesh)."""
    tp = mesh.shape["model"]
    if cfg.num_heads % tp != 0:
        raise ValueError(
            f"num_heads {cfg.num_heads} not divisible by tensor_parallel {tp}"
        )
    specs: dict[str, Any] = {
        "embed": P("model", None),
        "final_norm": P(),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "model"),
            "wk": P(None, None, "model"),
            "wv": P(None, None, "model"),
            "wo": P(None, "model", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "model"),
            "w_up": P(None, None, "model"),
            "w_down": P(None, "model", None),
        },
    }
    if cfg.attention_bias:
        specs["layers"]["bq"] = P(None, "model")
        specs["layers"]["bk"] = P(None, "model")
        specs["layers"]["bv"] = P(None, "model")
    if cfg.num_experts:
        # Mixtral-class MoE: experts over 'expert' (expert parallelism),
        # ffn width over 'model' (TP) — the two compose; the router is tiny
        # and replicated
        specs["layers"]["moe_gate"] = P(None, None, None)
        specs["layers"]["w_gate"] = P(None, "expert", None, "model")
        specs["layers"]["w_up"] = P(None, "expert", None, "model")
        specs["layers"]["w_down"] = P(None, "expert", "model", None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "model")

    from localai_tpu.models.llama import param_shapes

    shapes = shapes or param_shapes(cfg)
    return jax.tree.map(
        lambda sp, sh: _sanitize(sp, sh, mesh),
        specs, shapes,
        is_leaf=lambda x: isinstance(x, (P, tuple)) and not isinstance(x, dict),
    )


def kv_spec(cfg: LlamaConfig, mesh: Mesh) -> P:
    """KV cache [L, S, Hkv, C, hd]: layers on 'pipe' (pipeline capacity
    mode), slots on 'data', kv heads on 'model'.

    When tp does not divide the kv-head count (deep-GQA models on wide
    meshes), the kv heads are replicated instead — attention q-heads stay
    sharded and XLA broadcasts the cache reads.
    """
    tp = mesh.shape["model"]
    heads = "model" if cfg.num_kv_heads % tp == 0 and tp <= cfg.num_kv_heads else None
    if heads is None and tp > 1:
        log.warning(
            "kv heads (%d) not divisible by tensor_parallel (%d); "
            "replicating KV cache", cfg.num_kv_heads, tp,
        )
    layers = ("pipe" if mesh.shape.get("pipe", 1) > 1
              and cfg.num_layers % mesh.shape["pipe"] == 0 else None)
    return P(layers, "data", heads, None, None)


def paged_kv_spec(cfg: LlamaConfig, mesh: Mesh) -> P:
    """Paged block pool [L, num_blocks, Hkv, block_tokens, hd]: kv heads on
    'model', everything else replicated.

    The pool has no slot axis — blocks are shared by all slots through the
    host-side block tables — so unlike the contiguous cache there is
    nothing to put on 'data'; the [S, MB] device table mirror carries the
    'data' sharding instead (runner.block_tables). The block axis stays
    unsharded on purpose: table values are global physical block ids, and
    every device must be able to walk any slot's table against its own
    head shard. Same deep-GQA fallback as kv_spec: when tp does not
    divide the kv-head count the pool replicates and q-heads stay
    sharded."""
    tp = mesh.shape["model"]
    heads = ("model" if cfg.num_kv_heads % tp == 0 and tp <= cfg.num_kv_heads
             else None)
    if heads is None and tp > 1:
        log.warning(
            "kv heads (%d) not divisible by tensor_parallel (%d); "
            "replicating the paged KV pool", cfg.num_kv_heads, tp,
        )
    return P(None, None, heads, None, None)


def block_table_spec() -> P:
    """Device mirror of the allocator's block tables [S, MB]: slots on
    'data' alongside DecodeState, columns replicated."""
    return P("data", None)


def tp_param_specs(cfg: LlamaConfig, mesh: Mesh, params: Any) -> Any:
    """Per-leaf PartitionSpecs for (a subset of) the trunk params under
    manual tensor parallelism ('model' axis), mirroring shard_params'
    placement — quantized leaves expand to (q, scale) specs. The single
    spec source for every manual-SPMD shard_map over the trunk
    (parallel.ring sequence-parallel prefill, parallel.overlap decode)."""
    specs = param_specs(cfg, mesh, shapes=None)
    # drop spec entries (e.g. lm_head) the caller's param subset omits
    specs = {k: v for k, v in specs.items() if k in params}
    return jax.tree.map(
        lambda sp, arr: expand_quantized_spec(sp, arr, mesh),
        specs, {k: params[k] for k in specs},
        is_leaf=lambda x: isinstance(x, P),
    )


def overlap_intermediate_spec() -> P:
    """Layout of the reduce-scattered row-parallel intermediate in the
    collective/compute-overlap decode path (parallel.overlap): each
    psum_scatter chunk of the attention-out / mlp-down product lands
    [S, T, D/tp] with the hidden dim on 'model' before its all_gather
    re-replicates it. Exposed so tests can pin the decomposition's
    layout contract."""
    return P(None, None, "model")


def state_specs(mesh: Mesh) -> dict:
    """PartitionSpecs for DecodeState fields (see engine.runner)."""
    return {
        "tokens": P("data"),
        "positions": P("data"),
        "active": P("data"),
        "keys": P("data"),
        "counts": P("data", "model"),
        "bias": P("data", "model"),
        "params": P("data"),
    }


def expand_quantized_spec(spec_leaf: P, arr: Any, mesh: Mesh) -> Any:
    """Spec for one param leaf: plain arrays keep ``spec_leaf``; quantized
    weights (models.quant.QuantizedTensor) expand to a QuantizedTensor of
    specs — q with the weight's spec, the per-output-channel scale with the
    same spec minus the contracted axis — so a 'model'-sharded weight keeps
    its scales sharded alongside its output channels and the dequant
    epilogue stays local. The single source of truth for both placement
    (shard_params) and manual-SPMD in_specs (parallel.ring)."""
    from localai_tpu.models.quant import QuantizedTensor, quantized_spec

    if isinstance(arr, QuantizedTensor):
        s_spec = _sanitize(
            quantized_spec(spec_leaf, arr.axis, grouped=arr.mode == "w4"),
            arr.scale.shape, mesh,
        )
        # carry ALL metadata from the source tensor: tree.map pairs this
        # spec tree with the param tree, and aux data (axis, mode,
        # kernel_ok) is part of treedef equality
        return QuantizedTensor(
            q=spec_leaf, scale=s_spec, axis=arr.axis, mode=arr.mode,
            kernel_ok=arr.kernel_ok)
    return spec_leaf


def shard_params(
    params: Any, cfg: LlamaConfig, mesh: Mesh
) -> Any:
    """Place an already-loaded param pytree onto the mesh (specs per
    param_specs + expand_quantized_spec)."""
    specs = param_specs(cfg, mesh)

    def put(spec_leaf, arr):
        spec = expand_quantized_spec(spec_leaf, arr, mesh)
        return jax.tree.map(
            lambda s, a: jax.device_put(a, NamedSharding(mesh, s)),
            spec, arr, is_leaf=lambda x: isinstance(x, P),
        )

    return jax.tree.map(
        put, specs, params, is_leaf=lambda x: isinstance(x, P)
    )


def make_shard_fn(cfg: LlamaConfig, mesh: Mesh, dtype: str = "bfloat16"):
    """shard_fn for models.loader.load_llama_params: places each tensor
    shard-by-shard at load time so the full checkpoint never materializes
    unsharded in device memory."""
    import jax.numpy as jnp

    specs = param_specs(cfg, mesh)
    dt = jnp.dtype(dtype)

    def fn(path: tuple, arr: np.ndarray) -> jax.Array:
        node: Any = specs
        for k in path:
            key = getattr(k, "key", getattr(k, "name", k))
            node = node[key]
        return jax.device_put(
            jnp.asarray(arr, dt), NamedSharding(mesh, node)
        )

    return fn


def slots_per_data_shard(num_slots: int, mesh: Mesh) -> int:
    dp = mesh.shape["data"]
    if num_slots % dp != 0:
        raise ValueError(f"num_slots {num_slots} not divisible by data={dp}")
    return num_slots // dp
