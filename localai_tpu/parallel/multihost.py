"""Multi-host serving: jax.distributed init + deterministic command
mirroring.

Parity: the reference's RPC weight-sharding worker tier
(/root/reference/backend/cpp/llama/grpc-server.cpp run with llama.cpp's
RPC backend + core/p2p worker discovery) — one leader fans work out to
follower hosts holding weight shards. The TPU-native shape is
multi-controller JAX: every host calls jax.distributed.initialize, sees
the global device set, and must execute the SAME jitted programs in the
SAME order so XLA's ICI/DCN collectives line up. The serving stack is
dynamic (requests arrive only at the leader), so the leader re-broadcasts
every engine-mutating call (admit / step_n / set_bias / release) over a
lightweight TCP command channel; followers replay them against their
local ModelRunner replica (same config, same seed → identical traces,
identical collective schedule). Model parallelism itself stays inside
XLA via the mesh (parallel/mesh.py) — this module only solves the
"same program, same order, every host" contract.

Scale note: commands are tiny (token ids + sampling params; the bias row
is the largest at V floats) and ride DCN once per dispatch of
multi_step×slots tokens — negligible next to the per-step ICI traffic
XLA already schedules.
"""

from __future__ import annotations

import base64
import io
import json
import logging
import socket
import struct
import threading
from typing import Any, Optional

import numpy as np

log = logging.getLogger(__name__)


def initialize(coordinator: str, num_processes: int, process_id: int,
               local_device_ids: Optional[list[int]] = None) -> None:
    """jax.distributed.initialize wrapper (must run before first jax use).

    After this, jax.devices() spans every host and a Mesh built over it
    gives pjit programs whose collectives cross ICI/DCN as laid out."""
    import jax

    kwargs: dict[str, Any] = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    log.info("jax.distributed up: process %d/%d, %d global / %d local "
             "devices", process_id, num_processes,
             jax.device_count(), jax.local_device_count())


# ---------------------------------------------------------------------------
# command channel


def _pack(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return struct.pack(">I", len(body)) + body


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("command channel closed")
        buf += chunk
    return buf


def _encode_arg(v: Any) -> Any:
    if isinstance(v, np.ndarray) or (
        hasattr(v, "__array__") and not isinstance(v, (int, float, bool))
        and not isinstance(v, (list, tuple, str, dict))
    ):
        bio = io.BytesIO()
        np.save(bio, np.asarray(v), allow_pickle=False)
        return {"__np__": base64.b64encode(bio.getvalue()).decode()}
    return v


def _decode_arg(v: Any) -> Any:
    if isinstance(v, dict) and "__np__" in v:
        return np.load(io.BytesIO(base64.b64decode(v["__np__"])),
                       allow_pickle=False)
    return v


class CommandLeader:
    """Accepts follower connections and broadcasts every command in
    issue order. Followers that lag apply backpressure (sendall) — the
    group advances in lockstep, which is exactly the SPMD contract.

    Joining requires a token handshake when ``token`` is set (the group's
    shared ``peer_token``): the broadcast stream carries every user
    prompt, so an unauthenticated listener would be an exfiltration
    channel — and a stranger's disconnect would poison the SPMD group."""

    def __init__(self, port: int = 0, expected: int = 0,
                 token: str = ""):
        self._srv = socket.create_server(("0.0.0.0", port))
        self.port = self._srv.getsockname()[1]
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        self.token = token
        self._accepting = threading.Thread(
            target=self._accept_loop, daemon=True, name="mh-accept"
        )
        self._accepting.start()
        self.expected = expected

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # handshake on its own thread: a silent connection (port
            # scanner, TCP health check) must not stall other joins for
            # its 10s timeout
            threading.Thread(
                target=self._admit, args=(conn, addr), daemon=True,
                name="mh-handshake",
            ).start()

    def _admit(self, conn: socket.socket, addr) -> None:
        try:
            self._handshake(conn)
        except Exception as e:  # noqa: BLE001 — reject, keep serving
            log.warning("multihost: rejected connection from %s (%s)",
                        addr, e)
            conn.close()
            return
        with self._lock:
            self._conns.append(conn)
            n = len(self._conns)
        log.info("multihost: follower %s joined (%d connected)", addr, n)

    def _handshake(self, conn: socket.socket) -> None:
        import hmac

        conn.settimeout(10.0)
        (length,) = struct.unpack(">I", _read_exact(conn, 4))
        if length > 4096:
            raise ValueError("oversized handshake")
        hello = json.loads(_read_exact(conn, length))
        offered = str(hello.get("token", ""))
        if self.token and not hmac.compare_digest(offered, self.token):
            conn.sendall(_pack({"ok": False, "error": "bad token"}))
            raise PermissionError("bad peer token")
        conn.sendall(_pack({"ok": True}))
        conn.settimeout(None)

    def wait_for(self, n: int, timeout: float = 120.0) -> None:
        import time

        deadline = time.monotonic() + timeout
        joined = 0
        while time.monotonic() < deadline:
            with self._lock:
                joined = len(self._conns)
            if joined >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(f"only {joined} followers joined")

    def broadcast(self, model: str, method: str, *args, **kwargs) -> None:
        msg = _pack({
            "model": model,
            "m": method,
            "a": [_encode_arg(a) for a in args],
            "k": {k: _encode_arg(v) for k, v in kwargs.items()},
        })
        with self._lock:
            dead = []
            for conn in self._conns:
                try:
                    conn.sendall(msg)
                except OSError as e:
                    log.error("multihost: follower lost (%s)", e)
                    dead.append(conn)
            for conn in dead:
                # a lost follower breaks SPMD — surviving processes would
                # deadlock in collectives. Fail loudly; the supervisor
                # restarts the group (the reference's worker tier dies the
                # same way when an RPC shard drops).
                self._conns.remove(conn)
            if dead and self.expected:
                raise RuntimeError(
                    "multihost follower disconnected; restart the group"
                )

    def close(self) -> None:
        self._srv.close()
        with self._lock:
            for conn in self._conns:
                conn.close()
            self._conns.clear()


class CommandFollower:
    """Connects to the leader and replays commands onto registered
    ModelRunner replicas (keyed by model name) until the channel closes."""

    def __init__(self, leader: str, targets: dict[str, Any],
                 connect_timeout: float = 120.0, token: str = ""):
        import time

        host, _, port = leader.rpartition(":")
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=10.0)
                break
            except OSError:
                # leader may still be booting; keep retrying until the
                # window closes (group formation is racy by nature)
                if time.monotonic() >= deadline:
                    raise
                time.sleep(1.0)
        # handshake: offer the shared peer token, wait for the verdict
        self._sock.sendall(_pack({"token": token}))
        (length,) = struct.unpack(">I", _read_exact(self._sock, 4))
        verdict = json.loads(_read_exact(self._sock, length))
        if not verdict.get("ok"):
            self._sock.close()
            raise PermissionError(
                f"leader rejected follower: {verdict.get('error')}"
            )
        self._sock.settimeout(None)
        self.targets = targets

    def run_forever(self) -> None:
        try:
            while True:
                self.step()
        except ConnectionError:
            log.info("multihost: leader channel closed; follower exiting")

    def step(self) -> None:
        """Apply exactly one mirrored command (tests drive this)."""
        (length,) = struct.unpack(">I", _read_exact(self._sock, 4))
        msg = json.loads(_read_exact(self._sock, length))
        target = self.targets.get(msg["model"])
        if target is None:
            # every host must run every program or collectives desync —
            # a model this follower doesn't serve is a deployment error
            raise RuntimeError(
                f"follower has no replica of model {msg['model']!r}"
            )
        args = [_decode_arg(a) for a in msg["a"]]
        kwargs = {k: _decode_arg(v) for k, v in msg["k"].items()}
        getattr(target, msg["m"])(*args, **kwargs)

    def close(self) -> None:
        self._sock.close()


_leader_singleton: Optional[CommandLeader] = None
_leader_lock = threading.Lock()


def get_leader(port: int, expected: int = 0,
               token: str = "") -> CommandLeader:
    """Process-wide command channel (all mirrored models share it; the
    model name in each message routes replay on the follower side)."""
    global _leader_singleton
    with _leader_lock:
        if _leader_singleton is None:
            _leader_singleton = CommandLeader(port, expected=expected,
                                              token=token)
        return _leader_singleton


# methods whose device effects must replay on every host; the leader's
# return values are host-local reads and never cross the channel
MIRRORED = (
    "admit", "step", "step_n", "step_async", "step_n_async",
    "step_frozen_n", "set_bias", "release", "acquire_slot", "embed",
)


class MirroredRunner:
    """Leader-side ModelRunner proxy: broadcast each mutating call to the
    follower group, then apply it locally. Pure reads pass through.

    Determinism contract: followers constructed their runner from the
    same config/seed, so replaying the call stream step-for-step keeps
    every host inside the same jitted program at the same time."""

    def __init__(self, runner: Any, leader: CommandLeader, model: str):
        self._runner = runner
        self._leader = leader
        self._model = model

    def __getattr__(self, name: str):
        attr = getattr(self._runner, name)
        if name not in MIRRORED or not callable(attr):
            return attr

        def call(*args, **kwargs):
            self._leader.broadcast(self._model, name, *args, **kwargs)
            return attr(*args, **kwargs)

        return call
