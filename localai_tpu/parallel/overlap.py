"""Collective/compute overlap for the meshed paged decode hot path.

The GSPMD meshed decode (pjit + NamedSharding, the PR 8 default) leaves
the two per-layer Megatron psums — after attention-out and after mlp-down
— as monolithic all-reduces whose ICI latency sits on the critical path
of every decoded token. This module runs the SAME trunk math as a manual
shard_map over the mesh (like parallel.ring does for sequence-parallel
prefill) so the reduction can be *decomposed*: each row-parallel product
splits into chunks along the hidden dim, every chunk goes through
``psum_scatter`` (each device sums only its D/tp tile —
parallel.sharding.overlap_intermediate_spec is the scattered layout)
followed by a tiled ``all_gather``, and because the chunks are
independent collectives instead of one fused all-reduce, XLA's
latency-hiding scheduler can start chunk ``i``'s ICI transfer while
chunk ``i+1``'s partial product (and the next layer-region matmul) is
still on the MXU. Communication volume is identical to the plain psum
(reduce-scatter + all-gather IS the canonical all-reduce decomposition);
only the exposure of the latency changes.

Numerics: ``psum_scatter`` + ``all_gather`` computes the same per-element
device sums as ``psum`` — on a 2-wide 'model' axis there is exactly one
addition per element, so greedy decode is BYTE-IDENTICAL between
``mode="overlap"`` and ``mode="psum"`` (pinned by tests/test_overlap.py);
on wider meshes the summation tree may differ at the ULP level, the same
caveat every all-reduce implementation carries.

Scope gates (``resolve_mode``): paged KV, 'model' the only busy mesh axis
(data/seq/expert/pipe == 1 — the pool writes of distinct data shards
cannot be reconciled manually without an extra collective), dense MLP,
and tp dividing heads/kv-heads/ffn/hidden. Everything else keeps the
GSPMD path. Knob: ``LOCALAI_MESH_OVERLAP`` = auto/1 (overlap when
supported, the default), ``psum`` (manual shard_map, undecomposed psum —
the parity reference), ``0`` (GSPMD, the pre-overlap behavior).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from localai_tpu.models import llama as mdl
from localai_tpu.models import quant as qnt
from localai_tpu.models.llama import LlamaConfig
from localai_tpu.utils.jaxcompat import shard_map

log = logging.getLogger(__name__)

TRUNK_KEYS = ("embed", "final_norm", "layers")


def resolve_mode(cfg: LlamaConfig, mesh: Optional[Mesh],
                 requested: str = "auto") -> tuple[str, str]:
    """The overlap-path decision: ("overlap" | "psum" | "", reason).

    "" keeps the GSPMD decode; the reason explains any gate that fired
    (empty when the requested mode is simply honored)."""
    req = (requested or "auto").strip().lower()
    if req in ("0", "off", "none"):
        return "", ""
    if req not in ("auto", "1", "overlap", "psum"):
        return "", f"unknown LOCALAI_MESH_OVERLAP value {requested!r}"
    want = "psum" if req == "psum" else "overlap"
    if mesh is None:
        return "", ""
    tp = mesh.shape.get("model", 1)
    if tp <= 1:
        return "", ""
    busy = [ax for ax in ("data", "seq", "expert", "pipe")
            if mesh.shape.get(ax, 1) > 1]
    if busy:
        return "", (f"mesh also shards {busy}; manual-TP overlap needs "
                    "'model' as the only busy axis")
    if cfg.num_experts:
        return "", "MoE decode stays on the GSPMD path"
    if (cfg.num_heads % tp or cfg.num_kv_heads % tp
            or cfg.intermediate_size % tp or cfg.hidden_size % tp):
        return "", (
            f"heads ({cfg.num_heads} q / {cfg.num_kv_heads} kv), ffn "
            f"({cfg.intermediate_size}) or hidden ({cfg.hidden_size}) "
            f"not divisible by tensor_parallel {tp}")
    return want, ""


def make_reduce(mode: str, tp: int, chunks: int = 4,
                axis_name: str = "model"):
    """The row-parallel reduction for the manual-TP trunk.

    "psum": one fused all-reduce (the parity reference). "overlap": split
    the product into ``chunks`` independent psum_scatter+all_gather pairs
    along the hidden dim so their ICI transfers overlap neighboring
    compute. Falls back chunk-by-chunk to the largest split the dim
    supports; an indivisible dim degrades to the plain psum."""
    if tp <= 1:
        return None
    if mode == "psum":
        return lambda x: lax.psum(x, axis_name)

    def overlap_reduce(x):
        d = x.shape[-1]
        n = max(1, min(chunks, d))
        while n > 1 and d % (n * tp):
            n -= 1
        if d % tp:
            return lax.psum(x, axis_name)
        dim = x.ndim - 1
        pieces = jnp.split(x, n, axis=-1) if n > 1 else [x]
        out = [
            lax.all_gather(
                lax.psum_scatter(p, axis_name, scatter_dimension=dim,
                                 tiled=True),
                axis_name, axis=dim, tiled=True)
            for p in pieces
        ]
        return jnp.concatenate(out, axis=-1) if n > 1 else out[0]

    return overlap_reduce


def _embed_local(table, ids, dtype, axis_name: str = "model"):
    """Token gather under a vocab-sharded embedding: local rows + psum
    (same idiom as parallel.ring's sequence-parallel embed)."""
    v_local = table.shape[0]
    offset = lax.axis_index(axis_name) * v_local
    local = jnp.clip(ids - offset, 0, v_local - 1)
    rows = qnt.embed_rows(table, local, dtype)
    in_range = ((ids >= offset) & (ids < offset + v_local))[..., None]
    return lax.psum(jnp.where(in_range, rows, 0), axis_name)


def paged_decode_trunk(
    cfg: LlamaConfig,
    trunk: Any,              # {embed, final_norm, layers} param subset
    mesh: Mesh,
    tokens: jax.Array,       # [S] i32
    positions: jax.Array,    # [S] i32
    kv_stacked: tuple,       # PagedKVCache.stacked() — pool (+ scales)
    tables: jax.Array,       # [S, MB] i32 device table mirror
    rope: tuple[jax.Array, jax.Array],
    *,
    ctx_pad: int,
    mode: str = "overlap",
    chunks: int = 4,
    use_pallas: bool = False,
    interpret: bool = False,
    num_buffers: int = 2,
) -> tuple[jax.Array, tuple]:
    """One batched single-token paged decode FORWARD under manual tensor
    parallelism: returns (hidden [S, 1, D] replicated, new kv_stacked pool
    sharded as it arrived). Sampling/logits stay outside (the caller's
    ``_decode_tail`` — vocab-sharded logits keep their GSPMD path).

    The shard_map body is the per-device slice of the trunk: Megatron
    column/row-parallel matmuls over the local head/ffn shard, the paged
    attention (Pallas kernel or the gather ref) over the local kv-head
    shard of the pool, the KV scatter through the (replicated, data==1)
    block tables into the local shard, and the two per-layer reductions
    via :func:`make_reduce` — decomposed when ``mode="overlap"``."""
    from localai_tpu.engine import kvcache as kvc
    from localai_tpu.parallel import sharding as shd

    tp = mesh.shape["model"]
    pspec = shd.tp_param_specs(cfg, mesh, trunk)
    embed_spec = pspec["embed"].q if hasattr(pspec["embed"], "q") \
        else pspec["embed"]
    embed_sharded = tuple(embed_spec)[:1] == ("model",)
    dtype = jnp.dtype(cfg.dtype)
    quantized = len(kv_stacked) == 4
    heads = "model" if cfg.num_kv_heads % tp == 0 else None
    pool_spec = P(None, None, heads, None, None)
    scale_spec = P(None, None, heads, None)
    kv_specs = ((pool_spec, pool_spec, scale_spec, scale_spec)
                if quantized else (pool_spec, pool_spec))

    def local_fn(trunk, tokens, positions, kv_stacked, tables,
                 cos_t, sin_t):
        reduce = make_reduce(mode, tp, chunks)
        mask = kvc.decode_mask(cfg, positions, ctx_pad)
        write = kvc.paged_decode_write(tables, positions, raw=use_pallas)
        if embed_sharded:
            x = _embed_local(trunk["embed"], tokens[:, None], dtype)
        else:
            x = qnt.embed_rows(trunk["embed"], tokens[:, None], dtype)
        attn = None
        if use_pallas:
            from localai_tpu import ops

            kernel = partial(
                ops.paged_decode_attention,
                sliding_window=cfg.sliding_window,
                interpret=interpret, num_buffers=num_buffers,
            )

            def attn(q, keys, values, _mask):  # q [S,1,Hq_loc,hd]
                if quantized:  # (packed pool, f32 scales) — fused dequant
                    out = kernel(q[:, 0], keys[0], values[0], tables,
                                 positions, keys[1], values[1])
                else:
                    out = kernel(q[:, 0], keys, values, tables, positions)
                return out[:, None]

        hidden, new_stack = mdl.forward(
            cfg, trunk, tokens[:, None], positions[:, None],
            write, kv_stacked, mask, (cos_t, sin_t),
            attn=attn, embeds=x, reduce=reduce,
        )
        return hidden, new_stack

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(pspec, P(None), P(None), kv_specs, P(None, None),
                  P(), P()),
        out_specs=(P(None, None, None), kv_specs),
        check_vma=False,
    )
    return fn(trunk, tokens, positions, kv_stacked, tables,
              rope[0], rope[1])
