"""FLUX-class text→image pipeline: rectified-flow MMDiT + dual text
encoders (CLIP pooled + T5 sequence) + 16-channel VAE.

Parity: `FluxPipeline` in the reference's diffusers backend
(/root/reference/backend/python/diffusers/backend.py:21,249-262) and the
GPU AIO default image model (aio/gpu-8g/image-gen.yaml). Serves behind the
same `/v1/images/generations` route via resolve_image_model.

TPU design mirrors image.pipeline.DiffusionPipeline: one jitted velocity
step per latent bucket, the host loops the (dynamic) step count, and the
2x2 latent patchify keeps the token sequence MXU-batched. FLUX is
guidance-distilled — no CFG batch doubling; guidance rides the embedding.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.image import clip as clip_mod
from localai_tpu.image import mmdit
from localai_tpu.image import t5 as t5_mod
from localai_tpu.image import vae as vae_mod
from localai_tpu.image.pipeline import GenerationResult

log = logging.getLogger(__name__)


class FluxPipeline:
    """One loaded FLUX-class model (MMDiT + VAE + CLIP + T5)."""

    def __init__(self, cfg, params, vae_cfg, vae_params,
                 clip_cfg, clip_params, clip_tokenizer,
                 t5_cfg, t5_params, t5_tokenizer, *,
                 vae_shift: float = 0.0, vae_scale: float = 1.0,
                 default_steps: int = 4, default_guidance: float = 3.5,
                 max_t5_len: int = 128, ref: str = "",
                 dynamic_shift: bool = True, shift: float = 1.0,
                 default_cfg_scale: Optional[float] = None,
                 default_scheduler: str = "", clip_skip: int = 0):
        # the last three exist for ModelConfig.diffusers parity with the
        # UNet pipeline: cfg_scale maps onto the distilled guidance;
        # scheduler/clip_skip have no FLUX equivalent and are ignored
        if default_cfg_scale is not None:
            default_guidance = default_cfg_scale
        del default_scheduler, clip_skip
        self.cfg = cfg
        self.params = params
        self.vae_cfg = vae_cfg
        self.vae_params = vae_params
        self.clip_cfg = clip_cfg
        self.clip_params = clip_params
        self.clip_tokenizer = clip_tokenizer
        self.t5_cfg = t5_cfg
        self.t5_params = t5_params
        self.t5_tokenizer = t5_tokenizer
        self.vae_shift = vae_shift
        self.vae_scale = vae_scale
        self.default_steps = default_steps
        self.default_guidance = default_guidance
        self.dynamic_shift = dynamic_shift
        self.shift = shift
        self.max_t5_len = max_t5_len
        self.ref = ref
        self._encode = jax.jit(self._encode_fn)
        self._velocity = jax.jit(self._velocity_fn)
        self._decode = jax.jit(self._decode_fn, static_argnames=("h", "w"))
        self._encode_img = jax.jit(self._encode_img_fn)

    # -- jitted programs -------------------------------------------------

    def _encode_fn(self, clip_tokens, t5_tokens):
        _, pooled = clip_mod.encode_sdxl(
            self.clip_cfg, self.clip_params, clip_tokens)
        txt = t5_mod.encode(self.t5_cfg, self.t5_params, t5_tokens)
        return pooled, txt

    def _velocity_fn(self, latents, txt, pooled, sigma, guidance,
                     img_ids, txt_ids):
        return mmdit.forward(
            self.cfg, self.params, latents, txt, pooled,
            jnp.full((latents.shape[0],), sigma, jnp.float32),
            img_ids, txt_ids,
            guidance=jnp.full((latents.shape[0],), guidance, jnp.float32),
        )

    def _encode_img_fn(self, img):
        """img [1, H, W, 3] in [-1, 1] → packed model-space latent tokens
        [1, (h/2)(w/2), 4*Cz] — the exact inverse of _decode_fn's unpack.

        vae.encode returns z_raw * vae_cfg.scaling_factor (the SD
        convention baked into the vae module); FLUX model space is
        (z_raw - shift) * vae_scale — derive z_raw explicitly so the two
        scale sources can never silently diverge."""
        z_raw = (vae_mod.encode(self.vae_cfg, self.vae_params, img)
                 / self.vae_cfg.scaling_factor)
        zm = (z_raw - self.vae_shift) * self.vae_scale  # [1, h, w, Cz]
        _, h, w, cz = zm.shape
        x = zm.reshape(1, h // 2, 2, w // 2, 2, cz)
        x = x.transpose(0, 1, 3, 5, 2, 4)              # (B,h2,w2,C,ph,pw)
        return x.reshape(1, (h // 2) * (w // 2), 4 * cz)

    def _decode_fn(self, packed, *, h: int, w: int):
        """packed [1, (h/2)(w/2), 4*Cz] → image uint8 [H, W, 3]
        (h, w are LATENT dims). Token layout is channel-major (C, ph, pw) —
        diffusers FluxPipeline._pack_latents order, which the x_embedder
        weights of real checkpoints assume."""
        cz = self.vae_cfg.latent_channels
        x = packed.reshape(1, h // 2, w // 2, cz, 2, 2)
        # (B, h2, w2, C, ph, pw) → NHWC (B, h2·ph, w2·pw, C) — the image
        # stack is NHWC throughout (vae.decode takes [B, H, W, C])
        x = x.transpose(0, 1, 4, 2, 5, 3).reshape(1, h, w, cz)
        z = x / self.vae_scale + self.vae_shift
        img = vae_mod.decode(self.vae_cfg, self.vae_params, z)
        return jnp.clip((img + 1.0) * 127.5, 0, 255).astype(jnp.uint8)

    # -- host API --------------------------------------------------------

    def _tokenize_clip(self, text: str) -> np.ndarray:
        from localai_tpu.image.pipeline import tokenize_clip

        return tokenize_clip(self.clip_tokenizer, self.clip_cfg, text)

    def _tokenize_t5(self, text: str) -> np.ndarray:
        T = self.max_t5_len
        ids = list(self.t5_tokenizer.encode(text))[: T - 1] + [1]  # </s>
        row = np.zeros((1, T), np.int32)                           # <pad>=0
        row[0, : len(ids)] = ids
        return row

    @staticmethod
    def _bucket(v: int, lo: int = 64, quantum: int = 64, hi: int = 2048) -> int:
        from localai_tpu.image.pipeline import bucket_dim

        return bucket_dim(v, lo, quantum, hi)

    def generate(
        self,
        prompt: str,
        *,
        negative_prompt: str = "",   # accepted for API parity; FLUX is
                                     # guidance-distilled and ignores it
        width: int = 512,
        height: int = 512,
        steps: Optional[int] = None,
        cfg_scale: Optional[float] = None,   # mapped to distilled guidance
        seed: Optional[int] = None,
        scheduler: str = "",                 # FLUX always rectified-flow
        init_image=None,                     # [H, W, 3] uint8 (img2img)
        strength: float = 0.75,
        **_,
    ) -> GenerationResult:
        del negative_prompt, scheduler
        steps = steps or self.default_steps
        guidance = self.default_guidance if cfg_scale is None else cfg_scale
        width, height = self._bucket(width), self._bucket(height)
        ds = self.vae_cfg.downscale
        h, w = height // ds, width // ds        # latent dims (must be even)
        seed = int(seed) if seed is not None else int(
            np.random.SeedSequence().entropy % (2 ** 31))

        pooled, txt = self._encode(
            jnp.asarray(self._tokenize_clip(prompt)),
            jnp.asarray(self._tokenize_t5(prompt)),
        )
        n_img = (h // 2) * (w // 2)
        ids = np.zeros((n_img, 3), np.float32)
        ids[:, 1] = np.arange(n_img) // (w // 2)
        ids[:, 2] = np.arange(n_img) % (w // 2)
        img_ids = jnp.asarray(ids)
        txt_ids = jnp.zeros((txt.shape[1], 3), jnp.float32)

        key = jax.random.key(seed)
        cz = self.vae_cfg.latent_channels
        x = jax.random.normal(key, (1, n_img, 4 * cz), jnp.float32)

        sigmas = mmdit.flow_sigmas(
            steps, n_img, dynamic=self.dynamic_shift, shift=self.shift)
        i0 = 0
        if init_image is not None:
            # rectified-flow img2img (diffusers FluxImg2ImgPipeline
            # scale_noise): start at x = (1-sigma)*z0 + sigma*noise and run
            # the remaining int(steps*strength) steps
            run = max(1, min(steps, int(steps * strength)))
            i0 = steps - run
            img = jnp.asarray(init_image, jnp.float32) / 127.5 - 1.0
            img = jax.image.resize(img[None], (1, height, width, 3),
                                   "linear")
            z0 = self._encode_img(img)
            s0 = float(sigmas[i0])
            x = (1.0 - s0) * z0 + s0 * x
        for i in range(i0, steps):
            v = self._velocity(x, txt, pooled, float(sigmas[i]),
                               float(guidance), img_ids, txt_ids)
            x = x + (float(sigmas[i + 1]) - float(sigmas[i])) * v

        img = np.asarray(self._decode(x, h=h, w=w))[0]
        return GenerationResult(image=img, seed=seed)


def debug_flux_pipeline(seed: int = 0, **defaults) -> FluxPipeline:
    """Random-weight tiny FLUX (64x64 output; CPU-fast) — the flux-class
    analogue of debug:sd-tiny."""
    from localai_tpu.utils.tokenizer import ByteTokenizer

    cfg = mmdit.FluxConfig(
        in_channels=16, num_layers=2, num_single_layers=2,
        attention_head_dim=16, num_attention_heads=4,
        joint_attention_dim=32, pooled_projection_dim=64,
        guidance_embeds=True, axes_dims_rope=(4, 6, 6),
    )
    vae_cfg = vae_mod.VAEConfig(
        base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        latent_channels=4,
    )
    clip_cfg = clip_mod.CLIPTextConfig(
        vocab_size=258, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_length=16, eos_token_id=257,
    )
    t5_cfg = t5_mod.T5Config(
        vocab_size=258, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16,
    )
    k1, k2, k3, k4 = jax.random.split(jax.random.key(seed), 4)
    t5_shapes = {
        "embed": (t5_cfg.vocab_size, t5_cfg.d_model),
        "rel_embed": (t5_cfg.relative_attention_num_buckets,
                      t5_cfg.num_heads),
        "final_ln": (t5_cfg.d_model,),
        "layers": {
            "ln1": (t5_cfg.num_layers, t5_cfg.d_model),
            "wq": (t5_cfg.num_layers, t5_cfg.d_model,
                   t5_cfg.num_heads * t5_cfg.d_kv),
            "wk": (t5_cfg.num_layers, t5_cfg.d_model,
                   t5_cfg.num_heads * t5_cfg.d_kv),
            "wv": (t5_cfg.num_layers, t5_cfg.d_model,
                   t5_cfg.num_heads * t5_cfg.d_kv),
            "wo": (t5_cfg.num_layers, t5_cfg.num_heads * t5_cfg.d_kv,
                   t5_cfg.d_model),
            "ln2": (t5_cfg.num_layers, t5_cfg.d_model),
            "wi0": (t5_cfg.num_layers, t5_cfg.d_model, t5_cfg.d_ff),
            "wi1": (t5_cfg.num_layers, t5_cfg.d_model, t5_cfg.d_ff),
            "wo2": (t5_cfg.num_layers, t5_cfg.d_ff, t5_cfg.d_model),
        },
    }
    flat, tdef = jax.tree.flatten_with_path(
        t5_shapes, is_leaf=lambda x: isinstance(x, tuple))
    t5_keys = jax.random.split(k4, len(flat))
    # init keyed by leaf NAME: only the norm gains are ones — a shape
    # heuristic would also catch the embedding table, making every token's
    # embedding identical and the debug pipeline prompt-blind
    t5_params = jax.tree.unflatten(tdef, [
        jnp.ones(s, jnp.float32) if str(p[-1].key).startswith(("ln",
                                                               "final_ln"))
        else jax.random.normal(k, s, jnp.float32) * 0.05
        for (p, s), k in zip(flat, t5_keys)
    ])
    defaults.setdefault("default_steps", 2)
    return FluxPipeline(
        cfg, mmdit.init_params(k1, cfg),
        vae_cfg, vae_mod.init_params(k2, vae_cfg),
        clip_cfg, clip_mod.init_params(k3, clip_cfg), ByteTokenizer(),
        t5_cfg, t5_params, ByteTokenizer(),
        ref="debug:flux-tiny", **defaults,
    )


# -- loading ----------------------------------------------------------------

def load_flux_pipeline(d: str | Path, **defaults) -> FluxPipeline:
    """diffusers FLUX layout: transformer/ vae/ text_encoder/ (CLIP)
    text_encoder_2/ (T5) tokenizer/ tokenizer_2/."""
    from localai_tpu.image.loader import (
        _load_clip_tokenizer,
        _to_device,
        load_text_encoder,
        load_vae,
    )

    d = Path(d)
    tcfg_json = json.loads((d / "transformer" / "config.json").read_text())
    cfg = mmdit.FluxConfig.from_hf(tcfg_json)
    params = _load_transformer(d / "transformer", cfg)
    vae_cfg, vae_params = load_vae(d / "vae")
    vae_json = json.loads((d / "vae" / "config.json").read_text())
    clip_cfg, clip_params = load_text_encoder(d / "text_encoder")
    t5_cfg, t5_params = t5_mod.load_hf_t5(d / "text_encoder_2")
    clip_tok = _load_clip_tokenizer(d / "tokenizer", clip_cfg)
    t5_tok = _load_t5_tokenizer(d / "tokenizer_2")
    # the scheduler config decides the sigma shift: schnell declares
    # use_dynamic_shifting=false + shift=1.0, dev dynamic shifting
    sched: dict = {}
    sched_path = d / "scheduler" / "scheduler_config.json"
    if sched_path.exists():
        try:
            sched = json.loads(sched_path.read_text())
        except ValueError:
            log.warning("unreadable scheduler_config.json in %s", d)
    defaults.setdefault(
        "dynamic_shift", bool(sched.get("use_dynamic_shifting", True)))
    defaults.setdefault("shift", float(sched.get("shift", 1.0)))
    log.info("loaded FLUX pipeline from %s (dim %d, %d+%d blocks)",
             d, cfg.dim, cfg.num_layers, cfg.num_single_layers)
    return FluxPipeline(
        cfg, _to_device(params, cfg.dtype),
        vae_cfg, _to_device(vae_params, vae_cfg.dtype),
        clip_cfg, _to_device(clip_params, clip_cfg.dtype),
        clip_tok,
        t5_cfg, _to_device(t5_params, t5_cfg.dtype), t5_tok,
        vae_shift=vae_json.get("shift_factor", 0.0) or 0.0,
        vae_scale=vae_json.get("scaling_factor", 1.0) or 1.0,
        ref=str(d), **defaults,
    )


def _load_t5_tokenizer(d: Path):
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(str(d))

        class _Wrap:
            vocab_size = tok.vocab_size

            def encode(self, text: str, add_bos: bool = False):
                return tok(text, add_special_tokens=False).input_ids

            def decode(self, ids):
                return tok.decode(ids)

        return _Wrap()
    except Exception as e:  # noqa: BLE001
        log.warning("T5 tokenizer load failed (%s); using byte tokenizer", e)
        from localai_tpu.utils.tokenizer import ByteTokenizer

        return ByteTokenizer()


def _load_transformer(td: Path, cfg: mmdit.FluxConfig) -> dict:
    """diffusers FluxTransformer2DModel state dict → mmdit param tree."""
    from localai_tpu.image.loader import _np, _open_dir

    t = _open_dir(td)

    def lin(prefix):
        return _np(t, f"{prefix}.weight").T, _np(t, f"{prefix}.bias")

    def mlp2(prefix):
        w1, b1 = lin(f"{prefix}.linear_1")
        w2, b2 = lin(f"{prefix}.linear_2")
        return {"w1": w1, "b1": b1, "w2": w2, "b2": b2}

    params: dict = {}
    params["x_embed_w"], params["x_embed_b"] = lin("x_embedder")
    params["ctx_embed_w"], params["ctx_embed_b"] = lin("context_embedder")
    params["time_mlp"] = mlp2("time_text_embed.timestep_embedder")
    params["text_mlp"] = mlp2("time_text_embed.text_embedder")
    if cfg.guidance_embeds:
        params["guid_mlp"] = mlp2("time_text_embed.guidance_embedder")
    params["norm_out_w"], params["norm_out_b"] = lin("norm_out.linear")
    params["proj_out_w"], params["proj_out_b"] = lin("proj_out")

    def stack_lin(fmt, n):
        ws, bs = [], []
        for i in range(n):
            w, b = lin(fmt.format(i=i))
            ws.append(w)
            bs.append(b)
        return np.stack(ws), np.stack(bs)

    def stack_w(fmt, n):
        return np.stack([_np(t, fmt.format(i=i)) for i in range(n)])

    Ld, Ls = cfg.num_layers, cfg.num_single_layers
    D = "transformer_blocks.{i}."
    dd: dict = {}
    dd["mod_x_w"], dd["mod_x_b"] = stack_lin(D + "norm1.linear", Ld)
    dd["mod_c_w"], dd["mod_c_b"] = stack_lin(D + "norm1_context.linear", Ld)
    for ours, theirs in (("wq_x", "attn.to_q"), ("wk_x", "attn.to_k"),
                         ("wv_x", "attn.to_v"), ("wo_x", "attn.to_out.0"),
                         ("wq_c", "attn.add_q_proj"),
                         ("wk_c", "attn.add_k_proj"),
                         ("wv_c", "attn.add_v_proj"),
                         ("wo_c", "attn.to_add_out")):
        dd[ours], dd["b" + ours[1:]] = stack_lin(D + theirs, Ld)
    dd["qn_x"] = stack_w(D + "attn.norm_q.weight", Ld)
    dd["kn_x"] = stack_w(D + "attn.norm_k.weight", Ld)
    dd["qn_c"] = stack_w(D + "attn.norm_added_q.weight", Ld)
    dd["kn_c"] = stack_w(D + "attn.norm_added_k.weight", Ld)
    dd["ff_x_w1"], dd["ff_x_b1"] = stack_lin(D + "ff.net.0.proj", Ld)
    dd["ff_x_w2"], dd["ff_x_b2"] = stack_lin(D + "ff.net.2", Ld)
    dd["ff_c_w1"], dd["ff_c_b1"] = stack_lin(D + "ff_context.net.0.proj", Ld)
    dd["ff_c_w2"], dd["ff_c_b2"] = stack_lin(D + "ff_context.net.2", Ld)
    params["double"] = dd

    S = "single_transformer_blocks.{i}."
    ss: dict = {}
    ss["mod_w"], ss["mod_b"] = stack_lin(S + "norm.linear", Ls)
    for ours, theirs in (("wq", "attn.to_q"), ("wk", "attn.to_k"),
                         ("wv", "attn.to_v")):
        ss[ours], ss["b" + ours[1:]] = stack_lin(S + theirs, Ls)
    ss["qn"] = stack_w(S + "attn.norm_q.weight", Ls)
    ss["kn"] = stack_w(S + "attn.norm_k.weight", Ls)
    ss["mlp_w"], ss["mlp_b"] = stack_lin(S + "proj_mlp", Ls)
    ss["out_w"], ss["out_b"] = stack_lin(S + "proj_out", Ls)
    params["single"] = ss
    return params
