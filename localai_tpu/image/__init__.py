"""Image generation: JAX latent diffusion for TPU.

The TPU-native replacement for the reference's image backends — the
diffusers Python worker (/root/reference/backend/python/diffusers/
backend.py:74-474) and the NCNN stable-diffusion Go backend
(/root/reference/backend/go/image/stablediffusion/stablediffusion.go) —
rebuilt as pure-functional JAX: an SD-class UNet with cross-attention,
an AutoencoderKL VAE, a CLIP text encoder, and sigma-space samplers, all
jitted with static shapes (one compiled step program per latent size).
FLUX-class rectified-flow MMDiT models (image.flux / image.mmdit, dual
CLIP+T5 conditioning) serve behind the same resolve_image_model router.
"""

from localai_tpu.image.pipeline import DiffusionPipeline, resolve_image_model

__all__ = ["DiffusionPipeline", "resolve_image_model"]
