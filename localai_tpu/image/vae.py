"""AutoencoderKL VAE (SD-class) — functional JAX, NHWC.

Latent codec for the diffusion pipeline: encoder for img2img init latents,
decoder for final images. Capability parity: the VAE inside the reference's
diffusers pipelines (/root/reference/backend/python/diffusers/backend.py
txt2img/img2img paths). ResBlocks without time embedding, one single-head
spatial attention at the bottleneck, nearest-up/stride-2-down resampling.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from localai_tpu.image.unet import conv2d, group_norm

PyTree = Any


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    scaling_factor: float = 0.18215
    dtype: str = "bfloat16"

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mult) - 1)

    @classmethod
    def from_hf(cls, hf: dict) -> "VAEConfig":
        block_out = hf.get("block_out_channels", [128, 256, 512, 512])
        base = block_out[0]
        return cls(
            in_channels=hf.get("in_channels", 3),
            latent_channels=hf.get("latent_channels", 4),
            base_channels=base,
            channel_mult=tuple(c // base for c in block_out),
            num_res_blocks=hf.get("layers_per_block", 2),
            scaling_factor=hf.get("scaling_factor", 0.18215),
        )


def _res(x, p):
    h = jax.nn.silu(group_norm(x, p["norm1"]))
    h = conv2d(h, p["conv1"])
    h = jax.nn.silu(group_norm(h, p["norm2"]))
    h = conv2d(h, p["conv2"])
    if "skip" in p:
        x = conv2d(x, p["skip"])
    return x + h


def _attn(x, p):
    """Single-head spatial self-attention at the bottleneck (f32 softmax)."""
    B, H, W, C = x.shape
    h = group_norm(x, p["norm"]).reshape(B, H * W, C)
    q = h @ p["wq"].astype(h.dtype) + p["bq"].astype(h.dtype)
    k = h @ p["wk"].astype(h.dtype) + p["bk"].astype(h.dtype)
    v = h @ p["wv"].astype(h.dtype) + p["bv"].astype(h.dtype)
    scores = jnp.einsum("bnc,bmc->bnm", q, k) / math.sqrt(C)
    probs = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(h.dtype)
    out = jnp.einsum("bnm,bmc->bnc", probs, v)
    out = out @ p["wo"].astype(h.dtype) + p["bo"].astype(h.dtype)
    return x + out.reshape(B, H, W, C)


def decode(cfg: VAEConfig, params: PyTree, latents) -> jax.Array:
    """Latents [B,h,w,L] (already divided by scaling_factor) → images
    [B, h*downscale, w*downscale, 3] in [-1, 1] (f32)."""
    p = params["decoder"]
    x = latents.astype(jnp.dtype(cfg.dtype))
    x = conv2d(x, params["post_quant_conv"])
    x = conv2d(x, p["conv_in"])
    x = _res(x, p["mid"]["res1"])
    x = _attn(x, p["mid"]["attn"])
    x = _res(x, p["mid"]["res2"])
    for lp in p["up"]:
        for rp in lp["res"]:
            x = _res(x, rp)
        if "up" in lp:
            B, H, W, C = x.shape
            x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
            x = conv2d(x, lp["up"])
    x = jax.nn.silu(group_norm(x, p["norm_out"]))
    return conv2d(x, p["conv_out"]).astype(jnp.float32)


def encode(cfg: VAEConfig, params: PyTree, images, rng=None) -> jax.Array:
    """Images [B,H,W,3] in [-1,1] → latents [B,H/ds,W/ds,L] scaled by
    scaling_factor (mode of the posterior unless rng is given)."""
    p = params["encoder"]
    x = images.astype(jnp.dtype(cfg.dtype))
    x = conv2d(x, p["conv_in"])
    for lp in p["down"]:
        for rp in lp["res"]:
            x = _res(x, rp)
        if "down" in lp:
            x = conv2d(x, lp["down"], stride=2, padding=((0, 1), (0, 1)))
    x = _res(x, p["mid"]["res1"])
    x = _attn(x, p["mid"]["attn"])
    x = _res(x, p["mid"]["res2"])
    x = jax.nn.silu(group_norm(x, p["norm_out"]))
    x = conv2d(x, p["conv_out"])              # [B,h,w,2L]: mean ‖ logvar
    x = conv2d(x, params["quant_conv"])
    mean, logvar = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if rng is not None:
        std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
        mean = mean + std * jax.random.normal(rng, mean.shape)
    return mean * cfg.scaling_factor


# ---------------------------------------------------------------------------
# shapes / init
# ---------------------------------------------------------------------------

def _conv_shape(cin, cout, k=3):
    return {"w": (k, k, cin, cout), "b": (cout,)}


def _res_shapes(cin, cout):
    p = {
        "norm1": {"g": (cin,), "b": (cin,)},
        "conv1": _conv_shape(cin, cout),
        "norm2": {"g": (cout,), "b": (cout,)},
        "conv2": _conv_shape(cout, cout),
    }
    if cin != cout:
        p["skip"] = _conv_shape(cin, cout, k=1)
    return p


def _attn_shapes(ch):
    return {
        "norm": {"g": (ch,), "b": (ch,)},
        "wq": (ch, ch), "bq": (ch,), "wk": (ch, ch), "bk": (ch,),
        "wv": (ch, ch), "bv": (ch,), "wo": (ch, ch), "bo": (ch,),
    }


def param_shapes(cfg: VAEConfig) -> PyTree:
    bc = cfg.base_channels
    chs = [bc * m for m in cfg.channel_mult]
    top = chs[-1]
    enc_down = []
    ch = bc
    for lvl, out_ch in enumerate(chs):
        lp: dict[str, Any] = {"res": []}
        for _ in range(cfg.num_res_blocks):
            lp["res"].append(_res_shapes(ch, out_ch))
            ch = out_ch
        if lvl != len(chs) - 1:
            lp["down"] = _conv_shape(ch, ch)
        enc_down.append(lp)
    dec_up = []
    ch = top
    for lvl in reversed(range(len(chs))):
        out_ch = chs[lvl]
        lp = {"res": []}
        for _ in range(cfg.num_res_blocks + 1):
            lp["res"].append(_res_shapes(ch, out_ch))
            ch = out_ch
        if lvl != 0:
            lp["up"] = _conv_shape(ch, ch)
        dec_up.append(lp)
    L = cfg.latent_channels
    return {
        "encoder": {
            "conv_in": _conv_shape(cfg.in_channels, bc),
            "down": enc_down,
            "mid": {"res1": _res_shapes(top, top), "attn": _attn_shapes(top),
                    "res2": _res_shapes(top, top)},
            "norm_out": {"g": (top,), "b": (top,)},
            "conv_out": _conv_shape(top, 2 * L),
        },
        "quant_conv": _conv_shape(2 * L, 2 * L, k=1),
        "post_quant_conv": _conv_shape(L, L, k=1),
        "decoder": {
            "conv_in": _conv_shape(L, top),
            "mid": {"res1": _res_shapes(top, top), "attn": _attn_shapes(top),
                    "res2": _res_shapes(top, top)},
            "up": dec_up,
            "norm_out": {"g": (ch,), "b": (ch,)},
            "conv_out": _conv_shape(ch, cfg.in_channels),
        },
    }


def init_params(rng: jax.Array, cfg: VAEConfig) -> PyTree:
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))
    dtype = jnp.dtype(cfg.dtype)

    def mk(k, shape):
        if len(shape) == 1:
            return jnp.ones(shape, jnp.float32)
        fan_in = math.prod(shape[:-1])
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(max(fan_in, 1))).astype(dtype)

    params = jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else ""
        if name.startswith("b") and name != "blocks":
            return jnp.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)
