"""Diffusers-layout checkpoint ingestion → JAX pytrees.

The image-model analogue of localai_tpu.models.loader: reads a local
diffusers directory (model_index.json + unet/ vae/ text_encoder/ tokenizer/
with safetensors weights — the layout `StableDiffusionPipeline.from_pretrained`
consumes in the reference, /root/reference/backend/python/diffusers/
backend.py:208-219) and maps the torch state dicts onto the functional
param trees of localai_tpu.image.{unet,vae,clip}. Torch conv kernels are
OIHW → transposed to HWIO (TPU-native); linear weights [out,in] → [in,out].
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import numpy as np

log = logging.getLogger(__name__)


def _open_dir(d: Path) -> dict[str, Any]:
    from safetensors import safe_open

    tensors: dict[str, Any] = {}
    files = sorted(d.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {d}")
    for fp in files:
        h = safe_open(str(fp), framework="numpy")
        for name in h.keys():
            tensors[name] = (h, name)
    return tensors


def _np(tensors, key: str) -> np.ndarray:
    h, k = tensors[key]
    arr = h.get_tensor(k)
    if arr.dtype == np.uint16:  # bf16 written as raw views by some writers
        import ml_dtypes

        arr = arr.view(ml_dtypes.bfloat16)
    return np.asarray(arr, np.float32)


def _conv(tensors, prefix: str) -> dict:
    w = _np(tensors, f"{prefix}.weight")
    return {"w": w.transpose(2, 3, 1, 0), "b": _np(tensors, f"{prefix}.bias")}


def _lin(tensors, prefix: str, *, bias: bool = True) -> tuple:
    w = _np(tensors, f"{prefix}.weight")
    if w.ndim == 4:  # 1x1 conv posing as a linear (older VAE attn blocks)
        w = w[:, :, 0, 0]
    out = w.T
    return (out, _np(tensors, f"{prefix}.bias")) if bias else (out,)


def _norm(tensors, prefix: str) -> dict:
    return {"g": _np(tensors, f"{prefix}.weight"),
            "b": _np(tensors, f"{prefix}.bias")}


def _proj_1x1(tensors, prefix: str) -> dict:
    """proj_in/proj_out: 1×1 conv in SD1.x, plain linear in SD2.x — load
    either into the 1×1-conv param shape."""
    w = _np(tensors, f"{prefix}.weight")
    if w.ndim == 2:  # linear [out,in] → [1,1,in,out]
        w = w.T[None, None]
    else:
        w = w.transpose(2, 3, 1, 0)
    return {"w": w, "b": _np(tensors, f"{prefix}.bias")}


# ---------------------------------------------------------------------------
# UNet
# ---------------------------------------------------------------------------

def _res_params(t, prefix: str, *, temb: bool = True) -> dict:
    p = {
        "norm1": _norm(t, f"{prefix}.norm1"),
        "conv1": _conv(t, f"{prefix}.conv1"),
        "norm2": _norm(t, f"{prefix}.norm2"),
        "conv2": _conv(t, f"{prefix}.conv2"),
    }
    if temb:
        w, b = _lin(t, f"{prefix}.time_emb_proj")
        p["temb"] = {"w": w, "b": b}
    if f"{prefix}.conv_shortcut.weight" in t:
        p["skip"] = _conv(t, f"{prefix}.conv_shortcut")
    return p


def _xattn_params(t, prefix: str) -> dict:
    (wq,) = _lin(t, f"{prefix}.to_q", bias=False)
    (wk,) = _lin(t, f"{prefix}.to_k", bias=False)
    (wv,) = _lin(t, f"{prefix}.to_v", bias=False)
    wo, bo = _lin(t, f"{prefix}.to_out.0")
    return {"wq": wq, "wk": wk, "wv": wv, "wo": wo, "bo": bo}


def _st_params(t, prefix: str) -> dict:
    blocks = []
    i = 0
    while f"{prefix}.transformer_blocks.{i}.norm1.weight" in t:
        bp = f"{prefix}.transformer_blocks.{i}"
        w1, b1 = _lin(t, f"{bp}.ff.net.0.proj")
        w2, b2 = _lin(t, f"{bp}.ff.net.2")
        blocks.append({
            "ln1": _norm(t, f"{bp}.norm1"),
            "attn1": _xattn_params(t, f"{bp}.attn1"),
            "ln2": _norm(t, f"{bp}.norm2"),
            "attn2": _xattn_params(t, f"{bp}.attn2"),
            "ln3": _norm(t, f"{bp}.norm3"),
            "ff": {"w1": w1, "b1": b1, "w2": w2, "b2": b2},
        })
        i += 1
    return {
        "norm": _norm(t, f"{prefix}.norm"),
        "proj_in": _proj_1x1(t, f"{prefix}.proj_in"),
        "blocks": blocks,
        "proj_out": _proj_1x1(t, f"{prefix}.proj_out"),
    }


def load_unet(d: Path):
    from localai_tpu.image.unet import UNetConfig

    with open(d / "config.json") as f:
        cfg = UNetConfig.from_hf(json.load(f))
    t = _open_dir(d)
    w1, b1 = _lin(t, "time_embedding.linear_1")
    w2, b2 = _lin(t, "time_embedding.linear_2")
    params: dict[str, Any] = {
        "conv_in": _conv(t, "conv_in"),
        "time_emb": {"w1": w1, "b1": b1, "w2": w2, "b2": b2},
        "norm_out": _norm(t, "conv_norm_out"),
        "conv_out": _conv(t, "conv_out"),
    }
    if "add_embedding.linear_1.weight" in t:
        # SDXL text_time micro-conditioning MLP
        aw1, ab1 = _lin(t, "add_embedding.linear_1")
        aw2, ab2 = _lin(t, "add_embedding.linear_2")
        params["add_emb"] = {"w1": aw1, "b1": ab1, "w2": aw2, "b2": ab2}
    down = []
    for lvl in range(len(cfg.channel_mult)):
        base = f"down_blocks.{lvl}"
        has_attn = f"{base}.attentions.0.norm.weight" in t
        lp: dict[str, Any] = {
            "res": [_res_params(t, f"{base}.resnets.{j}")
                    for j in range(cfg.num_res_blocks)],
            "attn": [_st_params(t, f"{base}.attentions.{j}")
                     for j in range(cfg.num_res_blocks)] if has_attn else None,
        }
        if f"{base}.downsamplers.0.conv.weight" in t:
            lp["down"] = _conv(t, f"{base}.downsamplers.0.conv")
        down.append(lp)
    params["down"] = down
    params["mid"] = {
        "res1": _res_params(t, "mid_block.resnets.0"),
        "attn": _st_params(t, "mid_block.attentions.0"),
        "res2": _res_params(t, "mid_block.resnets.1"),
    }
    up = []
    for i in range(len(cfg.channel_mult)):
        base = f"up_blocks.{i}"
        has_attn = f"{base}.attentions.0.norm.weight" in t
        lp = {
            "res": [_res_params(t, f"{base}.resnets.{j}")
                    for j in range(cfg.num_res_blocks + 1)],
            "attn": [_st_params(t, f"{base}.attentions.{j}")
                     for j in range(cfg.num_res_blocks + 1)] if has_attn else None,
        }
        if f"{base}.upsamplers.0.conv.weight" in t:
            lp["up"] = _conv(t, f"{base}.upsamplers.0.conv")
        up.append(lp)
    params["up"] = up
    return cfg, params


# ---------------------------------------------------------------------------
# VAE
# ---------------------------------------------------------------------------

def _vae_res(t, prefix: str) -> dict:
    return _res_params(t, prefix, temb=False)


def _vae_attn(t, prefix: str) -> dict:
    # newer diffusers: group_norm + to_q/to_k/to_v/to_out.0 linears;
    # older: norm + q/k/v/proj_out 1x1 convs
    if f"{prefix}.group_norm.weight" in t:
        names = ("group_norm", "to_q", "to_k", "to_v", "to_out.0")
    else:
        names = ("norm", "q", "k", "v", "proj_out")
    norm, q, k, v, o = names
    wq, bq = _lin(t, f"{prefix}.{q}")
    wk, bk = _lin(t, f"{prefix}.{k}")
    wv, bv = _lin(t, f"{prefix}.{v}")
    wo, bo = _lin(t, f"{prefix}.{o}")
    return {"norm": _norm(t, f"{prefix}.{norm}"),
            "wq": wq, "bq": bq, "wk": wk, "bk": bk,
            "wv": wv, "bv": bv, "wo": wo, "bo": bo}


def _vae_mid(t, prefix: str) -> dict:
    return {
        "res1": _vae_res(t, f"{prefix}.resnets.0"),
        "attn": _vae_attn(t, f"{prefix}.attentions.0"),
        "res2": _vae_res(t, f"{prefix}.resnets.1"),
    }


def load_vae(d: Path):
    from localai_tpu.image.vae import VAEConfig

    with open(d / "config.json") as f:
        cfg = VAEConfig.from_hf(json.load(f))
    t = _open_dir(d)
    levels = len(cfg.channel_mult)
    enc_down = []
    for lvl in range(levels):
        base = f"encoder.down_blocks.{lvl}"
        lp: dict[str, Any] = {
            "res": [_vae_res(t, f"{base}.resnets.{j}")
                    for j in range(cfg.num_res_blocks)],
        }
        if f"{base}.downsamplers.0.conv.weight" in t:
            lp["down"] = _conv(t, f"{base}.downsamplers.0.conv")
        enc_down.append(lp)
    dec_up = []
    for i in range(levels):
        base = f"decoder.up_blocks.{i}"
        lp = {
            "res": [_vae_res(t, f"{base}.resnets.{j}")
                    for j in range(cfg.num_res_blocks + 1)],
        }
        if f"{base}.upsamplers.0.conv.weight" in t:
            lp["up"] = _conv(t, f"{base}.upsamplers.0.conv")
        dec_up.append(lp)
    params = {
        "encoder": {
            "conv_in": _conv(t, "encoder.conv_in"),
            "down": enc_down,
            "mid": _vae_mid(t, "encoder.mid_block"),
            "norm_out": _norm(t, "encoder.conv_norm_out"),
            "conv_out": _conv(t, "encoder.conv_out"),
        },
        "quant_conv": _conv(t, "quant_conv"),
        "post_quant_conv": _conv(t, "post_quant_conv"),
        "decoder": {
            "conv_in": _conv(t, "decoder.conv_in"),
            "mid": _vae_mid(t, "decoder.mid_block"),
            "up": dec_up,
            "norm_out": _norm(t, "decoder.conv_norm_out"),
            "conv_out": _conv(t, "decoder.conv_out"),
        },
    }
    return cfg, params


# ---------------------------------------------------------------------------
# CLIP text encoder
# ---------------------------------------------------------------------------

def load_text_encoder(d: Path):
    from localai_tpu.image.clip import CLIPTextConfig

    with open(d / "config.json") as f:
        cfg = CLIPTextConfig.from_hf(json.load(f))
    t = _open_dir(d)
    pre = "text_model."
    layers = []
    for i in range(cfg.num_layers):
        base = f"{pre}encoder.layers.{i}"
        attn = {}
        for ours, theirs in (("q", "q_proj"), ("k", "k_proj"),
                             ("v", "v_proj"), ("o", "out_proj")):
            w, b = _lin(t, f"{base}.self_attn.{theirs}")
            attn[f"w{ours}"] = w
            attn[f"b{ours}"] = b
        w1, b1 = _lin(t, f"{base}.mlp.fc1")
        w2, b2 = _lin(t, f"{base}.mlp.fc2")
        layers.append({
            "ln1": _norm(t, f"{base}.layer_norm1"),
            "attn": attn,
            "ln2": _norm(t, f"{base}.layer_norm2"),
            "mlp": {"w1": w1, "b1": b1, "w2": w2, "b2": b2},
        })
    params = {
        "token_emb": _np(t, f"{pre}embeddings.token_embedding.weight"),
        "pos_emb": _np(t, f"{pre}embeddings.position_embedding.weight"),
        "layers": layers,
        "ln_f": _norm(t, f"{pre}final_layer_norm"),
    }
    if "text_projection.weight" in t:
        # SDXL text_encoder_2 pools through a projection (no bias)
        params["text_projection"] = _np(t, "text_projection.weight").T
    return cfg, params


def load_diffusers_pipeline(d: Path, *, lora_adapter: str = "",
                            lora_scale: float = 1.0, **defaults):
    """Directory with unet/ vae/ text_encoder/ tokenizer/ → DiffusionPipeline."""
    from localai_tpu.image.pipeline import DiffusionPipeline

    d = Path(d)
    unet_cfg, unet_params = load_unet(d / "unet")
    vae_cfg, vae_params = load_vae(d / "vae")
    text_cfg, text_params = load_text_encoder(d / "text_encoder")
    extra = {}
    if (d / "text_encoder_2").is_dir():
        # SDXL layout: second (OpenCLIP-class) encoder + tokenizer_2
        text2_cfg, text2_params = load_text_encoder(d / "text_encoder_2")
        extra = {
            "text2_cfg": text2_cfg,
            "text2_params": _to_device(text2_params, text2_cfg.dtype),
            "tokenizer2": _load_clip_tokenizer(d / "tokenizer_2",
                                               text2_cfg),
        }
    if lora_adapter:
        # merged host-side before device placement: the fused weights keep
        # the jitted UNet unchanged (see image/lora.py)
        from localai_tpu.image.lora import apply_lora

        apply_lora(unet_params, text_params, lora_adapter,
                   scale=lora_scale)
    tokenizer = _load_clip_tokenizer(d / "tokenizer", text_cfg)
    log.info("loaded diffusers pipeline from %s (unet %dch, ctx %d)",
             d, unet_cfg.model_channels, unet_cfg.context_dim)
    return DiffusionPipeline(
        unet_cfg, _to_device(unet_params, unet_cfg.dtype),
        vae_cfg, _to_device(vae_params, vae_cfg.dtype),
        text_cfg, _to_device(text_params, text_cfg.dtype),
        tokenizer, ref=str(d), **extra, **defaults,
    )


def _to_device(params, dtype: str):
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)

    def conv(a):
        return jnp.asarray(a, dt if a.ndim > 1 else jnp.float32)

    import jax

    return jax.tree.map(conv, params)


def _load_clip_tokenizer(d: Path, text_cfg):
    """CLIP BPE tokenizer from a diffusers tokenizer/ dir, wrapped in the
    repo's Tokenizer protocol; byte fallback keeps debug flows alive."""
    try:
        from transformers import CLIPTokenizer, CLIPTokenizerFast

        try:
            tok = CLIPTokenizerFast.from_pretrained(str(d))
        except Exception:  # noqa: BLE001
            tok = CLIPTokenizer.from_pretrained(str(d))

        class _Wrap:
            vocab_size = tok.vocab_size
            eos_ids = {tok.eos_token_id}

            def encode(self, text: str, add_bos: bool = False):
                return tok(text).input_ids

            def decode(self, ids):
                return tok.decode(ids)

        return _Wrap()
    except Exception as e:  # noqa: BLE001
        log.warning("CLIP tokenizer load failed (%s); using byte tokenizer", e)
        from localai_tpu.utils.tokenizer import ByteTokenizer

        return ByteTokenizer()
