"""T5 text encoder (encoder-only) in JAX — the FLUX-class pipelines'
sequence conditioning model.

Parity: the reference's diffusers backend loads FLUX.1 whose second text
encoder is T5-XXL (/root/reference/backend/python/diffusers/backend.py:
249-262, `FluxPipeline.from_pretrained`). This is the encoder stack of HF
`T5EncoderModel` (relative-position-bias attention, pre-RMSNorm, gated-GELU
FFN, no biases), loadable from its safetensors and torch-verified in
tests/test_flux.py.

TPU notes: the layer loop is a ``lax.scan`` over stacked weights; the
relative position bias is computed once (shared across layers, as in T5)
and added to the attention logits.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PyTree = Any


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096
    d_kv: int = 64
    d_ff: int = 10240
    num_layers: int = 24
    num_heads: int = 64
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    max_length: int = 512
    dtype: str = "float32"

    @classmethod
    def from_hf(cls, hf: dict) -> "T5Config":
        return cls(
            vocab_size=hf.get("vocab_size", 32128),
            d_model=hf.get("d_model", 4096),
            d_kv=hf.get("d_kv", 64),
            d_ff=hf.get("d_ff", 10240),
            num_layers=hf.get("num_layers", 24),
            num_heads=hf.get("num_heads", 64),
            relative_attention_num_buckets=hf.get(
                "relative_attention_num_buckets", 32),
            relative_attention_max_distance=hf.get(
                "relative_attention_max_distance", 128),
            layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-6),
        )


def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _relative_buckets(rel_pos, num_buckets: int, max_dist: int):
    """HF T5 bidirectional relative-position bucketing."""
    nb = num_buckets // 2
    ret = jnp.where(rel_pos > 0, nb, 0)
    n = jnp.abs(rel_pos)
    max_exact = nb // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-9)
        / float(np.log(max_dist / max_exact)) * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return ret + jnp.where(is_small, n, large)


def position_bias(cfg: T5Config, rel_embed: jax.Array, T: int) -> jax.Array:
    """[H, T, T] f32 — shared across layers (computed by layer 0 in HF)."""
    ctx = jnp.arange(T)[:, None]
    mem = jnp.arange(T)[None, :]
    buckets = _relative_buckets(
        mem - ctx, cfg.relative_attention_num_buckets,
        cfg.relative_attention_max_distance,
    )
    return rel_embed[buckets].transpose(2, 0, 1).astype(jnp.float32)


def encode(cfg: T5Config, params: PyTree, tokens: jax.Array,
           mask: jax.Array | None = None) -> jax.Array:
    """tokens [B, T] i32 → hidden states [B, T, D].

    ``mask`` [B, T] bool (True = real token); None attends everywhere —
    matching diffusers' FLUX text encoding, which passes full attention
    over the padded T5 sequence."""
    H, dk = cfg.num_heads, cfg.d_kv
    x = params["embed"][tokens].astype(jnp.dtype(cfg.dtype))
    T = tokens.shape[-1]
    bias = position_bias(cfg, params["rel_embed"], T)  # [H, T, T]
    if mask is not None:
        bias = jnp.where(mask[:, None, None, :], bias[None], -1e9)
    else:
        bias = bias[None]

    def body(h, lp):
        a_in = _rms(h, lp["ln1"], cfg.layer_norm_epsilon)
        q = (a_in @ lp["wq"]).reshape(*a_in.shape[:-1], H, dk)
        k = (a_in @ lp["wk"]).reshape(*a_in.shape[:-1], H, dk)
        v = (a_in @ lp["wv"]).reshape(*a_in.shape[:-1], H, dk)
        # T5 does NOT scale by sqrt(dk): the init absorbs it
        scores = jnp.einsum("bthd,bshd->bhts", q, k)
        scores = scores.astype(jnp.float32) + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        att = jnp.einsum("bhts,bshd->bthd", probs, v)
        att = att.reshape(*att.shape[:-2], H * dk)
        h = h + att @ lp["wo"]

        f_in = _rms(h, lp["ln2"], cfg.layer_norm_epsilon)
        gelu = jax.nn.gelu(f_in @ lp["wi0"], approximate=True)
        h = h + (gelu * (f_in @ lp["wi1"])) @ lp["wo2"]
        return h, None

    x, _ = lax.scan(body, x, params["layers"])
    return _rms(x, params["final_ln"], cfg.layer_norm_epsilon)


def load_hf_t5(d: str | Path) -> tuple[T5Config, PyTree]:
    """Read an HF T5EncoderModel dir (config.json + safetensors)."""
    import json

    from localai_tpu.image.loader import _np, _open_dir

    d = Path(d)
    cfg = T5Config.from_hf(json.loads((d / "config.json").read_text()))
    tensors = _open_dir(d)
    pre = "encoder.block.{i}.layer."

    def stack(fmt: str, transpose: bool = True) -> np.ndarray:
        mats = []
        for i in range(cfg.num_layers):
            a = _np(tensors, fmt.format(i=i))
            mats.append(a.T if transpose else a)
        return np.stack(mats)

    params = {
        "embed": _np(tensors, "shared.weight"),
        "rel_embed": _np(
            tensors,
            "encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight",
        ),
        "final_ln": _np(tensors, "encoder.final_layer_norm.weight"),
        "layers": {
            "ln1": stack(pre + "0.layer_norm.weight", False),
            "wq": stack(pre + "0.SelfAttention.q.weight"),
            "wk": stack(pre + "0.SelfAttention.k.weight"),
            "wv": stack(pre + "0.SelfAttention.v.weight"),
            "wo": stack(pre + "0.SelfAttention.o.weight"),
            "ln2": stack(pre + "1.layer_norm.weight", False),
            "wi0": stack(pre + "1.DenseReluDense.wi_0.weight"),
            "wi1": stack(pre + "1.DenseReluDense.wi_1.weight"),
            "wo2": stack(pre + "1.DenseReluDense.wo.weight"),
        },
    }
    return cfg, params
