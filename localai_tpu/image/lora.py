"""LoRA adapters for the diffusion pipeline: merged into base weights at
load time.

Parity: /root/reference/backend/python/diffusers/backend.py:300-381 —
`load_lora_weights` reads a kohya-format safetensors file
(``lora_unet_*`` / ``lora_te_*`` keys with lora_down/lora_up/alpha) and
folds ΔW = scale · (alpha/r) · up @ down into each target layer; the
diffusers/peft layout (``unet.…lora_A/lora_B``) is the other format in
the wild. Merging (not runtime adapters) is the TPU-right choice: the
fused weight keeps every matmul a single MXU op and the jitted UNet
unchanged — a runtime adapter would add two thin matmuls per layer per
step.

Key normalization: kohya flattens module paths with underscores
(``lora_unet_down_blocks_0_…_to_q``). We walk OUR param tree (whose
structure mirrors the diffusers module tree by construction —
image/loader.py) and emit every targetable site keyed by its flattened
name, so lookups are exact instead of parsing underscore-ambiguous names.
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path
from typing import Any, Callable, Optional

import numpy as np

log = logging.getLogger(__name__)


@dataclasses.dataclass
class _Site:
    """One LoRA-targetable weight: how to read and write it."""

    get: Callable[[], np.ndarray]
    set: Callable[[Any], None]
    kind: str  # linear | conv1x1 | conv


def _linear(d: dict, key: str) -> _Site:
    # ours: [in, out]; ΔW comes [out, in]
    return _Site(lambda: d[key], lambda v: d.__setitem__(key, v), "linear")


def _conv_site(d: dict, key: str = "w") -> _Site:
    # ours: [kh, kw, in, out]; ΔW [out, in, kh, kw]
    return _Site(lambda: d[key], lambda v: d.__setitem__(key, v), "conv")


def _conv1x1(d: dict, key: str = "w") -> _Site:
    return _Site(lambda: d[key], lambda v: d.__setitem__(key, v), "conv1x1")


def _attn_sites(out: dict, base: str, ap: dict) -> None:
    out[f"{base}.to_q"] = _linear(ap, "wq")
    out[f"{base}.to_k"] = _linear(ap, "wk")
    out[f"{base}.to_v"] = _linear(ap, "wv")
    out[f"{base}.to_out.0"] = _linear(ap, "wo")


def _st_sites(out: dict, base: str, sp: dict) -> None:
    out[f"{base}.proj_in"] = _conv1x1(sp["proj_in"])
    out[f"{base}.proj_out"] = _conv1x1(sp["proj_out"])
    for b, bp in enumerate(sp["blocks"]):
        tb = f"{base}.transformer_blocks.{b}"
        _attn_sites(out, f"{tb}.attn1", bp["attn1"])
        _attn_sites(out, f"{tb}.attn2", bp["attn2"])
        out[f"{tb}.ff.net.0.proj"] = _linear(bp["ff"], "w1")
        out[f"{tb}.ff.net.2"] = _linear(bp["ff"], "w2")


def _res_sites(out: dict, base: str, rp: dict) -> None:
    out[f"{base}.conv1"] = _conv_site(rp["conv1"])
    out[f"{base}.conv2"] = _conv_site(rp["conv2"])
    if "temb" in rp:
        out[f"{base}.time_emb_proj"] = _linear(rp["temb"], "w")
    if "skip" in rp:
        out[f"{base}.conv_shortcut"] = _conv_site(rp["skip"])


def unet_sites(params: dict) -> dict[str, _Site]:
    """Every LoRA-targetable UNet weight keyed by its diffusers module
    path (the tree mirrors image/loader.py's construction)."""
    out: dict[str, _Site] = {}
    for lvl, lp in enumerate(params["down"]):
        base = f"down_blocks.{lvl}"
        for j, rp in enumerate(lp["res"]):
            _res_sites(out, f"{base}.resnets.{j}", rp)
        for j, sp in enumerate(lp["attn"] or []):
            _st_sites(out, f"{base}.attentions.{j}", sp)
    _res_sites(out, "mid_block.resnets.0", params["mid"]["res1"])
    _res_sites(out, "mid_block.resnets.1", params["mid"]["res2"])
    _st_sites(out, "mid_block.attentions.0", params["mid"]["attn"])
    for lvl, lp in enumerate(params["up"]):
        base = f"up_blocks.{lvl}"
        for j, rp in enumerate(lp["res"]):
            _res_sites(out, f"{base}.resnets.{j}", rp)
        for j, sp in enumerate(lp["attn"] or []):
            _st_sites(out, f"{base}.attentions.{j}", sp)
    return out


def text_encoder_sites(params: dict) -> dict[str, _Site]:
    out: dict[str, _Site] = {}
    for i, layer in enumerate(params["layers"]):
        base = f"text_model.encoder.layers.{i}"
        ap = layer["attn"]
        for ours, theirs in (("q", "q_proj"), ("k", "k_proj"),
                             ("v", "v_proj"), ("o", "out_proj")):
            out[f"{base}.self_attn.{theirs}"] = _Site(
                lambda a=ap, k=f"w{ours}": a[k],
                lambda v, a=ap, k=f"w{ours}": a.__setitem__(k, v),
                "linear",
            )
        out[f"{base}.mlp.fc1"] = _linear(layer["mlp"], "w1")
        out[f"{base}.mlp.fc2"] = _linear(layer["mlp"], "w2")
    return out


# ---------------------------------------------------------------------------
# LoRA file parsing


@dataclasses.dataclass
class LoraLayer:
    down: np.ndarray   # [r, in] (or [r, in, kh, kw] for convs)
    up: np.ndarray     # [out, r] (or [out, r, 1, 1])
    alpha: Optional[float]

    def delta(self, scale: float) -> np.ndarray:
        """[out, in(...)] merged update (backend.py:360-376)."""
        r = self.down.shape[0]
        # alpha == 0 is a valid author-zeroed layer — only MISSING alpha
        # defaults to 1.0
        weight = scale * (
            (self.alpha / r) if self.alpha is not None else 1.0
        )
        if self.down.ndim == 4:
            up = self.up[:, :, 0, 0]                 # [out, r]
            dw = np.einsum("or,ri...->oi...", up, self.down)
        else:
            dw = self.up @ self.down                 # [out, in]
        return (weight * dw).astype(np.float32)


def read_lora_file(path: str | Path) -> dict[tuple[str, str], LoraLayer]:
    """LoRA safetensors → {(component, flat_module_name): LoraLayer} with
    component in {"unet", "te"}; accepts kohya (lora_unet_*/lora_te_*,
    lora_down/lora_up/alpha) and diffusers/peft (unet./text_encoder.
    prefixes, lora_A/lora_B) layouts."""
    from safetensors import safe_open

    raw: dict[str, np.ndarray] = {}
    with safe_open(str(path), framework="numpy") as h:
        for k in h.keys():
            arr = h.get_tensor(k)
            if arr.dtype == np.uint16:
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            raw[k] = np.asarray(arr, np.float32)

    groups: dict[tuple[str, str], dict] = {}

    def put(component: str, module: str, part: str, value) -> None:
        groups.setdefault((component, module.replace(".", "_")), {})[
            part] = value

    def split_component(k: str) -> tuple[str, str]:
        """diffusers-layout key → (component, module-relative key); the
        ONE prefix table shared by weights and alpha (drift here silently
        merged alphas at the wrong scale)."""
        for pre, comp in (("unet.", "unet"), ("text_encoder.", "te"),
                          ("te.", "te")):
            if k.startswith(pre):
                return comp, k[len(pre):]
        return "unet", k

    for key, val in raw.items():
        if key.startswith(("lora_unet_", "lora_te_")):
            component = "unet" if key.startswith("lora_unet_") else "te"
            body = key.split("_", 2)[-1]
            module, _, part = body.partition(".")
            if part.startswith("lora_down"):
                put(component, module, "down", val)
            elif part.startswith("lora_up"):
                put(component, module, "up", val)
            elif part == "alpha":
                put(component, module, "alpha", float(val))
        elif ".lora_A." in key or ".lora_B." in key or \
                ".lora.down." in key or ".lora.up." in key:
            component, k = split_component(key)
            for marker, part in ((".lora_A.", "down"), (".lora_B.", "up"),
                                 (".lora.down.", "down"),
                                 (".lora.up.", "up")):
                if marker in k:
                    module = k.split(marker)[0]
                    put(component, module, part, val)
                    break
        elif key.endswith(".alpha"):
            # diffusers/peft layout stores alpha beside lora_A/lora_B —
            # the same prefix split keeps it in their group
            component, k = split_component(key[: -len(".alpha")])
            put(component, k, "alpha", float(val))

    out: dict[tuple[str, str], LoraLayer] = {}
    for gk, g in groups.items():
        if "down" in g and "up" in g:
            out[gk] = LoraLayer(g["down"], g["up"], g.get("alpha"))
        else:
            log.debug("incomplete LoRA group %s (parts: %s)", gk,
                      sorted(g))
    return out


# ---------------------------------------------------------------------------
# merging


def apply_lora(
    unet_params: dict,
    text_params: Optional[dict],
    lora_path: str | Path,
    scale: float = 1.0,
) -> int:
    """Fold a LoRA file into the (host-side numpy) param trees in place.
    Returns the number of layers merged. Unknown target modules are
    skipped with a warning (a LoRA for a different architecture must not
    silently corrupt weights — shape mismatches raise)."""
    layers = read_lora_file(lora_path)
    if not layers:
        raise ValueError(f"no LoRA layers found in {lora_path}")
    sites: dict[tuple[str, str], _Site] = {}
    for name, site in unet_sites(unet_params).items():
        sites[("unet", name.replace(".", "_"))] = site
    if text_params is not None:
        for name, site in text_encoder_sites(text_params).items():
            sites[("te", name.replace(".", "_"))] = site

    merged = 0
    for key, layer in layers.items():
        site = sites.get(key)
        if site is None:
            log.warning("LoRA target %s/%s has no matching module; "
                        "skipping", *key)
            continue
        dw = layer.delta(scale)
        w = np.asarray(site.get(), np.float32)
        if site.kind == "linear":
            upd = dw.T                                   # [in, out]
        elif site.kind == "conv1x1":
            if dw.ndim == 4:
                dw = dw[:, :, 0, 0]
            upd = dw.T[None, None]                       # [1,1,in,out]
        else:  # conv [kh,kw,in,out] ← ΔW [out,in,kh,kw]
            if dw.ndim == 2:                             # 1x1-shaped file
                dw = dw[:, :, None, None]
            upd = dw.transpose(2, 3, 1, 0)
        if upd.shape != w.shape:
            raise ValueError(
                f"LoRA {key} shape {upd.shape} does not match target "
                f"{w.shape} — wrong base model?"
            )
        site.set(w + upd)
        merged += 1
    log.info("merged %d LoRA layer(s) from %s (scale %.2f)", merged,
             lora_path, scale)
    return merged
