"""Diffusion samplers in sigma space — the TPU reworking of the reference's
scheduler zoo (/root/reference/backend/python/diffusers/backend.py:74-143,
DiffusionScheduler enum + get_scheduler).

Supported names (aliases map onto four step rules + the Karras sigma
option, the way A1111/k-diffusion names map onto diffusers classes):

  ddim, euler, euler_a, dpmpp_2m, and k_* variants (Karras sigma schedule:
  k_euler, k_dpmpp_2m, ...); lms/heun/pndm/unipc/dpm_2* accept and map to
  the nearest supported rule so reference YAMLs keep working.

Design: schedules are tiny host-side numpy; the per-step update is pure
jnp executed inside the pipeline's jitted step program. All rules share the
epsilon-prediction convention x = x0 + sigma * eps with model input scaled
by 1/sqrt(1+sigma^2) (k-diffusion parameterization).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# alias → (rule, karras)
_ALIASES = {
    "ddim": ("ddim", False),
    "pndm": ("ddim", False),
    "unipc": ("dpmpp_2m", False),
    "euler": ("euler", False),
    "euler_a": ("euler_a", False),
    "heun": ("euler", False),
    "lms": ("euler", False),
    "k_lms": ("euler", True),
    "dpm_2": ("euler", False),
    "k_dpm_2": ("euler", True),
    "dpm_2_a": ("euler_a", False),
    "k_dpm_2_a": ("euler_a", True),
    "dpmpp_2m": ("dpmpp_2m", False),
    "k_dpmpp_2m": ("dpmpp_2m", True),
    "dpmpp_sde": ("euler_a", False),
    "k_dpmpp_sde": ("euler_a", True),
    "dpmpp_2m_sde": ("dpmpp_2m", False),
    "k_dpmpp_2m_sde": ("dpmpp_2m", True),
    "k_euler": ("euler", True),
    "k_euler_a": ("euler_a", True),
}

ANCESTRAL_RULES = ("euler_a",)


def resolve(name: Optional[str]) -> tuple[str, bool]:
    """Scheduler name → (step rule, use_karras_sigmas)."""
    if not name:
        return "euler", False
    key = name.strip().lower()
    if key in _ALIASES:
        return _ALIASES[key]
    if key.startswith("k_") and key[2:] in _ALIASES:
        return _ALIASES[key[2:]][0], True
    raise ValueError(f"unknown scheduler {name!r}; have {sorted(_ALIASES)}")


@dataclasses.dataclass(frozen=True)
class NoiseSchedule:
    """The training noise schedule (SD default: scaled_linear betas)."""

    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012

    def alphas_cumprod(self) -> np.ndarray:
        betas = np.linspace(
            self.beta_start ** 0.5, self.beta_end ** 0.5,
            self.num_train_timesteps, dtype=np.float64,
        ) ** 2
        return np.cumprod(1.0 - betas)

    def all_sigmas(self) -> np.ndarray:
        ac = self.alphas_cumprod()
        return np.sqrt((1 - ac) / ac)


def build_sigmas(
    steps: int,
    schedule: NoiseSchedule = NoiseSchedule(),
    karras: bool = False,
    rho: float = 7.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (sigmas [steps+1] desc ending at 0, timesteps [steps] f32) —
    the timestep for each sigma interpolated into the training schedule
    (what the UNet's time embedding expects)."""
    all_sig = schedule.all_sigmas()
    if karras:
        smin, smax = all_sig[0], all_sig[-1]
        ramp = np.linspace(0, 1, steps)
        sigmas = (smax ** (1 / rho)
                  + ramp * (smin ** (1 / rho) - smax ** (1 / rho))) ** rho
    else:
        idx = np.linspace(len(all_sig) - 1, 0, steps)
        sigmas = np.interp(idx, np.arange(len(all_sig)), all_sig)
    # sigma → (fractional) training timestep, via log-sigma interpolation
    log_all = np.log(all_sig)
    timesteps = np.interp(np.log(sigmas), log_all, np.arange(len(all_sig)))
    sigmas = np.append(sigmas, 0.0).astype(np.float32)
    return sigmas, timesteps.astype(np.float32)


def scale_model_input(x: jax.Array, sigma) -> jax.Array:
    return x / jnp.sqrt(sigma ** 2 + 1.0)


def denoised_from_eps(x: jax.Array, eps: jax.Array, sigma) -> jax.Array:
    return x - sigma * eps


def step(
    rule: str,
    x: jax.Array,            # current sample (x0 + sigma*eps convention)
    denoised: jax.Array,     # model's x0 estimate at sigma
    sigma,                   # current sigma (scalar)
    sigma_next,              # next sigma (scalar; 0 at the last step)
    prev_denoised: Optional[jax.Array] = None,   # for multistep rules
    prev_sigma=None,
    noise: Optional[jax.Array] = None,           # for ancestral rules
) -> jax.Array:
    """One sampler update x(sigma) → x(sigma_next). Shapes are static; this
    runs inside the pipeline's jitted step program."""
    if rule == "euler":
        d = (x - denoised) / sigma
        return x + d * (sigma_next - sigma)
    if rule == "ddim":
        # deterministic DDIM expressed in sigma space:
        # x' = x0 + (sigma_next/sigma) * (x - x0)
        return denoised + (x - denoised) * (sigma_next / sigma)
    if rule == "euler_a":
        # ancestral split of the step into a down-step + fresh noise
        var_next = sigma_next ** 2
        up2 = var_next * (sigma ** 2 - var_next) / jnp.maximum(sigma ** 2, 1e-12)
        sigma_up = jnp.sqrt(jnp.maximum(up2, 0.0))
        sigma_down = jnp.sqrt(jnp.maximum(var_next - up2, 0.0))
        d = (x - denoised) / sigma
        x = x + d * (sigma_down - sigma)
        if noise is not None:
            x = x + noise * sigma_up
        return x
    if rule == "dpmpp_2m":
        # DPM-Solver++ (2M) deterministic multistep (k-diffusion form);
        # sigma_next=0 degenerates to ratio→0, -(exp(-h)-1)→1, d=denoised,
        # i.e. x' = denoised, matching the reference sampler's last step.
        def lam(s):
            return -jnp.log(jnp.maximum(s, 1e-10))

        l_cur, l_next = lam(sigma), lam(sigma_next)
        h = l_next - l_cur
        if prev_denoised is None:
            d = denoised
        else:
            h_last = l_cur - lam(prev_sigma)
            r = h_last / h
            d = (1 + 1 / (2 * r)) * denoised - (1 / (2 * r)) * prev_denoised
            d = jnp.where(sigma_next > 0, d, denoised)
        ratio = sigma_next / jnp.maximum(sigma, 1e-10)
        return ratio * x - (jnp.exp(-h) - 1.0) * d
    raise ValueError(f"unknown step rule {rule!r}")
