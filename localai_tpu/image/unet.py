"""SD-class conditional UNet — pure functional JAX, NHWC (TPU-native layout).

Capability parity target: the denoising network behind the reference's
diffusers pipelines (/root/reference/backend/python/diffusers/backend.py:
184-260, StableDiffusionPipeline class). Architecture follows the SD-1.x
UNet2DConditionModel family (configurable dims so tiny debug presets and
real checkpoints share one code path): ResBlocks with timestep embedding,
spatial transformers with self+cross attention over the text context, skip
connections, stride-2 conv down / nearest-up. Convs run in NHWC with HWIO
kernels (XLA's native TPU conv layout); norms and softmax in float32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    attn_levels: tuple[int, ...] = (0, 1, 2)   # levels with spatial transformers
    transformer_depth: int = 1
    num_heads: int = 8
    heads_per_level: tuple[int, ...] = ()      # SDXL: per-level head counts
                                               # (empty → num_heads everywhere)
    context_dim: int = 768                     # CLIP hidden size
    # SDXL micro-conditioning (addition_embed_type="text_time"): pooled
    # text + size/crop time_ids through an extra MLP added to the
    # timestep embedding
    addition_embed: bool = False
    addition_time_embed_dim: int = 256
    dtype: str = "bfloat16"

    def heads_at(self, level: int) -> int:
        if self.heads_per_level:
            return self.heads_per_level[
                min(level, len(self.heads_per_level) - 1)]
        return self.num_heads

    @classmethod
    def from_hf(cls, hf: dict) -> "UNetConfig":
        """Build from a diffusers unet/config.json dict."""
        block_out = hf.get("block_out_channels", [320, 640, 1280, 1280])
        mc = block_out[0]
        down_types = hf.get("down_block_types", [])
        attn_levels = tuple(
            i for i, t in enumerate(down_types) if "CrossAttn" in t
        ) or tuple(range(len(block_out) - 1))
        # diffusers quirk: attention_head_dim historically holds the HEAD
        # COUNT for SD-class unets (8 for SD1.5, [5,10,20] for SDXL)
        heads = hf.get("num_attention_heads") or hf.get(
            "attention_head_dim", 8)
        heads_per_level: tuple[int, ...] = ()
        if isinstance(heads, (list, tuple)):
            heads_per_level = tuple(int(h) for h in heads)
            heads = heads_per_level[0]
        add = hf.get("addition_embed_type") == "text_time"
        time_dim = hf.get("addition_time_embed_dim", 256)
        return cls(
            in_channels=hf.get("in_channels", 4),
            out_channels=hf.get("out_channels", 4),
            model_channels=mc,
            channel_mult=tuple(c // mc for c in block_out),
            num_res_blocks=hf.get("layers_per_block", 2),
            attn_levels=attn_levels,
            transformer_depth=hf.get("transformer_layers_per_block", 1)
            if isinstance(hf.get("transformer_layers_per_block", 1), int) else 1,
            num_heads=heads,
            heads_per_level=heads_per_level,
            context_dim=hf.get("cross_attention_dim", 768),
            addition_embed=add,
            addition_time_embed_dim=time_dim,
        )


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def conv2d(x, p, *, stride: int = 1, padding="SAME") -> jax.Array:
    out = lax.conv_general_dilated(
        x, p["w"].astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + p["b"].astype(x.dtype)


def group_norm(x, p, *, groups: int = 32, eps: float = 1e-5) -> jax.Array:
    """GroupNorm over channel groups, computed in f32 (TPU numerics)."""
    B, H, W, C = x.shape
    g = min(groups, C)
    xf = x.astype(jnp.float32).reshape(B, H, W, g, C // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    xf = xf.reshape(B, H, W, C)
    return (xf * p["g"] + p["b"]).astype(x.dtype)


def layer_norm(x, p, *, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps) * p["g"] + p["b"]
    return out.astype(x.dtype)


def attention(q, k, v, num_heads: int) -> jax.Array:
    """Multi-head dot-product attention over [B, N, C] / [B, M, C]."""
    B, N, C = q.shape
    M = k.shape[1]
    hd = C // num_heads
    q = q.reshape(B, N, num_heads, hd)
    k = k.reshape(B, M, num_heads, hd)
    v = v.reshape(B, M, num_heads, hd)
    scores = jnp.einsum("bnhd,bmhd->bhnm", q, k) / math.sqrt(hd)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhnm,bmhd->bnhd", probs, v)
    return out.reshape(B, N, C)


def timestep_embedding(t, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal timestep features [B, dim] (f32)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


# ---------------------------------------------------------------------------
# blocks
# ---------------------------------------------------------------------------

def res_block(x, temb, p) -> jax.Array:
    h = jax.nn.silu(group_norm(x, p["norm1"]))
    h = conv2d(h, p["conv1"])
    t = jax.nn.silu(temb) @ p["temb"]["w"].astype(temb.dtype) + p["temb"]["b"].astype(temb.dtype)
    h = h + t.astype(h.dtype)[:, None, None, :]
    h = jax.nn.silu(group_norm(h, p["norm2"]))
    h = conv2d(h, p["conv2"])
    if "skip" in p:
        x = conv2d(x, p["skip"])
    return x + h


def _geglu(x, p) -> jax.Array:
    h = x @ p["w1"].astype(x.dtype) + p["b1"].astype(x.dtype)
    a, b = jnp.split(h, 2, axis=-1)
    h = a * jax.nn.gelu(b)
    return h @ p["w2"].astype(x.dtype) + p["b2"].astype(x.dtype)


def _attn_proj(x, ctx, p, num_heads: int) -> jax.Array:
    q = x @ p["wq"].astype(x.dtype)
    k = ctx @ p["wk"].astype(ctx.dtype)
    v = ctx @ p["wv"].astype(ctx.dtype)
    out = attention(q, k, v, num_heads)
    return out @ p["wo"].astype(x.dtype) + p["bo"].astype(x.dtype)


def spatial_transformer(x, context, p, cfg: UNetConfig,
                        num_heads: int = 0) -> jax.Array:
    """GN → 1×1 in → transformer blocks (self, cross, GEGLU FF) → 1×1 out,
    residual around the whole stack."""
    heads = num_heads or cfg.num_heads
    B, H, W, C = x.shape
    h = group_norm(x, p["norm"])
    h = conv2d(h, p["proj_in"])
    h = h.reshape(B, H * W, C)
    for bp in p["blocks"]:
        h = h + _attn_proj(layer_norm(h, bp["ln1"]), layer_norm(h, bp["ln1"]),
                           bp["attn1"], heads)
        h = h + _attn_proj(layer_norm(h, bp["ln2"]), context,
                           bp["attn2"], heads)
        h = h + _geglu(layer_norm(h, bp["ln3"]), bp["ff"])
    h = h.reshape(B, H, W, C)
    h = conv2d(h, p["proj_out"])
    return x + h


def downsample(x, p) -> jax.Array:
    # stride-2 conv with the (0,1) asymmetric padding SD uses
    return conv2d(x, p, stride=2, padding=((0, 1), (0, 1)))


def upsample(x, p) -> jax.Array:
    B, H, W, C = x.shape
    x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
    return conv2d(x, p)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def forward(cfg: UNetConfig, params: PyTree, latents, timesteps, context,
            pooled_text=None, time_ids=None,
            down_residuals=None, mid_residual=None):
    """Denoise step: latents [B,h,w,Cin], timesteps [B], context [B,T,ctx]
    → predicted noise [B,h,w,Cout].

    SDXL micro-conditioning (cfg.addition_embed): ``pooled_text``
    [B, pooled_dim] and ``time_ids`` [B, 6] feed the text_time addition
    MLP, added to the timestep embedding. ControlNet guidance:
    ``down_residuals`` (one per skip) add onto the saved skips and
    ``mid_residual`` onto the mid-block output (image/controlnet.py)."""
    dtype = jnp.dtype(cfg.dtype)
    x = latents.astype(dtype)
    context = context.astype(dtype)

    temb = timestep_embedding(timesteps, cfg.model_channels)
    te = params["time_emb"]
    temb = temb @ te["w1"] + te["b1"]
    temb = jax.nn.silu(temb) @ te["w2"] + te["b2"]

    if cfg.addition_embed and pooled_text is not None:
        B = pooled_text.shape[0]
        # sinusoidal per time_id, flattened: [B, 6*addition_time_embed_dim]
        tid = timestep_embedding(
            time_ids.reshape(-1), cfg.addition_time_embed_dim
        ).reshape(B, -1)
        aug = jnp.concatenate(
            [pooled_text.astype(jnp.float32), tid], axis=-1
        )
        ae = params["add_emb"]
        aug = aug @ ae["w1"] + ae["b1"]
        aug = jax.nn.silu(aug) @ ae["w2"] + ae["b2"]
        temb = temb + aug

    h = conv2d(x, params["conv_in"])
    skips = [h]
    for lvl, lp in enumerate(params["down"]):
        for i, rp in enumerate(lp["res"]):
            h = res_block(h, temb, rp)
            if lp.get("attn"):
                h = spatial_transformer(h, context, lp["attn"][i], cfg,
                                        cfg.heads_at(lvl))
            skips.append(h)
        if lp.get("down"):
            h = downsample(h, lp["down"])
            skips.append(h)

    if down_residuals is not None:
        skips = [s + r.astype(s.dtype)
                 for s, r in zip(skips, down_residuals)]

    mid = params["mid"]
    n_lvls = len(params["down"])
    h = res_block(h, temb, mid["res1"])
    h = spatial_transformer(h, context, mid["attn"], cfg,
                            cfg.heads_at(n_lvls - 1))
    h = res_block(h, temb, mid["res2"])
    if mid_residual is not None:
        h = h + mid_residual.astype(h.dtype)

    for lvl, lp in enumerate(params["up"]):
        for i, rp in enumerate(lp["res"]):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = res_block(h, temb, rp)
            if lp.get("attn"):
                h = spatial_transformer(h, context, lp["attn"][i], cfg,
                                        cfg.heads_at(n_lvls - 1 - lvl))
        if lp.get("up"):
            h = upsample(h, lp["up"])

    h = jax.nn.silu(group_norm(h, params["norm_out"]))
    return conv2d(h, params["conv_out"]).astype(jnp.float32)


# ---------------------------------------------------------------------------
# shapes / init
# ---------------------------------------------------------------------------

def _conv_shape(cin, cout, k=3):
    return {"w": (k, k, cin, cout), "b": (cout,)}


def _res_shapes(cin, cout, tdim):
    p = {
        "norm1": {"g": (cin,), "b": (cin,)},
        "conv1": _conv_shape(cin, cout),
        "temb": {"w": (tdim, cout), "b": (cout,)},
        "norm2": {"g": (cout,), "b": (cout,)},
        "conv2": _conv_shape(cout, cout),
    }
    if cin != cout:
        p["skip"] = _conv_shape(cin, cout, k=1)
    return p


def _st_shapes(ch, cfg: UNetConfig):
    def attn(kv_dim):
        return {"wq": (ch, ch), "wk": (kv_dim, ch), "wv": (kv_dim, ch),
                "wo": (ch, ch), "bo": (ch,)}

    inner = ch * 4
    block = {
        "ln1": {"g": (ch,), "b": (ch,)}, "attn1": attn(ch),
        "ln2": {"g": (ch,), "b": (ch,)}, "attn2": attn(cfg.context_dim),
        "ln3": {"g": (ch,), "b": (ch,)},
        "ff": {"w1": (ch, inner * 2), "b1": (inner * 2,),
               "w2": (inner, ch), "b2": (ch,)},
    }
    return {
        "norm": {"g": (ch,), "b": (ch,)},
        "proj_in": _conv_shape(ch, ch, k=1),
        "blocks": [dict(block) for _ in range(cfg.transformer_depth)],
        "proj_out": _conv_shape(ch, ch, k=1),
    }


def param_shapes(cfg: UNetConfig) -> PyTree:
    mc = cfg.model_channels
    tdim = mc * 4
    shapes: dict[str, Any] = {
        "conv_in": _conv_shape(cfg.in_channels, mc),
        "time_emb": {"w1": (mc, tdim), "b1": (tdim,),
                     "w2": (tdim, tdim), "b2": (tdim,)},
    }
    down = []
    ch = mc
    level_out_ch = []   # channels of each skip, in push order
    skip_chs = [mc]
    for lvl, mult in enumerate(cfg.channel_mult):
        out_ch = mc * mult
        lp: dict[str, Any] = {"res": [], "attn": [] if lvl in cfg.attn_levels else None}
        for _ in range(cfg.num_res_blocks):
            lp["res"].append(_res_shapes(ch, out_ch, tdim))
            if lp["attn"] is not None:
                lp["attn"].append(_st_shapes(out_ch, cfg))
            ch = out_ch
            skip_chs.append(ch)
        if lvl != len(cfg.channel_mult) - 1:
            lp["down"] = _conv_shape(ch, ch)
            skip_chs.append(ch)
        down.append(lp)
        level_out_ch.append(out_ch)
    shapes["down"] = down
    shapes["mid"] = {
        "res1": _res_shapes(ch, ch, tdim),
        "attn": _st_shapes(ch, cfg),
        "res2": _res_shapes(ch, ch, tdim),
    }
    up = []
    for lvl in reversed(range(len(cfg.channel_mult))):
        out_ch = mc * cfg.channel_mult[lvl]
        lp = {"res": [], "attn": [] if lvl in cfg.attn_levels else None}
        for _ in range(cfg.num_res_blocks + 1):
            skip = skip_chs.pop()
            lp["res"].append(_res_shapes(ch + skip, out_ch, tdim))
            if lp["attn"] is not None:
                lp["attn"].append(_st_shapes(out_ch, cfg))
            ch = out_ch
        if lvl != 0:
            lp["up"] = _conv_shape(ch, ch)
        up.append(lp)
    shapes["up"] = up
    shapes["norm_out"] = {"g": (ch,), "b": (ch,)}
    shapes["conv_out"] = _conv_shape(ch, cfg.out_channels)
    return shapes


def init_params(rng: jax.Array, cfg: UNetConfig) -> PyTree:
    """Random init (debug presets / tests; real weights come from the
    diffusers-layout loader, localai_tpu.image.loader)."""
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))
    dtype = jnp.dtype(cfg.dtype)

    def mk(k, shape):
        if len(shape) == 1:
            return jnp.ones(shape, jnp.float32) if shape else jnp.zeros(shape)
        fan_in = math.prod(shape[:-1])
        scale = 1.0 / math.sqrt(max(fan_in, 1))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params = jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])
    return _zero_biases(params)


def _zero_biases(params: PyTree) -> PyTree:
    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else ""
        if name in ("b", "b1", "b2", "bo"):
            return jnp.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)
