"""CLIP-class text encoder — the conditioning tower for latent diffusion.

Capability parity: the text_encoder of the reference's diffusers pipelines
(/root/reference/backend/python/diffusers/backend.py:171-176 CLIPModel
handling). Pre-LN transformer with causal masking, learned position
embeddings, quick-GELU activation (CLIP ViT-L/14 family), final LN.
Supports clip_skip (use hidden states N layers before the end — parity:
Diffusers CLIPSkip config, backend.proto diffusers options).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from localai_tpu.image.unet import layer_norm

PyTree = Any


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_length: int = 77
    eos_token_id: int = 49407
    activation: str = "quick_gelu"
    dtype: str = "bfloat16"

    @classmethod
    def from_hf(cls, hf: dict) -> "CLIPTextConfig":
        return cls(
            vocab_size=hf.get("vocab_size", 49408),
            hidden_size=hf.get("hidden_size", 768),
            intermediate_size=hf.get("intermediate_size", 3072),
            num_layers=hf.get("num_hidden_layers", 12),
            num_heads=hf.get("num_attention_heads", 12),
            max_length=hf.get("max_position_embeddings", 77),
            eos_token_id=hf.get("eos_token_id", 49407),
            activation=hf.get("hidden_act", "quick_gelu"),
        )


def _act(cfg: CLIPTextConfig, x):
    if cfg.activation == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x)


def _mha(x, p, num_heads: int, mask):
    B, T, C = x.shape
    hd = C // num_heads

    def proj(w, b):
        return (x @ p[w].astype(x.dtype) + p[b].astype(x.dtype)).reshape(
            B, T, num_heads, hd
        )

    q, k, v = proj("wq", "bq"), proj("wk", "bk"), proj("wv", "bv")
    scores = jnp.einsum("bnhd,bmhd->bhnm", q, k) / math.sqrt(hd)
    scores = scores.astype(jnp.float32) + mask
    probs = jax.nn.softmax(scores, -1).astype(x.dtype)
    out = jnp.einsum("bhnm,bmhd->bnhd", probs, v).reshape(B, T, C)
    return out @ p["wo"].astype(x.dtype) + p["bo"].astype(x.dtype)


def _run_layers(cfg: CLIPTextConfig, params: PyTree, tokens,
                stop_after: int) -> tuple[jax.Array, jax.Array]:
    """THE encoder loop (shared by forward and encode_sdxl so mask /
    activation / residual semantics cannot drift between the SD and SDXL
    paths). Returns (hidden after ``stop_after`` layers, hidden after the
    second-to-last executed layer)."""
    dtype = jnp.dtype(cfg.dtype)
    B, T = tokens.shape
    x = params["token_emb"][tokens].astype(dtype)
    x = x + params["pos_emb"][:T].astype(dtype)
    causal = jnp.triu(jnp.full((T, T), -1e9, jnp.float32), 1)[None, None]
    penultimate = x
    for li, lp in enumerate(params["layers"]):
        if li >= stop_after:
            break
        x = x + _mha(layer_norm(x, lp["ln1"]), lp["attn"], cfg.num_heads,
                     causal)
        h = layer_norm(x, lp["ln2"])
        h = _act(cfg, h @ lp["mlp"]["w1"].astype(h.dtype)
                 + lp["mlp"]["b1"].astype(h.dtype))
        x = x + (h @ lp["mlp"]["w2"].astype(h.dtype)
                 + lp["mlp"]["b2"].astype(h.dtype))
        if li == stop_after - 2:
            penultimate = x
    return x, penultimate


def forward(cfg: CLIPTextConfig, params: PyTree, tokens,
            clip_skip: int = 0) -> jax.Array:
    """tokens [B, T] i32 → hidden states [B, T, C] (the context fed to the
    UNet cross-attention). clip_skip=N>0 returns the states N layers early
    (diffusers convention: skip=1 is the default final-layer output)."""
    stop = len(params["layers"]) - max(0, clip_skip - 1)
    x, _ = _run_layers(cfg, params, tokens, stop)
    return layer_norm(x, params["ln_f"])


def encode_sdxl(cfg: CLIPTextConfig, params: PyTree, tokens
                ) -> tuple[jax.Array, jax.Array]:
    """SDXL text conditioning: (penultimate hidden states [B,T,C] — the
    hidden_states[-2] diffusers feeds the UNet, WITHOUT the final
    layer norm — and the pooled embedding [B, proj|C] from the final
    layer at the EOT position, through text_projection when present)."""
    x, penultimate = _run_layers(cfg, params, tokens,
                                 len(params["layers"]))
    final = layer_norm(x, params["ln_f"])
    # EOT position: CLIP pools at the highest token id (the end token)
    eot = jnp.argmax(tokens, axis=-1)
    pooled = jnp.take_along_axis(
        final, eot[:, None, None].repeat(final.shape[-1], -1), axis=1
    )[:, 0]
    if "text_projection" in params:
        pooled = pooled @ params["text_projection"].astype(pooled.dtype)
    return penultimate, pooled


def param_shapes(cfg: CLIPTextConfig) -> PyTree:
    C, I = cfg.hidden_size, cfg.intermediate_size
    layer = {
        "ln1": {"g": (C,), "b": (C,)},
        "attn": {"wq": (C, C), "bq": (C,), "wk": (C, C), "bk": (C,),
                 "wv": (C, C), "bv": (C,), "wo": (C, C), "bo": (C,)},
        "ln2": {"g": (C,), "b": (C,)},
        "mlp": {"w1": (C, I), "b1": (I,), "w2": (I, C), "b2": (C,)},
    }
    return {
        "token_emb": (cfg.vocab_size, C),
        "pos_emb": (cfg.max_length, C),
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "ln_f": {"g": (C,), "b": (C,)},
    }


def init_params(rng: jax.Array, cfg: CLIPTextConfig) -> PyTree:
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))
    dtype = jnp.dtype(cfg.dtype)

    def mk(k, shape):
        if len(shape) == 1:
            return jnp.ones(shape, jnp.float32)
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dtype)

    params = jax.tree.unflatten(treedef, [mk(k, s) for k, s in zip(keys, flat)])

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else ""
        if name in ("bq", "bk", "bv", "bo", "b1", "b2", "b"):
            return jnp.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)
