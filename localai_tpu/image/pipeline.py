"""Latent-diffusion pipeline: text→image and image→image on TPU.

Capability parity: the reference's diffusers worker pipelines
(/root/reference/backend/python/diffusers/backend.py:184-474 — txt2img,
img2img, schedulers, cfg_scale, clip_skip, negative prompts, seeds) and
the NCNN fallback (/root/reference/backend/go/image/stablediffusion).

TPU design: ONE jitted step program per latent size — the UNet runs
cond+uncond in a single batch-2 call (classifier-free guidance without two
dispatches), the Python loop over steps stays on host (step count is
dynamic per request; the per-step dispatch is negligible next to the UNet).
Latent sizes are bucketed by rounding requested W/H up to multiples of 64,
bounding XLA recompiles the way prefill buckets do for the LLM engine.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.image import clip as clip_mod
from localai_tpu.image import schedulers as sch
from localai_tpu.image import unet as unet_mod
from localai_tpu.image import vae as vae_mod

log = logging.getLogger(__name__)


@dataclasses.dataclass
class GenerationResult:
    image: np.ndarray          # [H, W, 3] uint8
    seed: int


def bucket_dim(v: int, lo: int = 64, quantum: int = 64,
               hi: int = 2048) -> int:
    """Round a requested image dimension up to the compile-bucket quantum
    (shared by the UNet and FLUX pipelines — one recompile-bounding
    contract)."""
    v = max(lo, min(v, hi))
    return ((v + quantum - 1) // quantum) * quantum


def tokenize_clip(tokenizer, text_cfg, text: str) -> np.ndarray:
    """[1, max_length] i32 CLIP token row, eos-padded (the SD/FLUX primary
    text-encoder convention)."""
    T = text_cfg.max_length
    eos = text_cfg.eos_token_id
    ids = list(tokenizer.encode(text))[: T - 1]
    row = np.full((1, T), eos, np.int32)
    row[0, : len(ids)] = ids
    return row


class DiffusionPipeline:
    """One loaded diffusion model (UNet + VAE + text encoder + tokenizer)."""

    def __init__(self, unet_cfg, unet_params, vae_cfg, vae_params,
                 text_cfg, text_params, tokenizer, *,
                 text2_cfg=None, text2_params=None, tokenizer2=None,
                 default_scheduler: str = "euler",
                 default_steps: int = 15, default_cfg_scale: float = 7.0,
                 clip_skip: int = 0, ref: str = ""):
        self.unet_cfg = unet_cfg
        self.unet_params = unet_params
        self.vae_cfg = vae_cfg
        self.vae_params = vae_params
        self.text_cfg = text_cfg
        self.text_params = text_params
        self.tokenizer = tokenizer
        # SDXL: second text encoder (OpenCLIP-class) + its tokenizer; the
        # two hidden states concatenate into the UNet context and encoder
        # 2's projected pooled output feeds the text_time conditioning
        self.text2_cfg = text2_cfg
        self.text2_params = text2_params
        self.tokenizer2 = tokenizer2 or tokenizer
        self.default_scheduler = default_scheduler
        self.default_steps = default_steps
        self.default_cfg_scale = default_cfg_scale
        self.clip_skip = clip_skip
        self.ref = ref
        # ControlNet (optional): set via attach_controlnet()
        self.controlnet_cfg = None
        self.controlnet_params = None
        self._encode_text = jax.jit(self._encode_text_fn)
        if self.is_sdxl:
            self._encode_text_xl = jax.jit(self._encode_text_xl_fn)
        self._unet_step = jax.jit(self._unet_step_fn)
        self._decode = jax.jit(self._decode_fn)
        self._encode_img = jax.jit(self._encode_img_fn)

    @property
    def is_sdxl(self) -> bool:
        return self.text2_params is not None and getattr(
            self.unet_cfg, "addition_embed", False)

    # -- jitted programs -------------------------------------------------

    def _encode_text_fn(self, tokens):
        return clip_mod.forward(
            self.text_cfg, self.text_params, tokens, clip_skip=self.clip_skip
        )

    def _encode_text_xl_fn(self, tokens1, tokens2):
        """SDXL conditioning: concat of both encoders' penultimate hidden
        states + encoder 2's projected pooled output."""
        h1, _ = clip_mod.encode_sdxl(
            self.text_cfg, self.text_params, tokens1)
        h2, pooled = clip_mod.encode_sdxl(
            self.text2_cfg, self.text2_params, tokens2)
        return jnp.concatenate(
            [h1.astype(jnp.float32), h2.astype(jnp.float32)], axis=-1
        ), pooled.astype(jnp.float32)

    def _unet_step_fn(self, x, sigma, t, cond, cfg_scale):
        """Batched CFG: one UNet dispatch over [uncond; cond]; with a
        ControlNet attached, its residual pass rides the same batch."""
        xin = sch.scale_model_input(x, sigma)
        both = jnp.concatenate([xin, xin], axis=0)
        ts = jnp.full((both.shape[0],), t, jnp.float32)
        down_res = mid_res = None
        if "control_image" in cond and self.controlnet_params is not None:
            from localai_tpu.image import controlnet as cn

            down_res, mid_res = cn.forward(
                self.controlnet_cfg, self.controlnet_params, both, ts,
                cond["context"], cond["control_image"],
                conditioning_scale=cond["control_scale"],
                pooled_text=cond.get("pooled"),
                time_ids=cond.get("time_ids"),
            )
        eps = unet_mod.forward(
            self.unet_cfg, self.unet_params, both, ts, cond["context"],
            pooled_text=cond.get("pooled"), time_ids=cond.get("time_ids"),
            down_residuals=down_res, mid_residual=mid_res,
        )
        eps_u, eps_c = jnp.split(eps, 2, axis=0)
        eps = eps_u + cfg_scale * (eps_c - eps_u)
        return sch.denoised_from_eps(x, eps, sigma)

    def attach_controlnet(self, ref: str, model_path: str = "models"):
        """Load a ControlNetModel next to this pipeline (parity:
        backend.py:192-208)."""
        from localai_tpu.image import controlnet as cn
        from localai_tpu.image.loader import _to_device

        self.controlnet_cfg, params = cn.resolve_controlnet(
            ref, model_path)
        self.controlnet_params = _to_device(params,
                                            self.controlnet_cfg.dtype)
        return self

    def _decode_fn(self, latents):
        img = vae_mod.decode(
            self.vae_cfg, self.vae_params,
            latents / self.vae_cfg.scaling_factor,
        )
        return jnp.clip((img + 1.0) * 127.5, 0, 255).astype(jnp.uint8)

    def _encode_img_fn(self, img):
        return vae_mod.encode(self.vae_cfg, self.vae_params, img)

    # -- host API --------------------------------------------------------

    def _tokenize(self, text: str) -> np.ndarray:
        return tokenize_clip(self.tokenizer, self.text_cfg, text)

    def _tokenize2(self, text: str) -> np.ndarray:
        """SDXL's second (OpenCLIP) tokenizer pads with id 0 ("!"), NOT
        the eos token — pad-position hidden states feed cross-attention,
        so the padding id is part of the trained conditioning. The eos
        token stays the highest id, which is what the pooled-embedding
        argmax keys on."""
        T = self.text2_cfg.max_length
        eos = self.text2_cfg.eos_token_id
        ids = list(self.tokenizer2.encode(text))[: T - 1]
        if not ids or ids[-1] != eos:
            ids = ids[: T - 1] + [eos]
        row = np.zeros((1, T), np.int32)
        row[0, : len(ids)] = ids
        return row

    def _prepare_cond(self, prompt: str, negative: str,
                      width: int, height: int) -> dict:
        """The conditioning pytree fed to every UNet step: [uncond; cond]
        context, plus SDXL's pooled text + size/crop time_ids."""
        toks = np.concatenate(
            [self._tokenize(negative or ""), self._tokenize(prompt)], axis=0
        )
        if not self.is_sdxl:
            return {"context": self._encode_text(jnp.asarray(toks))}
        toks2 = np.concatenate(
            [self._tokenize2(negative or ""), self._tokenize2(prompt)],
            axis=0,
        )
        context, pooled = self._encode_text_xl(
            jnp.asarray(toks), jnp.asarray(toks2)
        )
        if not negative:
            # SDXL base ships force_zeros_for_empty_prompt=true: an empty
            # negative conditions on ZERO embeddings, not on the encoded
            # empty string (diffusers parity)
            context = context.at[0].set(0.0)
            pooled = pooled.at[0].set(0.0)
        # micro-conditioning: (orig_h, orig_w, crop_t, crop_l, tgt_h, tgt_w)
        tid = jnp.asarray(
            [[height, width, 0, 0, height, width]] * 2, jnp.float32
        )
        return {"context": context, "pooled": pooled, "time_ids": tid}

    @staticmethod
    def _bucket(v: int, lo: int = 64, quantum: int = 64, hi: int = 2048) -> int:
        return bucket_dim(v, lo, quantum, hi)

    def generate(
        self,
        prompt: str,
        *,
        negative_prompt: str = "",
        width: int = 512,
        height: int = 512,
        steps: Optional[int] = None,
        cfg_scale: Optional[float] = None,
        seed: Optional[int] = None,
        scheduler: Optional[str] = None,
        init_image: Optional[np.ndarray] = None,   # [H,W,3] uint8 (img2img)
        strength: float = 0.75,
        control_image: Optional[np.ndarray] = None,  # [H,W,3] uint8
        control_scale: float = 1.0,
    ) -> GenerationResult:
        rule, karras = sch.resolve(scheduler or self.default_scheduler)
        steps = int(steps or self.default_steps)
        guidance = float(
            self.default_cfg_scale if cfg_scale is None else cfg_scale
        )
        if seed is None or seed < 0:
            seed = int(np.random.randint(0, 2 ** 31 - 1))
        rng = jax.random.key(seed)
        ds = self.vae_cfg.downscale
        width, height = self._bucket(width), self._bucket(height)
        lw, lh = width // ds, height // ds
        L = self.vae_cfg.latent_channels

        cond = self._prepare_cond(prompt, negative_prompt, width, height)
        if control_image is not None and self.controlnet_params is not None:
            ci = jnp.asarray(control_image, jnp.float32)[None] / 255.0
            ci = jax.image.resize(ci, (1, height, width, 3), "linear")
            cond["control_image"] = jnp.concatenate([ci, ci], axis=0)
            cond["control_scale"] = jnp.float32(control_scale)
        sigmas, timesteps = sch.build_sigmas(steps, karras=karras)

        rng, nkey = jax.random.split(rng)
        noise = jax.random.normal(nkey, (1, lh, lw, L), jnp.float32)
        start = 0
        if init_image is not None:
            # img2img: start the trajectory at sigma[start] around the
            # encoded init latents (strength 1.0 = full re-noise)
            start = min(steps - 1, int(steps * (1.0 - strength)))
            img = jnp.asarray(init_image, jnp.float32) / 127.5 - 1.0
            img = jax.image.resize(img[None], (1, height, width, 3), "linear")
            x = self._encode_img(img) + noise * sigmas[start]
        else:
            x = noise * sigmas[0]

        prev_denoised = None
        prev_sigma = None
        for i in range(start, steps):
            sigma, sigma_next = float(sigmas[i]), float(sigmas[i + 1])
            denoised = self._unet_step(
                x, jnp.float32(sigma), jnp.float32(timesteps[i]), cond,
                jnp.float32(guidance),
            )
            noise_i = None
            if rule in sch.ANCESTRAL_RULES:
                rng, k = jax.random.split(rng)
                noise_i = jax.random.normal(k, x.shape, jnp.float32)
            x = sch.step(
                rule, x, denoised, jnp.float32(sigma), jnp.float32(sigma_next),
                prev_denoised=prev_denoised,
                prev_sigma=None if prev_sigma is None else jnp.float32(prev_sigma),
                noise=noise_i,
            )
            prev_denoised, prev_sigma = denoised, sigma

        img = np.asarray(self._decode(x))[0]
        return GenerationResult(image=img, seed=seed)


# ---------------------------------------------------------------------------
# resolution: ref → pipeline
# ---------------------------------------------------------------------------

_DEBUG_PRESETS = {
    # tiny: 64x64 output, runs in seconds on CPU — the test/debug preset
    # (the analogue of the LLM debug:* presets; zero-egress environment)
    "sd-tiny": dict(
        unet=unet_mod.UNetConfig(
            model_channels=32, channel_mult=(1, 2), num_res_blocks=1,
            attn_levels=(0, 1), num_heads=4, context_dim=64,
        ),
        vae=vae_mod.VAEConfig(
            base_channels=32, channel_mult=(1, 2), num_res_blocks=1,
        ),
        text=clip_mod.CLIPTextConfig(
            vocab_size=258, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, max_length=16, eos_token_id=257,
        ),
    ),
}


def _debug_pipeline(name: str, seed: int = 0, **defaults) -> DiffusionPipeline:
    from localai_tpu.utils.tokenizer import ByteTokenizer

    preset = _DEBUG_PRESETS[name]
    k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
    return DiffusionPipeline(
        preset["unet"], unet_mod.init_params(k1, preset["unet"]),
        preset["vae"], vae_mod.init_params(k2, preset["vae"]),
        preset["text"], clip_mod.init_params(k3, preset["text"]),
        ByteTokenizer(), ref=f"debug:{name}", **defaults,
    )


def resolve_image_model(
    ref: str,
    model_path: str | Path = "models",
    **defaults,
) -> DiffusionPipeline:
    """ref → loaded DiffusionPipeline.

    * ``debug:sd-tiny`` — random-weight preset (tests/benchmarks)
    * a diffusers-layout dir (model_index.json + unet/ vae/ text_encoder/
      tokenizer/) — SD-class safetensors checkpoint
    """
    if ref.startswith("debug:"):
        name = ref.split(":", 1)[1]
        if name == "flux-tiny":
            from localai_tpu.image.flux import debug_flux_pipeline

            defaults.pop("lora_adapter", None)
            defaults.pop("lora_scale", None)
            return debug_flux_pipeline(**defaults)
        if name not in _DEBUG_PRESETS:
            raise ValueError(
                f"unknown debug image preset {name!r}; have "
                f"{sorted(_DEBUG_PRESETS) + ['flux-tiny']}"
            )
        defaults.pop("lora_adapter", None)
        defaults.pop("lora_scale", None)
        return _debug_pipeline(name, **defaults)
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "transformer").is_dir():
            # FLUX-class layout: MMDiT under transformer/, T5 under
            # text_encoder_2/ — distinct from the UNet layout below
            from localai_tpu.image.flux import load_flux_pipeline

            defaults.pop("lora_adapter", None)
            defaults.pop("lora_scale", None)
            return load_flux_pipeline(cand, **defaults)
        if (cand / "model_index.json").exists() or (cand / "unet").is_dir():
            from localai_tpu.image.loader import load_diffusers_pipeline

            return load_diffusers_pipeline(cand, **defaults)
    raise FileNotFoundError(
        f"image model ref {ref!r} not found (looked for a diffusers layout "
        f"under {ref} and {Path(model_path) / ref})"
    )
