"""FLUX-class rectified-flow transformer (MMDiT) in JAX.

Parity target: the reference's diffusers backend serving FLUX.1
(/root/reference/backend/python/diffusers/backend.py:21,249-262 —
`FluxPipeline`, the GPU AIO image default `aio/gpu-8g/image-gen.yaml`).
Architecture follows diffusers `FluxTransformer2DModel`: double-stream
MMDiT blocks (separate image/text streams with joint attention and
AdaLN-zero modulation from timestep+pooled-text+guidance embeddings),
then single-stream blocks over the merged sequence (parallel attention +
MLP), 3-axis rotary position embeddings over (batch, y, x) ids, and an
AdaLN-continuous output head — verified against an independent torch
implementation in tests/test_flux.py.

TPU design: the whole velocity prediction is ONE jitted call per latent
bucket; double and single blocks each run as a ``lax.scan`` over stacked
weights (one compiled body per block type regardless of depth); all
matmuls are batched over the packed 2x2-patch token sequence — MXU-shaped,
static lengths.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PyTree = Any


@dataclasses.dataclass
class FluxConfig:
    in_channels: int = 64            # 16 latent ch x 2x2 patch
    num_layers: int = 19             # double-stream blocks
    num_single_layers: int = 38      # single-stream blocks
    attention_head_dim: int = 128
    num_attention_heads: int = 24
    joint_attention_dim: int = 4096  # T5 d_model
    pooled_projection_dim: int = 768 # CLIP pooled dim
    guidance_embeds: bool = True     # FLUX.1-dev distilled guidance
    axes_dims_rope: tuple = (16, 56, 56)
    dtype: str = "float32"

    @property
    def dim(self) -> int:
        return self.attention_head_dim * self.num_attention_heads

    @classmethod
    def from_hf(cls, hf: dict) -> "FluxConfig":
        return cls(
            in_channels=hf.get("in_channels", 64),
            num_layers=hf.get("num_layers", 19),
            num_single_layers=hf.get("num_single_layers", 38),
            attention_head_dim=hf.get("attention_head_dim", 128),
            num_attention_heads=hf.get("num_attention_heads", 24),
            joint_attention_dim=hf.get("joint_attention_dim", 4096),
            pooled_projection_dim=hf.get("pooled_projection_dim", 768),
            guidance_embeds=hf.get("guidance_embeds", True),
            axes_dims_rope=tuple(hf.get("axes_dims_rope", (16, 56, 56))),
        )


# -- embeddings -------------------------------------------------------------

def timestep_embedding(t: jax.Array, dim: int = 256) -> jax.Array:
    """diffusers Timesteps(flip_sin_to_cos=True, shift=0): [B, dim] f32."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def rope_3d(cfg: FluxConfig, ids: jax.Array) -> tuple[jax.Array, jax.Array]:
    """ids [N, 3] → (cos, sin) [N, head_dim], interleaved-pair layout
    (diffusers get_1d_rotary_pos_embed with repeat_interleave_real)."""
    cos_parts, sin_parts = [], []
    for axis, dim in enumerate(cfg.axes_dims_rope):
        freqs = 1.0 / (10000.0 ** (jnp.arange(0, dim, 2,
                                              dtype=jnp.float32) / dim))
        angles = ids[:, axis].astype(jnp.float32)[:, None] * freqs[None]
        cos_parts.append(jnp.repeat(jnp.cos(angles), 2, axis=-1))
        sin_parts.append(jnp.repeat(jnp.sin(angles), 2, axis=-1))
    return (jnp.concatenate(cos_parts, -1).astype(jnp.float32),
            jnp.concatenate(sin_parts, -1).astype(jnp.float32))


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, H, N, hd], cos/sin [N, hd] interleaved pairs."""
    xr = x.reshape(*x.shape[:-1], -1, 2)
    rot = jnp.stack([-xr[..., 1], xr[..., 0]], axis=-1).reshape(x.shape)
    return (x.astype(jnp.float32) * cos + rot.astype(jnp.float32) * sin
            ).astype(x.dtype)


def _ln(x, eps: float = 1e-6):
    """LayerNorm without affine (elementwise_affine=False everywhere)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype)


def _rmsn(x, w, eps: float = 1e-6):
    """Per-head qk RMSNorm (weight over head_dim)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps) * w.astype(jnp.float32)).astype(x.dtype)


def _mlp(p, x):
    return jax.nn.gelu(x @ p["w1"] + p["b1"], approximate=True) @ p["w2"] \
        + p["b2"]


def _heads(x, H):
    B, N, _ = x.shape
    return x.reshape(B, N, H, -1).transpose(0, 2, 1, 3)   # [B, H, N, hd]


def _unheads(x):
    B, H, N, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B, N, H * hd)


def _attention(q, k, v):
    """[B, H, N, hd] — plain sdpa in f32 softmax."""
    hd = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    probs = jax.nn.softmax(scores / math.sqrt(hd), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# -- forward ----------------------------------------------------------------

def forward(
    cfg: FluxConfig,
    params: PyTree,
    img: jax.Array,        # [B, Nimg, in_channels] packed 2x2 latent patches
    txt: jax.Array,        # [B, Ntxt, joint_attention_dim] T5 states
    pooled: jax.Array,     # [B, pooled_projection_dim] CLIP pooled
    timestep: jax.Array,   # [B] f32 in [0, 1] (sigma)
    img_ids: jax.Array,    # [Nimg, 3]
    txt_ids: jax.Array,    # [Ntxt, 3]
    guidance: Optional[jax.Array] = None,   # [B] f32 (dev-distilled)
) -> jax.Array:
    """Velocity prediction [B, Nimg, in_channels]."""
    H = cfg.num_attention_heads
    dt = jnp.dtype(cfg.dtype)
    Ntxt = txt.shape[1]

    temb = _mlp2(params["time_mlp"],
                 timestep_embedding(timestep * 1000.0))
    if cfg.guidance_embeds:
        g = guidance if guidance is not None else jnp.ones_like(timestep)
        temb = temb + _mlp2(params["guid_mlp"],
                            timestep_embedding(g * 1000.0))
    temb = temb + _mlp2(params["text_mlp"], pooled.astype(jnp.float32))
    temb = jax.nn.silu(temb)                                  # [B, dim]

    x = (img.astype(dt) @ params["x_embed_w"].astype(dt)
         + params["x_embed_b"].astype(dt))
    c = (txt.astype(dt) @ params["ctx_embed_w"].astype(dt)
         + params["ctx_embed_b"].astype(dt))

    cos, sin = rope_3d(cfg, jnp.concatenate([txt_ids, img_ids], axis=0))

    def mod(p, name):
        out = temb @ p[f"{name}_w"] + p[f"{name}_b"]
        return out.astype(dt)

    def double_body(carry, p):
        x, c = carry
        m_x = mod(p, "mod_x")[:, None]                  # [B, 1, 6*dim]
        m_c = mod(p, "mod_c")[:, None]
        sh_x, sc_x, g_x, shm_x, scm_x, gm_x = jnp.split(m_x, 6, axis=-1)
        sh_c, sc_c, g_c, shm_c, scm_c, gm_c = jnp.split(m_c, 6, axis=-1)

        xn = _ln(x) * (1 + sc_x) + sh_x
        cn = _ln(c) * (1 + sc_c) + sh_c
        q_x = _rmsn(_heads(xn @ p["wq_x"] + p["bq_x"], H), p["qn_x"])
        k_x = _rmsn(_heads(xn @ p["wk_x"] + p["bk_x"], H), p["kn_x"])
        v_x = _heads(xn @ p["wv_x"] + p["bv_x"], H)
        q_c = _rmsn(_heads(cn @ p["wq_c"] + p["bq_c"], H), p["qn_c"])
        k_c = _rmsn(_heads(cn @ p["wk_c"] + p["bk_c"], H), p["kn_c"])
        v_c = _heads(cn @ p["wv_c"] + p["bv_c"], H)

        q = _apply_rope(jnp.concatenate([q_c, q_x], axis=2), cos, sin)
        k = _apply_rope(jnp.concatenate([k_c, k_x], axis=2), cos, sin)
        v = jnp.concatenate([v_c, v_x], axis=2)
        att = _unheads(_attention(q, k, v))
        a_c, a_x = att[:, :Ntxt], att[:, Ntxt:]

        x = x + g_x * (a_x @ p["wo_x"] + p["bo_x"])
        xm = _ln(x) * (1 + scm_x) + shm_x
        x = x + gm_x * _mlp({"w1": p["ff_x_w1"], "b1": p["ff_x_b1"],
                             "w2": p["ff_x_w2"], "b2": p["ff_x_b2"]}, xm)
        c = c + g_c * (a_c @ p["wo_c"] + p["bo_c"])
        cm = _ln(c) * (1 + scm_c) + shm_c
        c = c + gm_c * _mlp({"w1": p["ff_c_w1"], "b1": p["ff_c_b1"],
                             "w2": p["ff_c_w2"], "b2": p["ff_c_b2"]}, cm)
        return (x, c), None

    (x, c), _ = lax.scan(double_body, (x, c), params["double"])

    s = jnp.concatenate([c, x], axis=1)                  # [B, Ntxt+Nimg, dim]

    def single_body(s, p):
        m = mod(p, "mod")[:, None]                       # [B, 1, 3*dim]
        sh, sc, g = jnp.split(m, 3, axis=-1)
        sn = _ln(s) * (1 + sc) + sh
        q = _rmsn(_heads(sn @ p["wq"] + p["bq"], H), p["qn"])
        k = _rmsn(_heads(sn @ p["wk"] + p["bk"], H), p["kn"])
        v = _heads(sn @ p["wv"] + p["bv"], H)
        att = _unheads(_attention(_apply_rope(q, cos, sin),
                                  _apply_rope(k, cos, sin), v))
        mlp = jax.nn.gelu(sn @ p["mlp_w"] + p["mlp_b"], approximate=True)
        proj = (jnp.concatenate([att, mlp], axis=-1) @ p["out_w"]
                + p["out_b"])
        return s + g * proj, None

    s, _ = lax.scan(single_body, s, params["single"])
    x = s[:, Ntxt:]

    # temb already went through SiLU above (every AdaLN consumer takes
    # silu(embedding) @ linear — diffusers applies the SiLU inside each
    # norm module; here it's hoisted once)
    out_mod = temb @ params["norm_out_w"] + params["norm_out_b"]
    scale, shift = jnp.split(out_mod.astype(dt)[:, None], 2, axis=-1)
    x = _ln(x) * (1 + scale) + shift
    return x @ params["proj_out_w"].astype(dt) + params["proj_out_b"].astype(dt)


def _mlp2(p, x):
    """linear_1 → SiLU → linear_2 (the diffusers TimestepEmbedding shape)."""
    return jax.nn.silu(x @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]


# -- parameters -------------------------------------------------------------

def param_shapes(cfg: FluxConfig) -> dict:
    D, Ld, Ls = cfg.dim, cfg.num_layers, cfg.num_single_layers
    hd = cfg.attention_head_dim
    F = 4 * D
    shapes: dict = {
        "x_embed_w": (cfg.in_channels, D), "x_embed_b": (D,),
        "ctx_embed_w": (cfg.joint_attention_dim, D), "ctx_embed_b": (D,),
        "time_mlp": {"w1": (256, D), "b1": (D,), "w2": (D, D), "b2": (D,)},
        "text_mlp": {"w1": (cfg.pooled_projection_dim, D), "b1": (D,),
                     "w2": (D, D), "b2": (D,)},
        "norm_out_w": (D, 2 * D), "norm_out_b": (2 * D,),
        "proj_out_w": (D, cfg.in_channels), "proj_out_b": (cfg.in_channels,),
        "double": {},
        "single": {},
    }
    if cfg.guidance_embeds:
        shapes["guid_mlp"] = {"w1": (256, D), "b1": (D,),
                              "w2": (D, D), "b2": (D,)}
    dd = {"mod_x_w": (D, 6 * D), "mod_x_b": (6 * D,),
          "mod_c_w": (D, 6 * D), "mod_c_b": (6 * D,)}
    for st in ("x", "c"):
        dd.update({
            f"wq_{st}": (D, D), f"bq_{st}": (D,),
            f"wk_{st}": (D, D), f"bk_{st}": (D,),
            f"wv_{st}": (D, D), f"bv_{st}": (D,),
            f"wo_{st}": (D, D), f"bo_{st}": (D,),
            f"qn_{st}": (hd,), f"kn_{st}": (hd,),
            f"ff_{st}_w1": (D, F), f"ff_{st}_b1": (F,),
            f"ff_{st}_w2": (F, D), f"ff_{st}_b2": (D,),
        })
    shapes["double"] = {k: (Ld,) + v for k, v in dd.items()}
    ss = {"mod_w": (D, 3 * D), "mod_b": (3 * D,),
          "wq": (D, D), "bq": (D,), "wk": (D, D), "bk": (D,),
          "wv": (D, D), "bv": (D,), "qn": (hd,), "kn": (hd,),
          "mlp_w": (D, F), "mlp_b": (F,),
          "out_w": (D + F, D), "out_b": (D,)}
    shapes["single"] = {k: (Ls,) + v for k, v in ss.items()}
    return shapes


def init_params(rng: jax.Array, cfg: FluxConfig) -> PyTree:
    """Random init keyed by leaf NAME (qk-norm gains → ones, biases →
    zeros, weights → 0.02-std gaussians) — shape heuristics would misfire
    on tiny test configs where dims collide."""
    shapes = param_shapes(cfg)
    flat, treedef = jax.tree.flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(flat))

    def mk(path, shape, k):
        name = path[-1].key
        if name.startswith(("qn", "kn")):
            return jnp.ones(shape, jnp.float32)
        if name.startswith("b") or name.endswith(("_b", "b1", "b2")):
            return jnp.zeros(shape, jnp.float32)
        return jax.random.normal(k, shape, jnp.float32) * 0.02

    return jax.tree.unflatten(
        treedef, [mk(p, s, k) for (p, s), k in zip(flat, keys)])


# -- rectified-flow schedule ------------------------------------------------

def flow_sigmas(steps: int, image_seq_len: int, *,
                base_shift: float = 0.5, max_shift: float = 1.15,
                dynamic: bool = True, shift: float = 1.0) -> np.ndarray:
    """FlowMatchEulerDiscrete sigmas, [steps + 1] with a trailing 0.

    ``dynamic`` applies FLUX.1-dev's resolution-dependent timestep shift
    (diffusers calculate_shift); ``dynamic=False`` applies the static
    ``shift`` the checkpoint's scheduler_config declares — FLUX.1-schnell
    is distilled for shift=1.0 (identity), so forcing the dynamic shift on
    it would run every step at the wrong sigma."""
    sigmas = np.linspace(1.0, 1.0 / steps, steps)
    if dynamic:
        m = (max_shift - base_shift) / (4096 - 256)
        b = base_shift - m * 256
        mu = image_seq_len * m + b
        sigmas = np.exp(mu) / (np.exp(mu) + (1.0 / sigmas - 1.0))
    elif shift != 1.0:
        sigmas = shift * sigmas / (1.0 + (shift - 1.0) * sigmas)
    return np.append(sigmas, 0.0).astype(np.float32)
