"""ControlNet: condition-image guidance for the diffusion pipeline.

Parity: /root/reference/backend/python/diffusers/backend.py:192-208 —
`control_net` model option loading a ControlNetModel next to the SD
pipeline. Architecture (diffusers ControlNetModel): a copy of the UNet's
encoder (conv_in → down blocks → mid) plus a small conv stack embedding
the condition image, emitting one zero-conv residual per UNet skip and
one for the mid block; the base UNet adds them during its up pass. The
JAX forward below reuses the unet module's blocks (same param mapping,
NHWC) so the checkpoint loader is the unet loader plus the controlnet-
specific heads."""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp

from localai_tpu.image import unet as unet_mod
from localai_tpu.image.unet import UNetConfig, conv2d

log = logging.getLogger(__name__)

PyTree = Any


def cond_embedding(p: PyTree, image) -> jax.Array:
    """Condition image [B,H,W,3] in [0,1] → [B,h,w,C] features (the
    controlnet_cond_embedding conv stack: conv_in, silu conv blocks with
    stride-2 downsamples, zero conv_out)."""
    h = jax.nn.silu(conv2d(image, p["conv_in"]))
    for blk in p["blocks"]:
        h = jax.nn.silu(conv2d(h, blk["a"]))
        h = jax.nn.silu(conv2d(h, blk["b"], stride=2))
    return conv2d(h, p["conv_out"])


def forward(cfg: UNetConfig, params: PyTree, latents, timesteps, context,
            cond_image, conditioning_scale=1.0,
            pooled_text=None, time_ids=None):
    """ControlNet pass → (down_residuals list, mid_residual), each scaled
    by conditioning_scale, shaped to add onto the base UNet's skips."""
    dtype = jnp.dtype(cfg.dtype)
    x = latents.astype(dtype)
    context = context.astype(dtype)

    temb = unet_mod.timestep_embedding(timesteps, cfg.model_channels)
    te = params["time_emb"]
    temb = temb @ te["w1"] + te["b1"]
    temb = jax.nn.silu(temb) @ te["w2"] + te["b2"]
    if cfg.addition_embed and pooled_text is not None:
        B = pooled_text.shape[0]
        tid = unet_mod.timestep_embedding(
            time_ids.reshape(-1), cfg.addition_time_embed_dim
        ).reshape(B, -1)
        aug = jnp.concatenate(
            [pooled_text.astype(jnp.float32), tid], axis=-1)
        ae = params["add_emb"]
        aug = aug @ ae["w1"] + ae["b1"]
        aug = jax.nn.silu(aug) @ ae["w2"] + ae["b2"]
        temb = temb + aug

    h = conv2d(x, params["conv_in"])
    h = h + cond_embedding(params["cond_emb"], cond_image.astype(dtype))

    feats = [h]
    for lvl, lp in enumerate(params["down"]):
        for i, rp in enumerate(lp["res"]):
            h = unet_mod.res_block(h, temb, rp)
            if lp.get("attn"):
                h = unet_mod.spatial_transformer(
                    h, context, lp["attn"][i], cfg, cfg.heads_at(lvl))
            feats.append(h)
        if lp.get("down"):
            h = unet_mod.downsample(h, lp["down"])
            feats.append(h)

    mid = params["mid"]
    n_lvls = len(params["down"])
    h = unet_mod.res_block(h, temb, mid["res1"])
    h = unet_mod.spatial_transformer(h, context, mid["attn"], cfg,
                                     cfg.heads_at(n_lvls - 1))
    h = unet_mod.res_block(h, temb, mid["res2"])

    scale = jnp.asarray(conditioning_scale, jnp.float32).astype(dtype)
    down_res = [
        conv2d(f, zp) * scale
        for f, zp in zip(feats, params["zero_convs"])
    ]
    mid_res = conv2d(h, params["mid_zero"]) * scale
    return down_res, mid_res


def load_controlnet(d: str | Path):
    """diffusers ControlNetModel dir → (UNetConfig, params)."""
    from localai_tpu.image.loader import (
        _conv,
        _lin,
        _open_dir,
        _res_params,
        _st_params,
    )

    d = Path(d)
    with open(d / "config.json") as f:
        cfg = UNetConfig.from_hf(json.load(f))
    t = _open_dir(d)
    w1, b1 = _lin(t, "time_embedding.linear_1")
    w2, b2 = _lin(t, "time_embedding.linear_2")
    params: dict[str, Any] = {
        "conv_in": _conv(t, "conv_in"),
        "time_emb": {"w1": w1, "b1": b1, "w2": w2, "b2": b2},
    }
    if "add_embedding.linear_1.weight" in t:
        aw1, ab1 = _lin(t, "add_embedding.linear_1")
        aw2, ab2 = _lin(t, "add_embedding.linear_2")
        params["add_emb"] = {"w1": aw1, "b1": ab1, "w2": aw2, "b2": ab2}

    # condition embedding conv stack
    ce = "controlnet_cond_embedding"
    blocks = []
    i = 0
    while f"{ce}.blocks.{i}.weight" in t:
        blocks.append({
            "a": _conv(t, f"{ce}.blocks.{i}"),
            "b": _conv(t, f"{ce}.blocks.{i + 1}"),
        })
        i += 2
    params["cond_emb"] = {
        "conv_in": _conv(t, f"{ce}.conv_in"),
        "blocks": blocks,
        "conv_out": _conv(t, f"{ce}.conv_out"),
    }

    down = []
    for lvl in range(len(cfg.channel_mult)):
        base = f"down_blocks.{lvl}"
        has_attn = f"{base}.attentions.0.norm.weight" in t
        lp: dict[str, Any] = {
            "res": [_res_params(t, f"{base}.resnets.{j}")
                    for j in range(cfg.num_res_blocks)],
            "attn": [_st_params(t, f"{base}.attentions.{j}")
                     for j in range(cfg.num_res_blocks)]
            if has_attn else None,
        }
        if f"{base}.downsamplers.0.conv.weight" in t:
            lp["down"] = _conv(t, f"{base}.downsamplers.0.conv")
        down.append(lp)
    params["down"] = down
    params["mid"] = {
        "res1": _res_params(t, "mid_block.resnets.0"),
        "attn": _st_params(t, "mid_block.attentions.0"),
        "res2": _res_params(t, "mid_block.resnets.1"),
    }
    zero = []
    j = 0
    while f"controlnet_down_blocks.{j}.weight" in t:
        zero.append(_conv(t, f"controlnet_down_blocks.{j}"))
        j += 1
    params["zero_convs"] = zero
    params["mid_zero"] = _conv(t, "controlnet_mid_block")
    return cfg, params


def resolve_controlnet(ref: str, model_path: str | Path = "models"):
    for cand in (Path(ref), Path(model_path) / ref):
        if (cand / "config.json").exists():
            return load_controlnet(cand)
    raise FileNotFoundError(f"controlnet ref {ref!r} not found")
