"""Worker process lifecycle: spawn, health-gate, respawn, watchdog.

Parity with the reference's model-lifecycle layer:
  * spawn + stdout/stderr tailing — pkg/model/process.go:73+
  * free-port allocation + N health attempts before failing —
    pkg/model/initializers.go:271-407 (grpcModel)
  * health-check-and-respawn of stale handles — pkg/model/loader.go:170-206
  * busy/idle watchdog killing hung or RAM-hogging workers —
    pkg/model/watchdog.go:19-156
  * external backends registered by address — external_backends.json,
    core/startup/config_file_watcher.go

The TPU twist: a worker is a Python process owning a JAX engine; on
multi-chip hosts each worker claims devices via env (JAX visible-device
pinning) rather than CUDA_VISIBLE_DEVICES.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from typing import Optional

from localai_tpu.worker.client import WorkerClient

log = logging.getLogger(__name__)


class WorkerProcess:
    """One spawned worker and its client handle."""

    def __init__(self, name: str, *, env: Optional[dict] = None,
                 health_attempts: int = 60, health_interval: float = 1.0,
                 parallel: bool = True, watchdog: "Watchdog | None" = None):
        self.name = name
        self.proc: Optional[subprocess.Popen] = None
        self.client: Optional[WorkerClient] = None
        self.port = 0
        self._env = env or {}
        self._health_attempts = health_attempts
        self._health_interval = health_interval
        self._parallel = parallel
        self._watchdog = watchdog
        self._log_thread: Optional[threading.Thread] = None

    def start(self) -> WorkerClient:
        env = dict(os.environ)
        env.update(self._env)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "localai_tpu.worker.server",
             "--addr", "127.0.0.1:0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, bufsize=1,
        )
        # the tail thread scans for WORKER_READY and forwards everything
        # else into our log (parity: process.go stdout/stderr tailing);
        # waiting on an Event keeps startup bounded even if the child
        # hangs silently before binding.
        self._ready_evt = threading.Event()
        self._ready_port = 0
        self._log_thread = threading.Thread(
            target=self._tail_log, daemon=True,
            name=f"worker-log-{self.name}",
        )
        self._log_thread.start()
        timeout = self._health_attempts * self._health_interval
        if not self._ready_evt.wait(timeout) or not self._ready_port:
            rc = self.proc.poll()
            self.stop()
            raise RuntimeError(
                f"worker {self.name} never reported a port"
                + (f" (exited rc={rc})" if rc is not None else "")
            )
        self.port = self._ready_port

        client = WorkerClient(f"127.0.0.1:{self.port}", parallel=self._parallel,
                              watchdog=self._watchdog)
        # health gate with retries (initializers.go:360-383)
        for _ in range(self._health_attempts):
            if client.health(timeout=2.0):
                self.client = client
                return client
            if self.proc.poll() is not None:
                break
            time.sleep(self._health_interval)
        self.stop()
        raise RuntimeError(f"worker {self.name} failed health check")

    def _tail_log(self) -> None:
        assert self.proc is not None and self.proc.stdout is not None
        for line in self.proc.stdout:
            if line.startswith("WORKER_READY port="):
                self._ready_port = int(line.strip().split("=", 1)[1])
                self._ready_evt.set()
                continue
            log.info("[%s] %s", self.name, line.rstrip())
        self._ready_evt.set()  # EOF: unblock a waiting start()

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def healthy(self) -> bool:
        return self.alive and self.client is not None and self.client.health()

    def stop(self, grace: float = 5.0) -> None:
        if self.client is not None:
            self.client.close()
            self.client = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(grace)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(5.0)
        self.proc = None


class Watchdog:
    """Busy/idle watchdog over worker addresses (watchdog.go:19-156).

    ``mark``/``unmark`` are called by WorkerClient around every RPC; the
    loop kills workers busy longer than busy_timeout (hung engine) or idle
    longer than idle_timeout (HBM/RAM hog)."""

    def __init__(self, *, busy_timeout: float = 300.0,
                 idle_timeout: float = 900.0, interval: float = 5.0):
        self.busy_timeout = busy_timeout
        self.idle_timeout = idle_timeout
        self.interval = interval
        self._busy_since: dict[str, float] = {}
        self._busy_count: dict[str, int] = {}
        self._idle_since: dict[str, float] = {}
        self._kill: dict[str, callable] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, address: str, kill_fn) -> None:
        with self._lock:
            self._kill[address] = kill_fn
            self._idle_since[address] = time.monotonic()

    def unregister(self, address: str) -> None:
        with self._lock:
            self._kill.pop(address, None)
            self._busy_since.pop(address, None)
            self._busy_count.pop(address, None)
            self._idle_since.pop(address, None)

    def mark(self, address: str) -> None:
        """Refcounted: a worker serving N overlapping RPCs stays busy until
        the last one finishes (the gRPC server handles 32 concurrently)."""
        with self._lock:
            n = self._busy_count.get(address, 0)
            self._busy_count[address] = n + 1
            if n == 0:
                self._busy_since[address] = time.monotonic()
            self._idle_since.pop(address, None)

    def unmark(self, address: str) -> None:
        with self._lock:
            n = self._busy_count.get(address, 0) - 1
            if n > 0:
                self._busy_count[address] = n
                return
            self._busy_count.pop(address, None)
            self._busy_since.pop(address, None)
            self._idle_since[address] = time.monotonic()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="worker-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval * 2)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            doomed: list[str] = []
            with self._lock:
                if self.busy_timeout:
                    doomed += [a for a, t in self._busy_since.items()
                               if now - t > self.busy_timeout]
                if self.idle_timeout:
                    doomed += [a for a, t in self._idle_since.items()
                               if now - t > self.idle_timeout]
                kills = [(a, self._kill.get(a)) for a in doomed]
            for addr, kill in kills:
                if kill is None:
                    continue
                log.warning("watchdog killing worker at %s", addr)
                try:
                    kill()
                finally:
                    self.unregister(addr)


class WorkerPool:
    """name → worker, with health-check-and-respawn on access
    (loader.go:170-206) and external-backend registration."""

    def __init__(self, *, watchdog: Optional[Watchdog] = None):
        self._workers: dict[str, WorkerProcess] = {}
        self._external: dict[str, WorkerClient] = {}
        self._lock = threading.Lock()          # guards the maps only
        self._name_locks: dict[str, threading.Lock] = {}
        self._watchdog = watchdog

    def _name_lock(self, name: str) -> threading.Lock:
        with self._lock:
            lk = self._name_locks.get(name)
            if lk is None:
                lk = self._name_locks[name] = threading.Lock()
            return lk

    def register_external(self, name: str, address: str) -> WorkerClient:
        """An externally managed worker speaking the same proto (parity:
        external gRPC backends, initializers.go externalBackends).
        Idempotent: re-registering the same name+address reuses the
        existing channel."""
        with self._lock:
            ext = self._external.get(name)
            if ext is not None and ext.address == address:
                return ext
        client = WorkerClient(address, watchdog=self._watchdog)
        with self._lock:
            self._external[name] = client
        # the displaced client (address change) is deliberately NOT closed:
        # another thread may be mid-stream on it; the channel is reclaimed
        # when its last in-flight RPC finishes and the object is collected
        return client

    def get(self, name: str, *, env: Optional[dict] = None) -> WorkerClient:
        # per-name lock: a cold spawn of one model (subprocess + engine
        # load, tens of seconds) must not serialize lookups of others
        with self._name_lock(name):
            with self._lock:
                ext = self._external.get(name)
                if ext is not None:
                    return ext
                wp = self._workers.get(name)
            if wp is not None:
                if wp.healthy():
                    return wp.client  # type: ignore[return-value]
                log.warning("worker %s unhealthy; respawning", name)
                with self._lock:
                    self._drop_locked(name)
            wp = WorkerProcess(name, env=env, watchdog=self._watchdog)
            client = wp.start()
            if self._watchdog is not None:
                self._watchdog.register(client.address, wp.stop)
            with self._lock:
                self._workers[name] = wp
            return client

    def _drop_locked(self, name: str) -> None:  # jaxlint: guarded-by(_lock)
        wp = self._workers.pop(name, None)
        if wp is not None:
            if self._watchdog is not None and wp.client is not None:
                self._watchdog.unregister(wp.client.address)
            wp.stop()

    def shutdown(self, name: str) -> bool:
        with self._lock:
            if name in self._workers:
                self._drop_locked(name)
                return True
            ext = self._external.pop(name, None)
        if ext is not None:
            ext.close()  # eviction only fires when idle — safe to close
            return True
        return False

    def shutdown_all(self) -> None:
        with self._lock:
            for name in list(self._workers):
                self._drop_locked(name)
            for client in self._external.values():
                client.close()
            self._external.clear()

    def names(self) -> list[str]:
        with self._lock:
            return sorted(set(self._workers) | set(self._external))
