"""WorkerClient: typed wrapper over the Backend stub.

Parity: the reference's Go client layer (/root/reference/pkg/grpc/
client.go:15-120) — per-call busy marking for the watchdog, optional
serialization when parallel requests are disabled, UTF-8-safe streaming
(byte chunks reassembled into runes happens worker-side here; deltas are
whole UTF-8 strings by construction, core/backend/llm.go:122-138 is no
longer needed).

Deadline discipline: EVERY RPC carries a default deadline — control-plane
calls (health/status/metrics/tokenize/stores) a short one, work-shaped
calls (predict/load/transcode) a generation-scale one — and the channel
runs gRPC keepalive pings so a peer that stops ACKing (SIGKILLed host,
network partition: no RST ever arrives) fails in-flight RPCs with
UNAVAILABLE instead of holding them to the full deadline. Streams are
additionally inactivity-bounded by the fleet tier
(fleet.net.bounded_stream), since their *total* deadline must stay
generation-scale.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Iterator, Optional

import grpc

from localai_tpu.worker import backend_pb2 as pb
from localai_tpu.worker import rpc
from localai_tpu.worker.rpc import BackendStub


# work-shaped RPCs (generation, model load, media): bounded, but at the
# scale of the work itself
WORK_TIMEOUT_S = 600.0
# control-plane RPCs (health already 5 s, status 5 s, metrics 10 s,
# tokenize/stores below): a wedged peer must cost seconds on these paths
CONTROL_TIMEOUT_S = 60.0


class WorkerClient:
    def __init__(self, address: str, *, parallel: bool = True,
                 watchdog: Optional[Any] = None):
        self.address = address
        self._channel = grpc.insecure_channel(
            address,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024),
                     # keepalive: a partitioned/SIGKILLed peer never sends
                     # a RST, so without pings an in-flight stream would
                     # only fail at its total deadline — 30 s ping + 10 s
                     # ack bound turns that silence into UNAVAILABLE
                     ("grpc.keepalive_time_ms", 30_000),
                     ("grpc.keepalive_timeout_ms", 10_000),
                     ("grpc.keepalive_permit_without_calls", 0),
                     ("grpc.http2.max_pings_without_data", 0)],
        )
        self._stub = BackendStub(self._channel)
        # parallel=False serializes all calls (parity: --parallel-requests
        # gate, client.go:102-118)
        self._op_lock = threading.Lock() if not parallel else None
        self._watchdog = watchdog
        self.busy = False

    # -- busy/watchdog bookkeeping ---------------------------------------

    def _enter(self):
        if self._op_lock is not None:
            self._op_lock.acquire()
        self.busy = True
        if self._watchdog is not None:
            self._watchdog.mark(self.address)

    def _exit(self):
        self.busy = False
        if self._watchdog is not None:
            self._watchdog.unmark(self.address)
        if self._op_lock is not None:
            self._op_lock.release()

    def _call(self, fn: Callable, request, timeout: Optional[float] = None,
              metadata: Optional[tuple] = None):
        self._enter()
        try:
            return fn(request, timeout=timeout, metadata=metadata)
        finally:
            self._exit()

    # -- RPC surface ------------------------------------------------------

    def health(self, timeout: float = 5.0) -> bool:
        try:
            reply = self._stub.Health(pb.HealthMessage(), timeout=timeout)
            return reply.message == b"OK"
        except grpc.RpcError:
            return False

    def load_model(self, *, model: str = "", config_yaml: str = "",
                   model_path: str = "", context_size: int = 0,
                   seed: int = 0, timeout: float = WORK_TIMEOUT_S) -> pb.Result:
        return self._call(self._stub.LoadModel, pb.ModelOptions(
            model=model, config_yaml=config_yaml, model_path=model_path,
            context_size=context_size, seed=seed,
        ), timeout)

    def predict(self, opts: pb.PredictOptions,
                timeout: float = WORK_TIMEOUT_S,
                trace_id: str = "") -> pb.Reply:
        return self._call(self._stub.Predict, opts, timeout,
                          metadata=rpc.trace_metadata(trace_id) or None)

    def predict_stream(self, opts: pb.PredictOptions,
                       timeout: float = WORK_TIMEOUT_S,
                       trace_id: str = "",
                       tenant: str = "") -> Iterator[pb.Reply]:
        self._enter()
        try:
            yield from self._stub.PredictStream(
                opts, timeout=timeout,
                metadata=(rpc.trace_metadata(trace_id)
                          + rpc.tenant_metadata(tenant)) or None,
            )
        finally:
            self._exit()

    def prefill_prefix(self, opts: pb.PredictOptions,
                       timeout: float = WORK_TIMEOUT_S,
                       trace_id: str = "") -> Iterator[pb.PrefixChunk]:
        """Run a prefill on this (prefill-role) replica and stream back its
        packed KV-prefix chunks (fleet disaggregation)."""
        self._enter()
        try:
            yield from self._stub.PrefillPrefix(
                opts, timeout=timeout,
                metadata=rpc.trace_metadata(trace_id) or None,
            )
        finally:
            self._exit()

    def transfer_prefix(self, chunks: Iterator[pb.PrefixChunk],
                        timeout: float = WORK_TIMEOUT_S,
                        trace_id: str = "") -> pb.Result:
        """Stream prefix chunks into this (decode-role) replica's cache."""
        return self._call(self._stub.TransferPrefix, chunks, timeout,
                          metadata=rpc.trace_metadata(trace_id) or None)

    def embedding(self, text: str = "", tokens: Optional[list[int]] = None,
                  timeout: float = WORK_TIMEOUT_S) -> list[float]:
        res = self._call(self._stub.Embedding, pb.EmbeddingRequest(
            text=text, tokens=tokens or []), timeout)
        return list(res.embeddings)

    def tokenize(self, text: str, add_bos: bool = False,
                 timeout: float = CONTROL_TIMEOUT_S) -> list[int]:
        res = self._call(self._stub.TokenizeString, pb.TokenizationRequest(
            text=text, add_bos=add_bos), timeout)
        return list(res.tokens)

    def status(self, timeout: float = 5.0) -> pb.StatusResponse:
        return self._stub.Status(pb.HealthMessage(), timeout=timeout)

    def metrics(self, timeout: float = 10.0) -> dict:
        res = self._stub.GetMetrics(pb.MetricsRequest(), timeout=timeout)
        return json.loads(res.json or "{}")

    def get_telemetry(self, *, trace_id: str = "", since: float = 0.0,
                      limit: int = 256, recent: int = 20,
                      timeout: float = CONTROL_TIMEOUT_S) -> dict:
        """Harvest this worker's telemetry pane (trace spans for one
        trace id or a recent window, flight-ring snapshot, scheduler
        metrics). Control-plane shaped: bounded deadline, host-side data
        only — the fleet tier passes its configured RPC deadline so a
        wedged replica costs one deadline, never a hung harvest.

        Proto3 cannot tell an explicit 0 from unset, so "no flight
        records" / "no recent traces" travel as -1 — the servicer maps
        0/unset to its defaults and negatives to zero, keeping the wire
        pane byte-for-byte consistent with an in-process replica's."""
        res = self._stub.GetTelemetry(pb.TelemetryRequest(
            trace_id=trace_id, since=since,
            limit=limit if limit > 0 else -1,
            recent=recent if recent > 0 else -1,
        ), timeout=timeout)
        return json.loads(res.json or "{}")

    def tts(self, text: str, *, voice: str = "", language: str = "",
            dst: str = "", timeout: float = WORK_TIMEOUT_S) -> pb.AudioResult:
        return self._call(self._stub.TTS, pb.TTSRequest(
            text=text, voice=voice, language=language, dst=dst), timeout)

    def sound_generation(self, text: str, *, duration: Optional[float] = None,
                         dst: str = "",
                         timeout: float = WORK_TIMEOUT_S) -> pb.AudioResult:
        req = pb.SoundGenerationRequest(text=text, dst=dst)
        if duration is not None:
            req.duration = duration
        return self._call(self._stub.SoundGeneration, req, timeout)

    def transcribe(self, *, path: str = "", audio: bytes = b"",
                   language: str = "", translate: bool = False,
                   timeout: float = WORK_TIMEOUT_S) -> pb.TranscriptResult:
        return self._call(self._stub.AudioTranscription, pb.TranscriptRequest(
            path=path, audio=audio, language=language, translate=translate,
        ), timeout)

    def generate_image(self, prompt: str, *, negative: str = "",
                       width: int = 512, height: int = 512, step: int = 0,
                       seed: int = 0, dst: str = "",
                       timeout: float = WORK_TIMEOUT_S) -> pb.ImageResult:
        return self._call(self._stub.GenerateImage, pb.GenerateImageRequest(
            positive_prompt=prompt, negative_prompt=negative,
            width=width, height=height, step=step, seed=seed, dst=dst,
        ), timeout)

    def rerank(self, query: str, documents: list[str], top_n: int = 0,
               timeout: float = WORK_TIMEOUT_S) -> pb.RerankResult:
        return self._call(self._stub.Rerank, pb.RerankRequest(
            query=query, documents=documents, top_n=top_n), timeout)

    def stores_set(self, keys: list[list[float]],
                   values: list[bytes], timeout: float = CONTROL_TIMEOUT_S) -> pb.Result:
        return self._call(self._stub.StoresSet, pb.StoresSetOptions(
            keys=[pb.StoresKey(floats=k) for k in keys],
            values=[pb.StoresValue(bytes=v) for v in values],
        ), timeout)

    def stores_get(self, keys: list[list[float]],
                   timeout: float = CONTROL_TIMEOUT_S) -> pb.StoresGetResult:
        return self._call(self._stub.StoresGet, pb.StoresGetOptions(
            keys=[pb.StoresKey(floats=k) for k in keys]), timeout)

    def stores_find(self, key: list[float], top_k: int,
                    timeout: float = CONTROL_TIMEOUT_S) -> pb.StoresFindResult:
        return self._call(self._stub.StoresFind, pb.StoresFindOptions(
            key=pb.StoresKey(floats=key), top_k=top_k), timeout)

    def stores_delete(self, keys: list[list[float]],
                      timeout: float = CONTROL_TIMEOUT_S) -> pb.Result:
        return self._call(self._stub.StoresDelete, pb.StoresDeleteOptions(
            keys=[pb.StoresKey(floats=k) for k in keys]), timeout)

    def close(self) -> None:
        self._channel.close()
