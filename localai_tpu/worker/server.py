"""The model-worker gRPC server: one process, one loaded model.

This is the process-isolation tier of the framework — the TPU-era
counterpart of the reference's backend workers (llama.cpp gRPC server,
/root/reference/backend/cpp/llama/grpc-server.cpp:2304-2458, and the Go
harness /root/reference/pkg/grpc/server.go:23-60+): the API server spawns
one of these per model (worker.process), so an engine crash never takes
down the API, and external/third-party workers can implement the same
contract (rpc.METHODS) in any language.

Inside the process the engine is the same ModelRunner + continuous-batching
Scheduler the in-process manager uses (models.manager.build_serving_model);
the worker adds only the wire surface.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading
from concurrent import futures
from typing import Any, Iterator, Optional

import grpc

from localai_tpu.faults import registry as _faults
from localai_tpu.worker import backend_pb2 as pb
from localai_tpu.worker import rpc

log = logging.getLogger(__name__)


def gen_request_from_options(req: pb.PredictOptions, sm,
                             trace_id: str = "", tenant: str = ""):
    """PredictOptions → GenRequest against a ServingModel (the wire→engine
    converter; inverse of worker.serving.predict_options). Shared by the
    gRPC servicer and in-process fleet replicas, so both replica kinds
    decode one request schema identically."""
    from localai_tpu.engine.scheduler import GenRequest

    if req.tokens:
        prompt = list(req.tokens)
    else:
        prompt = sm.tokenizer.encode(req.prompt, add_bos=req.add_bos)
    constraint = None
    if req.constraint_schema:
        from localai_tpu.functions.constraint import constraint_for_schema

        constraint = constraint_for_schema(
            json.loads(req.constraint_schema), sm.tokenizer
        )
    elif req.constraint_regex:
        from localai_tpu.functions.constraint import constraint_for_regex

        constraint = constraint_for_regex(req.constraint_regex, sm.tokenizer)

    def opt(name):
        return getattr(req, name) if req.HasField(name) else None

    return GenRequest(
        prompt=prompt,
        max_new_tokens=req.max_tokens or 2048,
        temperature=opt("temperature"),
        top_k=opt("top_k"),
        top_p=opt("top_p"),
        min_p=opt("min_p"),
        repeat_penalty=opt("repeat_penalty"),
        presence_penalty=opt("presence_penalty"),
        frequency_penalty=opt("frequency_penalty"),
        seed=opt("seed"),
        logit_bias=dict(req.logit_bias) or None,
        stop=tuple(req.stop),
        ignore_eos=req.ignore_eos,
        constraint=constraint,
        correlation_id=req.correlation_id,
        # propagated from the API tier over gRPC metadata: the worker's
        # engine spans record under the same trace id (obs subsystem)
        trace_id=trace_id or req.correlation_id,
        # hashed tenant bucket for the usage ledger (obs.ledger); callers
        # that deliberately leave it empty (InProcessReplica's inner
        # resubmit) keep their engine feed unattributed
        tenant=tenant,
        stream=req.stream,
    )


class BackendServicer:
    """LLM worker: Predict/PredictStream/Embedding + lifecycle RPCs.

    Modality RPCs (TTS, transcription, image gen, rerank, stores) are
    intentionally absent here — rpc.add_servicer answers UNIMPLEMENTED for
    them, and dedicated workers (audio/image/store servicers) implement
    them instead, exactly like the reference's per-modality backends.
    """

    def __init__(self) -> None:
        self._sm: Optional[Any] = None  # ServingModel
        self._load_error = ""
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def Health(self, request: pb.HealthMessage, context) -> pb.Reply:
        return pb.Reply(message=b"OK")

    def LoadModel(self, request: pb.ModelOptions, context) -> pb.Result:
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.config.model_config import ModelConfig
        from localai_tpu.models.manager import build_serving_model

        with self._lock:
            if self._sm is not None:
                return pb.Result(success=True, message="already loaded")
            try:
                if request.config_yaml:
                    import yaml

                    doc = yaml.safe_load(request.config_yaml) or {}
                else:
                    doc = {"name": request.model or "model",
                           "model": request.model}
                if request.model:
                    doc.setdefault("model", request.model)
                if request.context_size:
                    doc["context_size"] = request.context_size
                if request.seed:
                    doc["seed"] = request.seed
                mcfg = ModelConfig.model_validate(doc)
                app = AppConfig(model_path=request.model_path or "models")
                self._sm = build_serving_model(mcfg, app)
                return pb.Result(success=True, message="ok")
            except Exception as e:  # noqa: BLE001 — report, don't crash
                self._load_error = f"{type(e).__name__}: {e}"
                log.exception("LoadModel failed")
                return pb.Result(success=False, message=self._load_error)

    # _sm/_load_error are single-assignment references set by LoadModel
    # under the lock; serving paths read them lock-free — a reader sees
    # None (not loaded) or a fully constructed model, never a torn value
    def Status(self, request: pb.HealthMessage, context) -> pb.StatusResponse:  # jaxlint: disable=lock-guarded-attr
        if self._sm is None:
            state = (pb.StatusResponse.ERROR if self._load_error
                     else pb.StatusResponse.UNINITIALIZED)
            return pb.StatusResponse(state=state)
        busy = self._sm.scheduler.busy
        state = pb.StatusResponse.BUSY if busy else pb.StatusResponse.READY
        mem = {}
        try:
            import resource

            mem["maxrss_kb"] = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss
        except Exception:  # noqa: BLE001
            pass
        return pb.StatusResponse(state=state, memory=mem)

    def GetMetrics(self, request: pb.MetricsRequest,
                   context) -> pb.MetricsResponse:  # jaxlint: disable=lock-guarded-attr
        if self._sm is None:
            return pb.MetricsResponse(json="{}")
        payload = self._sm.scheduler.metrics()
        # the worker process has no HTTP surface, so its engine span trees
        # (recorded under trace ids propagated over the RPC metadata) ride
        # the metrics JSON — the API tier surfaces them at /backend/metrics
        from localai_tpu.obs.trace import STORE

        payload["recent_traces"] = [
            t.to_dict() for t in STORE.recent(limit=20, kind="request")
        ]
        return pb.MetricsResponse(json=json.dumps(payload))

    def GetTelemetry(self, request: pb.TelemetryRequest,
                     context) -> pb.TelemetryResponse:  # jaxlint: disable=lock-guarded-attr
        """Fleet telemetry harvest (obs/fleetview): this replica's spans
        for one trace id (or a recent window), its flight-ring snapshot,
        and its scheduler metrics dict — everything host-side, so the
        pull can never queue work behind a wedged device dispatch. The
        payload shape is owned by obs.fleetview.telemetry_payload (shared
        with InProcessReplica, so the replica kinds cannot drift)."""
        from localai_tpu.obs.fleetview import telemetry_payload

        sched = self._sm.scheduler if self._sm is not None else None
        # 0/unset → defaults; -1 is the client's explicit "none" (proto3
        # cannot carry a distinguishable 0), clamped back to 0 here
        payload = telemetry_payload(
            sched, trace_id=request.trace_id, since=request.since,
            limit=max(0, request.limit or 256),
            recent=max(0, request.recent or 20))
        return pb.TelemetryResponse(json=json.dumps(payload))

    # -- inference -------------------------------------------------------

    def _require_model(self, context):  # jaxlint: disable=lock-guarded-attr
        if self._sm is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                self._load_error or "no model loaded (call LoadModel first)",
            )
        return self._sm

    def _gen_request(self, req: pb.PredictOptions, sm, trace_id: str = "",
                     tenant: str = ""):
        return gen_request_from_options(req, sm, trace_id=trace_id,
                                        tenant=tenant)

    def Predict(self, request: pb.PredictOptions, context) -> pb.Reply:
        sm = self._require_model(context)
        handle = sm.scheduler.submit(self._gen_request(
            request, sm, trace_id=rpc.trace_id_from_context(context),
            tenant=rpc.tenant_from_context(context)))
        try:
            handle.result(timeout=600.0)
        finally:
            if handle.finish_reason is None:
                # timeout or abandoned RPC — free the decode slot
                handle.cancel()
        return pb.Reply(
            message=handle.text.encode("utf-8"),
            tokens=handle.completion_tokens,
            prompt_tokens=handle.prompt_tokens,
            finish_reason=handle.finish_reason or "stop",
        )

    def PredictStream(self, request: pb.PredictOptions,
                      context) -> Iterator[pb.Reply]:
        sm = self._require_model(context)
        handle = sm.scheduler.submit(self._gen_request(
            request, sm, trace_id=rpc.trace_id_from_context(context),
            tenant=rpc.tenant_from_context(context)))
        try:
            for item in handle:
                if _faults.ACTIVE:
                    # chaos: a worker stream that errors (raise) or
                    # crawls (sleep) mid-flight — the caller's failover/
                    # watchdog paths must absorb it
                    _faults.apply("worker.stream", key=sm.name)
                if item.finish_reason is not None:
                    yield pb.Reply(
                        message=b"",
                        tokens=handle.completion_tokens,
                        prompt_tokens=handle.prompt_tokens,
                        finish_reason=item.finish_reason,
                    )
                    break
                if item.delta:
                    yield pb.Reply(message=item.delta.encode("utf-8"))
        finally:
            if not context.is_active():
                handle.cancel()

    # -- fleet disaggregation (localai_tpu.fleet) ------------------------

    def _fleet_cache(self, sm):
        """The replica's in-memory prefix cache, attached lazily on first
        PrefillPrefix/TransferPrefix use. A configured disk prompt cache
        has the lookup/store surface but not the ``wait_for`` signalling
        the export blocks on, so the RAM tier FRONTS it (stores forward,
        missed lookups fall through — scheduler.attach_prompt_cache
        layer=True) instead of replacing it."""
        sched = sm.scheduler
        if not hasattr(sched.prompt_cache, "wait_for"):
            from localai_tpu.fleet.prefix import PrefixCache

            with self._lock:
                if not hasattr(sched.prompt_cache, "wait_for"):
                    sched.attach_prompt_cache(PrefixCache(
                        min_prefix=getattr(sm.runner, "prefix_reuse_min",
                                           16)), layer=True)
        return sched.prompt_cache

    def PrefillPrefix(self, request: pb.PredictOptions,
                      context) -> Iterator[pb.PrefixChunk]:
        """Prefill-replica half of the disaggregated handoff: run the
        prompt's prefill (one sampled token, then the slot frees), wait
        for the scheduler's off-thread prefix export, and stream the
        packed KV rows out in bounded chunks."""
        from localai_tpu.fleet.prefix import (PrefixUnavailable,
                                              export_prefix, pack_chunks)

        sm = self._require_model(context)
        cache = self._fleet_cache(sm)
        gr = self._gen_request(request, sm,
                               trace_id=rpc.trace_id_from_context(context))
        try:
            prompt, arrays = export_prefix(sm, gr, cache)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except PrefixUnavailable as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except RuntimeError as e:
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        for chunk in pack_chunks(prompt, arrays):
            yield pb.PrefixChunk(**chunk)

    def TransferPrefix(self, request_iterator, context) -> pb.Result:
        """Decode-replica half: assemble the streamed chunks and seed the
        prefix cache — the next PredictStream for this prompt
        load_prefix-resumes past the transferred rows at admission."""
        from localai_tpu.fleet.prefix import import_prefix

        sm = self._require_model(context)
        cache = self._fleet_cache(sm)
        try:
            n = import_prefix(cache, request_iterator)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.Result(success=True, message=f"{n} rows")

    def Embedding(self, request: pb.EmbeddingRequest,
                  context) -> pb.EmbeddingResult:
        sm = self._require_model(context)
        if request.tokens:
            toks = list(request.tokens)
        else:
            toks = sm.tokenizer.encode(request.text, add_bos=True)
        vec = sm.runner.embed(toks)
        return pb.EmbeddingResult(embeddings=[float(x) for x in vec])

    def TokenizeString(self, request: pb.TokenizationRequest,
                       context) -> pb.TokenizationResponse:
        sm = self._require_model(context)
        ids = sm.tokenizer.encode(request.text, add_bos=request.add_bos)
        return pb.TokenizationResponse(length=len(ids), tokens=ids)

    def shutdown(self) -> None:
        with self._lock:
            if self._sm is not None:
                self._sm.scheduler.shutdown()
                self._sm = None


class StoreServicer:
    """Standalone vector-store worker (parity: the local-store Go backend
    process, /root/reference/backend/go/stores/store.go, speaking the
    Stores RPCs of the shared contract)."""

    def __init__(self) -> None:
        from localai_tpu.stores import VectorStore

        self._store = VectorStore()

    def Health(self, request: pb.HealthMessage, context) -> pb.Reply:
        return pb.Reply(message=b"OK")

    def LoadModel(self, request: pb.ModelOptions, context) -> pb.Result:
        return pb.Result(success=True, message="store ready")

    def Status(self, request: pb.HealthMessage, context) -> pb.StatusResponse:
        return pb.StatusResponse(state=pb.StatusResponse.READY)

    def StoresSet(self, request: pb.StoresSetOptions, context) -> pb.Result:
        try:
            self._store.set(
                [list(k.floats) for k in request.keys],
                [v.bytes for v in request.values],
            )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.Result(success=True)

    def StoresDelete(self, request: pb.StoresDeleteOptions,
                     context) -> pb.Result:
        try:
            self._store.delete([list(k.floats) for k in request.keys])
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.Result(success=True)

    def StoresGet(self, request: pb.StoresGetOptions,
                  context) -> pb.StoresGetResult:
        try:
            keys, values = self._store.get(
                [list(k.floats) for k in request.keys]
            )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        out = pb.StoresGetResult()
        for k, v in zip(keys, values):
            if v is None:
                continue
            out.keys.append(pb.StoresKey(floats=k))
            out.values.append(pb.StoresValue(bytes=v))
        return out

    def StoresFind(self, request: pb.StoresFindOptions,
                   context) -> pb.StoresFindResult:
        if request.top_k < 0:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "top_k must be >= 1")
        try:
            keys, values, sims = self._store.find(
                list(request.key.floats), request.top_k or 10
            )
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        out = pb.StoresFindResult(similarities=sims)
        for k, v in zip(keys, values):
            out.keys.append(pb.StoresKey(floats=k))
            out.values.append(pb.StoresValue(bytes=v))
        return out

    def shutdown(self) -> None:
        pass


class AudioServicer:
    """Audio worker: AudioTranscription + TTS + SoundGeneration RPCs
    (parity: the whisper.cpp, piper and musicgen worker processes,
    /root/reference/backend/go/transcribe/whisper/whisper.go:21-105,
    backend/go/tts/piper.go:20-49, backend/python/transformers-musicgen)."""

    def __init__(self) -> None:
        self._whisper = None
        self._lock = threading.Lock()

    def Health(self, request: pb.HealthMessage, context) -> pb.Reply:
        return pb.Reply(message=b"OK")

    def Status(self, request: pb.HealthMessage, context) -> pb.StatusResponse:
        return pb.StatusResponse(state=pb.StatusResponse.READY)

    def LoadModel(self, request: pb.ModelOptions, context) -> pb.Result:
        from pathlib import Path

        from localai_tpu.models import whisper as wh

        with self._lock:
            try:
                ref = request.model or "debug:whisper"
                if ref.startswith("debug:"):
                    self._whisper = wh.debug_model(seed=request.seed)
                else:
                    base = Path(request.model_path or "models")
                    cand = Path(ref) if Path(ref).is_dir() else base / ref
                    self._whisper = wh.load_hf_whisper(cand)
                return pb.Result(success=True, message="ok")
            except Exception as e:  # noqa: BLE001
                log.exception("audio LoadModel failed")
                return pb.Result(success=False,
                                 message=f"{type(e).__name__}: {e}")

    # same single-assignment-reference pattern as BackendServicer._sm
    def AudioTranscription(self, request: pb.TranscriptRequest,
                           context) -> pb.TranscriptResult:  # jaxlint: disable=lock-guarded-attr
        from localai_tpu.audio import read_wav

        if self._whisper is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no model loaded (call LoadModel first)")
        data = request.audio
        if not data and request.path:
            try:
                data = open(request.path, "rb").read()
            except OSError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        try:
            audio = read_wav(data)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        res = self._whisper.transcribe(
            audio, language=request.language or None,
            translate=request.translate,
        )
        out = pb.TranscriptResult(text=res["text"])
        for seg in res["segments"]:
            out.segments.append(pb.TranscriptSegment(
                id=seg["id"],
                start=int(seg["start"] * 1e9),
                end=int(seg["end"] * 1e9),
                text=seg["text"],
                tokens=seg["tokens"],
            ))
        return out

    def TTS(self, request: pb.TTSRequest, context) -> pb.AudioResult:
        from localai_tpu.audio import write_wav
        from localai_tpu.audio import tts as ttsmod

        if not request.text:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "empty text")
        wav = write_wav(ttsmod.synthesize(
            request.text, voice=request.voice or "alloy"))
        if request.dst:
            with open(request.dst, "wb") as f:
                f.write(wav)
            return pb.AudioResult(success=True, message=request.dst)
        return pb.AudioResult(success=True, audio=wav)

    def SoundGeneration(self, request: pb.SoundGenerationRequest,
                        context) -> pb.AudioResult:
        from localai_tpu.audio import write_wav
        from localai_tpu.audio import tts as ttsmod

        if not request.text:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "empty text")
        dur = request.duration if request.HasField("duration") else 3.0
        temp = (request.temperature
                if request.HasField("temperature") else 1.0)
        wav = write_wav(ttsmod.generate_sound(request.text, dur, temp))
        if request.dst:
            with open(request.dst, "wb") as f:
                f.write(wav)
            return pb.AudioResult(success=True, message=request.dst)
        return pb.AudioResult(success=True, audio=wav)

    def shutdown(self) -> None:
        pass


class ImageServicer:
    """Image-generation worker behind the GenerateImage RPC (parity: the
    diffusers Python worker process, /root/reference/backend/python/
    diffusers/backend.py:263-474, and the NCNN stablediffusion backend,
    backend/go/image/stablediffusion/stablediffusion.go)."""

    def __init__(self) -> None:
        self._pipe = None
        self._lock = threading.Lock()

    def Health(self, request: pb.HealthMessage, context) -> pb.Reply:
        return pb.Reply(message=b"OK")

    def Status(self, request: pb.HealthMessage, context) -> pb.StatusResponse:
        return pb.StatusResponse(state=pb.StatusResponse.READY)

    def LoadModel(self, request: pb.ModelOptions, context) -> pb.Result:
        from localai_tpu.image import resolve_image_model

        with self._lock:
            try:
                self._pipe = resolve_image_model(
                    request.model or "debug:sd-tiny",
                    model_path=request.model_path or "models",
                )
                return pb.Result(success=True, message="ok")
            except Exception as e:  # noqa: BLE001
                log.exception("image LoadModel failed")
                return pb.Result(success=False,
                                 message=f"{type(e).__name__}: {e}")

    # same single-assignment-reference pattern as BackendServicer._sm
    def GenerateImage(self, request: pb.GenerateImageRequest,
                      context) -> pb.ImageResult:  # jaxlint: disable=lock-guarded-attr
        import io

        from PIL import Image

        if self._pipe is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "no model loaded (call LoadModel first)")
        try:
            result = self._pipe.generate(
                request.positive_prompt,
                negative_prompt=request.negative_prompt,
                width=request.width or 512,
                height=request.height or 512,
                steps=request.step or None,
                seed=request.seed if request.seed else None,
                cfg_scale=(request.cfg_scale
                           if request.HasField("cfg_scale") else None),
            )
        except Exception as e:  # noqa: BLE001
            log.exception("GenerateImage failed")
            return pb.ImageResult(success=False,
                                  message=f"{type(e).__name__}: {e}")
        buf = io.BytesIO()
        Image.fromarray(result.image).save(buf, format="PNG")
        png = buf.getvalue()
        if request.dst:
            with open(request.dst, "wb") as f:
                f.write(png)
            return pb.ImageResult(success=True, message=request.dst)
        return pb.ImageResult(success=True, image=png)

    def shutdown(self) -> None:
        pass


SERVICERS = {
    "llm": BackendServicer,
    "store": StoreServicer,
    "audio": AudioServicer,
    "image": ImageServicer,
}


def serve_worker(addr: str = "127.0.0.1:0",
                 servicer: Optional[Any] = None,
                 block: bool = True) -> tuple[grpc.Server, int]:
    """Start the worker gRPC server. Returns (server, bound_port)."""
    servicer = servicer or BackendServicer()
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=32),
        options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                 ("grpc.max_send_message_length", 256 * 1024 * 1024)],
    )
    rpc.add_servicer(server, servicer)
    port = server.add_insecure_port(addr)
    if port == 0:
        raise RuntimeError(f"could not bind worker to {addr}")
    server.start()
    log.info("worker listening on port %d", port)
    if block:
        stop = threading.Event()

        def _sig(*_a):
            stop.set()

        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
        stop.wait()
        if hasattr(servicer, "shutdown"):
            servicer.shutdown()
        server.stop(grace=5.0)
    return server, port


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="localai-tpu model worker")
    parser.add_argument("--addr", default="127.0.0.1:0",
                        help="host:port to bind (port 0 = ephemeral)")
    parser.add_argument("--servicer", default="llm",
                        help=f"which servicer to run ({'/'.join(SERVICERS)})")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=os.environ.get("LOCALAI_LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    # deterministic fault injection (chaos harness): LOCALAI_FAULT_* in a
    # spawned worker's env arms its registry at boot, never per request
    _faults.install_from_env()
    # honor JAX_PLATFORMS even when a sitecustomize imported jax before the
    # env var could take effect (jax.config wins until backend init)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception:  # noqa: BLE001 — backend already initialized
            pass
    try:
        servicer = SERVICERS[args.servicer]()
    except KeyError:
        parser.error(f"unknown servicer {args.servicer!r}; "
                     f"have {sorted(SERVICERS)}")
    _server, port = serve_worker(args.addr, servicer=servicer, block=False)
    # the parent process-manager greps this line for the bound port
    print(f"WORKER_READY port={port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    # stop the engine thread before tearing down grpc so no handler is
    # mid-flight when the C core unwinds
    servicer.shutdown()
    _server.stop(grace=2.0).wait(5.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
