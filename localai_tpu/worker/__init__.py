"""Process-isolated model workers speaking one narrow gRPC contract.

The reference's L2/L3 (SURVEY.md §1): one worker process per loaded model,
spawned/health-checked/respawned by the API server, all speaking
backend.proto. Here the contract is worker/backend.proto, the engine inside
each worker is the JAX ModelRunner+Scheduler, and external workers in any
language can register by address.
"""

from localai_tpu.worker.client import WorkerClient
from localai_tpu.worker.process import Watchdog, WorkerPool, WorkerProcess
