"""Hand-rolled gRPC service/stub glue for the Backend contract.

The image ships grpcio + protoc but not grpc_tools, so the usual
``backend_pb2_grpc.py`` cannot be generated; this module is its exact
functional equivalent (parity concept: the reference's generated Go stubs
mirrored by the hand-written Backend interface, /root/reference/pkg/grpc/
backend.go:37-60). One method table drives both the server-side generic
handler and the client stub, so the two can never drift.
"""

from __future__ import annotations

from typing import Any, Callable

import grpc

from localai_tpu.worker import backend_pb2 as pb

SERVICE = "localai_tpu.Backend"

# span propagation across the worker boundary (obs subsystem): the API
# tier sends its trace id as gRPC metadata; the worker stamps it onto the
# GenRequest so both processes record spans under ONE trace id. Metadata
# (not a proto field) keeps the wire contract backward-compatible with
# third-party workers that never read it.
TRACE_ID_METADATA_KEY = "x-localai-trace-id"


def trace_metadata(trace_id: str) -> tuple:
    """Per-call gRPC metadata carrying ``trace_id`` ('' → no metadata)."""
    if not trace_id:
        return ()
    return ((TRACE_ID_METADATA_KEY, trace_id),)


def trace_id_from_context(context: Any) -> str:
    """Read the propagated trace id out of a servicer context."""
    try:
        for key, value in context.invocation_metadata():
            if key == TRACE_ID_METADATA_KEY:
                return value
    except Exception:  # noqa: BLE001 — tracing must never fail an RPC
        pass
    return ""

# name → (is_server_streaming, request type, response type)
METHODS: dict[str, tuple[bool, Any, Any]] = {
    "Health": (False, pb.HealthMessage, pb.Reply),
    "LoadModel": (False, pb.ModelOptions, pb.Result),
    "Predict": (False, pb.PredictOptions, pb.Reply),
    "PredictStream": (True, pb.PredictOptions, pb.Reply),
    "Embedding": (False, pb.EmbeddingRequest, pb.EmbeddingResult),
    "TokenizeString": (False, pb.TokenizationRequest, pb.TokenizationResponse),
    "Status": (False, pb.HealthMessage, pb.StatusResponse),
    "GetMetrics": (False, pb.MetricsRequest, pb.MetricsResponse),
    "TTS": (False, pb.TTSRequest, pb.AudioResult),
    "SoundGeneration": (False, pb.SoundGenerationRequest, pb.AudioResult),
    "AudioTranscription": (False, pb.TranscriptRequest, pb.TranscriptResult),
    "GenerateImage": (False, pb.GenerateImageRequest, pb.ImageResult),
    "Rerank": (False, pb.RerankRequest, pb.RerankResult),
    "StoresSet": (False, pb.StoresSetOptions, pb.Result),
    "StoresDelete": (False, pb.StoresDeleteOptions, pb.Result),
    "StoresGet": (False, pb.StoresGetOptions, pb.StoresGetResult),
    "StoresFind": (False, pb.StoresFindOptions, pb.StoresFindResult),
}


def add_servicer(server: grpc.Server, servicer: Any) -> None:
    """Register every METHODS entry the servicer implements; missing ones
    answer UNIMPLEMENTED (parity: base.Base unimplemented defaults,
    /root/reference/pkg/grpc/base/base.go:16-49)."""
    handlers: dict[str, grpc.RpcMethodHandler] = {}
    for name, (streaming, req_t, resp_t) in METHODS.items():
        fn = getattr(servicer, name, None)
        if fn is None:
            def fn(request, context, _n=name):  # noqa: ANN001
                context.abort(grpc.StatusCode.UNIMPLEMENTED,
                              f"{_n} not implemented by this worker")
        make = (grpc.unary_stream_rpc_method_handler if streaming
                else grpc.unary_unary_rpc_method_handler)
        handlers[name] = make(
            fn,
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )


class BackendStub:
    """Client stub: one callable per method, typed by METHODS."""

    def __init__(self, channel: grpc.Channel):
        for name, (streaming, req_t, resp_t) in METHODS.items():
            factory: Callable = (
                channel.unary_stream if streaming else channel.unary_unary
            )
            setattr(self, name, factory(
                f"/{SERVICE}/{name}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ))
