"""Hand-rolled gRPC service/stub glue for the Backend contract.

The image ships grpcio + protoc but not grpc_tools, so the usual
``backend_pb2_grpc.py`` cannot be generated; this module is its exact
functional equivalent (parity concept: the reference's generated Go stubs
mirrored by the hand-written Backend interface, /root/reference/pkg/grpc/
backend.go:37-60). One method table drives both the server-side generic
handler and the client stub, so the two can never drift.
"""

from __future__ import annotations

from typing import Any, Callable

import grpc

from localai_tpu.worker import backend_pb2 as pb

SERVICE = "localai_tpu.Backend"

# span propagation across the worker boundary (obs subsystem): the API
# tier sends its trace id as gRPC metadata; the worker stamps it onto the
# GenRequest so both processes record spans under ONE trace id. Metadata
# (not a proto field) keeps the wire contract backward-compatible with
# third-party workers that never read it.
TRACE_ID_METADATA_KEY = "x-localai-trace-id"


def trace_metadata(trace_id: str) -> tuple:
    """Per-call gRPC metadata carrying ``trace_id`` ('' → no metadata)."""
    if not trace_id:
        return ()
    return ((TRACE_ID_METADATA_KEY, trace_id),)


def trace_id_from_context(context: Any) -> str:
    """Read the propagated trace id out of a servicer context."""
    try:
        for key, value in context.invocation_metadata():
            if key == TRACE_ID_METADATA_KEY:
                return value
    except Exception:  # noqa: BLE001 — tracing must never fail an RPC
        pass
    return ""


# tenant propagation for the usage ledger (obs.ledger): the front door
# hashes the API key into a bounded bucket id and forwards ONLY that —
# the raw key never crosses the wire. Metadata for the same reason as
# the trace id: third-party workers can ignore it.
TENANT_METADATA_KEY = "x-localai-tenant"


def tenant_metadata(tenant: str) -> tuple:
    """Per-call gRPC metadata carrying the hashed tenant bucket."""
    if not tenant:
        return ()
    return ((TENANT_METADATA_KEY, tenant),)


def tenant_from_context(context: Any) -> str:
    """Read the propagated tenant bucket out of a servicer context."""
    try:
        for key, value in context.invocation_metadata():
            if key == TENANT_METADATA_KEY:
                return value
    except Exception:  # noqa: BLE001 — accounting must never fail an RPC
        pass
    return ""

# streaming kinds: which side of the RPC is a message stream
UNARY = "unary"
SERVER_STREAM = "server_stream"
CLIENT_STREAM = "client_stream"

# name → (kind, request type, response type)
METHODS: dict[str, tuple[str, Any, Any]] = {
    "Health": (UNARY, pb.HealthMessage, pb.Reply),
    "LoadModel": (UNARY, pb.ModelOptions, pb.Result),
    "Predict": (UNARY, pb.PredictOptions, pb.Reply),
    "PredictStream": (SERVER_STREAM, pb.PredictOptions, pb.Reply),
    "Embedding": (UNARY, pb.EmbeddingRequest, pb.EmbeddingResult),
    "TokenizeString": (UNARY, pb.TokenizationRequest, pb.TokenizationResponse),
    "Status": (UNARY, pb.HealthMessage, pb.StatusResponse),
    "GetMetrics": (UNARY, pb.MetricsRequest, pb.MetricsResponse),
    "TTS": (UNARY, pb.TTSRequest, pb.AudioResult),
    "SoundGeneration": (UNARY, pb.SoundGenerationRequest, pb.AudioResult),
    "AudioTranscription": (UNARY, pb.TranscriptRequest, pb.TranscriptResult),
    "GenerateImage": (UNARY, pb.GenerateImageRequest, pb.ImageResult),
    "Rerank": (UNARY, pb.RerankRequest, pb.RerankResult),
    "StoresSet": (UNARY, pb.StoresSetOptions, pb.Result),
    "StoresDelete": (UNARY, pb.StoresDeleteOptions, pb.Result),
    "StoresGet": (UNARY, pb.StoresGetOptions, pb.StoresGetResult),
    "StoresFind": (UNARY, pb.StoresFindOptions, pb.StoresFindResult),
    # fleet disaggregation: prefill export out, prefix-block transfer in
    "PrefillPrefix": (SERVER_STREAM, pb.PredictOptions, pb.PrefixChunk),
    "TransferPrefix": (CLIENT_STREAM, pb.PrefixChunk, pb.Result),
    # fleet telemetry harvest: trace spans + flight ring + metrics in one
    # bounded control-plane pull (obs/fleetview stitching)
    "GetTelemetry": (UNARY, pb.TelemetryRequest, pb.TelemetryResponse),
}

_HANDLER_FACTORY = {
    UNARY: grpc.unary_unary_rpc_method_handler,
    SERVER_STREAM: grpc.unary_stream_rpc_method_handler,
    CLIENT_STREAM: grpc.stream_unary_rpc_method_handler,
}


def add_servicer(server: grpc.Server, servicer: Any) -> None:
    """Register every METHODS entry the servicer implements; missing ones
    answer UNIMPLEMENTED (parity: base.Base unimplemented defaults,
    /root/reference/pkg/grpc/base/base.go:16-49)."""
    handlers: dict[str, grpc.RpcMethodHandler] = {}
    for name, (kind, req_t, resp_t) in METHODS.items():
        fn = getattr(servicer, name, None)
        if fn is None:
            def fn(request, context, _n=name):  # noqa: ANN001
                context.abort(grpc.StatusCode.UNIMPLEMENTED,
                              f"{_n} not implemented by this worker")
        handlers[name] = _HANDLER_FACTORY[kind](
            fn,
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )


class BackendStub:
    """Client stub: one callable per method, typed by METHODS."""

    def __init__(self, channel: grpc.Channel):
        factories: dict[str, Callable] = {
            UNARY: channel.unary_unary,
            SERVER_STREAM: channel.unary_stream,
            CLIENT_STREAM: channel.stream_unary,
        }
        for name, (kind, req_t, resp_t) in METHODS.items():
            setattr(self, name, factories[kind](
                f"/{SERVICE}/{name}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ))
