"""Worker-backed serving: the ServingModel facade over a spawned gRPC
worker process.

This delivers the reference's central lifecycle property — a model crash
never takes down the API server (/root/reference/pkg/model/
initializers.go:271-407: spawn, health-gate, LoadModel over gRPC;
loader.go:170-206: health-check-and-respawn) — for models configured with
``backend: worker`` or registered in ``external_backends``.

The facade presents the same surface the HTTP endpoints use on the
in-process ServingModel (tokenizer/templates locally, ``scheduler.submit``
returning a GenHandle), but the engine runs in its own process; prompts go
over the wire as token ids and constraints as their source regex
(PredictOptions.constraint_regex — the worker rebuilds the FSM against the
same tokenizer).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Optional

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenHandle, GenRequest
from localai_tpu.obs import EngineTelemetry
from localai_tpu.obs import watchdog as obs_watchdog
from localai_tpu.worker import backend_pb2 as pb
from localai_tpu.worker.client import WorkerClient

log = logging.getLogger(__name__)

_SAMPLING_FIELDS = (
    "temperature", "top_k", "top_p", "min_p",
    "repeat_penalty", "presence_penalty", "frequency_penalty", "seed",
)


class WorkerGenHandle(GenHandle):
    """GenHandle fed from a PredictStream RPC instead of the engine thread.
    Token ids don't cross the wire, so completion counts come from the
    final Reply's usage fields — and when the stream dies BEFORE that
    final reply (worker killed mid-generation), from the count of
    streamed deltas (each Reply carries >= 1 sampled token), so a failed
    request never reports 0 tokens for work the engine actually did."""

    def __init__(self, req: GenRequest, rid: int):
        super().__init__(req, rid)
        self._completion_override: Optional[int] = None
        self._streamed_deltas = 0

    @property
    def completion_tokens(self) -> int:
        if self._completion_override is not None:
            return self._completion_override
        return max(len(self.token_ids), self._streamed_deltas)


def predict_options(gr: GenRequest) -> pb.PredictOptions:
    """GenRequest → wire options (inverse of worker.server._gen_request)."""
    opts = pb.PredictOptions(
        tokens=list(gr.prompt),
        max_tokens=gr.max_new_tokens,
        stop=list(gr.stop),
        ignore_eos=gr.ignore_eos,
        correlation_id=gr.correlation_id,
        stream=gr.stream,
    )
    for f in _SAMPLING_FIELDS:
        v = getattr(gr, f)
        if v is not None:
            setattr(opts, f, v)
    if gr.logit_bias:
        for k, v in gr.logit_bias.items():
            opts.logit_bias[int(k)] = float(v)
    if gr.constraint is not None:
        regex = getattr(gr.constraint, "source_regex", None)
        if regex:
            opts.constraint_regex = regex
        else:
            log.warning(
                "constraint without a serializable source regex; the "
                "worker will decode unconstrained"
            )
    return opts


def consume_stream(handle: WorkerGenHandle, replies, *,
                   watchdog=None, channel: str = "",
                   tr=None) -> tuple[str, bool]:
    """Drain one PredictStream-shaped reply iterator into ``handle``.

    The one place the wire protocol is interpreted on the API side —
    WorkerScheduler (single worker) and fleet.FleetScheduler (replica
    fleets) both feed their handles through here, so a protocol change
    cannot diverge their accounting. Returns ``(finish, got_final)``:
    ``got_final=False`` means the stream ended WITHOUT the final usage
    Reply — the worker/replica died mid-generation; the caller decides
    whether that is a failover signal (fleet) or a terminal error."""
    finish = "stop"
    got_final = False
    for reply in replies:
        if watchdog is not None:
            watchdog.pulse(channel)
        if handle.cancelled:
            finish = "cancelled"
            got_final = True
            break
        if reply.finish_reason:
            finish = reply.finish_reason
            got_final = True
            handle._completion_override = reply.tokens or None
            if reply.prompt_tokens:
                handle.prompt_tokens = reply.prompt_tokens
            break
        if reply.message:
            if tr is not None and handle.t_first_token is None:
                tr.event("first_delta")
            handle._streamed_deltas += 1
            handle._emit(reply.message.decode("utf-8", "replace"), None)
    return finish, got_final


class WorkerScheduler:
    """The scheduler-shaped surface of a worker-backed model: submit() runs
    a PredictStream RPC on a daemon thread feeding a GenHandle."""

    def __init__(self, owner: "WorkerServingModel"):
        self._owner = owner
        self._ids = itertools.count()
        self._inflight = 0
        self._lock = threading.Lock()
        # API-side view of the worker's requests: queued → rpc spans here,
        # engine-phase spans in the worker process under the same trace id
        self.telemetry = EngineTelemetry(model=owner.name)
        # the RPC stream is a device round-trip once removed: a wedged
        # worker (or its tunnel) stops the reply stream, and the watchdog
        # must see that silence like any other stall
        self.watchdog = obs_watchdog.WATCHDOG
        self._wd_channel = f"rpc:{owner.name}"
        self.watchdog.start()
        # SLO admission-control rejections happen at the API tier, so the
        # counter lives here (the worker process never sees shed requests)
        self.shed_total = 0

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._inflight > 0

    def submit(self, gr: GenRequest) -> GenHandle:
        handle = WorkerGenHandle(gr, next(self._ids))
        handle.trace = self.telemetry.queued(handle)
        if gr.mm_embeds is not None:
            # image embeddings don't cross the proto yet; fail loudly
            # rather than silently serving text-only
            self.telemetry.finished(handle.trace, handle, "error")
            handle._finish("error")
            log.error("worker-backed models do not support multimodal input")
            return handle
        # mark busy before the thread starts: an eviction sweep between
        # submit() and the thread's first instruction must not kill the
        # worker under an accepted request
        with self._lock:
            self._inflight += 1
        threading.Thread(
            target=self._run, args=(handle,), daemon=True,
            name=f"worker-req-{handle.id}",
        ).start()
        return handle

    def _run(self, handle: WorkerGenHandle) -> None:
        tr = handle.trace
        # armed across the whole RPC, pulsed per reply: a worker that stops
        # streaming (dead process, dead tunnel) trips the stall watchdog
        # even though grpc's own 600 s deadline is nowhere near
        self.watchdog.arm(self._wd_channel)
        try:
            client = self._owner.client()
            opts = predict_options(handle.request)
            req = handle.request
            if tr is not None:
                tr.end("queued")
                tr.begin("rpc", worker=client.address)
            finish, got_final = consume_stream(
                handle,
                client.predict_stream(
                    opts, timeout=600.0,
                    trace_id=req.trace_id or req.correlation_id,
                    tenant=req.tenant),
                watchdog=self.watchdog, channel=self._wd_channel, tr=tr)
            if not got_final:
                # the stream ended without the final usage Reply: the
                # worker died (or the tunnel dropped) mid-generation.
                # Mark the handle failed — completion_tokens falls back
                # to the streamed-delta count instead of reporting 0.
                finish = "error"
                log.warning(
                    "worker request %d: stream ended without a final "
                    "reply after %d deltas", handle.id,
                    handle._streamed_deltas)
            # trace retires before _finish unblocks the awaiting handler
            self.telemetry.finished(tr, handle, finish)
            handle._finish(finish)
        except Exception as e:  # noqa: BLE001 — worker crash ≠ API crash
            log.warning("worker request %d failed: %s", handle.id, e)
            self.telemetry.finished(tr, handle, "error")
            handle._finish("error")
        finally:
            self.watchdog.disarm(self._wd_channel)
            with self._lock:
                self._inflight -= 1

    def note_shed(self) -> None:
        """Record one API-level SLO admission rejection for this model."""
        with self._lock:
            self.shed_total += 1

    def metrics(self) -> dict:
        try:
            m = self._owner.client().metrics()
        except Exception as e:  # noqa: BLE001
            return {"error": str(e)}
        # monotone int scrape read; a one-increment-stale value is fine
        m["shed_total"] = self.shed_total  # jaxlint: disable=lock-guarded-attr
        return m

    def shutdown(self, timeout: float = 10.0) -> None:
        self._owner.close()


class WorkerServingModel:
    """ServingModel counterpart whose engine lives in a worker process.

    Tokenization/templating stay local (the reference templates in Go while
    llama.cpp owns the weights); generation RPCs go to the worker. The
    pool health-checks and respawns on access, and ensure_loaded() re-issues
    LoadModel after any respawn."""

    def __init__(self, mcfg: ModelConfig, app: AppConfig, pool,
                 *, external_address: Optional[str] = None):
        from localai_tpu.models.registry import resolve_tokenizer
        from localai_tpu.templates.cache import TemplateCache

        self.name = mcfg.name
        self.config = mcfg
        self.app = app
        self.pool = pool
        self.external_address = external_address
        self.tokenizer = resolve_tokenizer(
            mcfg.model or mcfg.name, app.model_path
        )
        self.templates = TemplateCache(app.model_path)
        self.vision = None
        self.image_token_id = 0
        if mcfg.mmproj:
            log.warning(
                "model %s: mmproj is not supported on worker-backed models "
                "yet; images will be ignored", mcfg.name,
            )
        self.scheduler = WorkerScheduler(self)
        self.loaded_at = time.monotonic()
        self.last_used = time.monotonic()
        self._client_lock = threading.Lock()
        self._loaded_client: Optional[WorkerClient] = None
        self.client()  # spawn + load eagerly so config errors surface now

    # -- lifecycle ---------------------------------------------------------

    def client(self) -> WorkerClient:
        """Healthy client for this model's worker: spawns/respawns via the
        pool and guarantees the model is loaded (a respawned process comes
        up empty)."""
        with self._client_lock:
            if self.external_address is not None:
                c = self.pool.register_external(self.name,
                                                self.external_address)
            else:
                c = self.pool.get(self.name, env=self.app.worker_env or None)
            # the pool hands back the same client object while the worker
            # stays healthy; a new object means a respawn (empty process) —
            # only then pay the Status round trip + LoadModel
            if c is not self._loaded_client:
                # load-once barrier, deliberately under the lock:
                # concurrent callers MUST wait for the respawned
                # worker's LoadModel — racing it would double-load
                self._ensure_loaded(c)  # jaxlint: disable=blocking-under-lock
                self._loaded_client = c
            return c

    def _ensure_loaded(self, c: WorkerClient) -> None:
        st = c.status()
        if st.state in (pb.StatusResponse.READY, pb.StatusResponse.BUSY):
            return
        import yaml

        doc = self.config.model_dump(exclude_none=True, exclude_defaults=True)
        doc["name"] = self.config.name
        doc["model"] = self.config.model or self.config.name
        doc.pop("backend", None)  # the worker itself runs in-process
        res = c.load_model(
            config_yaml=yaml.safe_dump(doc),
            model_path=str(self.app.model_path),
        )
        if not res.success:
            raise RuntimeError(
                f"worker LoadModel failed for {self.name}: {res.message}"
            )

    def touch(self) -> None:
        self.last_used = time.monotonic()

    @property
    def busy(self) -> bool:
        return self.scheduler.busy

    def alive(self) -> bool:
        """Cheap liveness only — this runs under the ModelManager lock, so
        no RPCs here (a blocking health check would serialize every model
        lookup behind one dead worker). Spawned workers: process poll.
        External workers: assumed alive; failures surface per-request."""
        if self.external_address is not None:
            return True
        wp = self.pool._workers.get(self.name)
        return wp is not None and wp.alive

    def engine_metrics(self) -> dict:
        return self.scheduler.metrics()

    def close(self) -> None:
        self.pool.shutdown(self.name)
