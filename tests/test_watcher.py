"""Dynamic config hot-reload (parity: core/startup/config_file_watcher.go
— edits to api_keys.json / external_backends.json take effect without a
server restart)."""

import json

import httpx

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from localai_tpu.config.watcher import ConfigWatcher, attach_standard_handlers


class _FakeState:
    def __init__(self, cfg):
        self.config = cfg


def test_api_keys_merge_and_reset(tmp_path):
    cfg = AppConfig(config_path=str(tmp_path), api_keys=["boot-key"])
    w = ConfigWatcher(tmp_path, interval=0.05)
    attach_standard_handlers(w, _FakeState(cfg))
    assert cfg.api_keys == ["boot-key"]

    (tmp_path / "api_keys.json").write_text(json.dumps(["dyn-key"]))
    w.poll_once()
    assert cfg.api_keys == ["boot-key", "dyn-key"]

    # removing the file restores the startup keys
    (tmp_path / "api_keys.json").unlink()
    w.poll_once()
    assert cfg.api_keys == ["boot-key"]


def test_bad_file_does_not_clobber_config(tmp_path):
    cfg = AppConfig(config_path=str(tmp_path), api_keys=["boot-key"])
    w = ConfigWatcher(tmp_path, interval=0.05)
    attach_standard_handlers(w, _FakeState(cfg))
    (tmp_path / "api_keys.json").write_text("{not json")
    w.poll_once()
    assert cfg.api_keys == ["boot-key"]


def test_external_backends_hot_reload(tmp_path):
    cfg = AppConfig(config_path=str(tmp_path),
                    external_backends={"static": "127.0.0.1:1"})
    w = ConfigWatcher(tmp_path, interval=0.05)
    attach_standard_handlers(w, _FakeState(cfg))
    (tmp_path / "external_backends.json").write_text(
        json.dumps({"mymodel": "127.0.0.1:9999"})
    )
    w.poll_once()
    assert cfg.external_backends == {
        "static": "127.0.0.1:1", "mymodel": "127.0.0.1:9999",
    }


def test_key_added_while_serving_takes_effect(tmp_path):
    """End-to-end: a key written to api_keys.json authenticates a request
    against the live server — no restart."""
    from test_api import _ServerThread

    from localai_tpu.api.server import AppState

    models = tmp_path / "models"
    conf = tmp_path / "conf"
    models.mkdir()
    conf.mkdir()
    cfg = AppConfig(model_path=str(models), config_path=str(conf),
                    api_keys=["boot-key"])
    loader = ConfigLoader(models)
    loader.load_from_path(context_size=cfg.context_size)
    state = AppState(cfg, loader)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            def models_with(key):
                return c.get("/v1/models",
                             headers={"Authorization": f"Bearer {key}"})

            assert models_with("hot-key").status_code == 401
            (conf / "api_keys.json").write_text(json.dumps(["hot-key"]))
            state.watcher.poll_once()
            assert models_with("hot-key").status_code == 200
            assert models_with("boot-key").status_code == 200
    finally:
        srv.stop()
