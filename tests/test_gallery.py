"""Gallery subsystem: index resolution, installs, async jobs, HTTP API.

Mirrors the reference's approach of driving gallery code with file://
fixture galleries (/root/reference/tests/fixtures/gallery_simple.yaml and
core/gallery/models_test.go) — no network needed.
"""

import json
import time

import pytest
import yaml

from localai_tpu.gallery import (
    Gallery,
    GalleryModel,
    GalleryOp,
    GalleryService,
    available_models,
    delete_model,
    find_model,
    install_model,
    resolve_embedded,
)
from localai_tpu.gallery.models import deep_merge


@pytest.fixture()
def fixture_gallery(tmp_path):
    """A file:// gallery with one model whose weight file is also file://."""
    blob = tmp_path / "weights.bin"
    blob.write_bytes(b"\x00" * 64)
    import hashlib

    sha = hashlib.sha256(blob.read_bytes()).hexdigest()
    index = [{
        "name": "fixture-model",
        "description": "test model",
        "license": "mit",
        "files": [{
            "filename": "fixture-model/weights.bin",
            "uri": f"file://{blob}",
            "sha256": sha,
        }],
        "config_file": {
            "model": "debug:tiny",
            "context_size": 64,
            "parameters": {"temperature": 0.2},
        },
        "overrides": {"parameters": {"top_k": 7}},
    }]
    path = tmp_path / "index.yaml"
    path.write_text(yaml.safe_dump(index))
    return Gallery(name="test", url=f"file://{path}")


def test_find_and_available(fixture_gallery, tmp_models_dir):
    models = available_models([fixture_gallery], tmp_models_dir)
    # configured gallery entries lead; the shipped index follows
    assert models[0].name == "fixture-model"
    assert not models[0].installed
    assert all(m.gallery == "shipped" for m in models[1:])

    assert find_model([fixture_gallery], "fixture-model") is not None
    assert find_model([fixture_gallery], "test@fixture-model") is not None
    assert find_model([fixture_gallery], "fixture-model@test") is not None
    assert find_model([fixture_gallery], "nope") is None


def test_install_and_delete(fixture_gallery, tmp_models_dir):
    model = find_model([fixture_gallery], "fixture-model")
    cfg_path = install_model(model, tmp_models_dir)
    assert cfg_path.exists()
    doc = yaml.safe_load(cfg_path.read_text())
    # config_file ⊕ overrides merge (mergo parity)
    assert doc["name"] == "fixture-model"
    assert doc["parameters"]["temperature"] == 0.2
    assert doc["parameters"]["top_k"] == 7
    assert (tmp_models_dir / "fixture-model/weights.bin").exists()

    # installed flag now set
    models = available_models([fixture_gallery], tmp_models_dir)
    assert models[0].installed

    assert delete_model("fixture-model", tmp_models_dir)
    assert not cfg_path.exists()
    # downloaded files (recorded in the install manifest) are removed too
    assert not (tmp_models_dir / "fixture-model/weights.bin").exists()
    assert not (tmp_models_dir / "fixture-model").exists()
    assert not delete_model("fixture-model", tmp_models_dir)


def test_sha_mismatch_rejected(tmp_path, tmp_models_dir):
    blob = tmp_path / "w.bin"
    blob.write_bytes(b"data")
    model = GalleryModel(
        name="bad",
        files=[{"filename": "bad/w.bin", "uri": f"file://{blob}",
                "sha256": "0" * 64}],
    )
    with pytest.raises(ValueError, match="sha256 mismatch"):
        install_model(model, tmp_models_dir)


def test_path_traversal_rejected(tmp_path, tmp_models_dir):
    blob = tmp_path / "w.bin"
    blob.write_bytes(b"data")
    model = GalleryModel(
        name="evil",
        files=[{"filename": "../../etc/evil.bin", "uri": f"file://{blob}"}],
    )
    with pytest.raises(ValueError, match="escapes"):
        install_model(model, tmp_models_dir)


def test_deep_merge():
    assert deep_merge(
        {"a": {"x": 1, "y": 2}, "b": 1},
        {"a": {"y": 3}, "c": 4},
    ) == {"a": {"x": 1, "y": 3}, "b": 1, "c": 4}


def test_embedded_library(tmp_models_dir):
    m = resolve_embedded("debug-tiny")
    assert m is not None
    path = install_model(m, tmp_models_dir)
    doc = yaml.safe_load(path.read_text())
    assert doc["model"] == "debug:tiny"
    assert resolve_embedded("no-such-model") is None


def _wait_job(svc, job_id, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = svc.status(job_id)
        if st is not None and st.processed:
            return st
        time.sleep(0.05)
    raise TimeoutError("job never finished")


def test_gallery_service_jobs(fixture_gallery, tmp_models_dir):
    installed = []
    svc = GalleryService(str(tmp_models_dir), [fixture_gallery],
                         on_installed=installed.append)
    try:
        job = svc.submit(GalleryOp(id="", kind="apply",
                                   gallery_ref="fixture-model"))
        st = _wait_job(svc, job)
        assert st.error == ""
        assert st.progress == 100.0
        assert installed and installed[0].name == "fixture-model.yaml"

        job2 = svc.submit(GalleryOp(id="", kind="delete",
                                    install_name="fixture-model"))
        st2 = _wait_job(svc, job2)
        assert st2.error == ""
        assert st2.deletion

        job3 = svc.submit(GalleryOp(id="", kind="apply",
                                    gallery_ref="missing-model"))
        st3 = _wait_job(svc, job3)
        assert "missing-model" in st3.error
    finally:
        svc.shutdown()


def test_gallery_http_api(fixture_gallery, tmp_models_dir):
    """Drive the gallery endpoints through the real HTTP app."""
    from tests.test_api import _ServerThread, make_state
    import httpx

    state = make_state(tmp_models_dir)
    state.add_gallery(fixture_gallery)
    srv = _ServerThread(state)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with httpx.Client(base_url=base, timeout=60.0) as client:
            r = client.get("/models/galleries")
            assert {g["name"] for g in r.json()} == {"test"}

            r = client.get("/models/available")
            names = {m["name"] for m in r.json()}
            assert "fixture-model" in names
            assert "debug-tiny" in names  # embedded library

            r = client.post("/models/apply", json={"id": "fixture-model"})
            assert r.status_code == 200, r.text
            uuid = r.json()["uuid"]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st = client.get(f"/models/jobs/{uuid}").json()
                if st["processed"]:
                    break
                time.sleep(0.05)
            assert st["processed"] and not st["error"], st

            # the installed model is immediately configured for serving
            r = client.get("/v1/models")
            assert "fixture-model" in {
                m["id"] for m in r.json()["data"]}

            r = client.post("/models/delete/fixture-model")
            uuid = r.json()["uuid"]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st = client.get(f"/models/jobs/{uuid}").json()
                if st["processed"]:
                    break
                time.sleep(0.05)
            assert st["processed"] and not st["error"], st

            r = client.get("/models/jobs")
            assert len(r.json()) == 2

            r = client.get("/models/jobs/nope")
            assert r.status_code == 404

            r = client.post("/models/galleries",
                            json={"name": "g2", "url": "file:///dev/null"})
            assert r.status_code == 200
            r = client.request("DELETE", "/models/galleries",
                               json={"name": "g2"})
            assert r.status_code == 200
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# shipped multi-family index (parity: the reference's bundled gallery)


def test_shipped_index_families_and_resolution(tmp_path):
    from localai_tpu.gallery import available_models, resolve_ref
    from localai_tpu.gallery.index_data import shipped_index

    models = shipped_index()
    assert len(models) >= 30
    # every north-star modality is represented
    backends = {
        (m.config_file or {}).get("backend", "") for m in models
    }
    assert {"", "whisper", "diffusers", "reranker",
            "bert-embeddings"} <= backends
    # entries are well-formed: a name, an installable payload, a config
    for m in models:
        assert m.name
        assert m.config_file and m.config_file.get("model")
        assert m.files or m.url
        for f in m.files:
            assert f.uri.startswith("huggingface://")

    # short-name resolution without any configured gallery
    m = resolve_ref([], "qwen2.5-7b-instruct")
    assert m is not None
    assert m.config_file["context_size"] == 131072
    assert resolve_ref([], "shipped@whisper-base") is not None
    assert resolve_ref([], "no-such-model") is None

    # shipped entries appear in the available listing, install-flagged
    listing = available_models([], tmp_path)
    names = {m.name for m in listing}
    assert "all-minilm-l6-v2" in names
    assert "stable-diffusion-1.5" in names
    (tmp_path / "whisper-base.yaml").write_text("name: whisper-base\n")
    listing = available_models([], tmp_path)
    flags = {m.name: m.installed for m in listing}
    assert flags["whisper-base"] is True
    assert flags["whisper-tiny"] is False


def test_shipped_index_yields_to_configured_galleries(tmp_path):
    """A configured gallery entry with the same name wins over shipped."""
    import json

    from localai_tpu.gallery import Gallery, available_models

    idx = tmp_path / "idx.json"
    idx.write_text(json.dumps([{
        "name": "whisper-base", "description": "gallery override",
        "url": "file:///unused.yaml",
    }]))
    g = Gallery(name="g", url=f"file://{idx}")
    listing = available_models([g], tmp_path)
    mine = [m for m in listing if m.name == "whisper-base"]
    assert len(mine) == 1
    assert mine[0].description == "gallery override"
