"""Multi-device sharding tests on the virtual 8-device CPU mesh (conftest
forces xla_force_host_platform_device_count=8 — the simulated-multi-host
strategy SURVEY.md §4 calls for, absent in the reference)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel import sharding as shd
from localai_tpu.parallel.mesh import MeshPlan, build_mesh
from localai_tpu.utils.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8
    return build_mesh(MeshPlan(data=2, model=4))


@pytest.fixture(scope="module")
def sharded_runner(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    runner = ModelRunner(
        tiny.cfg, params, num_slots=4, max_ctx=128,
        prefill_buckets=[16, 32], kv_dtype="float32", mesh=mesh,
    )
    return tiny, runner


def test_param_specs_cover_all_params(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    specs = shd.param_specs(tiny.cfg, mesh)
    jax.tree.map(
        lambda spec, arr: None, specs, tiny.params,
        is_leaf=lambda x: isinstance(x, P),
    )  # same treedef or this throws


def test_sharded_weights_are_distributed(sharded_runner, mesh):
    tiny, runner = sharded_runner
    wq = runner.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 8
    # column-parallel: last dim split over 'model' (4-way)
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[-1] == wq.shape[-1] // 4
    kv = runner.kv.k
    assert kv.sharding.shard_shape(kv.shape)[1] == kv.shape[1] // 2  # slots/dp


def test_sharded_generation_matches_single_device(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    prompt = list(b"sharding parity test")

    r1 = ModelRunner(tiny.cfg, tiny.params, num_slots=4, max_ctx=128,
                     prefill_buckets=[32], kv_dtype="float32")
    t1 = [r1.admit(r1.acquire_slot(), prompt, temperature=0.0)]
    t1 += [int(r1.step()[0]) for _ in range(8)]

    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    r2 = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                     prefill_buckets=[32], kv_dtype="float32", mesh=mesh)
    t2 = [r2.admit(r2.acquire_slot(), prompt, temperature=0.0)]
    t2 += [int(r2.step()[0]) for _ in range(8)]
    assert t1 == t2


def test_scheduler_on_sharded_runner(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    runner = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                         prefill_buckets=[32], kv_dtype="float32", mesh=mesh)
    s = Scheduler(runner, ByteTokenizer())
    try:
        tok = ByteTokenizer()
        hs = [
            s.submit(GenRequest(prompt=tok.encode(f"concurrent {i}"),
                                max_new_tokens=6, temperature=0.0))
            for i in range(5)
        ]
        for h in hs:
            h.result(120)
            assert h.finish_reason is not None
            assert h.completion_tokens > 0
    finally:
        s.shutdown()


def test_kv_replication_fallback_when_tp_exceeds_kv_heads():
    mesh8 = build_mesh(MeshPlan(model=8))
    tiny = resolve_model("debug:tiny", dtype="float32")  # 2 kv heads < 8
    spec = shd.kv_spec(tiny.cfg, mesh8)
    assert spec == P(None, "data", None, None, None)


def test_make_shard_fn_places_loader_tensors(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    fn = shd.make_shard_fn(tiny.cfg, mesh, dtype="float32")
    arr = np.zeros((tiny.cfg.num_layers, tiny.cfg.hidden_size,
                    tiny.cfg.num_heads * tiny.cfg.hd), np.float32)
    placed = fn(
        (jax.tree_util.DictKey("layers"), jax.tree_util.DictKey("wq")), arr
    )
    assert placed.sharding.shard_shape(placed.shape)[-1] == arr.shape[-1] // 4


# ---------------------------------------------------------------------------
# paged-pool partition rules + mesh spec parsing (ISSUE 8)


def test_paged_kv_spec_shards_kv_heads_on_model(mesh):
    small = resolve_model("debug:small", dtype="float32")  # 4 kv heads
    # [L, num_blocks, Hkv, bt, hd]: ONLY the kv-head axis shards — block
    # ids in the host tables are global, so the block axis must stay
    # whole on every device
    assert shd.paged_kv_spec(small.cfg, mesh) == \
        P(None, None, "model", None, None)


def test_paged_kv_spec_replicates_on_indivisible_kv_heads():
    mesh8 = build_mesh(MeshPlan(model=8))
    tiny = resolve_model("debug:tiny", dtype="float32")  # 2 kv heads < 8
    assert shd.paged_kv_spec(tiny.cfg, mesh8) == P(None, None, None,
                                                   None, None)


def test_block_table_spec_puts_slots_on_data():
    assert shd.block_table_spec() == P("data", None)


def test_meshed_paged_pool_and_tables_are_sharded():
    tiny = resolve_model("debug:tiny", dtype="float32")  # 2 kv heads
    mesh2 = build_mesh(MeshPlan(data=4, model=2))
    params = shd.shard_params(tiny.params, tiny.cfg, mesh2)
    r = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=64,
                    prefill_buckets=[16], kv_dtype="float32", mesh=mesh2,
                    paged=True, kv_block_tokens=16)
    k = r.kv.k
    # pool [L, N, Hkv, bt, hd]: kv heads split 2-way, block axis whole
    assert k.sharding.shard_shape(k.shape)[2] == k.shape[2] // 2
    assert k.sharding.shard_shape(k.shape)[1] == k.shape[1]
    bt = r.block_tables
    assert bt.sharding.shard_shape(bt.shape)[0] == bt.shape[0] // 4


def test_parse_mesh_spec_both_syntaxes_and_unknown_axis():
    from localai_tpu.parallel.mesh import parse_mesh_spec

    assert parse_mesh_spec("data=2,model=4") == {"data": 2, "model": 4}
    assert parse_mesh_spec("data:2,model:4") == {"data": 2, "model": 4}
    assert parse_mesh_spec("") is None
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_spec("modle=4")  # a typo must not serve unsharded


def test_default_tensor_parallel_prefers_all_devices():
    from localai_tpu.parallel.mesh import default_tensor_parallel

    assert default_tensor_parallel(8, num_heads=32) == 8   # model=all
    assert default_tensor_parallel(8, num_heads=12) == 4   # widest divisor
    assert default_tensor_parallel(8, num_heads=7) == 1    # no split
    assert default_tensor_parallel(1, num_heads=32) == 1


def test_localai_mesh_env_parses_into_app_config(monkeypatch):
    from localai_tpu.config.app_config import AppConfig

    monkeypatch.setenv("LOCALAI_MESH", "data:2,model:4")
    assert AppConfig.from_env().mesh_shape == {"data": 2, "model": 4}
    monkeypatch.setenv("LOCALAI_MESH", "")
    assert AppConfig.from_env().mesh_shape is None


def test_manager_serves_meshed_paged_by_default(monkeypatch):
    """ROADMAP item 3 acceptance: with >1 visible device the manager
    builds the mesh itself — no flag — and keeps the paged layout under
    it (LOCALAI_MESH_AUTO=1 stands in for a real accelerator host: the
    CPU backend is excluded from auto-meshing so tier-1 single-device
    semantics stay byte-identical)."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_runner

    mcfg = ModelConfig(**{
        "name": "meshed", "model": "debug:tiny",
        "engine": {"max_slots": 4, "prefill_buckets": [16, 32],
                   "dtype": "float32", "kv_dtype": "float32"},
    })
    app = AppConfig()

    monkeypatch.setenv("LOCALAI_MESH_AUTO", "1")
    _, runner = build_runner(mcfg, app)
    assert runner.mesh is not None and runner.paged
    # model=all: tiny's 4 q heads cap tp at 4, dp fills the rest
    assert runner.mesh.shape["model"] == 4
    assert runner.mesh.shape["data"] == 2

    # CPU without the force flag: no mesh, single-device paged unchanged
    monkeypatch.delenv("LOCALAI_MESH_AUTO")
    _, r2 = build_runner(mcfg, app)
    assert r2.mesh is None and r2.paged

    # explicit topology (--mesh / LOCALAI_MESH → mesh_shape) always wins
    app_explicit = AppConfig(mesh_shape={"data": 4, "model": 2})
    _, r3 = build_runner(mcfg, app_explicit)
    assert dict(r3.mesh.shape) == {"data": 4, "seq": 1, "pipe": 1,
                                   "expert": 1, "model": 2}
    assert r3.paged
