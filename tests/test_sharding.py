"""Multi-device sharding tests on the virtual 8-device CPU mesh (conftest
forces xla_force_host_platform_device_count=8 — the simulated-multi-host
strategy SURVEY.md §4 calls for, absent in the reference)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel import sharding as shd
from localai_tpu.parallel.mesh import MeshPlan, build_mesh
from localai_tpu.utils.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8
    return build_mesh(MeshPlan(data=2, model=4))


@pytest.fixture(scope="module")
def sharded_runner(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    runner = ModelRunner(
        tiny.cfg, params, num_slots=4, max_ctx=128,
        prefill_buckets=[16, 32], kv_dtype="float32", mesh=mesh,
    )
    return tiny, runner


def test_param_specs_cover_all_params(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    specs = shd.param_specs(tiny.cfg, mesh)
    jax.tree.map(
        lambda spec, arr: None, specs, tiny.params,
        is_leaf=lambda x: isinstance(x, P),
    )  # same treedef or this throws


def test_sharded_weights_are_distributed(sharded_runner, mesh):
    tiny, runner = sharded_runner
    wq = runner.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 8
    # column-parallel: last dim split over 'model' (4-way)
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[-1] == wq.shape[-1] // 4
    kv = runner.kv.k
    assert kv.sharding.shard_shape(kv.shape)[1] == kv.shape[1] // 2  # slots/dp


def test_sharded_generation_matches_single_device(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    prompt = list(b"sharding parity test")

    r1 = ModelRunner(tiny.cfg, tiny.params, num_slots=4, max_ctx=128,
                     prefill_buckets=[32], kv_dtype="float32")
    t1 = [r1.admit(r1.acquire_slot(), prompt, temperature=0.0)]
    t1 += [int(r1.step()[0]) for _ in range(8)]

    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    r2 = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                     prefill_buckets=[32], kv_dtype="float32", mesh=mesh)
    t2 = [r2.admit(r2.acquire_slot(), prompt, temperature=0.0)]
    t2 += [int(r2.step()[0]) for _ in range(8)]
    assert t1 == t2


def test_scheduler_on_sharded_runner(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    runner = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                         prefill_buckets=[32], kv_dtype="float32", mesh=mesh)
    s = Scheduler(runner, ByteTokenizer())
    try:
        tok = ByteTokenizer()
        hs = [
            s.submit(GenRequest(prompt=tok.encode(f"concurrent {i}"),
                                max_new_tokens=6, temperature=0.0))
            for i in range(5)
        ]
        for h in hs:
            h.result(120)
            assert h.finish_reason is not None
            assert h.completion_tokens > 0
    finally:
        s.shutdown()


def test_kv_replication_fallback_when_tp_exceeds_kv_heads():
    mesh8 = build_mesh(MeshPlan(model=8))
    tiny = resolve_model("debug:tiny", dtype="float32")  # 2 kv heads < 8
    spec = shd.kv_spec(tiny.cfg, mesh8)
    assert spec == P(None, "data", None, None, None)


def test_make_shard_fn_places_loader_tensors(mesh):
    tiny = resolve_model("debug:small", dtype="float32")
    fn = shd.make_shard_fn(tiny.cfg, mesh, dtype="float32")
    arr = np.zeros((tiny.cfg.num_layers, tiny.cfg.hidden_size,
                    tiny.cfg.num_heads * tiny.cfg.hd), np.float32)
    placed = fn(
        (jax.tree_util.DictKey("layers"), jax.tree_util.DictKey("wq")), arr
    )
    assert placed.sharding.shard_shape(placed.shape)[-1] == arr.shape[-1] // 4
