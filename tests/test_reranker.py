"""Cross-encoder rerank: joint (query ⊕ doc) scoring through a bert-class
encoder (parity: /root/reference/backend/python/rerankers/backend.py),
with cosine-of-embeddings as the fallback path.

The adversarial fixture: mean-pooled byte-embedding cosine is a
bag-of-tokens score — it CANNOT separate a document from its anagram
(identical multiset of bytes → identical mean embedding → identical
cosine). The cross-encoder attends over positions and the query/document
boundary, so it separates them.
"""

import json

import numpy as np
import pytest

from localai_tpu.models.reranker import (
    BertConfig,
    CrossEncoder,
    forward,
    resolve_reranker,
)


@pytest.fixture(scope="module")
def encoder() -> CrossEncoder:
    return resolve_reranker("debug:reranker-tiny")


def test_score_shapes_and_determinism(encoder):
    docs = ["first doc", "second doc", "third"]
    s1 = encoder.score("a query", docs)
    s2 = encoder.score("a query", docs)
    assert s1.shape == (3,)
    np.testing.assert_allclose(s1, s2, rtol=1e-5)
    # batch padding must not change scores: same pair alone or in a batch
    solo = encoder.score("a query", ["first doc"])
    np.testing.assert_allclose(solo[0], s1[0], rtol=1e-4)


def test_scores_are_query_conditioned(encoder):
    docs = ["alpha beta", "gamma delta"]
    a = encoder.score("query one", docs)
    b = encoder.score("a different query", docs)
    assert not np.allclose(a, b)


def test_cross_encoder_beats_cosine_structurally(encoder):
    """The two adversarial properties cosine-of-embeddings structurally
    CANNOT have, regardless of weights:

    * symmetry — cos(embed(a), embed(b)) == cos(embed(b), embed(a)) by
      definition, but relevance is directional (a question is relevant to
      its answer more than vice versa). The joint encoder is asymmetric
      (segment ids + packing order).
    * order blindness at the interaction level — cosine compares two
      independently pooled vectors; the joint encoder attends across the
      query/document boundary, so permuting the document changes the
      query-conditioned score even when pooled summaries barely move.
    """
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model

    doc = "the cat sat on the mat"
    anagram = "".join(sorted(doc))  # same bytes, destroyed order
    query = "where did the cat sit"

    # the fallback path the API uses for non-reranker models
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(tiny.cfg, tiny.params, num_slots=1, max_ctx=96,
                        prefill_buckets=[64], kv_dtype="float32")

    def cos(a, b):
        va = np.asarray(runner.embed(tiny.tokenizer.encode(a)))
        vb = np.asarray(runner.embed(tiny.tokenizer.encode(b)))
        return float(
            va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb))
        )

    # cosine is exactly symmetric; the joint score is not
    assert cos(query, doc) == pytest.approx(cos(doc, query), abs=1e-12)
    fwd = float(encoder.score(query, [doc])[0])
    rev = float(encoder.score(doc, [query])[0])
    assert abs(fwd - rev) > 1e-7, "joint scoring should be directional"

    # the anagram pair stays separable under the joint score
    ce = encoder.score(query, [doc, anagram])
    assert abs(float(ce[0]) - float(ce[1])) > 1e-7, (
        "cross-encoder collapsed the anagram pair"
    )


def test_long_document_truncation(encoder):
    long_doc = "x" * 5000
    s = encoder.score("q", [long_doc])
    assert np.isfinite(s).all()


def test_hf_bert_checkpoint_loading(tmp_path):
    """A bert cross-encoder checkpoint dir (config.json + safetensors +
    tokenizer.json) loads and scores — the ms-marco layout."""
    from safetensors.numpy import save_file

    cfg = BertConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_layers=1, num_heads=2, max_position_embeddings=64,
        type_vocab_size=2, cls_id=1, sep_id=2, pad_id=0,
    )
    rng = np.random.default_rng(0)

    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.02

    tensors = {
        "bert.embeddings.word_embeddings.weight": w(64, 32),
        "bert.embeddings.position_embeddings.weight": w(64, 32),
        "bert.embeddings.token_type_embeddings.weight": w(2, 32),
        "bert.embeddings.LayerNorm.weight": np.ones(32, np.float32),
        "bert.embeddings.LayerNorm.bias": np.zeros(32, np.float32),
        "bert.pooler.dense.weight": w(32, 32),
        "bert.pooler.dense.bias": np.zeros(32, np.float32),
        "classifier.weight": w(1, 32),
        "classifier.bias": np.zeros(1, np.float32),
    }
    p = "bert.encoder.layer.0"
    for name, shape in [
        (f"{p}.attention.self.query", (32, 32)),
        (f"{p}.attention.self.key", (32, 32)),
        (f"{p}.attention.self.value", (32, 32)),
        (f"{p}.attention.output.dense", (32, 32)),
        (f"{p}.intermediate.dense", (64, 32)),
        (f"{p}.output.dense", (32, 64)),
    ]:
        tensors[f"{name}.weight"] = w(*shape)
        tensors[f"{name}.bias"] = np.zeros(shape[0], np.float32)
    for lnn in (f"{p}.attention.output.LayerNorm", f"{p}.output.LayerNorm"):
        tensors[f"{lnn}.weight"] = np.ones(32, np.float32)
        tensors[f"{lnn}.bias"] = np.zeros(32, np.float32)

    d = tmp_path / "ce-model"
    d.mkdir()
    save_file(tensors, d / "model.safetensors")
    (d / "config.json").write_text(json.dumps({
        "model_type": "bert", "vocab_size": 64, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 1,
        "num_attention_heads": 2, "max_position_embeddings": 64,
        "type_vocab_size": 2, "pad_token_id": 0,
    }))
    # minimal wordlevel tokenizer.json
    vocab = {"[PAD]": 0, "[CLS]": 1, "[SEP]": 2,
             **{w_: i + 3 for i, w_ in enumerate(
                 ["cat", "dog", "sat", "ran", "the", "a"])}}
    (d / "tokenizer.json").write_text(json.dumps({
        "version": "1.0",
        "truncation": None, "padding": None,
        "added_tokens": [], "normalizer": {"type": "Lowercase"},
        "pre_tokenizer": {"type": "Whitespace"},
        "post_processor": None, "decoder": None,
        "model": {"type": "WordLevel", "vocab": vocab, "unk_token": "[PAD]"},
    }))

    enc = resolve_reranker(str(d))
    scores = enc.score("the cat", ["cat sat", "dog ran"])
    assert scores.shape == (2,)
    assert np.isfinite(scores).all()
    # loaded weights match a direct forward with the same params
    direct = forward(
        enc.params, enc.cfg,
        *(np.asarray(x)[None] for x in enc._pair(
            enc.tokenizer.encode("the cat"),
            enc.tokenizer.encode("cat sat"), 64)),
    )
    np.testing.assert_allclose(float(direct[0]), float(scores[0]),
                               rtol=1e-4)


def test_rerank_http_routes_to_cross_encoder(tmp_path):
    """`backend: reranker` models serve /v1/rerank through the joint
    scorer and appear under lifecycle management."""
    import httpx
    from test_api import _ServerThread, make_state

    (tmp_path / "ce.yaml").write_text(
        "name: ce\nmodel: 'debug:reranker-tiny'\nbackend: reranker\n"
        "known_usecases: [rerank]\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=60.0) as c:
            r = c.post("/v1/rerank", json={
                "model": "ce",
                "query": "where did the cat sit",
                "documents": ["the cat sat on the mat", "unrelated text",
                              "more filler"],
                "top_n": 2,
            })
            assert r.status_code == 200, r.text
            body = r.json()
            assert len(body["results"]) == 2
            assert body["usage"]["total_tokens"] > 0
            # scores are returned sorted
            rs = [x["relevance_score"] for x in body["results"]]
            assert rs == sorted(rs, reverse=True)
        assert srv.state.manager.loaded_names() == ["ce"]
        sm = srv.state.manager.get_reranker("ce")
        assert sm.engine_metrics()["type"] == "rerank"
        assert sm.engine_metrics()["pairs_scored"] == 3
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# sentence embeddings over the same trunk (sentencetransformers parity)


def test_sentence_encoder_embeddings():
    from localai_tpu.models.reranker import resolve_sentence_encoder

    enc = resolve_sentence_encoder("debug:bert-tiny")
    vecs, total = enc.embed_with_usage(
        ["the cat sat", "a dog ran fast", "short"])
    assert vecs.shape == (3, 64)
    # normalized
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0,
                               rtol=1e-4)
    assert total == sum(len(t.encode()) for t in
                        ["the cat sat", "a dog ran fast", "short"])
    # deterministic + batch-composition independent
    solo = enc.embed(["the cat sat"])
    np.testing.assert_allclose(solo[0], vecs[0], rtol=1e-4)
    # distinct inputs, distinct embeddings
    assert not np.allclose(vecs[0], vecs[1])


def test_embeddings_http_routes_to_bert(tmp_path):
    """`backend: bert-embeddings` models serve /v1/embeddings through the
    sentence encoder under lifecycle management."""
    import httpx
    from test_api import _ServerThread, make_state

    (tmp_path / "st.yaml").write_text(
        "name: st\nmodel: 'debug:bert-tiny'\nbackend: bert-embeddings\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=60.0) as c:
            r = c.post("/v1/embeddings", json={
                "model": "st",
                "input": ["hello world", "another text"],
            })
            assert r.status_code == 200, r.text
            body = r.json()
            assert len(body["data"]) == 2
            assert len(body["data"][0]["embedding"]) == 64
            assert body["usage"]["prompt_tokens"] > 0
        em = srv.state.manager.get_embedder("st")
        assert em.engine_metrics()["texts_embedded"] == 2
    finally:
        srv.stop()


def test_hf_sentence_transformer_layout_loads(tmp_path):
    """A trunk-only bert checkpoint (no pooler/classifier, no `bert.`
    prefix) loads as a sentence encoder."""
    from safetensors.numpy import save_file

    from localai_tpu.models.reranker import resolve_sentence_encoder

    rng = np.random.default_rng(0)

    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.02

    tensors = {
        "embeddings.word_embeddings.weight": w(64, 32),
        "embeddings.position_embeddings.weight": w(64, 32),
        "embeddings.token_type_embeddings.weight": w(2, 32),
        "embeddings.LayerNorm.weight": np.ones(32, np.float32),
        "embeddings.LayerNorm.bias": np.zeros(32, np.float32),
    }
    p = "encoder.layer.0"
    for name, shape in [
        (f"{p}.attention.self.query", (32, 32)),
        (f"{p}.attention.self.key", (32, 32)),
        (f"{p}.attention.self.value", (32, 32)),
        (f"{p}.attention.output.dense", (32, 32)),
        (f"{p}.intermediate.dense", (64, 32)),
        (f"{p}.output.dense", (32, 64)),
    ]:
        tensors[f"{name}.weight"] = w(*shape)
        tensors[f"{name}.bias"] = np.zeros(shape[0], np.float32)
    for lnn in (f"{p}.attention.output.LayerNorm", f"{p}.output.LayerNorm"):
        tensors[f"{lnn}.weight"] = np.ones(32, np.float32)
        tensors[f"{lnn}.bias"] = np.zeros(32, np.float32)
    d = tmp_path / "st-model"
    d.mkdir()
    save_file(tensors, d / "model.safetensors")
    (d / "config.json").write_text(json.dumps({
        "model_type": "bert", "vocab_size": 64, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 1,
        "num_attention_heads": 2, "max_position_embeddings": 64,
        "type_vocab_size": 2, "pad_token_id": 0,
    }))
    vocab = {"[PAD]": 0, "[CLS]": 1, "[SEP]": 2, "cat": 3, "dog": 4}
    (d / "tokenizer.json").write_text(json.dumps({
        "version": "1.0", "truncation": None, "padding": None,
        "added_tokens": [], "normalizer": {"type": "Lowercase"},
        "pre_tokenizer": {"type": "Whitespace"},
        "post_processor": None, "decoder": None,
        "model": {"type": "WordLevel", "vocab": vocab,
                  "unk_token": "[PAD]"},
    }))
    enc = resolve_sentence_encoder(str(d))
    vecs = enc.embed(["cat", "dog"])
    assert vecs.shape == (2, 32)
    assert np.isfinite(vecs).all()
