"""Multi-host command mirroring (parallel/multihost.py): a follower
replica replaying the leader's engine-call stream stays bit-identical —
the SPMD contract that keeps every host inside the same jitted program
(the TPU-native counterpart of the reference's RPC weight-sharding
worker tier)."""

import threading

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel.multihost import (
    CommandFollower,
    CommandLeader,
    MirroredRunner,
)


def _runner() -> ModelRunner:
    tiny = resolve_model("debug:tiny", dtype="float32")
    return ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                       prefill_buckets=[16, 32], kv_dtype="float32")


@pytest.fixture()
def pair():
    """Leader + follower runner replicas over a real TCP channel."""
    leader_ch = CommandLeader(port=0)
    replica = _runner()
    follower = CommandFollower(f"127.0.0.1:{leader_ch.port}",
                               {"m": replica})
    leader_ch.wait_for(1)
    leader = MirroredRunner(_runner(), leader_ch, "m")
    yield leader, replica, follower
    follower.close()
    leader_ch.close()


def test_replayed_stream_is_bit_identical(pair):
    leader, replica, follower = pair
    prompt = list(b"multihost determinism")

    slot = leader.acquire_slot()
    first = leader.admit(slot, prompt, temperature=0.0)
    follower.step()  # acquire_slot
    follower.step()  # admit
    # same prefill → same first sampled token on both "hosts"
    toks_l = [int(first)]
    toks_f = [int(np.asarray(replica.state.tokens)[slot])]

    for _ in range(3):
        rows = leader.step_n(4)
        follower.step()
        toks_l.extend(int(t) for t in rows[:, slot])
        # the replica advanced through the identical program
        assert int(np.asarray(replica.state.tokens)[slot]) == int(
            rows[-1, slot])
    assert toks_l[0] == toks_f[0]
    np.testing.assert_array_equal(
        np.asarray(leader.state.positions), np.asarray(
            replica.state.positions)
    )


def test_bias_rows_cross_the_channel(pair):
    leader, replica, follower = pair
    slot = leader.acquire_slot()
    follower.step()
    bias = np.zeros(512, np.float32)
    bias[5] = -1e30
    leader.set_bias(slot, bias)
    follower.step()
    np.testing.assert_array_equal(
        np.asarray(leader.state.bias[slot]),
        np.asarray(replica.state.bias[slot]),
    )


def test_unknown_model_fails_loudly():
    ch = CommandLeader(port=0)
    replica = _runner()
    f = CommandFollower(f"127.0.0.1:{ch.port}", {"expected": replica})
    ch.wait_for(1)
    ch.broadcast("other-model", "release", 0)
    with pytest.raises(RuntimeError, match="no replica"):
        f.step()
    f.close()
    ch.close()


def test_scheduler_over_mirrored_runner():
    """The full scheduler drives a MirroredRunner while a background
    follower thread replays — generations come out identical to the
    replica's state advancing in lockstep."""
    from localai_tpu.engine.scheduler import GenRequest, Scheduler
    from localai_tpu.utils.tokenizer import ByteTokenizer

    ch = CommandLeader(port=0)
    replica = _runner()
    follower = CommandFollower(f"127.0.0.1:{ch.port}", {"m": replica})
    stop = threading.Event()

    def replay():
        while not stop.is_set():
            try:
                follower.step()
            except (ConnectionError, OSError):
                return

    t = threading.Thread(target=replay, daemon=True)
    t.start()
    ch.wait_for(1)
    leader = MirroredRunner(_runner(), ch, "m")
    sched = Scheduler(leader, ByteTokenizer(), multi_step=4,
                      pipeline_depth=1)
    try:
        h = sched.submit(GenRequest(
            prompt=ByteTokenizer().encode("hello"), max_new_tokens=8,
            temperature=0.0,
        ))
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
        import time

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            # replica reaches the same position as the leader's slot 0
            if np.asarray(replica.state.positions)[0] == np.asarray(
                    leader.state.positions)[0]:
                break
            time.sleep(0.05)
        np.testing.assert_array_equal(
            np.asarray(leader.state.positions)[0],
            np.asarray(replica.state.positions)[0],
        )
    finally:
        sched.shutdown()
        stop.set()
        follower.close()
        ch.close()


def test_mirror_channel_requires_peer_token():
    """With a token set, unauthenticated connections are rejected and
    never join the follower group (the stream carries user prompts)."""
    import socket
    import struct
    import time

    ch = CommandLeader(port=0, token="sekrit")
    replica = _runner()
    # wrong token → refused
    with pytest.raises(PermissionError, match="rejected"):
        CommandFollower(f"127.0.0.1:{ch.port}", {"m": replica},
                        token="wrong", connect_timeout=5.0)
    # raw connection that never handshakes → never joins
    raw = socket.create_connection(("127.0.0.1", ch.port), timeout=5.0)
    raw.sendall(struct.pack(">I", 2) + b"{}")
    time.sleep(0.3)
    assert len(ch._conns) == 0
    raw.close()
    # right token joins and replays
    f = CommandFollower(f"127.0.0.1:{ch.port}", {"m": replica},
                        token="sekrit")
    ch.wait_for(1)
    ch.broadcast("m", "release", 0)
    f.step()
    f.close()
    ch.close()
