"""obs subsystem tests: span recorder + ring-buffer store, engine
telemetry through a real Scheduler run on the tiny debug model, and the
compile-watch wrapper."""

import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.registry import resolve_model
from localai_tpu.obs import (
    EngineTelemetry,
    Registry,
    RequestTrace,
    TraceStore,
)
from localai_tpu.obs import compile as obs_compile
from localai_tpu.utils.tokenizer import ByteTokenizer

# -- trace store -------------------------------------------------------------


def test_span_tree_shape():
    tr = RequestTrace("tid-1", "rid-1", model="m", prompt_tokens=5)
    tr.begin("queued")
    tr.end("queued")
    tr.begin("decode")
    tr.event("admitted", slot=2)
    tr.end("decode", tokens=7)
    d = tr.to_dict()
    assert d["trace_id"] == "tid-1" and d["model"] == "m"
    names = [c["name"] for c in d["children"]]
    assert names == ["queued", "decode", "admitted"]
    by_name = {c["name"]: c for c in d["children"]}
    assert by_name["queued"]["duration_ms"] is not None
    assert by_name["admitted"]["duration_ms"] == 0.0  # point event
    assert by_name["decode"]["attrs"]["tokens"] == 7


def test_end_without_begin_is_noop():
    tr = RequestTrace("t", "r")
    assert tr.end("never-started") is None
    assert tr.to_dict()["children"] == []


def test_store_ring_is_bounded_and_newest_first():
    store = TraceStore(capacity=3)
    for i in range(5):
        tr = RequestTrace(f"t{i}", f"r{i}")
        store.start(tr)
        store.finish(tr)
    recent = store.recent()
    assert [t.trace_id for t in recent] == ["t4", "t3", "t2"]
    assert store.find("t0") == []        # evicted by the ring
    assert store.find("t4")[0].finished


def test_trace_capacity_env_knob(monkeypatch):
    # LOCALAI_TRACE_CAPACITY sizes the per-kind finished-trace rings
    # (ISSUE 15 satellite); garbage/unset falls back to the 256 default,
    # and an explicit constructor capacity always wins
    from localai_tpu.obs import trace as obs_trace

    monkeypatch.setenv("LOCALAI_TRACE_CAPACITY", "7")
    assert obs_trace.default_capacity() == 7
    assert obs_trace.TraceStore().capacity == 7
    monkeypatch.setenv("LOCALAI_TRACE_CAPACITY", "garbage")
    assert obs_trace.default_capacity() == 256
    monkeypatch.setenv("LOCALAI_TRACE_CAPACITY", "-3")
    assert obs_trace.default_capacity() == 1  # clamped positive
    monkeypatch.delenv("LOCALAI_TRACE_CAPACITY")
    assert obs_trace.default_capacity() == 256
    assert obs_trace.TraceStore(capacity=3).capacity == 3


def test_store_find_matches_trace_or_request_id():
    store = TraceStore()
    a = RequestTrace("shared-tid", "req-a")
    b = RequestTrace("shared-tid", "req-b")
    for t in (a, b):
        store.start(t)
        store.finish(t)
    assert len(store.find("shared-tid")) == 2
    assert [t.request_id for t in store.find("req-b")] == ["req-b"]


def test_active_traces_visible_before_finish():
    store = TraceStore()
    tr = RequestTrace("t-active", "r-active")
    store.start(tr)
    assert not store.recent()[0].finished
    store.finish(tr)
    assert store.recent()[0].finished


# -- engine telemetry through a real scheduler run ---------------------------


@pytest.fixture(scope="module")
def obs_sched():
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
    )
    store = TraceStore()
    reg = Registry()
    telemetry = EngineTelemetry(model="tiny", registry=reg, store=store)
    s = Scheduler(runner, ByteTokenizer(), telemetry=telemetry)
    yield s, store, reg
    s.shutdown()


def test_request_trace_has_lifecycle_phases_and_latencies(obs_sched):
    sched, store, reg = obs_sched
    tok = ByteTokenizer()
    h = sched.generate(GenRequest(
        prompt=tok.encode("trace me"), max_new_tokens=8, temperature=0.0,
        trace_id="trace-test-1",
    ))
    assert h.finish_reason in ("stop", "length")
    traces = store.find("trace-test-1")
    assert len(traces) == 1
    d = traces[0].to_dict()
    names = [c["name"] for c in d["children"]]
    for phase in ("queued", "prefill", "decode", "admitted", "drained"):
        assert phase in names, f"missing {phase} in {names}"
    assert d["finished"]
    assert d["attrs"]["ttft_ms"] is not None
    assert d["attrs"]["tpot_ms"] is not None
    assert d["attrs"]["completion_tokens"] == h.completion_tokens
    by_name = {c["name"]: c for c in d["children"]}
    assert by_name["prefill"]["attrs"]["path"] == "full"
    # histograms observed once
    text = reg.render()
    assert 'localai_ttft_seconds_count{model="tiny"} 1' in text
    assert 'localai_requests_total' in text


def test_cancelled_request_counts_as_preemption(obs_sched):
    sched, store, reg = obs_sched
    tok = ByteTokenizer()
    h = sched.submit(GenRequest(
        prompt=tok.encode("cancel"), max_new_tokens=400, temperature=0.0,
        ignore_eos=True, trace_id="trace-cancel",
    ))
    # wait until it is actually decoding in a slot — a cancel while still
    # queued is deliberately NOT a preemption (no slot was churned)
    for _item in h:
        break
    h.cancel()
    h.result(timeout=60)
    assert h.finish_reason == "cancelled"
    tr = store.find("trace-cancel")[0]
    assert tr.finished
    assert tr.to_dict()["attrs"]["finish_reason"] == "cancelled"
    assert ('localai_preemptions_total{model="tiny",reason="cancelled"}'
            in reg.render())
    assert sched.metrics()["preemptions"] >= 1


def test_scheduler_metrics_expose_engine_gauges(obs_sched):
    sched, _store, _reg = obs_sched
    m = sched.metrics()
    assert 0.0 <= m["occupancy"] <= 1.0
    assert 0.0 <= m["kv_utilization"] <= 1.0
    assert m["dispatches"] >= 0
    assert "preemptions" in m


def test_scheduler_feeds_flight_ring(obs_sched):
    """Every drain writes one flight record from host mirrors; the
    windowed step-time percentiles surface in metrics() next to the EMA
    (which is per-token seconds), and note_shed feeds shed_total."""
    sched, _store, _reg = obs_sched
    tok = ByteTokenizer()
    # enough tokens for post-compile dispatches, so percentiles exist
    h = sched.generate(GenRequest(
        prompt=tok.encode("flight me"), max_new_tokens=40, temperature=0.0,
        ignore_eos=True,
    ))
    assert h.completion_tokens > 16
    assert sched.flight.count > 0
    rec = sched.flight.snapshot(limit=1)[-1]
    for key in ("ts", "program", "steps", "dispatch_ms", "occupancy",
                "queue_depth", "kv_utilization", "tokens", "preemptions"):
        assert key in rec
    assert rec["program"].startswith(("decode", "spec"))
    m = sched.metrics()
    assert m["step_ms_p50"] is not None and m["step_ms_p50"] > 0
    assert m["step_ms_p99"] >= m["step_ms_p50"]
    if m["step_time_ema"] is not None:  # per-token SECONDS vs windowed ms
        assert m["step_time_ema"] * 1e3 == pytest.approx(
            m["step_ms_p50"], rel=50.0)
    before = m["shed_total"]
    sched.note_shed()
    assert sched.metrics()["shed_total"] == before + 1
    # the ring's token accounting matches the engine's lifetime counter
    assert sched.flight.total_tokens <= sched.total_generated_tokens + (
        sum(len(c.handle.token_ids) for c in sched._slots.values()))


def test_scheduler_registers_flight_forensics(obs_sched):
    """The watchdog carries this engine's flight snapshot provider, so a
    stall dump includes the preceding dispatch timeline."""
    sched, _store, _reg = obs_sched
    key = f"flight:{sched._wd_channel}"
    assert key in sched.watchdog._contexts
    payload = sched.watchdog._contexts[key]()
    assert payload["channel"] == sched._wd_channel
    assert isinstance(payload["records"], list)
    assert "step_ms_p50" in payload


def test_update_engine_gauges_exports_step_time(obs_sched):
    from localai_tpu.obs import Registry, update_engine_gauges

    sched, _store, _reg = obs_sched
    reg = Registry()
    update_engine_gauges("tiny", sched.metrics(), registry=reg)
    text = reg.render()
    assert 'localai_step_time_ms{model="tiny",quantile="p50"}' in text
    assert 'localai_step_time_ms{model="tiny",quantile="p99"}' in text


def test_runner_records_compile_time(obs_sched):
    # the fixture scheduler has prefilled + decoded at least once, so the
    # watch()-wrapped jit entries must have recorded first-call compiles
    # (the runner wraps with the process-wide registry)
    from localai_tpu.obs import REGISTRY

    text = REGISTRY.render()
    assert 'localai_xla_compile_total{program="prefill"}' in text
    assert 'localai_xla_compile_seconds_total{program="prefill"}' in text
    assert 'program="decode' in text  # decode or decode_n, per multi_step


# -- compile watch in isolation ---------------------------------------------


def test_watch_records_once_per_shape():
    reg = Registry()
    calls = []

    def fake_jit(x, *, bucket):
        calls.append((x, bucket))
        return x

    watched = obs_compile.watch(fake_jit, "prog", registry=reg)
    watched(1, bucket=16)
    watched(2, bucket=16)  # seen shape — not a compile
    watched(3, bucket=32)  # new static arg — compile
    text = reg.render()
    assert 'localai_xla_compile_total{program="prog"} 2' in text
    assert len(calls) == 3


def test_scheduler_wires_watchdog_into_runner(obs_sched):
    sched, _store, _reg = obs_sched
    # one watchdog instance guards both the scheduler drain ("engine:*")
    # and the runner's blocking syncs ("device")
    assert sched.runner.watchdog is sched.watchdog
    tok = ByteTokenizer()
    h = sched.generate(GenRequest(
        prompt=tok.encode("watchdog"), max_new_tokens=4, temperature=0.0,
    ))
    assert h.finish_reason in ("stop", "length")
    status = sched.watchdog.status()
    assert "device" in status            # runner syncs heartbeat here
    assert not sched.watchdog.stalled()  # healthy engine: nothing stalled
    assert status["device"]["armed"] == 0  # nothing in flight now
